start_server {tags {"obuf-limits"}} {
    test {Client output buffer hard limit is enforced} {
        r config set client-output-buffer-limit {pubsub 100000 0 0}
        set rd1 [redis_deferring_client]

        $rd1 subscribe foo
        set reply [$rd1 read]
        assert {$reply eq "subscribe foo 1"}

        set omem 0
        while 1 {
            r publish foo bar
            set clients [split [r client list] "\r\n"]
            set c [split [lindex $clients 1] " "]
            if {![regexp {omem=([0-9]+)} $c - omem]} break
            if {$omem > 200000} break
        }
        assert {$omem >= 90000 && $omem < 200000}
        $rd1 close
    }

    test {Client output buffer soft limit is not enforced if time is not overreached} {
        r config set client-output-buffer-limit {pubsub 0 100000 10}
        set rd1 [redis_deferring_client]

        $rd1 subscribe foo
        set reply [$rd1 read]
        assert {$reply eq "subscribe foo 1"}

        set omem 0
        set start_time 0
        set time_elapsed 0
        while 1 {
            r publish foo bar
            set clients [split [r client list] "\r\n"]
            set c [split [lindex $clients 1] " "]
            if {![regexp {omem=([0-9]+)} $c - omem]} break
            if {$omem > 100000} {
                if {$start_time == 0} {set start_time [clock seconds]}
                set time_elapsed [expr {[clock seconds]-$start_time}]
                if {$time_elapsed >= 5} break
            }
        }
        assert {$omem >= 100000 && $time_elapsed >= 5 && $time_elapsed <= 10}
        $rd1 close
    }

    test {Client output buffer soft limit is enforced if time is overreached} {
        r config set client-output-buffer-limit {pubsub 0 100000 3}
        set rd1 [redis_deferring_client]

        $rd1 subscribe foo
        set reply [$rd1 read]
        assert {$reply eq "subscribe foo 1"}

        set omem 0
        set start_time 0
        set time_elapsed 0
        while 1 {
            r publish foo bar
            set clients [split [r client list] "\r\n"]
            set c [split [lindex $clients 1] " "]
            if {![regexp {omem=([0-9]+)} $c - omem]} break
            if {$omem > 100000} {
                if {$start_time == 0} {set start_time [clock seconds]}
                set time_elapsed [expr {[clock seconds]-$start_time}]
                if {$time_elapsed >= 10} break
            }
        }
        assert {$omem >= 100000 && $time_elapsed < 6}
        $rd1 close
    }
}
