start_server {tags {"other"}} {
    if {$::force_failure} {
        # This is used just for test suite development purposes.
        test {Failing test} {
            format err
        } {ok}
    }

    test {SAVE - make sure there are all the types as values} {
        # Wait for a background saving in progress to terminate
        waitForBgsave r
        r lpush mysavelist hello
        r lpush mysavelist world
        r set myemptykey {}
        r set mynormalkey {blablablba}
        r zadd mytestzset 10 a
        r zadd mytestzset 20 b
        r zadd mytestzset 30 c
        r save
    } {OK}

    tags {slow} {
        if {$::accurate} {set iterations 10000} else {set iterations 1000}
        foreach fuzztype {binary alpha compr} {
            test "FUZZ stresser with data model $fuzztype" {
                set err 0
                for {set i 0} {$i < $iterations} {incr i} {
                    set fuzz [randstring 0 512 $fuzztype]
                    r set foo $fuzz
                    set got [r get foo]
                    if {$got ne $fuzz} {
                        set err [list $fuzz $got]
                        break
                    }
                }
                set _ $err
            } {0}
        }
    }

    test {BGSAVE} {
        waitForBgsave r
        r flushdb
        r save
        r set x 10
        r bgsave
        waitForBgsave r
        r debug reload
        r get x
    } {10}

    test {SELECT an out of range DB} {
        catch {r select 1000000} err
        set _ $err
    } {*invalid*}

    tags {consistency} {
        if {![catch {package require sha1}]} {
            if {$::accurate} {set numops 10000} else {set numops 1000}
            test {Check consistency of different data types after a reload} {
                r flushdb
                createComplexDataset r $numops
                set dump [csvdump r]
                set sha1 [r debug digest]
                r debug reload
                set sha1_after [r debug digest]
                if {$sha1 eq $sha1_after} {
                    set _ 1
                } else {
                    set newdump [csvdump r]
                    puts "Consistency test failed!"
                    puts "You can inspect the two dumps in /tmp/repldump*.txt"

                    set fd [open /tmp/repldump1.txt w]
                    puts $fd $dump
                    close $fd
                    set fd [open /tmp/repldump2.txt w]
                    puts $fd $newdump
                    close $fd

                    set _ 0
                }
            } {1}

            test {Same dataset digest if saving/reloading as AOF?} {
                r bgrewriteaof
                waitForBgrewriteaof r
                r debug loadaof
                set sha1_after [r debug digest]
                if {$sha1 eq $sha1_after} {
                    set _ 1
                } else {
                    set newdump [csvdump r]
                    puts "Consistency test failed!"
                    puts "You can inspect the two dumps in /tmp/aofdump*.txt"

                    set fd [open /tmp/aofdump1.txt w]
                    puts $fd $dump
                    close $fd
                    set fd [open /tmp/aofdump2.txt w]
                    puts $fd $newdump
                    close $fd

                    set _ 0
                }
            } {1}
        }
    }

    test {EXPIRES after a reload (snapshot + append only file rewrite)} {
        r flushdb
        r set x 10
        r expire x 1000
        r save
        r debug reload
        set ttl [r ttl x]
        set e1 [expr {$ttl > 900 && $ttl <= 1000}]
        r bgrewriteaof
        waitForBgrewriteaof r
        r debug loadaof
        set ttl [r ttl x]
        set e2 [expr {$ttl > 900 && $ttl <= 1000}]
        list $e1 $e2
    } {1 1}

    test {EXPIRES after AOF reload (without rewrite)} {
        r flushdb
        r config set appendonly yes
        r set x somevalue
        r expire x 1000
        r setex y 2000 somevalue
        r set z somevalue
        r expireat z [expr {[clock seconds]+3000}]

        # Milliseconds variants
        r set px somevalue
        r pexpire px 1000000
        r psetex py 2000000 somevalue
        r set pz somevalue
        r pexpireat pz [expr {([clock seconds]+3000)*1000}]

        # Reload and check
        waitForBgrewriteaof r
        # We need to wait two seconds to avoid false positives here, otherwise
        # the DEBUG LOADAOF command may read a partial file.
        # Another solution would be to set the fsync policy to no, since this
        # prevents write() to be delayed by the completion of fsync().
        after 2000
        r debug loadaof
        set ttl [r ttl x]
        assert {$ttl > 900 && $ttl <= 1000}
        set ttl [r ttl y]
        assert {$ttl > 1900 && $ttl <= 2000}
        set ttl [r ttl z]
        assert {$ttl > 2900 && $ttl <= 3000}
        set ttl [r ttl px]
        assert {$ttl > 900 && $ttl <= 1000}
        set ttl [r ttl py]
        assert {$ttl > 1900 && $ttl <= 2000}
        set ttl [r ttl pz]
        assert {$ttl > 2900 && $ttl <= 3000}
        r config set appendonly no
    }

    tags {protocol} {
        test {PIPELINING stresser (also a regression for the old epoll bug)} {
            set fd2 [socket $::host $::port]
            fconfigure $fd2 -encoding binary -translation binary
            puts -nonewline $fd2 "SELECT 9\r\n"
            flush $fd2
            gets $fd2

            for {set i 0} {$i < 100000} {incr i} {
                set q {}
                set val "0000${i}0000"
                append q "SET key:$i $val\r\n"
                puts -nonewline $fd2 $q
                set q {}
                append q "GET key:$i\r\n"
                puts -nonewline $fd2 $q
            }
            flush $fd2

            for {set i 0} {$i < 100000} {incr i} {
                gets $fd2 line
                gets $fd2 count
                set count [string range $count 1 end]
                set val [read $fd2 $count]
                read $fd2 2
            }
            close $fd2
            set _ 1
        } {1}
    }

    test {APPEND basics} {
        list [r append foo bar] [r get foo] \
             [r append foo 100] [r get foo]
    } {3 bar 6 bar100}

    test {APPEND basics, integer encoded values} {
        set res {}
        r del foo
        r append foo 1
        r append foo 2
        lappend res [r get foo]
        r set foo 1
        r append foo 2
        lappend res [r get foo]
    } {12 12}

    test {APPEND fuzzing} {
        set err {}
        foreach type {binary alpha compr} {
            set buf {}
            r del x
            for {set i 0} {$i < 1000} {incr i} {
                set bin [randstring 0 10 $type]
                append buf $bin
                r append x $bin
            }
            if {$buf != [r get x]} {
                set err "Expected '$buf' found '[r get x]'"
                break
            }
        }
        set _ $err
    } {}

    # Leave the user with a clean DB before to exit
    test {FLUSHDB} {
        set aux {}
        r select 9
        r flushdb
        lappend aux [r dbsize]
        r select 10
        r flushdb
        lappend aux [r dbsize]
    } {0 0}

    test {Perform a final SAVE to leave a clean DB on disk} {
        waitForBgsave r
        r save
    } {OK}
}
