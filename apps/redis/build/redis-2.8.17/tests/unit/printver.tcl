start_server {} {
    set i [r info]
    regexp {redis_version:(.*?)\r\n} $i - version
    regexp {redis_git_sha1:(.*?)\r\n} $i - sha1
    puts "Testing Redis version $version ($sha1)"
}
