start_server {tags {"protocol"}} {
    test "Handle an empty query" {
        reconnect
        r write "\r\n"
        r flush
        assert_equal "PONG" [r ping]
    }

    test "Negative multibulk length" {
        reconnect
        r write "*-10\r\n"
        r flush
        assert_equal PONG [r ping]
    }

    test "Out of range multibulk length" {
        reconnect
        r write "*20000000\r\n"
        r flush
        assert_error "*invalid multibulk length*" {r read}
    }

    test "Wrong multibulk payload header" {
        reconnect
        r write "*3\r\n\$3\r\nSET\r\n\$1\r\nx\r\nfooz\r\n"
        r flush
        assert_error "*expected '$', got 'f'*" {r read}
    }

    test "Negative multibulk payload length" {
        reconnect
        r write "*3\r\n\$3\r\nSET\r\n\$1\r\nx\r\n\$-10\r\n"
        r flush
        assert_error "*invalid bulk length*" {r read}
    }

    test "Out of range multibulk payload length" {
        reconnect
        r write "*3\r\n\$3\r\nSET\r\n\$1\r\nx\r\n\$2000000000\r\n"
        r flush
        assert_error "*invalid bulk length*" {r read}
    }

    test "Non-number multibulk payload length" {
        reconnect
        r write "*3\r\n\$3\r\nSET\r\n\$1\r\nx\r\n\$blabla\r\n"
        r flush
        assert_error "*invalid bulk length*" {r read}
    }

    test "Multi bulk request not followed by bulk arguments" {
        reconnect
        r write "*1\r\nfoo\r\n"
        r flush
        assert_error "*expected '$', got 'f'*" {r read}
    }

    test "Generic wrong number of args" {
        reconnect
        assert_error "*wrong*arguments*ping*" {r ping x y z}
    }

    test "Unbalanced number of quotes" {
        reconnect
        r write "set \"\"\"test-key\"\"\" test-value\r\n"
        r write "ping\r\n"
        r flush
        assert_error "*unbalanced*" {r read}
    }

    set c 0
    foreach seq [list "\x00" "*\x00" "$\x00"] {
        incr c
        test "Protocol desync regression test #$c" {
            set s [socket [srv 0 host] [srv 0 port]]
            puts -nonewline $s $seq
            set payload [string repeat A 1024]"\n"
            set test_start [clock seconds]
            set test_time_limit 30
            while 1 {
                if {[catch {
                    puts -nonewline $s payload
                    flush $s
                    incr payload_size [string length $payload]
                }]} {
                    set retval [gets $s]
                    close $s
                    break
                } else {
                    set elapsed [expr {[clock seconds]-$test_start}]
                    if {$elapsed > $test_time_limit} {
                        close $s
                        error "assertion:Redis did not closed connection after protocol desync"
                    }
                }
            }
            set retval
        } {*Protocol error*}
    }
    unset c
}

start_server {tags {"regression"}} {
    test "Regression for a crash with blocking ops and pipelining" {
        set rd [redis_deferring_client]
        set fd [r channel]
        set proto "*3\r\n\$5\r\nBLPOP\r\n\$6\r\nnolist\r\n\$1\r\n0\r\n"
        puts -nonewline $fd $proto$proto
        flush $fd
        set res {}

        $rd rpush nolist a
        $rd read
        $rd rpush nolist a
        $rd read
    }
}
