start_server {tags {"pubsub"}} {
    proc __consume_subscribe_messages {client type channels} {
        set numsub -1
        set counts {}

        for {set i [llength $channels]} {$i > 0} {incr i -1} {
            set msg [$client read]
            assert_equal $type [lindex $msg 0]

            # when receiving subscribe messages the channels names
            # are ordered. when receiving unsubscribe messages
            # they are unordered
            set idx [lsearch -exact $channels [lindex $msg 1]]
            if {[string match "*unsubscribe" $type]} {
                assert {$idx >= 0}
            } else {
                assert {$idx == 0}
            }
            set channels [lreplace $channels $idx $idx]

            # aggregate the subscription count to return to the caller
            lappend counts [lindex $msg 2]
        }

        # we should have received messages for channels
        assert {[llength $channels] == 0}
        return $counts
    }

    proc subscribe {client channels} {
        $client subscribe {*}$channels
        __consume_subscribe_messages $client subscribe $channels
    }

    proc unsubscribe {client {channels {}}} {
        $client unsubscribe {*}$channels
        __consume_subscribe_messages $client unsubscribe $channels
    }

    proc psubscribe {client channels} {
        $client psubscribe {*}$channels
        __consume_subscribe_messages $client psubscribe $channels
    }

    proc punsubscribe {client {channels {}}} {
        $client punsubscribe {*}$channels
        __consume_subscribe_messages $client punsubscribe $channels
    }

    test "Pub/Sub PING" {
        set rd1 [redis_deferring_client]
        subscribe $rd1 somechannel
        # While subscribed to non-zero channels PING works in Pub/Sub mode.
        $rd1 ping
        $rd1 ping "foo"
        set reply1 [$rd1 read]
        set reply2 [$rd1 read]
        unsubscribe $rd1 somechannel
        # Now we are unsubscribed, PING should just return PONG.
        $rd1 ping
        set reply3 [$rd1 read]
        $rd1 close
        list $reply1 $reply2 $reply3
    } {{pong {}} {pong foo} PONG}

    test "PUBLISH/SUBSCRIBE basics" {
        set rd1 [redis_deferring_client]

        # subscribe to two channels
        assert_equal {1 2} [subscribe $rd1 {chan1 chan2}]
        assert_equal 1 [r publish chan1 hello]
        assert_equal 1 [r publish chan2 world]
        assert_equal {message chan1 hello} [$rd1 read]
        assert_equal {message chan2 world} [$rd1 read]

        # unsubscribe from one of the channels
        unsubscribe $rd1 {chan1}
        assert_equal 0 [r publish chan1 hello]
        assert_equal 1 [r publish chan2 world]
        assert_equal {message chan2 world} [$rd1 read]

        # unsubscribe from the remaining channel
        unsubscribe $rd1 {chan2}
        assert_equal 0 [r publish chan1 hello]
        assert_equal 0 [r publish chan2 world]

        # clean up clients
        $rd1 close
    }

    test "PUBLISH/SUBSCRIBE with two clients" {
        set rd1 [redis_deferring_client]
        set rd2 [redis_deferring_client]

        assert_equal {1} [subscribe $rd1 {chan1}]
        assert_equal {1} [subscribe $rd2 {chan1}]
        assert_equal 2 [r publish chan1 hello]
        assert_equal {message chan1 hello} [$rd1 read]
        assert_equal {message chan1 hello} [$rd2 read]

        # clean up clients
        $rd1 close
        $rd2 close
    }

    test "PUBLISH/SUBSCRIBE after UNSUBSCRIBE without arguments" {
        set rd1 [redis_deferring_client]
        assert_equal {1 2 3} [subscribe $rd1 {chan1 chan2 chan3}]
        unsubscribe $rd1
        assert_equal 0 [r publish chan1 hello]
        assert_equal 0 [r publish chan2 hello]
        assert_equal 0 [r publish chan3 hello]

        # clean up clients
        $rd1 close
    }

    test "SUBSCRIBE to one channel more than once" {
        set rd1 [redis_deferring_client]
        assert_equal {1 1 1} [subscribe $rd1 {chan1 chan1 chan1}]
        assert_equal 1 [r publish chan1 hello]
        assert_equal {message chan1 hello} [$rd1 read]

        # clean up clients
        $rd1 close
    }

    test "UNSUBSCRIBE from non-subscribed channels" {
        set rd1 [redis_deferring_client]
        assert_equal {0 0 0} [unsubscribe $rd1 {foo bar quux}]

        # clean up clients
        $rd1 close
    }

    test "PUBLISH/PSUBSCRIBE basics" {
        set rd1 [redis_deferring_client]

        # subscribe to two patterns
        assert_equal {1 2} [psubscribe $rd1 {foo.* bar.*}]
        assert_equal 1 [r publish foo.1 hello]
        assert_equal 1 [r publish bar.1 hello]
        assert_equal 0 [r publish foo1 hello]
        assert_equal 0 [r publish barfoo.1 hello]
        assert_equal 0 [r publish qux.1 hello]
        assert_equal {pmessage foo.* foo.1 hello} [$rd1 read]
        assert_equal {pmessage bar.* bar.1 hello} [$rd1 read]

        # unsubscribe from one of the patterns
        assert_equal {1} [punsubscribe $rd1 {foo.*}]
        assert_equal 0 [r publish foo.1 hello]
        assert_equal 1 [r publish bar.1 hello]
        assert_equal {pmessage bar.* bar.1 hello} [$rd1 read]

        # unsubscribe from the remaining pattern
        assert_equal {0} [punsubscribe $rd1 {bar.*}]
        assert_equal 0 [r publish foo.1 hello]
        assert_equal 0 [r publish bar.1 hello]

        # clean up clients
        $rd1 close
    }

    test "PUBLISH/PSUBSCRIBE with two clients" {
        set rd1 [redis_deferring_client]
        set rd2 [redis_deferring_client]

        assert_equal {1} [psubscribe $rd1 {chan.*}]
        assert_equal {1} [psubscribe $rd2 {chan.*}]
        assert_equal 2 [r publish chan.foo hello]
        assert_equal {pmessage chan.* chan.foo hello} [$rd1 read]
        assert_equal {pmessage chan.* chan.foo hello} [$rd2 read]

        # clean up clients
        $rd1 close
        $rd2 close
    }

    test "PUBLISH/PSUBSCRIBE after PUNSUBSCRIBE without arguments" {
        set rd1 [redis_deferring_client]
        assert_equal {1 2 3} [psubscribe $rd1 {chan1.* chan2.* chan3.*}]
        punsubscribe $rd1
        assert_equal 0 [r publish chan1.hi hello]
        assert_equal 0 [r publish chan2.hi hello]
        assert_equal 0 [r publish chan3.hi hello]

        # clean up clients
        $rd1 close
    }

    test "PUNSUBSCRIBE from non-subscribed channels" {
        set rd1 [redis_deferring_client]
        assert_equal {0 0 0} [punsubscribe $rd1 {foo.* bar.* quux.*}]

        # clean up clients
        $rd1 close
    }

    test "NUMSUB returns numbers, not strings (#1561)" {
        r pubsub numsub abc def
    } {abc 0 def 0}

    test "Mix SUBSCRIBE and PSUBSCRIBE" {
        set rd1 [redis_deferring_client]
        assert_equal {1} [subscribe $rd1 {foo.bar}]
        assert_equal {2} [psubscribe $rd1 {foo.*}]

        assert_equal 2 [r publish foo.bar hello]
        assert_equal {message foo.bar hello} [$rd1 read]
        assert_equal {pmessage foo.* foo.bar hello} [$rd1 read]

        # clean up clients
        $rd1 close
    }

    test "PUNSUBSCRIBE and UNSUBSCRIBE should always reply" {
        # Make sure we are not subscribed to any channel at all.
        r punsubscribe
        r unsubscribe
        # Now check if the commands still reply correctly.
        set reply1 [r punsubscribe]
        set reply2 [r unsubscribe]
        concat $reply1 $reply2
    } {punsubscribe {} 0 unsubscribe {} 0}

    ### Keyspace events notification tests

    test "Keyspace notifications: we receive keyspace notifications" {
        r config set notify-keyspace-events KA
        set rd1 [redis_deferring_client]
        assert_equal {1} [psubscribe $rd1 *]
        r set foo bar
        assert_equal {pmessage * __keyspace@9__:foo set} [$rd1 read]
        $rd1 close
    }

    test "Keyspace notifications: we receive keyevent notifications" {
        r config set notify-keyspace-events EA
        set rd1 [redis_deferring_client]
        assert_equal {1} [psubscribe $rd1 *]
        r set foo bar
        assert_equal {pmessage * __keyevent@9__:set foo} [$rd1 read]
        $rd1 close
    }

    test "Keyspace notifications: we can receive both kind of events" {
        r config set notify-keyspace-events KEA
        set rd1 [redis_deferring_client]
        assert_equal {1} [psubscribe $rd1 *]
        r set foo bar
        assert_equal {pmessage * __keyspace@9__:foo set} [$rd1 read]
        assert_equal {pmessage * __keyevent@9__:set foo} [$rd1 read]
        $rd1 close
    }

    test "Keyspace notifications: we are able to mask events" {
        r config set notify-keyspace-events KEl
        r del mylist
        set rd1 [redis_deferring_client]
        assert_equal {1} [psubscribe $rd1 *]
        r set foo bar
        r lpush mylist a
        # No notification for set, because only list commands are enabled.
        assert_equal {pmessage * __keyspace@9__:mylist lpush} [$rd1 read]
        assert_equal {pmessage * __keyevent@9__:lpush mylist} [$rd1 read]
        $rd1 close
    }

    test "Keyspace notifications: general events test" {
        r config set notify-keyspace-events KEg
        set rd1 [redis_deferring_client]
        assert_equal {1} [psubscribe $rd1 *]
        r set foo bar
        r expire foo 1
        r del foo
        assert_equal {pmessage * __keyspace@9__:foo expire} [$rd1 read]
        assert_equal {pmessage * __keyevent@9__:expire foo} [$rd1 read]
        assert_equal {pmessage * __keyspace@9__:foo del} [$rd1 read]
        assert_equal {pmessage * __keyevent@9__:del foo} [$rd1 read]
        $rd1 close
    }

    test "Keyspace notifications: list events test" {
        r config set notify-keyspace-events KEl
        r del mylist
        set rd1 [redis_deferring_client]
        assert_equal {1} [psubscribe $rd1 *]
        r lpush mylist a
        r rpush mylist a
        r rpop mylist
        assert_equal {pmessage * __keyspace@9__:mylist lpush} [$rd1 read]
        assert_equal {pmessage * __keyevent@9__:lpush mylist} [$rd1 read]
        assert_equal {pmessage * __keyspace@9__:mylist rpush} [$rd1 read]
        assert_equal {pmessage * __keyevent@9__:rpush mylist} [$rd1 read]
        assert_equal {pmessage * __keyspace@9__:mylist rpop} [$rd1 read]
        assert_equal {pmessage * __keyevent@9__:rpop mylist} [$rd1 read]
        $rd1 close
    }

    test "Keyspace notifications: set events test" {
        r config set notify-keyspace-events Ks
        r del myset
        set rd1 [redis_deferring_client]
        assert_equal {1} [psubscribe $rd1 *]
        r sadd myset a b c d
        r srem myset x
        r sadd myset x y z
        r srem myset x
        assert_equal {pmessage * __keyspace@9__:myset sadd} [$rd1 read]
        assert_equal {pmessage * __keyspace@9__:myset sadd} [$rd1 read]
        assert_equal {pmessage * __keyspace@9__:myset srem} [$rd1 read]
        $rd1 close
    }

    test "Keyspace notifications: zset events test" {
        r config set notify-keyspace-events Kz
        r del myzset
        set rd1 [redis_deferring_client]
        assert_equal {1} [psubscribe $rd1 *]
        r zadd myzset 1 a 2 b
        r zrem myzset x
        r zadd myzset 3 x 4 y 5 z
        r zrem myzset x
        assert_equal {pmessage * __keyspace@9__:myzset zadd} [$rd1 read]
        assert_equal {pmessage * __keyspace@9__:myzset zadd} [$rd1 read]
        assert_equal {pmessage * __keyspace@9__:myzset zrem} [$rd1 read]
        $rd1 close
    }

    test "Keyspace notifications: hash events test" {
        r config set notify-keyspace-events Kh
        r del myhash
        set rd1 [redis_deferring_client]
        assert_equal {1} [psubscribe $rd1 *]
        r hmset myhash yes 1 no 0
        r hincrby myhash yes 10
        assert_equal {pmessage * __keyspace@9__:myhash hset} [$rd1 read]
        assert_equal {pmessage * __keyspace@9__:myhash hincrby} [$rd1 read]
        $rd1 close
    }

    test "Keyspace notifications: expired events (triggered expire)" {
        r config set notify-keyspace-events Ex
        r del foo
        set rd1 [redis_deferring_client]
        assert_equal {1} [psubscribe $rd1 *]
        r psetex foo 100 1
        wait_for_condition 50 100 {
            [r exists foo] == 0
        } else {
            fail "Key does not expire?!"
        }
        assert_equal {pmessage * __keyevent@9__:expired foo} [$rd1 read]
        $rd1 close
    }

    test "Keyspace notifications: expired events (background expire)" {
        r config set notify-keyspace-events Ex
        r del foo
        set rd1 [redis_deferring_client]
        assert_equal {1} [psubscribe $rd1 *]
        r psetex foo 100 1
        assert_equal {pmessage * __keyevent@9__:expired foo} [$rd1 read]
        $rd1 close
    }

    test "Keyspace notifications: evicted events" {
        r config set notify-keyspace-events Ee
        r config set maxmemory-policy allkeys-lru
        r flushdb
        set rd1 [redis_deferring_client]
        assert_equal {1} [psubscribe $rd1 *]
        r set foo bar
        r config set maxmemory 1
        assert_equal {pmessage * __keyevent@9__:evicted foo} [$rd1 read]
        r config set maxmemory 0
        $rd1 close
    }

    test "Keyspace notifications: test CONFIG GET/SET of event flags" {
        r config set notify-keyspace-events gKE
        assert_equal {gKE} [lindex [r config get notify-keyspace-events] 1]
        r config set notify-keyspace-events {$lshzxeKE}
        assert_equal {$lshzxeKE} [lindex [r config get notify-keyspace-events] 1]
        r config set notify-keyspace-events KA
        assert_equal {AK} [lindex [r config get notify-keyspace-events] 1]
        r config set notify-keyspace-events EA
        assert_equal {AE} [lindex [r config get notify-keyspace-events] 1]
    }
}
