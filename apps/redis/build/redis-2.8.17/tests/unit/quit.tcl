start_server {tags {"quit"}} {
    proc format_command {args} {
        set cmd "*[llength $args]\r\n"
        foreach a $args {
            append cmd "$[string length $a]\r\n$a\r\n"
        }
        set _ $cmd
    }

    test "QUIT returns OK" {
        reconnect
        assert_equal OK [r quit]
        assert_error * {r ping}
    }

    test "Pipelined commands after QUIT must not be executed" {
        reconnect
        r write [format_command quit]
        r write [format_command set foo bar]
        r flush
        assert_equal OK [r read]
        assert_error * {r read}

        reconnect
        assert_equal {} [r get foo]
    }

    test "Pipelined commands after QUIT that exceed read buffer size" {
        reconnect
        r write [format_command quit]
        r write [format_command set foo [string repeat "x" 1024]]
        r flush
        assert_equal OK [r read]
        assert_error * {r read}

        reconnect
        assert_equal {} [r get foo]

    }
}
