start_server {tags {"scan"}} {
    test "SCAN basic" {
        r flushdb
        r debug populate 1000

        set cur 0
        set keys {}
        while 1 {
            set res [r scan $cur]
            set cur [lindex $res 0]
            set k [lindex $res 1]
            lappend keys {*}$k
            if {$cur == 0} break
        }

        set keys [lsort -unique $keys]
        assert_equal 1000 [llength $keys]
    }

    test "SCAN COUNT" {
        r flushdb
        r debug populate 1000

        set cur 0
        set keys {}
        while 1 {
            set res [r scan $cur count 5]
            set cur [lindex $res 0]
            set k [lindex $res 1]
            lappend keys {*}$k
            if {$cur == 0} break
        }

        set keys [lsort -unique $keys]
        assert_equal 1000 [llength $keys]
    }

    test "SCAN MATCH" {
        r flushdb
        r debug populate 1000

        set cur 0
        set keys {}
        while 1 {
            set res [r scan $cur match "key:1??"]
            set cur [lindex $res 0]
            set k [lindex $res 1]
            lappend keys {*}$k
            if {$cur == 0} break
        }

        set keys [lsort -unique $keys]
        assert_equal 100 [llength $keys]
    }

    foreach enc {intset hashtable} {
        test "SSCAN with encoding $enc" {
            # Create the Set
            r del set
            if {$enc eq {intset}} {
                set prefix ""
            } else {
                set prefix "ele:"
            }
            set elements {}
            for {set j 0} {$j < 100} {incr j} {
                lappend elements ${prefix}${j}
            }
            r sadd set {*}$elements

            # Verify that the encoding matches.
            assert {[r object encoding set] eq $enc}

            # Test SSCAN
            set cur 0
            set keys {}
            while 1 {
                set res [r sscan set $cur]
                set cur [lindex $res 0]
                set k [lindex $res 1]
                lappend keys {*}$k
                if {$cur == 0} break
            }

            set keys [lsort -unique $keys]
            assert_equal 100 [llength $keys]
        }
    }

    foreach enc {ziplist hashtable} {
        test "HSCAN with encoding $enc" {
            # Create the Hash
            r del hash
            if {$enc eq {ziplist}} {
                set count 30
            } else {
                set count 1000
            }
            set elements {}
            for {set j 0} {$j < $count} {incr j} {
                lappend elements key:$j $j
            }
            r hmset hash {*}$elements

            # Verify that the encoding matches.
            assert {[r object encoding hash] eq $enc}

            # Test HSCAN
            set cur 0
            set keys {}
            while 1 {
                set res [r hscan hash $cur]
                set cur [lindex $res 0]
                set k [lindex $res 1]
                lappend keys {*}$k
                if {$cur == 0} break
            }

            set keys2 {}
            foreach {k v} $keys {
                assert {$k eq "key:$v"}
                lappend keys2 $k
            }

            set keys2 [lsort -unique $keys2]
            assert_equal $count [llength $keys2]
        }
    }

    foreach enc {ziplist skiplist} {
        test "ZSCAN with encoding $enc" {
            # Create the Sorted Set
            r del zset
            if {$enc eq {ziplist}} {
                set count 30
            } else {
                set count 1000
            }
            set elements {}
            for {set j 0} {$j < $count} {incr j} {
                lappend elements $j key:$j
            }
            r zadd zset {*}$elements

            # Verify that the encoding matches.
            assert {[r object encoding zset] eq $enc}

            # Test ZSCAN
            set cur 0
            set keys {}
            while 1 {
                set res [r zscan zset $cur]
                set cur [lindex $res 0]
                set k [lindex $res 1]
                lappend keys {*}$k
                if {$cur == 0} break
            }

            set keys2 {}
            foreach {k v} $keys {
                assert {$k eq "key:$v"}
                lappend keys2 $k
            }

            set keys2 [lsort -unique $keys2]
            assert_equal $count [llength $keys2]
        }
    }

    test "SCAN guarantees check under write load" {
        r flushdb
        r debug populate 100

        # We start scanning here, so keys from 0 to 99 should all be
        # reported at the end of the iteration.
        set keys {}
        while 1 {
            set res [r scan $cur]
            set cur [lindex $res 0]
            set k [lindex $res 1]
            lappend keys {*}$k
            if {$cur == 0} break
            # Write 10 random keys at every SCAN iteration.
            for {set j 0} {$j < 10} {incr j} {
                r set addedkey:[randomInt 1000] foo
            }
        }

        set keys2 {}
        foreach k $keys {
            if {[string length $k] > 6} continue
            lappend keys2 $k
        }

        set keys2 [lsort -unique $keys2]
        assert_equal 100 [llength $keys2]
    }

    test "SSCAN with integer encoded object (issue #1345)" {
        set objects {1 a}
        r del set
        r sadd set {*}$objects
        set res [r sscan set 0 MATCH *a* COUNT 100]
        assert_equal [lsort -unique [lindex $res 1]] {a}
        set res [r sscan set 0 MATCH *1* COUNT 100]
        assert_equal [lsort -unique [lindex $res 1]] {1}
    }

    test "SSCAN with PATTERN" {
        r del mykey
        r sadd mykey foo fab fiz foobar 1 2 3 4
        set res [r sscan mykey 0 MATCH foo* COUNT 10000]
        lsort -unique [lindex $res 1]
    } {foo foobar}

    test "HSCAN with PATTERN" {
        r del mykey
        r hmset mykey foo 1 fab 2 fiz 3 foobar 10 1 a 2 b 3 c 4 d
        set res [r hscan mykey 0 MATCH foo* COUNT 10000]
        lsort -unique [lindex $res 1]
    } {1 10 foo foobar}

    test "ZSCAN with PATTERN" {
        r del mykey
        r zadd mykey 1 foo 2 fab 3 fiz 10 foobar
        set res [r zscan mykey 0 MATCH foo* COUNT 10000]
        lsort -unique [lindex $res 1]
    }
}
