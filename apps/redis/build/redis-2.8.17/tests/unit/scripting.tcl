start_server {tags {"scripting"}} {
    test {EVAL - Does Lua interpreter replies to our requests?} {
        r eval {return 'hello'} 0
    } {hello}

    test {EVAL - Lua integer -> Redis protocol type conversion} {
        r eval {return 100.5} 0
    } {100}

    test {EVAL - Lua string -> Redis protocol type conversion} {
        r eval {return 'hello world'} 0
    } {hello world}

    test {EVAL - Lua true boolean -> Redis protocol type conversion} {
        r eval {return true} 0
    } {1}

    test {EVAL - Lua false boolean -> Redis protocol type conversion} {
        r eval {return false} 0
    } {}

    test {EVAL - Lua status code reply -> Redis protocol type conversion} {
        r eval {return {ok='fine'}} 0
    } {fine}

    test {EVAL - Lua error reply -> Redis protocol type conversion} {
        catch {
            r eval {return {err='this is an error'}} 0
        } e
        set _ $e
    } {this is an error}

    test {EVAL - Lua table -> Redis protocol type conversion} {
        r eval {return {1,2,3,'ciao',{1,2}}} 0
    } {1 2 3 ciao {1 2}}

    test {EVAL - Are the KEYS and ARGV arrays populated correctly?} {
        r eval {return {KEYS[1],KEYS[2],ARGV[1],ARGV[2]}} 2 a b c d
    } {a b c d}

    test {EVAL - is Lua able to call Redis API?} {
        r set mykey myval
        r eval {return redis.call('get',KEYS[1])} 1 mykey
    } {myval}

    test {EVALSHA - Can we call a SHA1 if already defined?} {
        r evalsha fd758d1589d044dd850a6f05d52f2eefd27f033f 1 mykey
    } {myval}

    test {EVALSHA - Can we call a SHA1 in uppercase?} {
        r evalsha FD758D1589D044DD850A6F05D52F2EEFD27F033F 1 mykey
    } {myval}

    test {EVALSHA - Do we get an error on invalid SHA1?} {
        catch {r evalsha NotValidShaSUM 0} e
        set _ $e
    } {NOSCRIPT*}

    test {EVALSHA - Do we get an error on non defined SHA1?} {
        catch {r evalsha ffd632c7d33e571e9f24556ebed26c3479a87130 0} e
        set _ $e
    } {NOSCRIPT*}

    test {EVAL - Redis integer -> Lua type conversion} {
        r eval {
            local foo = redis.pcall('incr','x')
            return {type(foo),foo}
        } 0
    } {number 1}

    test {EVAL - Redis bulk -> Lua type conversion} {
        r set mykey myval
        r eval {
            local foo = redis.pcall('get','mykey')
            return {type(foo),foo}
        } 0
    } {string myval}

    test {EVAL - Redis multi bulk -> Lua type conversion} {
        r del mylist
        r rpush mylist a
        r rpush mylist b
        r rpush mylist c
        r eval {
            local foo = redis.pcall('lrange','mylist',0,-1)
            return {type(foo),foo[1],foo[2],foo[3],# foo}
        } 0
    } {table a b c 3}

    test {EVAL - Redis status reply -> Lua type conversion} {
        r eval {
            local foo = redis.pcall('set','mykey','myval')
            return {type(foo),foo['ok']}
        } 0
    } {table OK}

    test {EVAL - Redis error reply -> Lua type conversion} {
        r set mykey myval
        r eval {
            local foo = redis.pcall('incr','mykey')
            return {type(foo),foo['err']}
        } 0
    } {table {ERR value is not an integer or out of range}}

    test {EVAL - Redis nil bulk reply -> Lua type conversion} {
        r del mykey
        r eval {
            local foo = redis.pcall('get','mykey')
            return {type(foo),foo == false}
        } 0
    } {boolean 1}

    test {EVAL - Is the Lua client using the currently selected DB?} {
        r set mykey "this is DB 9"
        r select 10
        r set mykey "this is DB 10"
        r eval {return redis.pcall('get','mykey')} 0
    } {this is DB 10}

    test {EVAL - SELECT inside Lua should not affect the caller} {
        # here we DB 10 is selected
        r set mykey "original value"
        r eval {return redis.pcall('select','9')} 0
        set res [r get mykey]
        r select 9
        set res
    } {original value}

    if 0 {
        test {EVAL - Script can't run more than configured time limit} {
            r config set lua-time-limit 1
            catch {
                r eval {
                    local i = 0
                    while true do i=i+1 end
                } 0
            } e
            set _ $e
        } {*execution time*}
    }

    test {EVAL - Scripts can't run certain commands} {
        set e {}
        catch {r eval {return redis.pcall('spop','x')} 0} e
        set e
    } {*not allowed*}

    test {EVAL - Scripts can't run certain commands} {
        set e {}
        catch {
            r eval "redis.pcall('randomkey'); return redis.pcall('set','x','ciao')" 0
        } e
        set e
    } {*not allowed after*}

    test {EVAL - No arguments to redis.call/pcall is considered an error} {
        set e {}
        catch {r eval {return redis.call()} 0} e
        set e
    } {*one argument*}

    test {EVAL - redis.call variant raises a Lua error on Redis cmd error (1)} {
        set e {}
        catch {
            r eval "redis.call('nosuchcommand')" 0
        } e
        set e
    } {*Unknown Redis*}

    test {EVAL - redis.call variant raises a Lua error on Redis cmd error (1)} {
        set e {}
        catch {
            r eval "redis.call('get','a','b','c')" 0
        } e
        set e
    } {*number of args*}

    test {EVAL - redis.call variant raises a Lua error on Redis cmd error (1)} {
        set e {}
        r set foo bar
        catch {
            r eval {redis.call('lpush',KEYS[1],'val')} 1 foo
        } e
        set e
    } {*against a key*}

    test {SCRIPTING FLUSH - is able to clear the scripts cache?} {
        r set mykey myval
        set v [r evalsha fd758d1589d044dd850a6f05d52f2eefd27f033f 1 mykey]
        assert_equal $v myval
        set e ""
        r script flush
        catch {r evalsha fd758d1589d044dd850a6f05d52f2eefd27f033f 1 mykey} e
        set e
    } {NOSCRIPT*}

    test {SCRIPT EXISTS - can detect already defined scripts?} {
        r eval "return 1+1" 0
        r script exists a27e7e8a43702b7046d4f6a7ccf5b60cef6b9bd9 a27e7e8a43702b7046d4f6a7ccf5b60cef6b9bda
    } {1 0}

    test {SCRIPT LOAD - is able to register scripts in the scripting cache} {
        list \
            [r script load "return 'loaded'"] \
            [r evalsha b534286061d4b9e4026607613b95c06c06015ae8 0]
    } {b534286061d4b9e4026607613b95c06c06015ae8 loaded}

    test "In the context of Lua the output of random commands gets ordered" {
        r del myset
        r sadd myset a b c d e f g h i l m n o p q r s t u v z aa aaa azz
        r eval {return redis.call('smembers',KEYS[1])} 1 myset
    } {a aa aaa azz b c d e f g h i l m n o p q r s t u v z}

    test "SORT is normally not alpha re-ordered for the scripting engine" {
        r del myset
        r sadd myset 1 2 3 4 10
        r eval {return redis.call('sort',KEYS[1],'desc')} 1 myset
    } {10 4 3 2 1}

    test "SORT BY <constant> output gets ordered for scripting" {
        r del myset
        r sadd myset a b c d e f g h i l m n o p q r s t u v z aa aaa azz
        r eval {return redis.call('sort',KEYS[1],'by','_')} 1 myset
    } {a aa aaa azz b c d e f g h i l m n o p q r s t u v z}

    test "SORT BY <constant> with GET gets ordered for scripting" {
        r del myset
        r sadd myset a b c
        r eval {return redis.call('sort',KEYS[1],'by','_','get','#','get','_:*')} 1 myset
    } {a {} b {} c {}}

    test "redis.sha1hex() implementation" {
        list [r eval {return redis.sha1hex('')} 0] \
             [r eval {return redis.sha1hex('Pizza & Mandolino')} 0]
    } {da39a3ee5e6b4b0d3255bfef95601890afd80709 74822d82031af7493c20eefa13bd07ec4fada82f}

    test {Globals protection reading an undeclared global variable} {
        catch {r eval {return a} 0} e
        set e
    } {*ERR*attempted to access unexisting global*}

    test {Globals protection setting an undeclared global*} {
        catch {r eval {a=10} 0} e
        set e
    } {*ERR*attempted to create global*}

    test {Test an example script DECR_IF_GT} {
        set decr_if_gt {
            local current

            current = redis.call('get',KEYS[1])
            if not current then return nil end
            if current > ARGV[1] then
                return redis.call('decr',KEYS[1])
            else
                return redis.call('get',KEYS[1])
            end
        }
        r set foo 5
        set res {}
        lappend res [r eval $decr_if_gt 1 foo 2]
        lappend res [r eval $decr_if_gt 1 foo 2]
        lappend res [r eval $decr_if_gt 1 foo 2]
        lappend res [r eval $decr_if_gt 1 foo 2]
        lappend res [r eval $decr_if_gt 1 foo 2]
        set res
    } {4 3 2 2 2}

    test {Scripting engine resets PRNG at every script execution} {
        set rand1 [r eval {return tostring(math.random())} 0]
        set rand2 [r eval {return tostring(math.random())} 0]
        assert_equal $rand1 $rand2
    }

    test {Scripting engine PRNG can be seeded correctly} {
        set rand1 [r eval {
            math.randomseed(ARGV[1]); return tostring(math.random())
        } 0 10]
        set rand2 [r eval {
            math.randomseed(ARGV[1]); return tostring(math.random())
        } 0 10]
        set rand3 [r eval {
            math.randomseed(ARGV[1]); return tostring(math.random())
        } 0 20]
        assert_equal $rand1 $rand2
        assert {$rand2 ne $rand3}
    }

    test {EVAL does not leak in the Lua stack} {
        r set x 0
        # Use a non blocking client to speedup the loop.
        set rd [redis_deferring_client]
        for {set j 0} {$j < 10000} {incr j} {
            $rd eval {return redis.call("incr",KEYS[1])} 1 x
        }
        for {set j 0} {$j < 10000} {incr j} {
            $rd read
        }
        assert {[s used_memory_lua] < 1024*100}
        $rd close
        r get x
    } {10000}

    test {EVAL processes writes from AOF in read-only slaves} {
        r flushall
        r config set appendonly yes
        r eval {redis.call("set",KEYS[1],"100")} 1 foo
        r eval {redis.call("incr",KEYS[1])} 1 foo
        r eval {redis.call("incr",KEYS[1])} 1 foo
        wait_for_condition 50 100 {
            [s aof_rewrite_in_progress] == 0
        } else {
            fail "AOF rewrite can't complete after CONFIG SET appendonly yes."
        }
        r config set slave-read-only yes
        r slaveof 127.0.0.1 0
        r debug loadaof
        set res [r get foo]
        r slaveof no one
        set res
    } {102}

    test {We can call scripts rewriting client->argv from Lua} {
        r del myset
        r sadd myset a b c
        r mset a 1 b 2 c 3 d 4
        assert {[r spop myset] ne {}}
        assert {[r spop myset] ne {}}
        assert {[r spop myset] ne {}}
        assert {[r mget a b c d] eq {1 2 3 4}}
        assert {[r spop myset] eq {}}
    }

    test {Call Redis command with many args from Lua (issue #1764)} {
        r eval {
            local i
            local x={}
            redis.call('del','mylist')
            for i=1,100 do
                table.insert(x,i)
            end
            redis.call('rpush','mylist',unpack(x))
            return redis.call('lrange','mylist',0,-1)
        } 0
    } {1 2 3 4 5 6 7 8 9 10 11 12 13 14 15 16 17 18 19 20 21 22 23 24 25 26 27 28 29 30 31 32 33 34 35 36 37 38 39 40 41 42 43 44 45 46 47 48 49 50 51 52 53 54 55 56 57 58 59 60 61 62 63 64 65 66 67 68 69 70 71 72 73 74 75 76 77 78 79 80 81 82 83 84 85 86 87 88 89 90 91 92 93 94 95 96 97 98 99 100}

    test {Number conversion precision test (issue #1118)} {
        r eval {
              local value = 9007199254740991
              redis.call("set","foo",value)
              return redis.call("get","foo")
        } 0
    } {9007199254740991}

    test {String containing number precision test (regression of issue #1118)} {
        r eval {
            redis.call("set", "key", "12039611435714932082")
            return redis.call("get", "key")
        } 0
    } {12039611435714932082}

    test {Verify negative arg count is error instead of crash (issue #1842)} {
        catch { r eval { return "hello" } -12 } e
        set e
    } {ERR Number of keys can't be negative}

    test {Correct handling of reused argv (issue #1939)} {
        r eval {
              for i = 0, 10 do
                  redis.call('SET', 'a', '1')
                  redis.call('MGET', 'a', 'b', 'c')
                  redis.call('EXPIRE', 'a', 0)
                  redis.call('GET', 'a')
                  redis.call('MGET', 'a', 'b', 'c')
              end
        } 0
    }
}

# Start a new server since the last test in this stanza will kill the
# instance at all.
start_server {tags {"scripting"}} {
    test {Timedout read-only scripts can be killed by SCRIPT KILL} {
        set rd [redis_deferring_client]
        r config set lua-time-limit 10
        $rd eval {while true do end} 0
        after 200
        catch {r ping} e
        assert_match {BUSY*} $e
        r script kill
        after 200 ; # Give some time to Lua to call the hook again...
        assert_equal [r ping] "PONG"
    }

    test {Timedout script link is still usable after Lua returns} {
        r config set lua-time-limit 10
        r eval {for i=1,100000 do redis.call('ping') end return 'ok'} 0
        r ping
    } {PONG}

    test {Timedout scripts that modified data can't be killed by SCRIPT KILL} {
        set rd [redis_deferring_client]
        r config set lua-time-limit 10
        $rd eval {redis.call('set',KEYS[1],'y'); while true do end} 1 x
        after 200
        catch {r ping} e
        assert_match {BUSY*} $e
        catch {r script kill} e
        assert_match {UNKILLABLE*} $e
        catch {r ping} e
        assert_match {BUSY*} $e
    }

    # Note: keep this test at the end of this server stanza because it
    # kills the server.
    test {SHUTDOWN NOSAVE can kill a timedout script anyway} {
        # The server sould be still unresponding to normal commands.
        catch {r ping} e
        assert_match {BUSY*} $e
        catch {r shutdown nosave}
        # Make sure the server was killed
        catch {set rd [redis_deferring_client]} e
        assert_match {*connection refused*} $e
    }
}

start_server {tags {"scripting repl"}} {
    start_server {} {
        test {Before the slave connects we issue two EVAL commands} {
            # One with an error, but still executing a command.
            # SHA is: 67164fc43fa971f76fd1aaeeaf60c1c178d25876
            catch {
                r eval {redis.call('incr',KEYS[1]); redis.call('nonexisting')} 1 x
            }
            # One command is correct:
            # SHA is: 6f5ade10a69975e903c6d07b10ea44c6382381a5
            r eval {return redis.call('incr',KEYS[1])} 1 x
        } {2}

        test {Connect a slave to the main instance} {
            r -1 slaveof [srv 0 host] [srv 0 port]
            wait_for_condition 50 100 {
                [s -1 role] eq {slave} &&
                [string match {*master_link_status:up*} [r -1 info replication]]
            } else {
                fail "Can't turn the instance into a slave"
            }
        }

        test {Now use EVALSHA against the master, with both SHAs} {
            # The server should replicate successful and unsuccessful
            # commands as EVAL instead of EVALSHA.
            catch {
                r evalsha 67164fc43fa971f76fd1aaeeaf60c1c178d25876 1 x
            }
            r evalsha 6f5ade10a69975e903c6d07b10ea44c6382381a5 1 x
        } {4}

        test {If EVALSHA was replicated as EVAL, 'x' should be '4'} {
            wait_for_condition 50 100 {
                [r -1 get x] eq {4}
            } else {
                fail "Expected 4 in x, but value is '[r -1 get x]'"
            }
        }

        test {Replication of script multiple pushes to list with BLPOP} {
            set rd [redis_deferring_client]
            $rd brpop a 0
            r eval {
                redis.call("lpush",KEYS[1],"1");
                redis.call("lpush",KEYS[1],"2");
            } 1 a
            set res [$rd read]
            $rd close
            wait_for_condition 50 100 {
                [r -1 lrange a 0 -1] eq [r lrange a 0 -1]
            } else {
                fail "Expected list 'a' in slave and master to be the same, but they are respectively '[r -1 lrange a 0 -1]' and '[r lrange a 0 -1]'"
            }
            set res
        } {a 1}

        test {EVALSHA replication when first call is readonly} {
            r del x
            r eval {if tonumber(ARGV[1]) > 0 then redis.call('incr', KEYS[1]) end} 1 x 0
            r evalsha 6e0e2745aa546d0b50b801a20983b70710aef3ce 1 x 0
            r evalsha 6e0e2745aa546d0b50b801a20983b70710aef3ce 1 x 1
            wait_for_condition 50 100 {
                [r -1 get x] eq {1}
            } else {
                fail "Expected 1 in x, but value is '[r -1 get x]'"
            }
        }

        test {Lua scripts using SELECT are replicated correctly} {
            r eval {
                redis.call("set","foo1","bar1")
                redis.call("select","10")
                redis.call("incr","x")
                redis.call("select","11")
                redis.call("incr","z")
            } 0
            r eval {
                redis.call("set","foo1","bar1")
                redis.call("select","10")
                redis.call("incr","x")
                redis.call("select","11")
                redis.call("incr","z")
            } 0
            wait_for_condition 50 100 {
                [r -1 debug digest] eq [r debug digest]
            } else {
                fail "Master-Slave desync after Lua script using SELECT."
            }
        }
    }
}
