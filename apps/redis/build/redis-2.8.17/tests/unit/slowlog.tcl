start_server {tags {"slowlog"} overrides {slowlog-log-slower-than 1000000}} {
    test {SLOWLOG - check that it starts with an empty log} {
        r slowlog len
    } {0}

    test {SLOWLOG - only logs commands taking more time than specified} {
        r config set slowlog-log-slower-than 100000
        r ping
        assert_equal [r slowlog len] 0
        r debug sleep 0.2
        assert_equal [r slowlog len] 1
    }

    test {SLOWLOG - max entries is correctly handled} {
        r config set slowlog-log-slower-than 0
        r config set slowlog-max-len 10
        for {set i 0} {$i < 100} {incr i} {
            r ping
        }
        r slowlog len
    } {10}

    test {SLOWLOG - GET optional argument to limit output len works} {
        llength [r slowlog get 5]
    } {5}

    test {SLOWLOG - RESET subcommand works} {
        r config set slowlog-log-slower-than 100000
        r slowlog reset
        r slowlog len
    } {0}

    test {SLOWLOG - logged entry sanity check} {
        r debug sleep 0.2
        set e [lindex [r slowlog get] 0]
        assert_equal [llength $e] 4
        assert_equal [lindex $e 0] 105
        assert_equal [expr {[lindex $e 2] > 100000}] 1
        assert_equal [lindex $e 3] {debug sleep 0.2}
    }

    test {SLOWLOG - commands with too many arguments are trimmed} {
        r config set slowlog-log-slower-than 0
        r slowlog reset
        r sadd set 3 4 5 6 7 8 9 10 11 12 13 14 15 16 17 18 19 20 21 22 23 24 25 26 27 28 29 30 31 32 33
        set e [lindex [r slowlog get] 0]
        lindex $e 3
    } {sadd set 3 4 5 6 7 8 9 10 11 12 13 14 15 16 17 18 19 20 21 22 23 24 25 26 27 28 29 30 31 {... (2 more arguments)}}

    test {SLOWLOG - too long arguments are trimmed} {
        r config set slowlog-log-slower-than 0
        r slowlog reset
        set arg [string repeat A 129]
        r sadd set foo $arg
        set e [lindex [r slowlog get] 0]
        lindex $e 3
    } {sadd set foo {AAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAA... (1 more bytes)}}

    test {SLOWLOG - EXEC is not logged, just executed commands} {
        r config set slowlog-log-slower-than 100000
        r slowlog reset
        assert_equal [r slowlog len] 0
        r multi
        r debug sleep 0.2
        r exec
        assert_equal [r slowlog len] 1
        set e [lindex [r slowlog get] 0]
        assert_equal [lindex $e 3] {debug sleep 0.2}
    }
}
