start_server {
    tags {"sort"}
    overrides {
        "list-max-ziplist-value" 16
        "list-max-ziplist-entries" 32
        "set-max-intset-entries" 32
    }
} {
    proc create_random_dataset {num cmd} {
        set tosort {}
        set result {}
        array set seenrand {}
        r del tosort
        for {set i 0} {$i < $num} {incr i} {
            # Make sure all the weights are different because
            # Redis does not use a stable sort but Tcl does.
            while 1 {
                randpath {
                    set rint [expr int(rand()*1000000)]
                } {
                    set rint [expr rand()]
                }
                if {![info exists seenrand($rint)]} break
            }
            set seenrand($rint) x
            r $cmd tosort $i
            r set weight_$i $rint
            r hset wobj_$i weight $rint
            lappend tosort [list $i $rint]
        }
        set sorted [lsort -index 1 -real $tosort]
        for {set i 0} {$i < $num} {incr i} {
            lappend result [lindex $sorted $i 0]
        }
        set _ $result
    }

    foreach {num cmd enc title} {
        16 lpush ziplist "Ziplist"
        1000 lpush linkedlist "Linked list"
        10000 lpush linkedlist "Big Linked list"
        16 sadd intset "Intset"
        1000 sadd hashtable "Hash table"
        10000 sadd hashtable "Big Hash table"
    } {
        set result [create_random_dataset $num $cmd]
        assert_encoding $enc tosort

        test "$title: SORT BY key" {
            assert_equal $result [r sort tosort BY weight_*]
        }

        test "$title: SORT BY key with limit" {
            assert_equal [lrange $result 5 9] [r sort tosort BY weight_* LIMIT 5 5]
        }

        test "$title: SORT BY hash field" {
            assert_equal $result [r sort tosort BY wobj_*->weight]
        }
    }

    set result [create_random_dataset 16 lpush]
    test "SORT GET #" {
        assert_equal [lsort -integer $result] [r sort tosort GET #]
    }

    test "SORT GET <const>" {
        r del foo
        set res [r sort tosort GET foo]
        assert_equal 16 [llength $res]
        foreach item $res { assert_equal {} $item }
    }

    test "SORT GET (key and hash) with sanity check" {
        set l1 [r sort tosort GET # GET weight_*]
        set l2 [r sort tosort GET # GET wobj_*->weight]
        foreach {id1 w1} $l1 {id2 w2} $l2 {
            assert_equal $id1 $id2
            assert_equal $w1 [r get weight_$id1]
            assert_equal $w2 [r get weight_$id1]
        }
    }

    test "SORT BY key STORE" {
        r sort tosort BY weight_* store sort-res
        assert_equal $result [r lrange sort-res 0 -1]
        assert_equal 16 [r llen sort-res]
        assert_encoding ziplist sort-res
    }

    test "SORT BY hash field STORE" {
        r sort tosort BY wobj_*->weight store sort-res
        assert_equal $result [r lrange sort-res 0 -1]
        assert_equal 16 [r llen sort-res]
        assert_encoding ziplist sort-res
    }

    test "SORT DESC" {
        assert_equal [lsort -decreasing -integer $result] [r sort tosort DESC]
    }

    test "SORT ALPHA against integer encoded strings" {
        r del mylist
        r lpush mylist 2
        r lpush mylist 1
        r lpush mylist 3
        r lpush mylist 10
        r sort mylist alpha
    } {1 10 2 3}

    test "SORT sorted set" {
        r del zset
        r zadd zset 1 a
        r zadd zset 5 b
        r zadd zset 2 c
        r zadd zset 10 d
        r zadd zset 3 e
        r sort zset alpha desc
    } {e d c b a}

    test "SORT sorted set BY nosort should retain ordering" {
        r del zset
        r zadd zset 1 a
        r zadd zset 5 b
        r zadd zset 2 c
        r zadd zset 10 d
        r zadd zset 3 e
        r multi
        r sort zset by nosort asc
        r sort zset by nosort desc
        r exec
    } {{a c e b d} {d b e c a}}

    test "SORT sorted set BY nosort + LIMIT" {
        r del zset
        r zadd zset 1 a
        r zadd zset 5 b
        r zadd zset 2 c
        r zadd zset 10 d
        r zadd zset 3 e
        assert_equal [r sort zset by nosort asc limit 0 1] {a}
        assert_equal [r sort zset by nosort desc limit 0 1] {d}
        assert_equal [r sort zset by nosort asc limit 0 2] {a c}
        assert_equal [r sort zset by nosort desc limit 0 2] {d b}
        assert_equal [r sort zset by nosort limit 5 10] {}
        assert_equal [r sort zset by nosort limit -10 100] {a c e b d}
    }

    test "SORT sorted set BY nosort works as expected from scripts" {
        r del zset
        r zadd zset 1 a
        r zadd zset 5 b
        r zadd zset 2 c
        r zadd zset 10 d
        r zadd zset 3 e
        r eval {
            return {redis.call('sort',KEYS[1],'by','nosort','asc'),
                    redis.call('sort',KEYS[1],'by','nosort','desc')}
        } 1 zset
    } {{a c e b d} {d b e c a}}

    test "SORT sorted set: +inf and -inf handling" {
        r del zset
        r zadd zset -100 a
        r zadd zset 200 b
        r zadd zset -300 c
        r zadd zset 1000000 d
        r zadd zset +inf max
        r zadd zset -inf min
        r zrange zset 0 -1
    } {min c a b d max}

    test "SORT regression for issue #19, sorting floats" {
        r flushdb
        set floats {1.1 5.10 3.10 7.44 2.1 5.75 6.12 0.25 1.15}
        foreach x $floats {
            r lpush mylist $x
        }
        assert_equal [lsort -real $floats] [r sort mylist]
    }

    test "SORT with STORE returns zero if result is empty (github isse 224)" {
        r flushdb
        r sort foo store bar
    } {0}

    test "SORT with STORE does not create empty lists (github issue 224)" {
        r flushdb
        r lpush foo bar
        r sort foo alpha limit 10 10 store zap
        r exists zap
    } {0}

    test "SORT with STORE removes key if result is empty (github issue 227)" {
        r flushdb
        r lpush foo bar
        r sort emptylist store foo
        r exists foo
    } {0}

    test "SORT with BY <constant> and STORE should still order output" {
        r del myset mylist
        r sadd myset a b c d e f g h i l m n o p q r s t u v z aa aaa azz
        r sort myset alpha by _ store mylist
        r lrange mylist 0 -1
    } {a aa aaa azz b c d e f g h i l m n o p q r s t u v z}

    test "SORT will complain with numerical sorting and bad doubles (1)" {
        r del myset
        r sadd myset 1 2 3 4 not-a-double
        set e {}
        catch {r sort myset} e
        set e
    } {*ERR*double*}

    test "SORT will complain with numerical sorting and bad doubles (2)" {
        r del myset
        r sadd myset 1 2 3 4
        r mset score:1 10 score:2 20 score:3 30 score:4 not-a-double
        set e {}
        catch {r sort myset by score:*} e
        set e
    } {*ERR*double*}

    test "SORT BY sub-sorts lexicographically if score is the same" {
        r del myset
        r sadd myset a b c d e f g h i l m n o p q r s t u v z aa aaa azz
        foreach ele {a aa aaa azz b c d e f g h i l m n o p q r s t u v z} {
            set score:$ele 100
        }
        r sort myset by score:*
    } {a aa aaa azz b c d e f g h i l m n o p q r s t u v z}

    test "SORT GET with pattern ending with just -> does not get hash field" {
        r del mylist
        r lpush mylist a
        r set x:a-> 100
        r sort mylist by num get x:*->
    } {100}

    tags {"slow"} {
        set num 100
        set res [create_random_dataset $num lpush]

        test "SORT speed, $num element list BY key, 100 times" {
            set start [clock clicks -milliseconds]
            for {set i 0} {$i < 100} {incr i} {
                set sorted [r sort tosort BY weight_* LIMIT 0 10]
            }
            set elapsed [expr [clock clicks -milliseconds]-$start]
            if {$::verbose} {
                puts -nonewline "\n  Average time to sort: [expr double($elapsed)/100] milliseconds "
                flush stdout
            }
        }

        test "SORT speed, $num element list BY hash field, 100 times" {
            set start [clock clicks -milliseconds]
            for {set i 0} {$i < 100} {incr i} {
                set sorted [r sort tosort BY wobj_*->weight LIMIT 0 10]
            }
            set elapsed [expr [clock clicks -milliseconds]-$start]
            if {$::verbose} {
                puts -nonewline "\n  Average time to sort: [expr double($elapsed)/100] milliseconds "
                flush stdout
            }
        }

        test "SORT speed, $num element list directly, 100 times" {
            set start [clock clicks -milliseconds]
            for {set i 0} {$i < 100} {incr i} {
                set sorted [r sort tosort LIMIT 0 10]
            }
            set elapsed [expr [clock clicks -milliseconds]-$start]
            if {$::verbose} {
                puts -nonewline "\n  Average time to sort: [expr double($elapsed)/100] milliseconds "
                flush stdout
            }
        }

        test "SORT speed, $num element list BY <const>, 100 times" {
            set start [clock clicks -milliseconds]
            for {set i 0} {$i < 100} {incr i} {
                set sorted [r sort tosort BY nokey LIMIT 0 10]
            }
            set elapsed [expr [clock clicks -milliseconds]-$start]
            if {$::verbose} {
                puts -nonewline "\n  Average time to sort: [expr double($elapsed)/100] milliseconds "
                flush stdout
            }
        }
    }
}
