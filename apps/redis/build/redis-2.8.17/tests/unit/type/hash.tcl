start_server {tags {"hash"}} {
    test {HSET/HLEN - Small hash creation} {
        array set smallhash {}
        for {set i 0} {$i < 8} {incr i} {
            set key [randstring 0 8 alpha]
            set val [randstring 0 8 alpha]
            if {[info exists smallhash($key)]} {
                incr i -1
                continue
            }
            r hset smallhash $key $val
            set smallhash($key) $val
        }
        list [r hlen smallhash]
    } {8}

    test {Is the small hash encoded with a ziplist?} {
        assert_encoding ziplist smallhash
    }

    test {HSET/HLEN - Big hash creation} {
        array set bighash {}
        for {set i 0} {$i < 1024} {incr i} {
            set key [randstring 0 8 alpha]
            set val [randstring 0 8 alpha]
            if {[info exists bighash($key)]} {
                incr i -1
                continue
            }
            r hset bighash $key $val
            set bighash($key) $val
        }
        list [r hlen bighash]
    } {1024}

    test {Is the big hash encoded with a ziplist?} {
        assert_encoding hashtable bighash
    }

    test {HGET against the small hash} {
        set err {}
        foreach k [array names smallhash *] {
            if {$smallhash($k) ne [r hget smallhash $k]} {
                set err "$smallhash($k) != [r hget smallhash $k]"
                break
            }
        }
        set _ $err
    } {}

    test {HGET against the big hash} {
        set err {}
        foreach k [array names bighash *] {
            if {$bighash($k) ne [r hget bighash $k]} {
                set err "$bighash($k) != [r hget bighash $k]"
                break
            }
        }
        set _ $err
    } {}

    test {HGET against non existing key} {
        set rv {}
        lappend rv [r hget smallhash __123123123__]
        lappend rv [r hget bighash __123123123__]
        set _ $rv
    } {{} {}}

    test {HSET in update and insert mode} {
        set rv {}
        set k [lindex [array names smallhash *] 0]
        lappend rv [r hset smallhash $k newval1]
        set smallhash($k) newval1
        lappend rv [r hget smallhash $k]
        lappend rv [r hset smallhash __foobar123__ newval]
        set k [lindex [array names bighash *] 0]
        lappend rv [r hset bighash $k newval2]
        set bighash($k) newval2
        lappend rv [r hget bighash $k]
        lappend rv [r hset bighash __foobar123__ newval]
        lappend rv [r hdel smallhash __foobar123__]
        lappend rv [r hdel bighash __foobar123__]
        set _ $rv
    } {0 newval1 1 0 newval2 1 1 1}

    test {HSETNX target key missing - small hash} {
        r hsetnx smallhash __123123123__ foo
        r hget smallhash __123123123__
    } {foo}

    test {HSETNX target key exists - small hash} {
        r hsetnx smallhash __123123123__ bar
        set result [r hget smallhash __123123123__]
        r hdel smallhash __123123123__
        set _ $result
    } {foo}

    test {HSETNX target key missing - big hash} {
        r hsetnx bighash __123123123__ foo
        r hget bighash __123123123__
    } {foo}

    test {HSETNX target key exists - big hash} {
        r hsetnx bighash __123123123__ bar
        set result [r hget bighash __123123123__]
        r hdel bighash __123123123__
        set _ $result
    } {foo}

    test {HMSET wrong number of args} {
        catch {r hmset smallhash key1 val1 key2} err
        format $err
    } {*wrong number*}

    test {HMSET - small hash} {
        set args {}
        foreach {k v} [array get smallhash] {
            set newval [randstring 0 8 alpha]
            set smallhash($k) $newval
            lappend args $k $newval
        }
        r hmset smallhash {*}$args
    } {OK}

    test {HMSET - big hash} {
        set args {}
        foreach {k v} [array get bighash] {
            set newval [randstring 0 8 alpha]
            set bighash($k) $newval
            lappend args $k $newval
        }
        r hmset bighash {*}$args
    } {OK}

    test {HMGET against non existing key and fields} {
        set rv {}
        lappend rv [r hmget doesntexist __123123123__ __456456456__]
        lappend rv [r hmget smallhash __123123123__ __456456456__]
        lappend rv [r hmget bighash __123123123__ __456456456__]
        set _ $rv
    } {{{} {}} {{} {}} {{} {}}}

    test {HMGET against wrong type} {
        r set wrongtype somevalue
        assert_error "*wrong*" {r hmget wrongtype field1 field2}
    }

    test {HMGET - small hash} {
        set keys {}
        set vals {}
        foreach {k v} [array get smallhash] {
            lappend keys $k
            lappend vals $v
        }
        set err {}
        set result [r hmget smallhash {*}$keys]
        if {$vals ne $result} {
            set err "$vals != $result"
            break
        }
        set _ $err
    } {}

    test {HMGET - big hash} {
        set keys {}
        set vals {}
        foreach {k v} [array get bighash] {
            lappend keys $k
            lappend vals $v
        }
        set err {}
        set result [r hmget bighash {*}$keys]
        if {$vals ne $result} {
            set err "$vals != $result"
            break
        }
        set _ $err
    } {}

    test {HKEYS - small hash} {
        lsort [r hkeys smallhash]
    } [lsort [array names smallhash *]]

    test {HKEYS - big hash} {
        lsort [r hkeys bighash]
    } [lsort [array names bighash *]]

    test {HVALS - small hash} {
        set vals {}
        foreach {k v} [array get smallhash] {
            lappend vals $v
        }
        set _ [lsort $vals]
    } [lsort [r hvals smallhash]]

    test {HVALS - big hash} {
        set vals {}
        foreach {k v} [array get bighash] {
            lappend vals $v
        }
        set _ [lsort $vals]
    } [lsort [r hvals bighash]]

    test {HGETALL - small hash} {
        lsort [r hgetall smallhash]
    } [lsort [array get smallhash]]

    test {HGETALL - big hash} {
        lsort [r hgetall bighash]
    } [lsort [array get bighash]]

    test {HDEL and return value} {
        set rv {}
        lappend rv [r hdel smallhash nokey]
        lappend rv [r hdel bighash nokey]
        set k [lindex [array names smallhash *] 0]
        lappend rv [r hdel smallhash $k]
        lappend rv [r hdel smallhash $k]
        lappend rv [r hget smallhash $k]
        unset smallhash($k)
        set k [lindex [array names bighash *] 0]
        lappend rv [r hdel bighash $k]
        lappend rv [r hdel bighash $k]
        lappend rv [r hget bighash $k]
        unset bighash($k)
        set _ $rv
    } {0 0 1 0 {} 1 0 {}}

    test {HDEL - more than a single value} {
        set rv {}
        r del myhash
        r hmset myhash a 1 b 2 c 3
        assert_equal 0 [r hdel myhash x y]
        assert_equal 2 [r hdel myhash a c f]
        r hgetall myhash
    } {b 2}

    test {HDEL - hash becomes empty before deleting all specified fields} {
        r del myhash
        r hmset myhash a 1 b 2 c 3
        assert_equal 3 [r hdel myhash a b c d e]
        assert_equal 0 [r exists myhash]
    }

    test {HEXISTS} {
        set rv {}
        set k [lindex [array names smallhash *] 0]
        lappend rv [r hexists smallhash $k]
        lappend rv [r hexists smallhash nokey]
        set k [lindex [array names bighash *] 0]
        lappend rv [r hexists bighash $k]
        lappend rv [r hexists bighash nokey]
    } {1 0 1 0}

    test {Is a ziplist encoded Hash promoted on big payload?} {
        r hset smallhash foo [string repeat a 1024]
        r debug object smallhash
    } {*hashtable*}

    test {HINCRBY against non existing database key} {
        r del htest
        list [r hincrby htest foo 2]
    } {2}

    test {HINCRBY against non existing hash key} {
        set rv {}
        r hdel smallhash tmp
        r hdel bighash tmp
        lappend rv [r hincrby smallhash tmp 2]
        lappend rv [r hget smallhash tmp]
        lappend rv [r hincrby bighash tmp 2]
        lappend rv [r hget bighash tmp]
    } {2 2 2 2}

    test {HINCRBY against hash key created by hincrby itself} {
        set rv {}
        lappend rv [r hincrby smallhash tmp 3]
        lappend rv [r hget smallhash tmp]
        lappend rv [r hincrby bighash tmp 3]
        lappend rv [r hget bighash tmp]
    } {5 5 5 5}

    test {HINCRBY against hash key originally set with HSET} {
        r hset smallhash tmp 100
        r hset bighash tmp 100
        list [r hincrby smallhash tmp 2] [r hincrby bighash tmp 2]
    } {102 102}

    test {HINCRBY over 32bit value} {
        r hset smallhash tmp 17179869184
        r hset bighash tmp 17179869184
        list [r hincrby smallhash tmp 1] [r hincrby bighash tmp 1]
    } {17179869185 17179869185}

    test {HINCRBY over 32bit value with over 32bit increment} {
        r hset smallhash tmp 17179869184
        r hset bighash tmp 17179869184
        list [r hincrby smallhash tmp 17179869184] [r hincrby bighash tmp 17179869184]
    } {34359738368 34359738368}

    test {HINCRBY fails against hash value with spaces (left)} {
        r hset smallhash str " 11"
        r hset bighash str " 11"
        catch {r hincrby smallhash str 1} smallerr
        catch {r hincrby smallhash str 1} bigerr
        set rv {}
        lappend rv [string match "ERR*not an integer*" $smallerr]
        lappend rv [string match "ERR*not an integer*" $bigerr]
    } {1 1}

    test {HINCRBY fails against hash value with spaces (right)} {
        r hset smallhash str "11 "
        r hset bighash str "11 "
        catch {r hincrby smallhash str 1} smallerr
        catch {r hincrby smallhash str 1} bigerr
        set rv {}
        lappend rv [string match "ERR*not an integer*" $smallerr]
        lappend rv [string match "ERR*not an integer*" $bigerr]
    } {1 1}

    test {HINCRBY can detect overflows} {
        set e {}
        r hset hash n -9223372036854775484
        assert {[r hincrby hash n -1] == -9223372036854775485}
        catch {r hincrby hash n -10000} e
        set e
    } {*overflow*}

    test {HINCRBYFLOAT against non existing database key} {
        r del htest
        list [r hincrbyfloat htest foo 2.5]
    } {2.5}

    test {HINCRBYFLOAT against non existing hash key} {
        set rv {}
        r hdel smallhash tmp
        r hdel bighash tmp
        lappend rv [roundFloat [r hincrbyfloat smallhash tmp 2.5]]
        lappend rv [roundFloat [r hget smallhash tmp]]
        lappend rv [roundFloat [r hincrbyfloat bighash tmp 2.5]]
        lappend rv [roundFloat [r hget bighash tmp]]
    } {2.5 2.5 2.5 2.5}

    test {HINCRBYFLOAT against hash key created by hincrby itself} {
        set rv {}
        lappend rv [roundFloat [r hincrbyfloat smallhash tmp 3.5]]
        lappend rv [roundFloat [r hget smallhash tmp]]
        lappend rv [roundFloat [r hincrbyfloat bighash tmp 3.5]]
        lappend rv [roundFloat [r hget bighash tmp]]
    } {6 6 6 6}

    test {HINCRBYFLOAT against hash key originally set with HSET} {
        r hset smallhash tmp 100
        r hset bighash tmp 100
        list [roundFloat [r hincrbyfloat smallhash tmp 2.5]] \
             [roundFloat [r hincrbyfloat bighash tmp 2.5]]
    } {102.5 102.5}

    test {HINCRBYFLOAT over 32bit value} {
        r hset smallhash tmp 17179869184
        r hset bighash tmp 17179869184
        list [r hincrbyfloat smallhash tmp 1] \
             [r hincrbyfloat bighash tmp 1]
    } {17179869185 17179869185}

    test {HINCRBYFLOAT over 32bit value with over 32bit increment} {
        r hset smallhash tmp 17179869184
        r hset bighash tmp 17179869184
        list [r hincrbyfloat smallhash tmp 17179869184] \
             [r hincrbyfloat bighash tmp 17179869184]
    } {34359738368 34359738368}

    test {HINCRBYFLOAT fails against hash value with spaces (left)} {
        r hset smallhash str " 11"
        r hset bighash str " 11"
        catch {r hincrbyfloat smallhash str 1} smallerr
        catch {r hincrbyfloat smallhash str 1} bigerr
        set rv {}
        lappend rv [string match "ERR*not*float*" $smallerr]
        lappend rv [string match "ERR*not*float*" $bigerr]
    } {1 1}

    test {HINCRBYFLOAT fails against hash value with spaces (right)} {
        r hset smallhash str "11 "
        r hset bighash str "11 "
        catch {r hincrbyfloat smallhash str 1} smallerr
        catch {r hincrbyfloat smallhash str 1} bigerr
        set rv {}
        lappend rv [string match "ERR*not*float*" $smallerr]
        lappend rv [string match "ERR*not*float*" $bigerr]
    } {1 1}

    test {Hash ziplist regression test for large keys} {
        r hset hash kkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkk a
        r hset hash kkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkk b
        r hget hash kkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkkk
    } {b}

    foreach size {10 512} {
        test "Hash fuzzing #1 - $size fields" {
            for {set times 0} {$times < 10} {incr times} {
                catch {unset hash}
                array set hash {}
                r del hash

                # Create
                for {set j 0} {$j < $size} {incr j} {
                    set field [randomValue]
                    set value [randomValue]
                    r hset hash $field $value
                    set hash($field) $value
                }

                # Verify
                foreach {k v} [array get hash] {
                    assert_equal $v [r hget hash $k]
                }
                assert_equal [array size hash] [r hlen hash]
            }
        }

        test "Hash fuzzing #2 - $size fields" {
            for {set times 0} {$times < 10} {incr times} {
                catch {unset hash}
                array set hash {}
                r del hash

                # Create
                for {set j 0} {$j < $size} {incr j} {
                    randpath {
                        set field [randomValue]
                        set value [randomValue]
                        r hset hash $field $value
                        set hash($field) $value
                    } {
                        set field [randomSignedInt 512]
                        set value [randomSignedInt 512]
                        r hset hash $field $value
                        set hash($field) $value
                    } {
                        randpath {
                            set field [randomValue]
                        } {
                            set field [randomSignedInt 512]
                        }
                        r hdel hash $field
                        unset -nocomplain hash($field)
                    }
                }

                # Verify
                foreach {k v} [array get hash] {
                    assert_equal $v [r hget hash $k]
                }
                assert_equal [array size hash] [r hlen hash]
            }
        }
    }

    test {Stress test the hash ziplist -> hashtable encoding conversion} {
        r config set hash-max-ziplist-entries 32
        for {set j 0} {$j < 100} {incr j} {
            r del myhash
            for {set i 0} {$i < 64} {incr i} {
                r hset myhash [randomValue] [randomValue]
            }
            assert {[r object encoding myhash] eq {hashtable}}
        }
    }
}
