start_server {
    tags {"list"}
    overrides {
        "list-max-ziplist-value" 16
        "list-max-ziplist-entries" 256
    }
} {
    source "tests/unit/type/list-common.tcl"

    foreach {type large} [array get largevalue] {
        tags {"slow"} {
            test "LTRIM stress testing - $type" {
                set mylist {}
                set startlen 32
                r del mylist

                # Start with the large value to ensure the
                # right encoding is used.
                r rpush mylist $large
                lappend mylist $large

                for {set i 0} {$i < $startlen} {incr i} {
                    set str [randomInt 9223372036854775807]
                    r rpush mylist $str
                    lappend mylist $str
                }

                for {set i 0} {$i < 1000} {incr i} {
                    set min [expr {int(rand()*$startlen)}]
                    set max [expr {$min+int(rand()*$startlen)}]
                    set mylist [lrange $mylist $min $max]
                    r ltrim mylist $min $max
                    assert_equal $mylist [r lrange mylist 0 -1]

                    for {set j [r llen mylist]} {$j < $startlen} {incr j} {
                        set str [randomInt 9223372036854775807]
                        r rpush mylist $str
                        lappend mylist $str
                    }
                }
            }
        }
    }
}
