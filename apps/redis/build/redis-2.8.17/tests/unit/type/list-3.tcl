start_server {
    tags {list ziplist}
    overrides {
        "list-max-ziplist-value" 200000
        "list-max-ziplist-entries" 256
    }
} {
    test {Explicit regression for a list bug} {
        set mylist {49376042582 {BkG2o\pIC]4YYJa9cJ4GWZalG[4tin;1D2whSkCOW`mX;SFXGyS8sedcff3fQI^tgPCC@^Nu1J6o]meM@Lko]t_jRyo<xSJ1oObDYd`ppZuW6P@fS278YaOx=s6lvdFlMbP0[SbkI^Kr\HBXtuFaA^mDx:yzS4a[skiiPWhT<nNfAf=aQVfclcuwDrfe;iVuKdNvB9kbfq>tK?tH[\EvWqS]b`o2OCtjg:?nUTwdjpcUm]y:pg5q24q7LlCOwQE^}}
        r del l
        r rpush l [lindex $mylist 0]
        r rpush l [lindex $mylist 1]
        assert_equal [r lindex l 0] [lindex $mylist 0]
        assert_equal [r lindex l 1] [lindex $mylist 1]
    }

    tags {slow} {
        test {ziplist implementation: value encoding and backlink} {
            if {$::accurate} {set iterations 100} else {set iterations 10}
            for {set j 0} {$j < $iterations} {incr j} {
                r del l
                set l {}
                for {set i 0} {$i < 200} {incr i} {
                    randpath {
                        set data [string repeat x [randomInt 100000]]
                    } {
                        set data [randomInt 65536]
                    } {
                        set data [randomInt 4294967296]
                    } {
                        set data [randomInt 18446744073709551616]
                    } {
                        set data -[randomInt 65536]
                        if {$data eq {-0}} {set data 0}
                    } {
                        set data -[randomInt 4294967296]
                        if {$data eq {-0}} {set data 0}
                    } {
                        set data -[randomInt 18446744073709551616]
                        if {$data eq {-0}} {set data 0}
                    }
                    lappend l $data
                    r rpush l $data
                }
                assert_equal [llength $l] [r llen l]
                # Traverse backward
                for {set i 199} {$i >= 0} {incr i -1} {
                    if {[lindex $l $i] ne [r lindex l $i]} {
                        assert_equal [lindex $l $i] [r lindex l $i]
                    }
                }
            }
        }

        test {ziplist implementation: encoding stress testing} {
            for {set j 0} {$j < 200} {incr j} {
                r del l
                set l {}
                set len [randomInt 400]
                for {set i 0} {$i < $len} {incr i} {
                    set rv [randomValue]
                    randpath {
                        lappend l $rv
                        r rpush l $rv
                    } {
                        set l [concat [list $rv] $l]
                        r lpush l $rv
                    }
                }
                assert_equal [llength $l] [r llen l]
                for {set i 0} {$i < $len} {incr i} {
                    if {[lindex $l $i] ne [r lindex l $i]} {
                        assert_equal [lindex $l $i] [r lindex l $i]
                    }
                }
            }
        }
    }
}
