# We need a value larger than list-max-ziplist-value to make sure
# the list has the right encoding when it is swapped in again.
array set largevalue {}
set largevalue(ziplist) "hello"
set largevalue(linkedlist) [string repeat "hello" 4]
