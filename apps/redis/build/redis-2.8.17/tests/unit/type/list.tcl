start_server {
    tags {"list"}
    overrides {
        "list-max-ziplist-value" 16
        "list-max-ziplist-entries" 256
    }
} {
    source "tests/unit/type/list-common.tcl"

    test {LPUSH, RPUSH, LLENGTH, LINDEX, LPOP - ziplist} {
        # first lpush then rpush
        assert_equal 1 [r lpush myziplist1 a]
        assert_equal 2 [r rpush myziplist1 b]
        assert_equal 3 [r rpush myziplist1 c]
        assert_equal 3 [r llen myziplist1]
        assert_equal a [r lindex myziplist1 0]
        assert_equal b [r lindex myziplist1 1]
        assert_equal c [r lindex myziplist1 2]
        assert_equal {} [r lindex myziplist2 3]
        assert_equal c [r rpop myziplist1]
        assert_equal a [r lpop myziplist1]
        assert_encoding ziplist myziplist1

        # first rpush then lpush
        assert_equal 1 [r rpush myziplist2 a]
        assert_equal 2 [r lpush myziplist2 b]
        assert_equal 3 [r lpush myziplist2 c]
        assert_equal 3 [r llen myziplist2]
        assert_equal c [r lindex myziplist2 0]
        assert_equal b [r lindex myziplist2 1]
        assert_equal a [r lindex myziplist2 2]
        assert_equal {} [r lindex myziplist2 3]
        assert_equal a [r rpop myziplist2]
        assert_equal c [r lpop myziplist2]
        assert_encoding ziplist myziplist2
    }

    test {LPUSH, RPUSH, LLENGTH, LINDEX, LPOP - regular list} {
        # first lpush then rpush
        assert_equal 1 [r lpush mylist1 $largevalue(linkedlist)]
        assert_encoding linkedlist mylist1
        assert_equal 2 [r rpush mylist1 b]
        assert_equal 3 [r rpush mylist1 c]
        assert_equal 3 [r llen mylist1]
        assert_equal $largevalue(linkedlist) [r lindex mylist1 0]
        assert_equal b [r lindex mylist1 1]
        assert_equal c [r lindex mylist1 2]
        assert_equal {} [r lindex mylist1 3]
        assert_equal c [r rpop mylist1]
        assert_equal $largevalue(linkedlist) [r lpop mylist1]

        # first rpush then lpush
        assert_equal 1 [r rpush mylist2 $largevalue(linkedlist)]
        assert_encoding linkedlist mylist2
        assert_equal 2 [r lpush mylist2 b]
        assert_equal 3 [r lpush mylist2 c]
        assert_equal 3 [r llen mylist2]
        assert_equal c [r lindex mylist2 0]
        assert_equal b [r lindex mylist2 1]
        assert_equal $largevalue(linkedlist) [r lindex mylist2 2]
        assert_equal {} [r lindex mylist2 3]
        assert_equal $largevalue(linkedlist) [r rpop mylist2]
        assert_equal c [r lpop mylist2]
    }

    test {R/LPOP against empty list} {
        r lpop non-existing-list
    } {}

    test {Variadic RPUSH/LPUSH} {
        r del mylist
        assert_equal 4 [r lpush mylist a b c d]
        assert_equal 8 [r rpush mylist 0 1 2 3]
        assert_equal {d c b a 0 1 2 3} [r lrange mylist 0 -1]
    }

    test {DEL a list - ziplist} {
        assert_equal 1 [r del myziplist2]
        assert_equal 0 [r exists myziplist2]
        assert_equal 0 [r llen myziplist2]
    }

    test {DEL a list - regular list} {
        assert_equal 1 [r del mylist2]
        assert_equal 0 [r exists mylist2]
        assert_equal 0 [r llen mylist2]
    }

    proc create_ziplist {key entries} {
        r del $key
        foreach entry $entries { r rpush $key $entry }
        assert_encoding ziplist $key
    }

    proc create_linkedlist {key entries} {
        r del $key
        foreach entry $entries { r rpush $key $entry }
        assert_encoding linkedlist $key
    }

    foreach {type large} [array get largevalue] {
        test "BLPOP, BRPOP: single existing list - $type" {
            set rd [redis_deferring_client]
            create_$type blist "a b $large c d"

            $rd blpop blist 1
            assert_equal {blist a} [$rd read]
            $rd brpop blist 1
            assert_equal {blist d} [$rd read]

            $rd blpop blist 1
            assert_equal {blist b} [$rd read]
            $rd brpop blist 1
            assert_equal {blist c} [$rd read]
        }

        test "BLPOP, BRPOP: multiple existing lists - $type" {
            set rd [redis_deferring_client]
            create_$type blist1 "a $large c"
            create_$type blist2 "d $large f"

            $rd blpop blist1 blist2 1
            assert_equal {blist1 a} [$rd read]
            $rd brpop blist1 blist2 1
            assert_equal {blist1 c} [$rd read]
            assert_equal 1 [r llen blist1]
            assert_equal 3 [r llen blist2]

            $rd blpop blist2 blist1 1
            assert_equal {blist2 d} [$rd read]
            $rd brpop blist2 blist1 1
            assert_equal {blist2 f} [$rd read]
            assert_equal 1 [r llen blist1]
            assert_equal 1 [r llen blist2]
        }

        test "BLPOP, BRPOP: second list has an entry - $type" {
            set rd [redis_deferring_client]
            r del blist1
            create_$type blist2 "d $large f"

            $rd blpop blist1 blist2 1
            assert_equal {blist2 d} [$rd read]
            $rd brpop blist1 blist2 1
            assert_equal {blist2 f} [$rd read]
            assert_equal 0 [r llen blist1]
            assert_equal 1 [r llen blist2]
        }

        test "BRPOPLPUSH - $type" {
            r del target

            set rd [redis_deferring_client]
            create_$type blist "a b $large c d"

            $rd brpoplpush blist target 1
            assert_equal d [$rd read]

            assert_equal d [r rpop target]
            assert_equal "a b $large c" [r lrange blist 0 -1]
        }
    }

    test "BLPOP, LPUSH + DEL should not awake blocked client" {
        set rd [redis_deferring_client]
        r del list

        $rd blpop list 0
        r multi
        r lpush list a
        r del list
        r exec
        r del list
        r lpush list b
        $rd read
    } {list b}

    test "BLPOP, LPUSH + DEL + SET should not awake blocked client" {
        set rd [redis_deferring_client]
        r del list

        $rd blpop list 0
        r multi
        r lpush list a
        r del list
        r set list foo
        r exec
        r del list
        r lpush list b
        $rd read
    } {list b}

    test "BLPOP with same key multiple times should work (issue #801)" {
        set rd [redis_deferring_client]
        r del list1 list2

        # Data arriving after the BLPOP.
        $rd blpop list1 list2 list2 list1 0
        r lpush list1 a
        assert_equal [$rd read] {list1 a}
        $rd blpop list1 list2 list2 list1 0
        r lpush list2 b
        assert_equal [$rd read] {list2 b}

        # Data already there.
        r lpush list1 a
        r lpush list2 b
        $rd blpop list1 list2 list2 list1 0
        assert_equal [$rd read] {list1 a}
        $rd blpop list1 list2 list2 list1 0
        assert_equal [$rd read] {list2 b}
    }

    test "MULTI/EXEC is isolated from the point of view of BLPOP" {
        set rd [redis_deferring_client]
        r del list
        $rd blpop list 0
        r multi
        r lpush list a
        r lpush list b
        r lpush list c
        r exec
        $rd read
    } {list c}

    test "BLPOP with variadic LPUSH" {
        set rd [redis_deferring_client]
        r del blist target
        if {$::valgrind} {after 100}
        $rd blpop blist 0
        if {$::valgrind} {after 100}
        assert_equal 2 [r lpush blist foo bar]
        if {$::valgrind} {after 100}
        assert_equal {blist bar} [$rd read]
        assert_equal foo [lindex [r lrange blist 0 -1] 0]
    }

    test "BRPOPLPUSH with zero timeout should block indefinitely" {
        set rd [redis_deferring_client]
        r del blist target
        $rd brpoplpush blist target 0
        after 1000
        r rpush blist foo
        assert_equal foo [$rd read]
        assert_equal {foo} [r lrange target 0 -1]
    }

    test "BRPOPLPUSH with a client BLPOPing the target list" {
        set rd [redis_deferring_client]
        set rd2 [redis_deferring_client]
        r del blist target
        $rd2 blpop target 0
        $rd brpoplpush blist target 0
        after 1000
        r rpush blist foo
        assert_equal foo [$rd read]
        assert_equal {target foo} [$rd2 read]
        assert_equal 0 [r exists target]
    }

    test "BRPOPLPUSH with wrong source type" {
        set rd [redis_deferring_client]
        r del blist target
        r set blist nolist
        $rd brpoplpush blist target 1
        assert_error "WRONGTYPE*" {$rd read}
    }

    test "BRPOPLPUSH with wrong destination type" {
        set rd [redis_deferring_client]
        r del blist target
        r set target nolist
        r lpush blist foo
        $rd brpoplpush blist target 1
        assert_error "WRONGTYPE*" {$rd read}

        set rd [redis_deferring_client]
        r del blist target
        r set target nolist
        $rd brpoplpush blist target 0
        after 1000
        r rpush blist foo
        assert_error "WRONGTYPE*" {$rd read}
        assert_equal {foo} [r lrange blist 0 -1]
    }

    test "BRPOPLPUSH maintains order of elements after failure" {
        set rd [redis_deferring_client]
        r del blist target
        r set target nolist
        $rd brpoplpush blist target 0
        r rpush blist a b c
        assert_error "WRONGTYPE*" {$rd read}
        r lrange blist 0 -1
    } {a b c}

    test "BRPOPLPUSH with multiple blocked clients" {
        set rd1 [redis_deferring_client]
        set rd2 [redis_deferring_client]
        r del blist target1 target2
        r set target1 nolist
        $rd1 brpoplpush blist target1 0
        $rd2 brpoplpush blist target2 0
        r lpush blist foo

        assert_error "WRONGTYPE*" {$rd1 read}
        assert_equal {foo} [$rd2 read]
        assert_equal {foo} [r lrange target2 0 -1]
    }

    test "Linked BRPOPLPUSH" {
      set rd1 [redis_deferring_client]
      set rd2 [redis_deferring_client]

      r del list1 list2 list3

      $rd1 brpoplpush list1 list2 0
      $rd2 brpoplpush list2 list3 0

      r rpush list1 foo

      assert_equal {} [r lrange list1 0 -1]
      assert_equal {} [r lrange list2 0 -1]
      assert_equal {foo} [r lrange list3 0 -1]
    }

    test "Circular BRPOPLPUSH" {
      set rd1 [redis_deferring_client]
      set rd2 [redis_deferring_client]

      r del list1 list2

      $rd1 brpoplpush list1 list2 0
      $rd2 brpoplpush list2 list1 0

      r rpush list1 foo

      assert_equal {foo} [r lrange list1 0 -1]
      assert_equal {} [r lrange list2 0 -1]
    }

    test "Self-referential BRPOPLPUSH" {
      set rd [redis_deferring_client]

      r del blist

      $rd brpoplpush blist blist 0

      r rpush blist foo

      assert_equal {foo} [r lrange blist 0 -1]
    }

    test "BRPOPLPUSH inside a transaction" {
        r del xlist target
        r lpush xlist foo
        r lpush xlist bar

        r multi
        r brpoplpush xlist target 0
        r brpoplpush xlist target 0
        r brpoplpush xlist target 0
        r lrange xlist 0 -1
        r lrange target 0 -1
        r exec
    } {foo bar {} {} {bar foo}}

    test "PUSH resulting from BRPOPLPUSH affect WATCH" {
        set blocked_client [redis_deferring_client]
        set watching_client [redis_deferring_client]
        r del srclist dstlist somekey
        r set somekey somevalue
        $blocked_client brpoplpush srclist dstlist 0
        $watching_client watch dstlist
        $watching_client read
        $watching_client multi
        $watching_client read
        $watching_client get somekey
        $watching_client read
        r lpush srclist element
        $watching_client exec
        $watching_client read
    } {}

    test "BRPOPLPUSH does not affect WATCH while still blocked" {
        set blocked_client [redis_deferring_client]
        set watching_client [redis_deferring_client]
        r del srclist dstlist somekey
        r set somekey somevalue
        $blocked_client brpoplpush srclist dstlist 0
        $watching_client watch dstlist
        $watching_client read
        $watching_client multi
        $watching_client read
        $watching_client get somekey
        $watching_client read
        $watching_client exec
        # Blocked BLPOPLPUSH may create problems, unblock it.
        r lpush srclist element
        $watching_client read
    } {somevalue}

    test {BRPOPLPUSH timeout} {
      set rd [redis_deferring_client]

      $rd brpoplpush foo_list bar_list 1
      after 2000
      $rd read
    } {}

    test "BLPOP when new key is moved into place" {
        set rd [redis_deferring_client]

        $rd blpop foo 5
        r lpush bob abc def hij
        r rename bob foo
        $rd read
    } {foo hij}

    test "BLPOP when result key is created by SORT..STORE" {
        set rd [redis_deferring_client]

        # zero out list from previous test without explicit delete
        r lpop foo
        r lpop foo
        r lpop foo

        $rd blpop foo 5
        r lpush notfoo hello hola aguacate konichiwa zanzibar
        r sort notfoo ALPHA store foo
        $rd read
    } {foo aguacate}

    foreach {pop} {BLPOP BRPOP} {
        test "$pop: with single empty list argument" {
            set rd [redis_deferring_client]
            r del blist1
            $rd $pop blist1 1
            r rpush blist1 foo
            assert_equal {blist1 foo} [$rd read]
            assert_equal 0 [r exists blist1]
        }

        test "$pop: with negative timeout" {
            set rd [redis_deferring_client]
            $rd $pop blist1 -1
            assert_error "ERR*is negative*" {$rd read}
        }

        test "$pop: with non-integer timeout" {
            set rd [redis_deferring_client]
            $rd $pop blist1 1.1
            assert_error "ERR*not an integer*" {$rd read}
        }

        test "$pop: with zero timeout should block indefinitely" {
            # To test this, use a timeout of 0 and wait a second.
            # The blocking pop should still be waiting for a push.
            set rd [redis_deferring_client]
            $rd $pop blist1 0
            after 1000
            r rpush blist1 foo
            assert_equal {blist1 foo} [$rd read]
        }

        test "$pop: second argument is not a list" {
            set rd [redis_deferring_client]
            r del blist1 blist2
            r set blist2 nolist
            $rd $pop blist1 blist2 1
            assert_error "WRONGTYPE*" {$rd read}
        }

        test "$pop: timeout" {
            set rd [redis_deferring_client]
            r del blist1 blist2
            $rd $pop blist1 blist2 1
            assert_equal {} [$rd read]
        }

        test "$pop: arguments are empty" {
            set rd [redis_deferring_client]
            r del blist1 blist2

            $rd $pop blist1 blist2 1
            r rpush blist1 foo
            assert_equal {blist1 foo} [$rd read]
            assert_equal 0 [r exists blist1]
            assert_equal 0 [r exists blist2]

            $rd $pop blist1 blist2 1
            r rpush blist2 foo
            assert_equal {blist2 foo} [$rd read]
            assert_equal 0 [r exists blist1]
            assert_equal 0 [r exists blist2]
        }
    }

    test {BLPOP inside a transaction} {
        r del xlist
        r lpush xlist foo
        r lpush xlist bar
        r multi
        r blpop xlist 0
        r blpop xlist 0
        r blpop xlist 0
        r exec
    } {{xlist bar} {xlist foo} {}}

    test {LPUSHX, RPUSHX - generic} {
        r del xlist
        assert_equal 0 [r lpushx xlist a]
        assert_equal 0 [r llen xlist]
        assert_equal 0 [r rpushx xlist a]
        assert_equal 0 [r llen xlist]
    }

    foreach {type large} [array get largevalue] {
        test "LPUSHX, RPUSHX - $type" {
            create_$type xlist "$large c"
            assert_equal 3 [r rpushx xlist d]
            assert_equal 4 [r lpushx xlist a]
            assert_equal "a $large c d" [r lrange xlist 0 -1]
        }

        test "LINSERT - $type" {
            create_$type xlist "a $large c d"
            assert_equal 5 [r linsert xlist before c zz]
            assert_equal "a $large zz c d" [r lrange xlist 0 10]
            assert_equal 6 [r linsert xlist after c yy]
            assert_equal "a $large zz c yy d" [r lrange xlist 0 10]
            assert_equal 7 [r linsert xlist after d dd]
            assert_equal -1 [r linsert xlist after bad ddd]
            assert_equal "a $large zz c yy d dd" [r lrange xlist 0 10]
            assert_equal 8 [r linsert xlist before a aa]
            assert_equal -1 [r linsert xlist before bad aaa]
            assert_equal "aa a $large zz c yy d dd" [r lrange xlist 0 10]

            # check inserting integer encoded value
            assert_equal 9 [r linsert xlist before aa 42]
            assert_equal 42 [r lrange xlist 0 0]
        }
    }

    test {LINSERT raise error on bad syntax} {
        catch {[r linsert xlist aft3r aa 42]} e
        set e
    } {*ERR*syntax*error*}

    test {LPUSHX, RPUSHX convert from ziplist to list} {
        set large $largevalue(linkedlist)

        # convert when a large value is pushed
        create_ziplist xlist a
        assert_equal 2 [r rpushx xlist $large]
        assert_encoding linkedlist xlist
        create_ziplist xlist a
        assert_equal 2 [r lpushx xlist $large]
        assert_encoding linkedlist xlist

        # convert when the length threshold is exceeded
        create_ziplist xlist [lrepeat 256 a]
        assert_equal 257 [r rpushx xlist b]
        assert_encoding linkedlist xlist
        create_ziplist xlist [lrepeat 256 a]
        assert_equal 257 [r lpushx xlist b]
        assert_encoding linkedlist xlist
    }

    test {LINSERT convert from ziplist to list} {
        set large $largevalue(linkedlist)

        # convert when a large value is inserted
        create_ziplist xlist a
        assert_equal 2 [r linsert xlist before a $large]
        assert_encoding linkedlist xlist
        create_ziplist xlist a
        assert_equal 2 [r linsert xlist after a $large]
        assert_encoding linkedlist xlist

        # convert when the length threshold is exceeded
        create_ziplist xlist [lrepeat 256 a]
        assert_equal 257 [r linsert xlist before a a]
        assert_encoding linkedlist xlist
        create_ziplist xlist [lrepeat 256 a]
        assert_equal 257 [r linsert xlist after a a]
        assert_encoding linkedlist xlist

        # don't convert when the value could not be inserted
        create_ziplist xlist [lrepeat 256 a]
        assert_equal -1 [r linsert xlist before foo a]
        assert_encoding ziplist xlist
        create_ziplist xlist [lrepeat 256 a]
        assert_equal -1 [r linsert xlist after foo a]
        assert_encoding ziplist xlist
    }

    foreach {type num} {ziplist 250 linkedlist 500} {
        proc check_numbered_list_consistency {key} {
            set len [r llen $key]
            for {set i 0} {$i < $len} {incr i} {
                assert_equal $i [r lindex $key $i]
                assert_equal [expr $len-1-$i] [r lindex $key [expr (-$i)-1]]
            }
        }

        proc check_random_access_consistency {key} {
            set len [r llen $key]
            for {set i 0} {$i < $len} {incr i} {
                set rint [expr int(rand()*$len)]
                assert_equal $rint [r lindex $key $rint]
                assert_equal [expr $len-1-$rint] [r lindex $key [expr (-$rint)-1]]
            }
        }

        test "LINDEX consistency test - $type" {
            r del mylist
            for {set i 0} {$i < $num} {incr i} {
                r rpush mylist $i
            }
            assert_encoding $type mylist
            check_numbered_list_consistency mylist
        }

        test "LINDEX random access - $type" {
            assert_encoding $type mylist
            check_random_access_consistency mylist
        }

        test "Check if list is still ok after a DEBUG RELOAD - $type" {
            r debug reload
            assert_encoding $type mylist
            check_numbered_list_consistency mylist
            check_random_access_consistency mylist
        }
    }

    test {LLEN against non-list value error} {
        r del mylist
        r set mylist foobar
        assert_error WRONGTYPE* {r llen mylist}
    }

    test {LLEN against non existing key} {
        assert_equal 0 [r llen not-a-key]
    }

    test {LINDEX against non-list value error} {
        assert_error WRONGTYPE* {r lindex mylist 0}
    }

    test {LINDEX against non existing key} {
        assert_equal "" [r lindex not-a-key 10]
    }

    test {LPUSH against non-list value error} {
        assert_error WRONGTYPE* {r lpush mylist 0}
    }

    test {RPUSH against non-list value error} {
        assert_error WRONGTYPE* {r rpush mylist 0}
    }

    foreach {type large} [array get largevalue] {
        test "RPOPLPUSH base case - $type" {
            r del mylist1 mylist2
            create_$type mylist1 "a $large c d"
            assert_equal d [r rpoplpush mylist1 mylist2]
            assert_equal c [r rpoplpush mylist1 mylist2]
            assert_equal "a $large" [r lrange mylist1 0 -1]
            assert_equal "c d" [r lrange mylist2 0 -1]
            assert_encoding ziplist mylist2
        }

        test "RPOPLPUSH with the same list as src and dst - $type" {
            create_$type mylist "a $large c"
            assert_equal "a $large c" [r lrange mylist 0 -1]
            assert_equal c [r rpoplpush mylist mylist]
            assert_equal "c a $large" [r lrange mylist 0 -1]
        }

        foreach {othertype otherlarge} [array get largevalue] {
            test "RPOPLPUSH with $type source and existing target $othertype" {
                create_$type srclist "a b c $large"
                create_$othertype dstlist "$otherlarge"
                assert_equal $large [r rpoplpush srclist dstlist]
                assert_equal c [r rpoplpush srclist dstlist]
                assert_equal "a b" [r lrange srclist 0 -1]
                assert_equal "c $large $otherlarge" [r lrange dstlist 0 -1]

                # When we rpoplpush'ed a large value, dstlist should be
                # converted to the same encoding as srclist.
                if {$type eq "linkedlist"} {
                    assert_encoding linkedlist dstlist
                }
            }
        }
    }

    test {RPOPLPUSH against non existing key} {
        r del srclist dstlist
        assert_equal {} [r rpoplpush srclist dstlist]
        assert_equal 0 [r exists srclist]
        assert_equal 0 [r exists dstlist]
    }

    test {RPOPLPUSH against non list src key} {
        r del srclist dstlist
        r set srclist x
        assert_error WRONGTYPE* {r rpoplpush srclist dstlist}
        assert_type string srclist
        assert_equal 0 [r exists newlist]
    }

    test {RPOPLPUSH against non list dst key} {
        create_ziplist srclist {a b c d}
        r set dstlist x
        assert_error WRONGTYPE* {r rpoplpush srclist dstlist}
        assert_type string dstlist
        assert_equal {a b c d} [r lrange srclist 0 -1]
    }

    test {RPOPLPUSH against non existing src key} {
        r del srclist dstlist
        assert_equal {} [r rpoplpush srclist dstlist]
    } {}

    foreach {type large} [array get largevalue] {
        test "Basic LPOP/RPOP - $type" {
            create_$type mylist "$large 1 2"
            assert_equal $large [r lpop mylist]
            assert_equal 2 [r rpop mylist]
            assert_equal 1 [r lpop mylist]
            assert_equal 0 [r llen mylist]

            # pop on empty list
            assert_equal {} [r lpop mylist]
            assert_equal {} [r rpop mylist]
        }
    }

    test {LPOP/RPOP against non list value} {
        r set notalist foo
        assert_error WRONGTYPE* {r lpop notalist}
        assert_error WRONGTYPE* {r rpop notalist}
    }

    foreach {type num} {ziplist 250 linkedlist 500} {
        test "Mass RPOP/LPOP - $type" {
            r del mylist
            set sum1 0
            for {set i 0} {$i < $num} {incr i} {
                r lpush mylist $i
                incr sum1 $i
            }
            assert_encoding $type mylist
            set sum2 0
            for {set i 0} {$i < [expr $num/2]} {incr i} {
                incr sum2 [r lpop mylist]
                incr sum2 [r rpop mylist]
            }
            assert_equal $sum1 $sum2
        }
    }

    foreach {type large} [array get largevalue] {
        test "LRANGE basics - $type" {
            create_$type mylist "$large 1 2 3 4 5 6 7 8 9"
            assert_equal {1 2 3 4 5 6 7 8} [r lrange mylist 1 -2]
            assert_equal {7 8 9} [r lrange mylist -3 -1]
            assert_equal {4} [r lrange mylist 4 4]
        }

        test "LRANGE inverted indexes - $type" {
            create_$type mylist "$large 1 2 3 4 5 6 7 8 9"
            assert_equal {} [r lrange mylist 6 2]
        }

        test "LRANGE out of range indexes including the full list - $type" {
            create_$type mylist "$large 1 2 3"
            assert_equal "$large 1 2 3" [r lrange mylist -1000 1000]
        }

        test "LRANGE out of range negative end index - $type" {
            create_$type mylist "$large 1 2 3"
            assert_equal $large [r lrange mylist 0 -4]
            assert_equal {} [r lrange mylist 0 -5]
        }
    }

    test {LRANGE against non existing key} {
        assert_equal {} [r lrange nosuchkey 0 1]
    }

    foreach {type large} [array get largevalue] {
        proc trim_list {type min max} {
            upvar 1 large large
            r del mylist
            create_$type mylist "1 2 3 4 $large"
            r ltrim mylist $min $max
            r lrange mylist 0 -1
        }

        test "LTRIM basics - $type" {
            assert_equal "1" [trim_list $type 0 0]
            assert_equal "1 2" [trim_list $type 0 1]
            assert_equal "1 2 3" [trim_list $type 0 2]
            assert_equal "2 3" [trim_list $type 1 2]
            assert_equal "2 3 4 $large" [trim_list $type 1 -1]
            assert_equal "2 3 4" [trim_list $type 1 -2]
            assert_equal "4 $large" [trim_list $type -2 -1]
            assert_equal "$large" [trim_list $type -1 -1]
            assert_equal "1 2 3 4 $large" [trim_list $type -5 -1]
            assert_equal "1 2 3 4 $large" [trim_list $type -10 10]
            assert_equal "1 2 3 4 $large" [trim_list $type 0 5]
            assert_equal "1 2 3 4 $large" [trim_list $type 0 10]
        }

        test "LTRIM out of range negative end index - $type" {
            assert_equal {1} [trim_list $type 0 -5]
            assert_equal {} [trim_list $type 0 -6]
        }

    }

    foreach {type large} [array get largevalue] {
        test "LSET - $type" {
            create_$type mylist "99 98 $large 96 95"
            r lset mylist 1 foo
            r lset mylist -1 bar
            assert_equal "99 foo $large 96 bar" [r lrange mylist 0 -1]
        }

        test "LSET out of range index - $type" {
            assert_error ERR*range* {r lset mylist 10 foo}
        }
    }

    test {LSET against non existing key} {
        assert_error ERR*key* {r lset nosuchkey 10 foo}
    }

    test {LSET against non list value} {
        r set nolist foobar
        assert_error WRONGTYPE* {r lset nolist 0 foo}
    }

    foreach {type e} [array get largevalue] {
        test "LREM remove all the occurrences - $type" {
            create_$type mylist "$e foo bar foobar foobared zap bar test foo"
            assert_equal 2 [r lrem mylist 0 bar]
            assert_equal "$e foo foobar foobared zap test foo" [r lrange mylist 0 -1]
        }

        test "LREM remove the first occurrence - $type" {
            assert_equal 1 [r lrem mylist 1 foo]
            assert_equal "$e foobar foobared zap test foo" [r lrange mylist 0 -1]
        }

        test "LREM remove non existing element - $type" {
            assert_equal 0 [r lrem mylist 1 nosuchelement]
            assert_equal "$e foobar foobared zap test foo" [r lrange mylist 0 -1]
        }

        test "LREM starting from tail with negative count - $type" {
            create_$type mylist "$e foo bar foobar foobared zap bar test foo foo"
            assert_equal 1 [r lrem mylist -1 bar]
            assert_equal "$e foo bar foobar foobared zap test foo foo" [r lrange mylist 0 -1]
        }

        test "LREM starting from tail with negative count (2) - $type" {
            assert_equal 2 [r lrem mylist -2 foo]
            assert_equal "$e foo bar foobar foobared zap test" [r lrange mylist 0 -1]
        }

        test "LREM deleting objects that may be int encoded - $type" {
            create_$type myotherlist "$e 1 2 3"
            assert_equal 1 [r lrem myotherlist 1 2]
            assert_equal 3 [r llen myotherlist]
        }
    }

    test "Regression for bug 593 - chaining BRPOPLPUSH with other blocking cmds" {
        set rd1 [redis_deferring_client]
        set rd2 [redis_deferring_client]

        $rd1 brpoplpush a b 0
        $rd1 brpoplpush a b 0
        $rd2 brpoplpush b c 0
        after 1000
        r lpush a data
        $rd1 close
        $rd2 close
        r ping
    } {PONG}
}
