start_server {
    tags {"set"}
    overrides {
        "set-max-intset-entries" 512
    }
} {
    proc create_set {key entries} {
        r del $key
        foreach entry $entries { r sadd $key $entry }
    }

    test {SADD, SCARD, SISMEMBER, SMEMBERS basics - regular set} {
        create_set myset {foo}
        assert_encoding hashtable myset
        assert_equal 1 [r sadd myset bar]
        assert_equal 0 [r sadd myset bar]
        assert_equal 2 [r scard myset]
        assert_equal 1 [r sismember myset foo]
        assert_equal 1 [r sismember myset bar]
        assert_equal 0 [r sismember myset bla]
        assert_equal {bar foo} [lsort [r smembers myset]]
    }

    test {SADD, SCARD, SISMEMBER, SMEMBERS basics - intset} {
        create_set myset {17}
        assert_encoding intset myset
        assert_equal 1 [r sadd myset 16]
        assert_equal 0 [r sadd myset 16]
        assert_equal 2 [r scard myset]
        assert_equal 1 [r sismember myset 16]
        assert_equal 1 [r sismember myset 17]
        assert_equal 0 [r sismember myset 18]
        assert_equal {16 17} [lsort [r smembers myset]]
    }

    test {SADD against non set} {
        r lpush mylist foo
        assert_error WRONGTYPE* {r sadd mylist bar}
    }

    test "SADD a non-integer against an intset" {
        create_set myset {1 2 3}
        assert_encoding intset myset
        assert_equal 1 [r sadd myset a]
        assert_encoding hashtable myset
    }

    test "SADD an integer larger than 64 bits" {
        create_set myset {213244124402402314402033402}
        assert_encoding hashtable myset
        assert_equal 1 [r sismember myset 213244124402402314402033402]
    }

    test "SADD overflows the maximum allowed integers in an intset" {
        r del myset
        for {set i 0} {$i < 512} {incr i} { r sadd myset $i }
        assert_encoding intset myset
        assert_equal 1 [r sadd myset 512]
        assert_encoding hashtable myset
    }

    test {Variadic SADD} {
        r del myset
        assert_equal 3 [r sadd myset a b c]
        assert_equal 2 [r sadd myset A a b c B]
        assert_equal [lsort {A a b c B}] [lsort [r smembers myset]]
    }

    test "Set encoding after DEBUG RELOAD" {
        r del myintset myhashset mylargeintset
        for {set i 0} {$i <  100} {incr i} { r sadd myintset $i }
        for {set i 0} {$i < 1280} {incr i} { r sadd mylargeintset $i }
        for {set i 0} {$i <  256} {incr i} { r sadd myhashset [format "i%03d" $i] }
        assert_encoding intset myintset
        assert_encoding hashtable mylargeintset
        assert_encoding hashtable myhashset

        r debug reload
        assert_encoding intset myintset
        assert_encoding hashtable mylargeintset
        assert_encoding hashtable myhashset
    }

    test {SREM basics - regular set} {
        create_set myset {foo bar ciao}
        assert_encoding hashtable myset
        assert_equal 0 [r srem myset qux]
        assert_equal 1 [r srem myset foo]
        assert_equal {bar ciao} [lsort [r smembers myset]]
    }

    test {SREM basics - intset} {
        create_set myset {3 4 5}
        assert_encoding intset myset
        assert_equal 0 [r srem myset 6]
        assert_equal 1 [r srem myset 4]
        assert_equal {3 5} [lsort [r smembers myset]]
    }

    test {SREM with multiple arguments} {
        r del myset
        r sadd myset a b c d
        assert_equal 0 [r srem myset k k k]
        assert_equal 2 [r srem myset b d x y]
        lsort [r smembers myset]
    } {a c}

    test {SREM variadic version with more args needed to destroy the key} {
        r del myset
        r sadd myset 1 2 3
        r srem myset 1 2 3 4 5 6 7 8
    } {3}

    foreach {type} {hashtable intset} {
        for {set i 1} {$i <= 5} {incr i} {
            r del [format "set%d" $i]
        }
        for {set i 0} {$i < 200} {incr i} {
            r sadd set1 $i
            r sadd set2 [expr $i+195]
        }
        foreach i {199 195 1000 2000} {
            r sadd set3 $i
        }
        for {set i 5} {$i < 200} {incr i} {
            r sadd set4 $i
        }
        r sadd set5 0

        # To make sure the sets are encoded as the type we are testing -- also
        # when the VM is enabled and the values may be swapped in and out
        # while the tests are running -- an extra element is added to every
        # set that determines its encoding.
        set large 200
        if {$type eq "hashtable"} {
            set large foo
        }

        for {set i 1} {$i <= 5} {incr i} {
            r sadd [format "set%d" $i] $large
        }

        test "Generated sets must be encoded as $type" {
            for {set i 1} {$i <= 5} {incr i} {
                assert_encoding $type [format "set%d" $i]
            }
        }

        test "SINTER with two sets - $type" {
            assert_equal [list 195 196 197 198 199 $large] [lsort [r sinter set1 set2]]
        }

        test "SINTERSTORE with two sets - $type" {
            r sinterstore setres set1 set2
            assert_encoding $type setres
            assert_equal [list 195 196 197 198 199 $large] [lsort [r smembers setres]]
        }

        test "SINTERSTORE with two sets, after a DEBUG RELOAD - $type" {
            r debug reload
            r sinterstore setres set1 set2
            assert_encoding $type setres
            assert_equal [list 195 196 197 198 199 $large] [lsort [r smembers setres]]
        }

        test "SUNION with two sets - $type" {
            set expected [lsort -uniq "[r smembers set1] [r smembers set2]"]
            assert_equal $expected [lsort [r sunion set1 set2]]
        }

        test "SUNIONSTORE with two sets - $type" {
            r sunionstore setres set1 set2
            assert_encoding $type setres
            set expected [lsort -uniq "[r smembers set1] [r smembers set2]"]
            assert_equal $expected [lsort [r smembers setres]]
        }

        test "SINTER against three sets - $type" {
            assert_equal [list 195 199 $large] [lsort [r sinter set1 set2 set3]]
        }

        test "SINTERSTORE with three sets - $type" {
            r sinterstore setres set1 set2 set3
            assert_equal [list 195 199 $large] [lsort [r smembers setres]]
        }

        test "SUNION with non existing keys - $type" {
            set expected [lsort -uniq "[r smembers set1] [r smembers set2]"]
            assert_equal $expected [lsort [r sunion nokey1 set1 set2 nokey2]]
        }

        test "SDIFF with two sets - $type" {
            assert_equal {0 1 2 3 4} [lsort [r sdiff set1 set4]]
        }

        test "SDIFF with three sets - $type" {
            assert_equal {1 2 3 4} [lsort [r sdiff set1 set4 set5]]
        }

        test "SDIFFSTORE with three sets - $type" {
            r sdiffstore setres set1 set4 set5
            # When we start with intsets, we should always end with intsets.
            if {$type eq {intset}} {
                assert_encoding intset setres
            }
            assert_equal {1 2 3 4} [lsort [r smembers setres]]
        }
    }

    test "SDIFF with first set empty" {
        r del set1 set2 set3
        r sadd set2 1 2 3 4
        r sadd set3 a b c d
        r sdiff set1 set2 set3
    } {}

    test "SDIFF with same set two times" {
        r del set1
        r sadd set1 a b c 1 2 3 4 5 6
        r sdiff set1 set1
    } {}

    test "SDIFF fuzzing" {
        for {set j 0} {$j < 100} {incr j} {
            unset -nocomplain s
            array set s {}
            set args {}
            set num_sets [expr {[randomInt 10]+1}]
            for {set i 0} {$i < $num_sets} {incr i} {
                set num_elements [randomInt 100]
                r del set_$i
                lappend args set_$i
                while {$num_elements} {
                    set ele [randomValue]
                    r sadd set_$i $ele
                    if {$i == 0} {
                        set s($ele) x
                    } else {
                        unset -nocomplain s($ele)
                    }
                    incr num_elements -1
                }
            }
            set result [lsort [r sdiff {*}$args]]
            assert_equal $result [lsort [array names s]]
        }
    }

    test "SINTER against non-set should throw error" {
        r set key1 x
        assert_error "WRONGTYPE*" {r sinter key1 noset}
    }

    test "SUNION against non-set should throw error" {
        r set key1 x
        assert_error "WRONGTYPE*" {r sunion key1 noset}
    }

    test "SINTER should handle non existing key as empty" {
        r del set1 set2 set3
        r sadd set1 a b c
        r sadd set2 b c d
        r sinter set1 set2 set3
    } {}

    test "SINTER with same integer elements but different encoding" {
        r del set1 set2
        r sadd set1 1 2 3
        r sadd set2 1 2 3 a
        r srem set2 a
        assert_encoding intset set1
        assert_encoding hashtable set2
        lsort [r sinter set1 set2]
    } {1 2 3}

    test "SINTERSTORE against non existing keys should delete dstkey" {
        r set setres xxx
        assert_equal 0 [r sinterstore setres foo111 bar222]
        assert_equal 0 [r exists setres]
    }

    test "SUNIONSTORE against non existing keys should delete dstkey" {
        r set setres xxx
        assert_equal 0 [r sunionstore setres foo111 bar222]
        assert_equal 0 [r exists setres]
    }

    foreach {type contents} {hashtable {a b c} intset {1 2 3}} {
        test "SPOP basics - $type" {
            create_set myset $contents
            assert_encoding $type myset
            assert_equal $contents [lsort [list [r spop myset] [r spop myset] [r spop myset]]]
            assert_equal 0 [r scard myset]
        }

        test "SRANDMEMBER - $type" {
            create_set myset $contents
            unset -nocomplain myset
            array set myset {}
            for {set i 0} {$i < 100} {incr i} {
                set myset([r srandmember myset]) 1
            }
            assert_equal $contents [lsort [array names myset]]
        }
    }

    test "SRANDMEMBER with <count> against non existing key" {
        r srandmember nonexisting_key 100
    } {}

    foreach {type contents} {
        hashtable {
            1 5 10 50 125 50000 33959417 4775547 65434162
            12098459 427716 483706 2726473884 72615637475
            MARY PATRICIA LINDA BARBARA ELIZABETH JENNIFER MARIA
            SUSAN MARGARET DOROTHY LISA NANCY KAREN BETTY HELEN
            SANDRA DONNA CAROL RUTH SHARON MICHELLE LAURA SARAH
            KIMBERLY DEBORAH JESSICA SHIRLEY CYNTHIA ANGELA MELISSA
            BRENDA AMY ANNA REBECCA VIRGINIA KATHLEEN
        }
        intset {
            0 1 2 3 4 5 6 7 8 9 10 11 12 13 14 15 16 17 18 19
            20 21 22 23 24 25 26 27 28 29
            30 31 32 33 34 35 36 37 38 39
            40 41 42 43 44 45 46 47 48 49
        }
    } {
        test "SRANDMEMBER with <count> - $type" {
            create_set myset $contents
            unset -nocomplain myset
            array set myset {}
            foreach ele [r smembers myset] {
                set myset($ele) 1
            }
            assert_equal [lsort $contents] [lsort [array names myset]]

            # Make sure that a count of 0 is handled correctly.
            assert_equal [r srandmember myset 0] {}

            # We'll stress different parts of the code, see the implementation
            # of SRANDMEMBER for more information, but basically there are
            # four different code paths.
            #
            # PATH 1: Use negative count.
            #
            # 1) Check that it returns repeated elements.
            set res [r srandmember myset -100]
            assert_equal [llength $res] 100

            # 2) Check that all the elements actually belong to the
            # original set.
            foreach ele $res {
                assert {[info exists myset($ele)]}
            }

            # 3) Check that eventually all the elements are returned.
            unset -nocomplain auxset
            set iterations 1000
            while {$iterations != 0} {
                incr iterations -1
                set res [r srandmember myset -10]
                foreach ele $res {
                    set auxset($ele) 1
                }
                if {[lsort [array names myset]] eq
                    [lsort [array names auxset]]} {
                    break;
                }
            }
            assert {$iterations != 0}

            # PATH 2: positive count (unique behavior) with requested size
            # equal or greater than set size.
            foreach size {50 100} {
                set res [r srandmember myset $size]
                assert_equal [llength $res] 50
                assert_equal [lsort $res] [lsort [array names myset]]
            }

            # PATH 3: Ask almost as elements as there are in the set.
            # In this case the implementation will duplicate the original
            # set and will remove random elements up to the requested size.
            #
            # PATH 4: Ask a number of elements definitely smaller than
            # the set size.
            #
            # We can test both the code paths just changing the size but
            # using the same code.

            foreach size {45 5} {
                set res [r srandmember myset $size]
                assert_equal [llength $res] $size

                # 1) Check that all the elements actually belong to the
                # original set.
                foreach ele $res {
                    assert {[info exists myset($ele)]}
                }

                # 2) Check that eventually all the elements are returned.
                unset -nocomplain auxset
                set iterations 1000
                while {$iterations != 0} {
                    incr iterations -1
                    set res [r srandmember myset -10]
                    foreach ele $res {
                        set auxset($ele) 1
                    }
                    if {[lsort [array names myset]] eq
                        [lsort [array names auxset]]} {
                        break;
                    }
                }
                assert {$iterations != 0}
            }
        }
    }

    proc setup_move {} {
        r del myset3 myset4
        create_set myset1 {1 a b}
        create_set myset2 {2 3 4}
        assert_encoding hashtable myset1
        assert_encoding intset myset2
    }

    test "SMOVE basics - from regular set to intset" {
        # move a non-integer element to an intset should convert encoding
        setup_move
        assert_equal 1 [r smove myset1 myset2 a]
        assert_equal {1 b} [lsort [r smembers myset1]]
        assert_equal {2 3 4 a} [lsort [r smembers myset2]]
        assert_encoding hashtable myset2

        # move an integer element should not convert the encoding
        setup_move
        assert_equal 1 [r smove myset1 myset2 1]
        assert_equal {a b} [lsort [r smembers myset1]]
        assert_equal {1 2 3 4} [lsort [r smembers myset2]]
        assert_encoding intset myset2
    }

    test "SMOVE basics - from intset to regular set" {
        setup_move
        assert_equal 1 [r smove myset2 myset1 2]
        assert_equal {1 2 a b} [lsort [r smembers myset1]]
        assert_equal {3 4} [lsort [r smembers myset2]]
    }

    test "SMOVE non existing key" {
        setup_move
        assert_equal 0 [r smove myset1 myset2 foo]
        assert_equal {1 a b} [lsort [r smembers myset1]]
        assert_equal {2 3 4} [lsort [r smembers myset2]]
    }

    test "SMOVE non existing src set" {
        setup_move
        assert_equal 0 [r smove noset myset2 foo]
        assert_equal {2 3 4} [lsort [r smembers myset2]]
    }

    test "SMOVE from regular set to non existing destination set" {
        setup_move
        assert_equal 1 [r smove myset1 myset3 a]
        assert_equal {1 b} [lsort [r smembers myset1]]
        assert_equal {a} [lsort [r smembers myset3]]
        assert_encoding hashtable myset3
    }

    test "SMOVE from intset to non existing destination set" {
        setup_move
        assert_equal 1 [r smove myset2 myset3 2]
        assert_equal {3 4} [lsort [r smembers myset2]]
        assert_equal {2} [lsort [r smembers myset3]]
        assert_encoding intset myset3
    }

    test "SMOVE wrong src key type" {
        r set x 10
        assert_error "WRONGTYPE*" {r smove x myset2 foo}
    }

    test "SMOVE wrong dst key type" {
        r set x 10
        assert_error "WRONGTYPE*" {r smove myset2 x foo}
    }

    test "SMOVE with identical source and destination" {
        r del set
        r sadd set a b c
        r smove set set b
        lsort [r smembers set]
    } {a b c}

    tags {slow} {
        test {intsets implementation stress testing} {
            for {set j 0} {$j < 20} {incr j} {
                unset -nocomplain s
                array set s {}
                r del s
                set len [randomInt 1024]
                for {set i 0} {$i < $len} {incr i} {
                    randpath {
                        set data [randomInt 65536]
                    } {
                        set data [randomInt 4294967296]
                    } {
                        set data [randomInt 18446744073709551616]
                    }
                    set s($data) {}
                    r sadd s $data
                }
                assert_equal [lsort [r smembers s]] [lsort [array names s]]
                set len [array size s]
                for {set i 0} {$i < $len} {incr i} {
                    set e [r spop s]
                    if {![info exists s($e)]} {
                        puts "Can't find '$e' on local array"
                        puts "Local array: [lsort [r smembers s]]"
                        puts "Remote array: [lsort [array names s]]"
                        error "exception"
                    }
                    array unset s $e
                }
                assert_equal [r scard s] 0
                assert_equal [array size s] 0
            }
        }
    }
}
