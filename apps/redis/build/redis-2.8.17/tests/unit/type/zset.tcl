start_server {tags {"zset"}} {
    proc create_zset {key items} {
        r del $key
        foreach {score entry} $items {
            r zadd $key $score $entry
        }
    }

    proc basics {encoding} {
        if {$encoding == "ziplist"} {
            r config set zset-max-ziplist-entries 128
            r config set zset-max-ziplist-value 64
        } elseif {$encoding == "skiplist"} {
            r config set zset-max-ziplist-entries 0
            r config set zset-max-ziplist-value 0
        } else {
            puts "Unknown sorted set encoding"
            exit
        }

        test "Check encoding - $encoding" {
            r del ztmp
            r zadd ztmp 10 x
            assert_encoding $encoding ztmp
        }

        test "ZSET basic ZADD and score update - $encoding" {
            r del ztmp
            r zadd ztmp 10 x
            r zadd ztmp 20 y
            r zadd ztmp 30 z
            assert_equal {x y z} [r zrange ztmp 0 -1]

            r zadd ztmp 1 y
            assert_equal {y x z} [r zrange ztmp 0 -1]
        }

        test "ZSET element can't be set to NaN with ZADD - $encoding" {
            assert_error "*not*float*" {r zadd myzset nan abc}
        }

        test "ZSET element can't be set to NaN with ZINCRBY" {
            assert_error "*not*float*" {r zadd myzset nan abc}
        }

        test "ZINCRBY calls leading to NaN result in error" {
            r zincrby myzset +inf abc
            assert_error "*NaN*" {r zincrby myzset -inf abc}
        }

        test {ZADD - Variadic version base case} {
            r del myzset
            list [r zadd myzset 10 a 20 b 30 c] [r zrange myzset 0 -1 withscores]
        } {3 {a 10 b 20 c 30}}

        test {ZADD - Return value is the number of actually added items} {
            list [r zadd myzset 5 x 20 b 30 c] [r zrange myzset 0 -1 withscores]
        } {1 {x 5 a 10 b 20 c 30}}

        test {ZADD - Variadic version does not add nothing on single parsing err} {
            r del myzset
            catch {r zadd myzset 10 a 20 b 30.badscore c} e
            assert_match {*ERR*not*float*} $e
            r exists myzset
        } {0}

        test {ZADD - Variadic version will raise error on missing arg} {
            r del myzset
            catch {r zadd myzset 10 a 20 b 30 c 40} e
            assert_match {*ERR*syntax*} $e
        }

        test {ZINCRBY does not work variadic even if shares ZADD implementation} {
            r del myzset
            catch {r zincrby myzset 10 a 20 b 30 c} e
            assert_match {*ERR*wrong*number*arg*} $e
        }

        test "ZCARD basics - $encoding" {
            assert_equal 3 [r zcard ztmp]
            assert_equal 0 [r zcard zdoesntexist]
        }

        test "ZREM removes key after last element is removed" {
            r del ztmp
            r zadd ztmp 10 x
            r zadd ztmp 20 y

            assert_equal 1 [r exists ztmp]
            assert_equal 0 [r zrem ztmp z]
            assert_equal 1 [r zrem ztmp y]
            assert_equal 1 [r zrem ztmp x]
            assert_equal 0 [r exists ztmp]
        }

        test "ZREM variadic version" {
            r del ztmp
            r zadd ztmp 10 a 20 b 30 c
            assert_equal 2 [r zrem ztmp x y a b k]
            assert_equal 0 [r zrem ztmp foo bar]
            assert_equal 1 [r zrem ztmp c]
            r exists ztmp
        } {0}

        test "ZREM variadic version -- remove elements after key deletion" {
            r del ztmp
            r zadd ztmp 10 a 20 b 30 c
            r zrem ztmp a b c d e f g
        } {3}

        test "ZRANGE basics - $encoding" {
            r del ztmp
            r zadd ztmp 1 a
            r zadd ztmp 2 b
            r zadd ztmp 3 c
            r zadd ztmp 4 d

            assert_equal {a b c d} [r zrange ztmp 0 -1]
            assert_equal {a b c} [r zrange ztmp 0 -2]
            assert_equal {b c d} [r zrange ztmp 1 -1]
            assert_equal {b c} [r zrange ztmp 1 -2]
            assert_equal {c d} [r zrange ztmp -2 -1]
            assert_equal {c} [r zrange ztmp -2 -2]

            # out of range start index
            assert_equal {a b c} [r zrange ztmp -5 2]
            assert_equal {a b} [r zrange ztmp -5 1]
            assert_equal {} [r zrange ztmp 5 -1]
            assert_equal {} [r zrange ztmp 5 -2]

            # out of range end index
            assert_equal {a b c d} [r zrange ztmp 0 5]
            assert_equal {b c d} [r zrange ztmp 1 5]
            assert_equal {} [r zrange ztmp 0 -5]
            assert_equal {} [r zrange ztmp 1 -5]

            # withscores
            assert_equal {a 1 b 2 c 3 d 4} [r zrange ztmp 0 -1 withscores]
        }

        test "ZREVRANGE basics - $encoding" {
            r del ztmp
            r zadd ztmp 1 a
            r zadd ztmp 2 b
            r zadd ztmp 3 c
            r zadd ztmp 4 d

            assert_equal {d c b a} [r zrevrange ztmp 0 -1]
            assert_equal {d c b} [r zrevrange ztmp 0 -2]
            assert_equal {c b a} [r zrevrange ztmp 1 -1]
            assert_equal {c b} [r zrevrange ztmp 1 -2]
            assert_equal {b a} [r zrevrange ztmp -2 -1]
            assert_equal {b} [r zrevrange ztmp -2 -2]

            # out of range start index
            assert_equal {d c b} [r zrevrange ztmp -5 2]
            assert_equal {d c} [r zrevrange ztmp -5 1]
            assert_equal {} [r zrevrange ztmp 5 -1]
            assert_equal {} [r zrevrange ztmp 5 -2]

            # out of range end index
            assert_equal {d c b a} [r zrevrange ztmp 0 5]
            assert_equal {c b a} [r zrevrange ztmp 1 5]
            assert_equal {} [r zrevrange ztmp 0 -5]
            assert_equal {} [r zrevrange ztmp 1 -5]

            # withscores
            assert_equal {d 4 c 3 b 2 a 1} [r zrevrange ztmp 0 -1 withscores]
        }

        test "ZRANK/ZREVRANK basics - $encoding" {
            r del zranktmp
            r zadd zranktmp 10 x
            r zadd zranktmp 20 y
            r zadd zranktmp 30 z
            assert_equal 0 [r zrank zranktmp x]
            assert_equal 1 [r zrank zranktmp y]
            assert_equal 2 [r zrank zranktmp z]
            assert_equal "" [r zrank zranktmp foo]
            assert_equal 2 [r zrevrank zranktmp x]
            assert_equal 1 [r zrevrank zranktmp y]
            assert_equal 0 [r zrevrank zranktmp z]
            assert_equal "" [r zrevrank zranktmp foo]
        }

        test "ZRANK - after deletion - $encoding" {
            r zrem zranktmp y
            assert_equal 0 [r zrank zranktmp x]
            assert_equal 1 [r zrank zranktmp z]
        }

        test "ZINCRBY - can create a new sorted set - $encoding" {
            r del zset
            r zincrby zset 1 foo
            assert_equal {foo} [r zrange zset 0 -1]
            assert_equal 1 [r zscore zset foo]
        }

        test "ZINCRBY - increment and decrement - $encoding" {
            r zincrby zset 2 foo
            r zincrby zset 1 bar
            assert_equal {bar foo} [r zrange zset 0 -1]

            r zincrby zset 10 bar
            r zincrby zset -5 foo
            r zincrby zset -5 bar
            assert_equal {foo bar} [r zrange zset 0 -1]

            assert_equal -2 [r zscore zset foo]
            assert_equal  6 [r zscore zset bar]
        }

        proc create_default_zset {} {
            create_zset zset {-inf a 1 b 2 c 3 d 4 e 5 f +inf g}
        }

        test "ZRANGEBYSCORE/ZREVRANGEBYSCORE/ZCOUNT basics" {
            create_default_zset

            # inclusive range
            assert_equal {a b c} [r zrangebyscore zset -inf 2]
            assert_equal {b c d} [r zrangebyscore zset 0 3]
            assert_equal {d e f} [r zrangebyscore zset 3 6]
            assert_equal {e f g} [r zrangebyscore zset 4 +inf]
            assert_equal {c b a} [r zrevrangebyscore zset 2 -inf]
            assert_equal {d c b} [r zrevrangebyscore zset 3 0]
            assert_equal {f e d} [r zrevrangebyscore zset 6 3]
            assert_equal {g f e} [r zrevrangebyscore zset +inf 4]
            assert_equal 3 [r zcount zset 0 3]

            # exclusive range
            assert_equal {b}   [r zrangebyscore zset (-inf (2]
            assert_equal {b c} [r zrangebyscore zset (0 (3]
            assert_equal {e f} [r zrangebyscore zset (3 (6]
            assert_equal {f}   [r zrangebyscore zset (4 (+inf]
            assert_equal {b}   [r zrevrangebyscore zset (2 (-inf]
            assert_equal {c b} [r zrevrangebyscore zset (3 (0]
            assert_equal {f e} [r zrevrangebyscore zset (6 (3]
            assert_equal {f}   [r zrevrangebyscore zset (+inf (4]
            assert_equal 2 [r zcount zset (0 (3]

            # test empty ranges
            r zrem zset a
            r zrem zset g

            # inclusive
            assert_equal {} [r zrangebyscore zset 4 2]
            assert_equal {} [r zrangebyscore zset 6 +inf]
            assert_equal {} [r zrangebyscore zset -inf -6]
            assert_equal {} [r zrevrangebyscore zset +inf 6]
            assert_equal {} [r zrevrangebyscore zset -6 -inf]

            # exclusive
            assert_equal {} [r zrangebyscore zset (4 (2]
            assert_equal {} [r zrangebyscore zset 2 (2]
            assert_equal {} [r zrangebyscore zset (2 2]
            assert_equal {} [r zrangebyscore zset (6 (+inf]
            assert_equal {} [r zrangebyscore zset (-inf (-6]
            assert_equal {} [r zrevrangebyscore zset (+inf (6]
            assert_equal {} [r zrevrangebyscore zset (-6 (-inf]

            # empty inner range
            assert_equal {} [r zrangebyscore zset 2.4 2.6]
            assert_equal {} [r zrangebyscore zset (2.4 2.6]
            assert_equal {} [r zrangebyscore zset 2.4 (2.6]
            assert_equal {} [r zrangebyscore zset (2.4 (2.6]
        }

        test "ZRANGEBYSCORE with WITHSCORES" {
            create_default_zset
            assert_equal {b 1 c 2 d 3} [r zrangebyscore zset 0 3 withscores]
            assert_equal {d 3 c 2 b 1} [r zrevrangebyscore zset 3 0 withscores]
        }

        test "ZRANGEBYSCORE with LIMIT" {
            create_default_zset
            assert_equal {b c}   [r zrangebyscore zset 0 10 LIMIT 0 2]
            assert_equal {d e f} [r zrangebyscore zset 0 10 LIMIT 2 3]
            assert_equal {d e f} [r zrangebyscore zset 0 10 LIMIT 2 10]
            assert_equal {}      [r zrangebyscore zset 0 10 LIMIT 20 10]
            assert_equal {f e}   [r zrevrangebyscore zset 10 0 LIMIT 0 2]
            assert_equal {d c b} [r zrevrangebyscore zset 10 0 LIMIT 2 3]
            assert_equal {d c b} [r zrevrangebyscore zset 10 0 LIMIT 2 10]
            assert_equal {}      [r zrevrangebyscore zset 10 0 LIMIT 20 10]
        }

        test "ZRANGEBYSCORE with LIMIT and WITHSCORES" {
            create_default_zset
            assert_equal {e 4 f 5} [r zrangebyscore zset 2 5 LIMIT 2 3 WITHSCORES]
            assert_equal {d 3 c 2} [r zrevrangebyscore zset 5 2 LIMIT 2 3 WITHSCORES]
        }

        test "ZRANGEBYSCORE with non-value min or max" {
            assert_error "*not*float*" {r zrangebyscore fooz str 1}
            assert_error "*not*float*" {r zrangebyscore fooz 1 str}
            assert_error "*not*float*" {r zrangebyscore fooz 1 NaN}
        }

        proc create_default_lex_zset {} {
            create_zset zset {0 alpha 0 bar 0 cool 0 down
                              0 elephant 0 foo 0 great 0 hill
                              0 omega}
        }

        test "ZRANGEBYLEX/ZREVRANGEBYLEX/ZCOUNT basics" {
            create_default_lex_zset

            # inclusive range
            assert_equal {alpha bar cool} [r zrangebylex zset - \[cool]
            assert_equal {bar cool down} [r zrangebylex zset \[bar \[down]
            assert_equal {great hill omega} [r zrangebylex zset \[g +]
            assert_equal {cool bar alpha} [r zrevrangebylex zset \[cool -]
            assert_equal {down cool bar} [r zrevrangebylex zset \[down \[bar]
            assert_equal {omega hill great foo elephant down} [r zrevrangebylex zset + \[d]
            assert_equal 3 [r zlexcount zset \[ele \[h]

            # exclusive range
            assert_equal {alpha bar} [r zrangebylex zset - (cool]
            assert_equal {cool} [r zrangebylex zset (bar (down]
            assert_equal {hill omega} [r zrangebylex zset (great +]
            assert_equal {bar alpha} [r zrevrangebylex zset (cool -]
            assert_equal {cool} [r zrevrangebylex zset (down (bar]
            assert_equal {omega hill} [r zrevrangebylex zset + (great]
            assert_equal 2 [r zlexcount zset (ele (great]

            # inclusive and exclusive
            assert_equal {} [r zrangebylex zset (az (b]
            assert_equal {} [r zrangebylex zset (z +]
            assert_equal {} [r zrangebylex zset - \[aaaa]
            assert_equal {} [r zrevrangebylex zset \[elez \[elex]
            assert_equal {} [r zrevrangebylex zset (hill (omega]
        }

        test "ZRANGEBYSLEX with LIMIT" {
            create_default_lex_zset
            assert_equal {alpha bar} [r zrangebylex zset - \[cool LIMIT 0 2]
            assert_equal {bar cool} [r zrangebylex zset - \[cool LIMIT 1 2]
            assert_equal {} [r zrangebylex zset \[bar \[down LIMIT 0 0]
            assert_equal {} [r zrangebylex zset \[bar \[down LIMIT 2 0]
            assert_equal {bar} [r zrangebylex zset \[bar \[down LIMIT 0 1]
            assert_equal {cool} [r zrangebylex zset \[bar \[down LIMIT 1 1]
            assert_equal {bar cool down} [r zrangebylex zset \[bar \[down LIMIT 0 100]
            assert_equal {omega hill great foo elephant} [r zrevrangebylex zset + \[d LIMIT 0 5]
            assert_equal {omega hill great foo} [r zrevrangebylex zset + \[d LIMIT 0 4]
        }

        test "ZRANGEBYLEX with invalid lex range specifiers" {
            assert_error "*not*string*" {r zrangebylex fooz foo bar}
            assert_error "*not*string*" {r zrangebylex fooz \[foo bar}
            assert_error "*not*string*" {r zrangebylex fooz foo \[bar}
            assert_error "*not*string*" {r zrangebylex fooz +x \[bar}
            assert_error "*not*string*" {r zrangebylex fooz -x \[bar}
        }

        test "ZREMRANGEBYSCORE basics" {
            proc remrangebyscore {min max} {
                create_zset zset {1 a 2 b 3 c 4 d 5 e}
                assert_equal 1 [r exists zset]
                r zremrangebyscore zset $min $max
            }

            # inner range
            assert_equal 3 [remrangebyscore 2 4]
            assert_equal {a e} [r zrange zset 0 -1]

            # start underflow
            assert_equal 1 [remrangebyscore -10 1]
            assert_equal {b c d e} [r zrange zset 0 -1]

            # end overflow
            assert_equal 1 [remrangebyscore 5 10]
            assert_equal {a b c d} [r zrange zset 0 -1]

            # switch min and max
            assert_equal 0 [remrangebyscore 4 2]
            assert_equal {a b c d e} [r zrange zset 0 -1]

            # -inf to mid
            assert_equal 3 [remrangebyscore -inf 3]
            assert_equal {d e} [r zrange zset 0 -1]

            # mid to +inf
            assert_equal 3 [remrangebyscore 3 +inf]
            assert_equal {a b} [r zrange zset 0 -1]

            # -inf to +inf
            assert_equal 5 [remrangebyscore -inf +inf]
            assert_equal {} [r zrange zset 0 -1]

            # exclusive min
            assert_equal 4 [remrangebyscore (1 5]
            assert_equal {a} [r zrange zset 0 -1]
            assert_equal 3 [remrangebyscore (2 5]
            assert_equal {a b} [r zrange zset 0 -1]

            # exclusive max
            assert_equal 4 [remrangebyscore 1 (5]
            assert_equal {e} [r zrange zset 0 -1]
            assert_equal 3 [remrangebyscore 1 (4]
            assert_equal {d e} [r zrange zset 0 -1]

            # exclusive min and max
            assert_equal 3 [remrangebyscore (1 (5]
            assert_equal {a e} [r zrange zset 0 -1]

            # destroy when empty
            assert_equal 5 [remrangebyscore 1 5]
            assert_equal 0 [r exists zset]
        }

        test "ZREMRANGEBYSCORE with non-value min or max" {
            assert_error "*not*float*" {r zremrangebyscore fooz str 1}
            assert_error "*not*float*" {r zremrangebyscore fooz 1 str}
            assert_error "*not*float*" {r zremrangebyscore fooz 1 NaN}
        }

        test "ZREMRANGEBYRANK basics" {
            proc remrangebyrank {min max} {
                create_zset zset {1 a 2 b 3 c 4 d 5 e}
                assert_equal 1 [r exists zset]
                r zremrangebyrank zset $min $max
            }

            # inner range
            assert_equal 3 [remrangebyrank 1 3]
            assert_equal {a e} [r zrange zset 0 -1]

            # start underflow
            assert_equal 1 [remrangebyrank -10 0]
            assert_equal {b c d e} [r zrange zset 0 -1]

            # start overflow
            assert_equal 0 [remrangebyrank 10 -1]
            assert_equal {a b c d e} [r zrange zset 0 -1]

            # end underflow
            assert_equal 0 [remrangebyrank 0 -10]
            assert_equal {a b c d e} [r zrange zset 0 -1]

            # end overflow
            assert_equal 5 [remrangebyrank 0 10]
            assert_equal {} [r zrange zset 0 -1]

            # destroy when empty
            assert_equal 5 [remrangebyrank 0 4]
            assert_equal 0 [r exists zset]
        }

        test "ZUNIONSTORE against non-existing key doesn't set destination - $encoding" {
            r del zseta
            assert_equal 0 [r zunionstore dst_key 1 zseta]
            assert_equal 0 [r exists dst_key]
        }

        test "ZUNIONSTORE with empty set - $encoding" {
            r del zseta zsetb
            r zadd zseta 1 a
            r zadd zseta 2 b
            r zunionstore zsetc 2 zseta zsetb
            r zrange zsetc 0 -1 withscores
        } {a 1 b 2}

        test "ZUNIONSTORE basics - $encoding" {
            r del zseta zsetb zsetc
            r zadd zseta 1 a
            r zadd zseta 2 b
            r zadd zseta 3 c
            r zadd zsetb 1 b
            r zadd zsetb 2 c
            r zadd zsetb 3 d

            assert_equal 4 [r zunionstore zsetc 2 zseta zsetb]
            assert_equal {a 1 b 3 d 3 c 5} [r zrange zsetc 0 -1 withscores]
        }

        test "ZUNIONSTORE with weights - $encoding" {
            assert_equal 4 [r zunionstore zsetc 2 zseta zsetb weights 2 3]
            assert_equal {a 2 b 7 d 9 c 12} [r zrange zsetc 0 -1 withscores]
        }

        test "ZUNIONSTORE with a regular set and weights - $encoding" {
            r del seta
            r sadd seta a
            r sadd seta b
            r sadd seta c

            assert_equal 4 [r zunionstore zsetc 2 seta zsetb weights 2 3]
            assert_equal {a 2 b 5 c 8 d 9} [r zrange zsetc 0 -1 withscores]
        }

        test "ZUNIONSTORE with AGGREGATE MIN - $encoding" {
            assert_equal 4 [r zunionstore zsetc 2 zseta zsetb aggregate min]
            assert_equal {a 1 b 1 c 2 d 3} [r zrange zsetc 0 -1 withscores]
        }

        test "ZUNIONSTORE with AGGREGATE MAX - $encoding" {
            assert_equal 4 [r zunionstore zsetc 2 zseta zsetb aggregate max]
            assert_equal {a 1 b 2 c 3 d 3} [r zrange zsetc 0 -1 withscores]
        }

        test "ZINTERSTORE basics - $encoding" {
            assert_equal 2 [r zinterstore zsetc 2 zseta zsetb]
            assert_equal {b 3 c 5} [r zrange zsetc 0 -1 withscores]
        }

        test "ZINTERSTORE with weights - $encoding" {
            assert_equal 2 [r zinterstore zsetc 2 zseta zsetb weights 2 3]
            assert_equal {b 7 c 12} [r zrange zsetc 0 -1 withscores]
        }

        test "ZINTERSTORE with a regular set and weights - $encoding" {
            r del seta
            r sadd seta a
            r sadd seta b
            r sadd seta c
            assert_equal 2 [r zinterstore zsetc 2 seta zsetb weights 2 3]
            assert_equal {b 5 c 8} [r zrange zsetc 0 -1 withscores]
        }

        test "ZINTERSTORE with AGGREGATE MIN - $encoding" {
            assert_equal 2 [r zinterstore zsetc 2 zseta zsetb aggregate min]
            assert_equal {b 1 c 2} [r zrange zsetc 0 -1 withscores]
        }

        test "ZINTERSTORE with AGGREGATE MAX - $encoding" {
            assert_equal 2 [r zinterstore zsetc 2 zseta zsetb aggregate max]
            assert_equal {b 2 c 3} [r zrange zsetc 0 -1 withscores]
        }

        foreach cmd {ZUNIONSTORE ZINTERSTORE} {
            test "$cmd with +inf/-inf scores - $encoding" {
                r del zsetinf1 zsetinf2

                r zadd zsetinf1 +inf key
                r zadd zsetinf2 +inf key
                r $cmd zsetinf3 2 zsetinf1 zsetinf2
                assert_equal inf [r zscore zsetinf3 key]

                r zadd zsetinf1 -inf key
                r zadd zsetinf2 +inf key
                r $cmd zsetinf3 2 zsetinf1 zsetinf2
                assert_equal 0 [r zscore zsetinf3 key]

                r zadd zsetinf1 +inf key
                r zadd zsetinf2 -inf key
                r $cmd zsetinf3 2 zsetinf1 zsetinf2
                assert_equal 0 [r zscore zsetinf3 key]

                r zadd zsetinf1 -inf key
                r zadd zsetinf2 -inf key
                r $cmd zsetinf3 2 zsetinf1 zsetinf2
                assert_equal -inf [r zscore zsetinf3 key]
            }

            test "$cmd with NaN weights $encoding" {
                r del zsetinf1 zsetinf2

                r zadd zsetinf1 1.0 key
                r zadd zsetinf2 1.0 key
                assert_error "*weight*not*float*" {
                    r $cmd zsetinf3 2 zsetinf1 zsetinf2 weights nan nan
                }
            }
        }
    }

    basics ziplist
    basics skiplist

    test {ZINTERSTORE regression with two sets, intset+hashtable} {
        r del seta setb setc
        r sadd set1 a
        r sadd set2 10
        r zinterstore set3 2 set1 set2
    } {0}

    test {ZUNIONSTORE regression, should not create NaN in scores} {
        r zadd z -inf neginf
        r zunionstore out 1 z weights 0
        r zrange out 0 -1 withscores
    } {neginf 0}

    test {ZINTERSTORE #516 regression, mixed sets and ziplist zsets} {
        r sadd one 100 101 102 103
        r sadd two 100 200 201 202
        r zadd three 1 500 1 501 1 502 1 503 1 100
        r zinterstore to_here 3 one two three WEIGHTS 0 0 1
        r zrange to_here 0 -1
    } {100}

    test {ZUNIONSTORE result is sorted} {
        # Create two sets with common and not common elements, perform
        # the UNION, check that elements are still sorted.
        r del one two dest
        set cmd1 [list r zadd one]
        set cmd2 [list r zadd two]
        for {set j 0} {$j < 1000} {incr j} {
            lappend cmd1 [expr rand()] [randomInt 1000]
            lappend cmd2 [expr rand()] [randomInt 1000]
        }
        {*}$cmd1
        {*}$cmd2
        assert {[r zcard one] > 100}
        assert {[r zcard two] > 100}
        r zunionstore dest 2 one two
        set oldscore 0
        foreach {ele score} [r zrange dest 0 -1 withscores] {
            assert {$score >= $oldscore}
            set oldscore $score
        }
    }

    proc stressers {encoding} {
        if {$encoding == "ziplist"} {
            # Little extra to allow proper fuzzing in the sorting stresser
            r config set zset-max-ziplist-entries 256
            r config set zset-max-ziplist-value 64
            set elements 128
        } elseif {$encoding == "skiplist"} {
            r config set zset-max-ziplist-entries 0
            r config set zset-max-ziplist-value 0
            if {$::accurate} {set elements 1000} else {set elements 100}
        } else {
            puts "Unknown sorted set encoding"
            exit
        }

        test "ZSCORE - $encoding" {
            r del zscoretest
            set aux {}
            for {set i 0} {$i < $elements} {incr i} {
                set score [expr rand()]
                lappend aux $score
                r zadd zscoretest $score $i
            }

            assert_encoding $encoding zscoretest
            for {set i 0} {$i < $elements} {incr i} {
                assert_equal [lindex $aux $i] [r zscore zscoretest $i]
            }
        }

        test "ZSCORE after a DEBUG RELOAD - $encoding" {
            r del zscoretest
            set aux {}
            for {set i 0} {$i < $elements} {incr i} {
                set score [expr rand()]
                lappend aux $score
                r zadd zscoretest $score $i
            }

            r debug reload
            assert_encoding $encoding zscoretest
            for {set i 0} {$i < $elements} {incr i} {
                assert_equal [lindex $aux $i] [r zscore zscoretest $i]
            }
        }

        test "ZSET sorting stresser - $encoding" {
            set delta 0
            for {set test 0} {$test < 2} {incr test} {
                unset -nocomplain auxarray
                array set auxarray {}
                set auxlist {}
                r del myzset
                for {set i 0} {$i < $elements} {incr i} {
                    if {$test == 0} {
                        set score [expr rand()]
                    } else {
                        set score [expr int(rand()*10)]
                    }
                    set auxarray($i) $score
                    r zadd myzset $score $i
                    # Random update
                    if {[expr rand()] < .2} {
                        set j [expr int(rand()*1000)]
                        if {$test == 0} {
                            set score [expr rand()]
                        } else {
                            set score [expr int(rand()*10)]
                        }
                        set auxarray($j) $score
                        r zadd myzset $score $j
                    }
                }
                foreach {item score} [array get auxarray] {
                    lappend auxlist [list $score $item]
                }
                set sorted [lsort -command zlistAlikeSort $auxlist]
                set auxlist {}
                foreach x $sorted {
                    lappend auxlist [lindex $x 1]
                }

                assert_encoding $encoding myzset
                set fromredis [r zrange myzset 0 -1]
                set delta 0
                for {set i 0} {$i < [llength $fromredis]} {incr i} {
                    if {[lindex $fromredis $i] != [lindex $auxlist $i]} {
                        incr delta
                    }
                }
            }
            assert_equal 0 $delta
        }

        test "ZRANGEBYSCORE fuzzy test, 100 ranges in $elements element sorted set - $encoding" {
            set err {}
            r del zset
            for {set i 0} {$i < $elements} {incr i} {
                r zadd zset [expr rand()] $i
            }

            assert_encoding $encoding zset
            for {set i 0} {$i < 100} {incr i} {
                set min [expr rand()]
                set max [expr rand()]
                if {$min > $max} {
                    set aux $min
                    set min $max
                    set max $aux
                }
                set low [r zrangebyscore zset -inf $min]
                set ok [r zrangebyscore zset $min $max]
                set high [r zrangebyscore zset $max +inf]
                set lowx [r zrangebyscore zset -inf ($min]
                set okx [r zrangebyscore zset ($min ($max]
                set highx [r zrangebyscore zset ($max +inf]

                if {[r zcount zset -inf $min] != [llength $low]} {
                    append err "Error, len does not match zcount\n"
                }
                if {[r zcount zset $min $max] != [llength $ok]} {
                    append err "Error, len does not match zcount\n"
                }
                if {[r zcount zset $max +inf] != [llength $high]} {
                    append err "Error, len does not match zcount\n"
                }
                if {[r zcount zset -inf ($min] != [llength $lowx]} {
                    append err "Error, len does not match zcount\n"
                }
                if {[r zcount zset ($min ($max] != [llength $okx]} {
                    append err "Error, len does not match zcount\n"
                }
                if {[r zcount zset ($max +inf] != [llength $highx]} {
                    append err "Error, len does not match zcount\n"
                }

                foreach x $low {
                    set score [r zscore zset $x]
                    if {$score > $min} {
                        append err "Error, score for $x is $score > $min\n"
                    }
                }
                foreach x $lowx {
                    set score [r zscore zset $x]
                    if {$score >= $min} {
                        append err "Error, score for $x is $score >= $min\n"
                    }
                }
                foreach x $ok {
                    set score [r zscore zset $x]
                    if {$score < $min || $score > $max} {
                        append err "Error, score for $x is $score outside $min-$max range\n"
                    }
                }
                foreach x $okx {
                    set score [r zscore zset $x]
                    if {$score <= $min || $score >= $max} {
                        append err "Error, score for $x is $score outside $min-$max open range\n"
                    }
                }
                foreach x $high {
                    set score [r zscore zset $x]
                    if {$score < $max} {
                        append err "Error, score for $x is $score < $max\n"
                    }
                }
                foreach x $highx {
                    set score [r zscore zset $x]
                    if {$score <= $max} {
                        append err "Error, score for $x is $score <= $max\n"
                    }
                }
            }
            assert_equal {} $err
        }

        test "ZRANGEBYLEX fuzzy test, 100 ranges in $elements element sorted set - $encoding" {
            set lexset {}
            r del zset
            for {set j 0} {$j < $elements} {incr j} {
                set e [randstring 0 30 alpha]
                lappend lexset $e
                r zadd zset 0 $e
            }
            set lexset [lsort -unique $lexset]
            for {set j 0} {$j < 100} {incr j} {
                set min [randstring 0 30 alpha]
                set max [randstring 0 30 alpha]
                set mininc [randomInt 2]
                set maxinc [randomInt 2]
                if {$mininc} {set cmin "\[$min"} else {set cmin "($min"}
                if {$maxinc} {set cmax "\[$max"} else {set cmax "($max"}
                set rev [randomInt 2]
                if {$rev} {
                    set cmd zrevrangebylex
                } else {
                    set cmd zrangebylex
                }

                # Make sure data is the same in both sides
                assert {[r zrange zset 0 -1] eq $lexset}

                # Get the Redis output
                set output [r $cmd zset $cmin $cmax]
                if {$rev} {
                    set outlen [r zlexcount zset $cmax $cmin]
                } else {
                    set outlen [r zlexcount zset $cmin $cmax]
                }

                # Compute the same output via Tcl
                set o {}
                set copy $lexset
                if {(!$rev && [string compare $min $max] > 0) ||
                    ($rev && [string compare $max $min] > 0)} {
                    # Empty output when ranges are inverted.
                } else {
                    if {$rev} {
                        # Invert the Tcl array using Redis itself.
                        set copy [r zrevrange zset 0 -1]
                        # Invert min / max as well
                        lassign [list $min $max $mininc $maxinc] \
                            max min maxinc mininc
                    }
                    foreach e $copy {
                        set mincmp [string compare $e $min]
                        set maxcmp [string compare $e $max]
                        if {
                             ($mininc && $mincmp >= 0 || !$mininc && $mincmp > 0)
                             &&
                             ($maxinc && $maxcmp <= 0 || !$maxinc && $maxcmp < 0)
                        } {
                            lappend o $e
                        }
                    }
                }
                assert {$o eq $output}
                assert {$outlen eq [llength $output]}
            }
        }

        test "ZREMRANGEBYLEX fuzzy test, 100 ranges in $elements element sorted set - $encoding" {
            set lexset {}
            r del zset zsetcopy
            for {set j 0} {$j < $elements} {incr j} {
                set e [randstring 0 30 alpha]
                lappend lexset $e
                r zadd zset 0 $e
            }
            set lexset [lsort -unique $lexset]
            for {set j 0} {$j < 100} {incr j} {
                # Copy...
                r zunionstore zsetcopy 1 zset
                set lexsetcopy $lexset

                set min [randstring 0 30 alpha]
                set max [randstring 0 30 alpha]
                set mininc [randomInt 2]
                set maxinc [randomInt 2]
                if {$mininc} {set cmin "\[$min"} else {set cmin "($min"}
                if {$maxinc} {set cmax "\[$max"} else {set cmax "($max"}

                # Make sure data is the same in both sides
                assert {[r zrange zset 0 -1] eq $lexset}

                # Get the range we are going to remove
                set torem [r zrangebylex zset $cmin $cmax]
                set toremlen [r zlexcount zset $cmin $cmax]
                r zremrangebylex zsetcopy $cmin $cmax
                set output [r zrange zsetcopy 0 -1]

                # Remove the range with Tcl from the original list
                if {$toremlen} {
                    set first [lsearch -exact $lexsetcopy [lindex $torem 0]]
                    set last [expr {$first+$toremlen-1}]
                    set lexsetcopy [lreplace $lexsetcopy $first $last]
                }
                assert {$lexsetcopy eq $output}
            }
        }

        test "ZSETs skiplist implementation backlink consistency test - $encoding" {
            set diff 0
            for {set j 0} {$j < $elements} {incr j} {
                r zadd myzset [expr rand()] "Element-$j"
                r zrem myzset "Element-[expr int(rand()*$elements)]"
            }

            assert_encoding $encoding myzset
            set l1 [r zrange myzset 0 -1]
            set l2 [r zrevrange myzset 0 -1]
            for {set j 0} {$j < [llength $l1]} {incr j} {
                if {[lindex $l1 $j] ne [lindex $l2 end-$j]} {
                    incr diff
                }
            }
            assert_equal 0 $diff
        }

        test "ZSETs ZRANK augmented skip list stress testing - $encoding" {
            set err {}
            r del myzset
            for {set k 0} {$k < 2000} {incr k} {
                set i [expr {$k % $elements}]
                if {[expr rand()] < .2} {
                    r zrem myzset $i
                } else {
                    set score [expr rand()]
                    r zadd myzset $score $i
                    assert_encoding $encoding myzset
                }

                set card [r zcard myzset]
                if {$card > 0} {
                    set index [randomInt $card]
                    set ele [lindex [r zrange myzset $index $index] 0]
                    set rank [r zrank myzset $ele]
                    if {$rank != $index} {
                        set err "$ele RANK is wrong! ($rank != $index)"
                        break
                    }
                }
            }
            assert_equal {} $err
        }
    }

    tags {"slow"} {
        stressers ziplist
        stressers skiplist
    }
}
