# Build a symbol table for static symbols of redis.c
# Useful to get stack traces on segfault without a debugger. See redis.c
# for more information.
#
# Copyright(C) 2009 Salvatore Sanfilippo, under the BSD license.

set fd [open redis.c]
set symlist {}
while {[gets $fd line] != -1} {
    if {[regexp {^static +[A-z0-9]+[ *]+([A-z0-9]*)\(} $line - sym]} {
        lappend symlist $sym
    }
}
set symlist [lsort -unique $symlist]
puts "static struct redisFunctionSym symsTable\[\] = {"
foreach sym $symlist {
    puts "{\"$sym\",(unsigned long)$sym},"
}
puts "{NULL,0}"
puts "};"

close $fd
