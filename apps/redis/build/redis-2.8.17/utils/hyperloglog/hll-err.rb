# hll-err.rb - Copyright (C) 2014 Salvatore Sanfilippo
# BSD license, See the COPYING file for more information.
#
# Check error of HyperLogLog Redis implementation for different set sizes.

require 'rubygems'
require 'redis'
require 'digest/sha1'

r = Redis.new
r.del('hll')
i = 0
while true do
    100.times {
        elements = []
        1000.times {
            ele = Digest::SHA1.hexdigest(i.to_s)
            elements << ele
            i += 1
        }
        r.pfadd('hll',*elements)
    }
    approx = r.pfcount('hll')
    abs_err = (approx-i).abs
    rel_err = 100.to_f*abs_err/i
    puts "#{i} vs #{approx}: #{rel_err}%"
end
