# hll-err.rb - Copyright (C) 2014 Salvatore Sanfilippo
# BSD license, See the COPYING file for more information.
#
# This program is suited to output average and maximum errors of
# the Redis HyperLogLog implementation in a format suitable to print
# graphs using gnuplot.

require 'rubygems'
require 'redis'
require 'digest/sha1'

# Generate an array of [cardinality,relative_error] pairs
# in the 0 - max range, with the specified step.
#
# 'r' is the Redis object used to perform the queries.
# 'seed' must be different every time you want a test performed
# with a different set. The function guarantees that if 'seed' is the
# same, exactly the same dataset is used, and when it is different,
# a totally unrelated different data set is used (without any common
# element in practice).
def run_experiment(r,seed,max,step)
    r.del('hll')
    i = 0
    samples = []
    step = 1000 if step > 1000
    while i < max do
        elements = []
        step.times {
            ele = Digest::SHA1.hexdigest(i.to_s+seed.to_s)
            elements << ele
            i += 1
        }
        r.pfadd('hll',*elements)
        approx = r.pfcount('hll')
        err = approx-i
        rel_err = 100.to_f*err/i
        samples << [i,rel_err]
    end
    samples
end

def filter_samples(numsets,max,step,filter)
    r = Redis.new
    dataset = {}
    (0...numsets).each{|i|
        dataset[i] = run_experiment(r,i,max,step)
        STDERR.puts "Set #{i}"
    }
    dataset[0].each_with_index{|ele,index|
        if filter == :max
            card=ele[0]
            err=ele[1].abs
            (1...numsets).each{|i|
                err = dataset[i][index][1] if err < dataset[i][index][1]
            }
            puts "#{card} #{err}"
        elsif filter == :avg
            card=ele[0]
            err = 0
            (0...numsets).each{|i|
                err += dataset[i][index][1]
            }
            err /= numsets
            puts "#{card} #{err}"
        elsif filter == :absavg
            card=ele[0]
            err = 0
            (0...numsets).each{|i|
                err += dataset[i][index][1].abs
            }
            err /= numsets
            puts "#{card} #{err}"
        elsif filter == :all
            (0...numsets).each{|i|
                card,err = dataset[i][index]
                puts "#{card} #{err}"
            }
        else
            raise "Unknown filter #{filter}"
        end
    }
end

if ARGV.length != 4
    puts "Usage: hll-gnuplot-graph <samples> <max> <step> (max|avg|absavg|all)"
    exit 1
end
filter_samples(ARGV[0].to_i,ARGV[1].to_i,ARGV[2].to_i,ARGV[3].to_sym)
