#!/bin/sh
if [ $# != "1" ]
then
    echo "Usage: ./mkrelease.sh <git-ref>"
    exit 1
fi

TAG=$1
TARNAME="redis-${TAG}.tar"
echo "Generating /tmp/${TARNAME}"
git archive $TAG --prefix redis-${TAG}/ > /tmp/$TARNAME || exit 1
echo "Gizipping the archive"
rm -f /tmp/$TARNAME.gz
gzip -9 /tmp/$TARNAME
