# redis-copy.rb - Copyright (C) 2009-2010 Salvatore Sanfilippo
# BSD license, See the COPYING file for more information.
#
# Copy the whole dataset from one Redis instance to another one
#
# WARNING: this utility is deprecated and serves as a legacy adapter
#          for the more-robust redis-copy gem.

require 'shellwords'

def redisCopy(opts={})
  src = "#{opts[:srchost]}:#{opts[:srcport]}"
  dst = "#{opts[:dsthost]}:#{opts[:dstport]}"
  `redis-copy #{src.shellescape} #{dst.shellescape}`
rescue Errno::ENOENT
  $stderr.puts 'This utility requires the redis-copy executable',
               'from the redis-copy gem on https://rubygems.org',
               'To install it, run `gem install redis-copy`.'
  exit 1
end

$stderr.puts "This utility is deprecated. Use the redis-copy gem instead."
if ARGV.length != 4
    puts "Usage: redis-copy.rb <srchost> <srcport> <dsthost> <dstport>"
    exit 1
end
puts "WARNING: it's up to you to FLUSHDB the destination host before to continue, press any key when ready."
STDIN.gets
srchost = ARGV[0]
srcport = ARGV[1]
dsthost = ARGV[2]
dstport = ARGV[3]
puts "Copying #{srchost}:#{srcport} into #{dsthost}:#{dstport}"
redisCopy(:srchost => srchost, :srcport => srcport.to_i,
          :dsthost => dsthost, :dstport => dstport.to_i)
