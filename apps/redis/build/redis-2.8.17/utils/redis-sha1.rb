# redis-sha1.rb - Copyright (C) 2009 Salvatore Sanfilippo
# BSD license, See the COPYING file for more information.
#
# Performs the SHA1 sum of the whole datset.
# This is useful to spot bugs in persistence related code and to make sure
# Slaves and Masters are in SYNC.
#
# If you hack this code make sure to sort keys and set elements as this are
# unsorted elements. Otherwise the sum may differ with equal dataset.

require 'rubygems'
require 'redis'
require 'digest/sha1'

def redisSha1(opts={})
    sha1=""
    r = Redis.new(opts)
    r.keys('*').sort.each{|k|
        vtype = r.type?(k)
        if vtype == "string"
            len = 1
            sha1 = Digest::SHA1.hexdigest(sha1+k)
            sha1 = Digest::SHA1.hexdigest(sha1+r.get(k))
        elsif vtype == "list"
            len = r.llen(k)
            if len != 0
                sha1 = Digest::SHA1.hexdigest(sha1+k)
                sha1 = Digest::SHA1.hexdigest(sha1+r.list_range(k,0,-1).join("\x01"))
            end
        elsif vtype == "set"
            len = r.scard(k)
            if len != 0
                sha1 = Digest::SHA1.hexdigest(sha1+k)
                sha1 = Digest::SHA1.hexdigest(sha1+r.set_members(k).to_a.sort.join("\x02"))
            end
        elsif vtype == "zset"
            len = r.zcard(k)
            if len != 0
                sha1 = Digest::SHA1.hexdigest(sha1+k)
                sha1 = Digest::SHA1.hexdigest(sha1+r.zrange(k,0,-1).join("\x01"))
            end
        end
        # puts "#{k} => #{sha1}" if len != 0
    }
    sha1
end

host = ARGV[0] || "127.0.0.1"
port = ARGV[1] || "6379"
db = ARGV[2] || "0"
puts "Performing SHA1 of Redis server #{host} #{port} DB: #{db}"
p "Dataset SHA1: #{redisSha1(:host => host, :port => port.to_i, :db => db)}"
