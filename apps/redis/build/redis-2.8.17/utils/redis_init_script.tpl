
case "$1" in
    start)
        if [ -f $PIDFILE ]
        then
            echo "$PIDFILE exists, process is already running or crashed"
        else
            echo "Starting Redis server..."
            $EXEC $CONF
        fi
        ;;
    stop)
        if [ ! -f $PIDFILE ]
        then
            echo "$PIDFILE does not exist, process is not running"
        else
            PID=$(cat $PIDFILE)
            echo "Stopping ..."
            $CLIEXEC -p $REDISPORT shutdown
            while [ -x /proc/${PID} ]
            do
                echo "Waiting for Redis to shutdown ..."
                sleep 1
            done
            echo "Redis stopped"
        fi
        ;;
    status)
        if [ ! -f $PIDFILE ]
        then
            echo 'Redis is not running'
        else
            echo "Redis is running ($(<$PIDFILE))"
        fi
        ;;
    restart)
        $0 stop
        $0 start
        ;;
    *)
        echo "Please use start, stop, restart or status as first argument"
        ;;
esac
