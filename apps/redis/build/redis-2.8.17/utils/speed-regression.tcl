#!/usr/bin/env tclsh8.5
# Copyright (C) 2011 Salvatore Sanfilippo
# Released under the BSD license like Redis itself

source ../tests/support/redis.tcl
set ::port 12123
set ::tests {PING,SET,GET,INCR,LPUSH,LPOP,SADD,SPOP,LRANGE_100,LRANGE_600,MSET}
set ::datasize 16
set ::requests 100000

proc run-tests branches {
    set runs {}
    set branch_id 0
    foreach b $branches {
        cd ../src
        puts "Benchmarking $b"
        exec -ignorestderr git checkout $b 2> /dev/null
        exec -ignorestderr make clean 2> /dev/null
        puts "  compiling..."
        exec -ignorestderr make 2> /dev/null

        if {$branch_id == 0} {
            puts "  copy redis-benchmark from unstable to /tmp..."
            exec -ignorestderr cp ./redis-benchmark /tmp
            incr branch_id
            continue
        }

        # Start the Redis server
        puts "  starting the server... [exec ./redis-server -v]"
        set pids [exec echo "port $::port\nloglevel warning\n" | ./redis-server - > /dev/null 2> /dev/null &]
        puts "  pids: $pids"
        after 1000
        puts "  running the benchmark"

        set r [redis 127.0.0.1 $::port]
        set i [$r info]
        puts "  redis INFO shows version: [lindex [split $i] 0]"
        $r close

        set output [exec /tmp/redis-benchmark -n $::requests -t $::tests -d $::datasize --csv -p $::port]
        lappend runs $b $output
        puts "  killing server..."
        catch {exec kill -9 [lindex $pids 0]}
        catch {exec kill -9 [lindex $pids 1]}
        incr branch_id
    }
    return $runs
}

proc get-result-with-name {output name} {
    foreach line [split $output "\n"] {
        lassign [split $line ","] key value
        set key [string tolower [string range $key 1 end-1]]
        set value [string range $value 1 end-1]
        if {$key eq [string tolower $name]} {
            return $value
        }
    }
    return "n/a"
}

proc get-test-names output {
    set names {}
    foreach line [split $output "\n"] {
        lassign [split $line ","] key value
        set key [string tolower [string range $key 1 end-1]]
        lappend names $key
    }
    return $names
}

proc combine-results {results} {
    set tests [get-test-names [lindex $results 1]]
    foreach test $tests {
        puts $test
        foreach {branch output} $results {
            puts [format "%-20s %s" \
                $branch [get-result-with-name $output $test]]
        }
        puts {}
    }
}

proc main {} {
    # Note: the first branch is only used in order to get the redis-benchmark
    # executable. Tests are performed starting from the second branch.
    set branches {
        slowset 2.2.0 2.4.0 unstable slowset
    }
    set results [run-tests $branches]
    puts "\n"
    puts "# Test results: datasize=$::datasize requests=$::requests"
    puts [combine-results $results]
}

# Force the user to run the script from the 'utils' directory.
if {![file exists speed-regression.tcl]} {
    puts "Please make sure to run speed-regression.tcl while inside /utils."
    puts "Example: cd utils; ./speed-regression.tcl"
    exit 1
}

# Make sure there is not already a server runnign on port 12123
set is_not_running [catch {set r [redis 127.0.0.1 $::port]}]
if {!$is_not_running} {
    puts "Sorry, you have a running server on port $::port"
    exit 1
}

# parse arguments
for {set j 0} {$j < [llength $argv]} {incr j} {
    set opt [lindex $argv $j]
    set arg [lindex $argv [expr $j+1]]
    if {$opt eq {--tests}} {
        set ::tests $arg
        incr j
    } elseif {$opt eq {--datasize}} {
        set ::datasize $arg
        incr j
    } elseif {$opt eq {--requests}} {
        set ::requests $arg
        incr j
    } else {
        puts "Wrong argument: $opt"
        exit 1
    }
}

main
