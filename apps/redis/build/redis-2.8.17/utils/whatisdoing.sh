# This script is from http://poormansprofiler.org/

#!/bin/bash
nsamples=1
sleeptime=0
pid=$(pidof redis-server)

for x in $(seq 1 $nsamples)
  do
    gdb -ex "set pagination 0" -ex "thread apply all bt" -batch -p $pid
    sleep $sleeptime
  done | \
awk '
  BEGIN { s = ""; } 
  /Thread/ { print s; s = ""; } 
  /^\#/ { if (s != "" ) { s = s "," $4} else { s = $4 } } 
  END { print s }' | \
sort | uniq -c | sort -r -n -k 1,1
