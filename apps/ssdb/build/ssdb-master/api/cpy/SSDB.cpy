/**
 * Copyright (c) 2012, ideawu
 * All rights reserved.
 * @author: ideawu
 * @link: http://www.ideawu.com/
 *
 * SSDB Cpy client SDK.
 */

import socket;

class SSDB_Response{
	function init(code='', data_or_message=null){
		this.type = 'none';
		this.code = code;
		this.data = null;
		this.message = null;
		this.set(code, data_or_message);
	}
	
	function set(code, data_or_message=null){
		this.code = code;
		if(code == 'ok'){
			this.data = data_or_message;
		}else{
			if(isinstance(data_or_message, list)){
				if(len(data_or_message) > 0){
					this.message = data_or_message[0];
				}
			}else{
				this.message = data_or_message;
			}
		}
	}

	function __repr__(){
		return str(this.code) + ' ' + str(this.message) + ' ' + str(this.data);
	}

	function ok(){
		return this.code == 'ok';
	}

	function not_found(){
		return this.code == 'not_found';
	}
	
	function str_resp(resp){
		this.type = 'val';
		if(resp[0] == 'ok'){
			if(len(resp) == 2){
				this.set('ok', resp[1]);
			}else{
				this.set('server_error', 'Invalid response');
			}
		}else{
			this.set(resp[0], resp[1 .. ]);
		}
		return this;
	}
	
	function str_resp(resp){
		this.type = 'val';
		if(resp[0] == 'ok'){
			if(len(resp) == 2){
				this.set('ok', resp[1]);
			}else{
				this.set('server_error', 'Invalid response');
			}
		}else{
			this.set(resp[0], resp[1 .. ]);
		}
		return this;
	}
	
	function int_resp(resp){
		this.type = 'val';
		if(resp[0] == 'ok'){
			if(len(resp) == 2){
				try{
					val = int(resp[1]);
					this.set('ok', val);
				}catch(Exception e){
					this.set('server_error', 'Invalid response');
				}
			}else{
				this.set('server_error', 'Invalid response');
			}
		}else{
			this.set(resp[0], resp[1 .. ]);
		}
		return this;
	}
	
	function float_resp(resp){
		this.type = 'val';
		if(resp[0] == 'ok'){
			if(len(resp) == 2){
				try{
					val = float(resp[1]);
					this.set('ok', val);
				}catch(Exception e){
					this.set('server_error', 'Invalid response');
				}
			}else{
				this.set('server_error', 'Invalid response');
			}
		}else{
			this.set(resp[0], resp[1 .. ]);
		}
		return this;
	}
	
	function list_resp(resp){
		this.type = 'list';
		this.set(resp[0], resp[1 ..]);
		return this;
	}
	
	function int_map_resp(resp){
		this.type = 'map';
		if(resp[0] == 'ok'){
			if(len(resp) % 2 == 1){
				data = {'index':[], 'items':{}};
				for(i=1; i<len(resp); i+=2){
					k = resp[i];
					v = resp[i + 1];
					try{
						v = int(v);
					}catch(Exception e){
						v = -1;
					}
					data['index'].append(k);
					data['items'][k] = v;
				}
				this.set('ok', data);
			}else{
				this.set('server_error', 'Invalid response');
			}
		}else{
			this.set(resp[0], resp[1 .. ]);
		}
		return this;
	}
	
	function str_map_resp(resp){
		this.type = 'map';
		if(resp[0] == 'ok'){
			if(len(resp) % 2 == 1){
				data = {'index':[], 'items':{}};
				for(i=1; i<len(resp); i+=2){
					k = resp[i];
					v = resp[i + 1];
					data['index'].append(k);
					data['items'][k] = v;
				}
				this.set('ok', data);
			}else{
				this.set('server_error', 'Invalid response');
			}
		}else{
			this.set(resp[0], resp[1 .. ]);
		}
		return this;
	}
}

class SSDB{
	function init(host, port){
		this.recv_buf = '';
		this._closed = false;
		this.sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM);
		this.sock.connect(tuple([host, port]));
		this.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1);
	}

	function close(){
		if(!this._closed){
			this.sock.close();
			this._closed = True;
		}
	}

	function closed(){
		return this._closed;
	}

	function request(cmd, params=null){        
		if(params == null){
			params = [];
		}
		params = [cmd] + params;
		this.send(params);

		resp = this.recv();
		if(resp == null){
			return new SSDB_Response('error', 'Unknown error');
		}
		if(len(resp) == 0){
			return new SSDB_Response('disconnected', 'Connection closed');
		}
		
		ret = new SSDB_Response();
		switch(cmd){
			case 'ping':
			case 'set':
			case 'del':
			case 'qset':
			case 'zset':
			case 'hset':
			case 'qpush':
			case 'qpush_front':
			case 'qpush_back':
			case 'zdel':
			case 'hdel':
			case 'multi_set':
			case 'multi_del':
			case 'multi_hset':
			case 'multi_hdel':
			case 'multi_zset':
			case 'multi_zdel':
				if(len(resp) > 1){
					return ret.int_resp(resp);
				}else{
					return new SSDB_Response(resp[0], null);
				}
				break;
			case 'version':
			case 'substr':
			case 'get':
			case 'getset':
			case 'hget':
			case 'qfront':
			case 'qback':
			case 'qget':
				return ret.str_resp(resp);
				break;
			case 'qpop':
			case 'qpop_front':
			case 'qpop_back':
				size = 1;
				try{
					size = int(params[2]);
				}catch(Exception e){
				}
				if(size == 1){
					return ret.str_resp(resp);
				}else{
					return ret.list_resp(resp);
				}
				break;
			case 'dbsize':
			case 'getbit':
			case 'setbit':
			case 'countbit':
			case 'bitcount':
			case 'strlen':
			case 'ttl':
			case 'expire':
			case 'setnx':
			case 'incr':
			case 'decr':
			case 'zincr':
			case 'zdecr':
			case 'hincr':
			case 'hdecr':
			case 'hsize':
			case 'zsize':
			case 'qsize':
			case 'zget':
			case 'zrank':
			case 'zrrank':
			case 'zsum':
			case 'zcount':
			case 'zremrangebyrank':
			case 'zremrangebyscore':
			case 'hclear':
			case 'zclear':
			case 'qclear':
			case 'qpush':
			case 'qpush_front':
			case 'qpush_back':
			case 'qtrim_front':
			case 'qtrim_back':
				return ret.int_resp(resp);
				break;
			case 'zavg':
				return ret.float_resp(resp);
				break;
			case 'keys':
			case 'rkeys':
			case 'zkeys':
			case 'zrkeys':
			case 'hkeys':
			case 'hrkeys':
			case 'list':
			case 'hlist':
			case 'hrlist':
			case 'zlist':
			case 'zrlist':
				return ret.list_resp(resp);
				break;
			case 'scan':
			case 'rscan':
			case 'hgetall':
			case 'hscan':
			case 'hrscan':
				return ret.str_map_resp(resp);
				break;
			case 'zscan':
			case 'zrscan':
			case 'zrange':
			case 'zrrange':
			case 'zpop_front':
			case 'zpop_back':
				return ret.int_map_resp(resp);
				break;
			case 'auth':
            case 'exists':
            case 'hexists':
            case 'zexists':
				return ret.int_resp(resp);
                break;
            case 'multi_exists':
            case 'multi_hexists':
            case 'multi_zexists':
				return ret.int_map_resp(resp);
				break;
			case 'multi_get':
			case 'multi_hget':
				return ret.str_map_resp(resp);
				break;
			case 'multi_hsize':
			case 'multi_zsize':
			case 'multi_zget':
				return ret.int_map_resp(resp);
				break;
			default:
				return ret.list_resp(resp);
				break;
		}
		return new SSDB_Response('error', 'Unknown error');
	}

	function send(data){
		ps = [];
		foreach(data as p){
			p = str(p);
			ps.append(str(len(p)));
			ps.append(p);
		}
		nl = '\n';
		s = nl.join(ps) + '\n\n';
		#print '> ' + repr(s);
		try{
			while(true){
				ret = this.sock.send(s);
				if(ret == 0){
					return -1;
				}
				s = s[ret .. ];
				if(len(s) == 0){
					break;
				}
			}
		}catch(socket.error e){
			return -1;
		}
		//except socket.error as (val, msg):
		return ret;
	}

	function net_read(){
		try{
			data = this.sock.recv(1024*8);
			#print '< ' + repr(data);
		}catch(Exception e){
			data = '';
		}
		if(data == ''){
			this.close();
			return 0;
		}
		this.recv_buf += data;
		return len(data);
	}

	function recv(){
		while(true){
			ret = this.parse();
			if(ret == null){
				if(this.net_read() == 0){
					return [];
				}
			}else{
				return ret;
			}
		}
	}

	function parse(){
		//if(len(this.recv_buf)){print 'recv_buf: ' + repr(this.recv_buf);}
		ret = [];
		spos = 0;
		epos = 0;
		while(true){
			spos = epos;
			epos = this.recv_buf.find('\n', spos);
			if(epos == -1){
				break;
			}
			epos += 1;
			line = this.recv_buf[spos .. epos];
			spos = epos;

			if(line.strip() == ''){ // head end
				if(len(ret) == 0){
					continue;
				}else{
					this.recv_buf = this.recv_buf[spos .. ];
					return ret;
				}
			}

			try{
				num = int(line);
			}catch(Exception e){
				// error
				return [];
			}
			epos = spos + num;
			if(epos > len(this.recv_buf)){
				break;
			}
			data = this.recv_buf[spos .. epos];
			ret.append(data);

			spos = epos;
			epos = this.recv_buf.find('\n', spos);
			if(epos == -1){
				break;
			}
			epos += 1;
		}

		return null;
	}
}
