/**
 * Copyright (c) 2012, ideawu
 * All rights reserved.
 * @author: ideawu
 * @link: http://www.ideawu.com/
 *
 * SSDB cpy API demo.
 */

import SSDB.SSDB;

try{
	ssdb = new SSDB('127.0.0.1', 8888);
}catch(Exception e){
	print e;
	sys.exit(0);
}

print(ssdb.request('set', ['test', '123']));
print(ssdb.request('get', ['test']));
print(ssdb.request('incr', ['test', '1']));
print(ssdb.request('decr', ['test', '1']));
print(ssdb.request('scan', ['a', 'z', 10]));
print(ssdb.request('rscan', ['z', 'a', 10]));
print(ssdb.request('keys', ['a', 'z', 10]));
print(ssdb.request('del', ['test']));
print(ssdb.request('get', ['test']));
print "\n";
print(ssdb.request('zset', ['test', 'a', 20]));
print(ssdb.request('zget', ['test', 'a']));
print(ssdb.request('zincr', ['test', 'a', 20]));
print(ssdb.request('zdecr', ['test', 'a', 20]));
print(ssdb.request('zscan', ['test', 'a', 0, 100, 10]));
print(ssdb.request('zrscan', ['test', 'a', 100, 0, 10]));
print(ssdb.request('zkeys', ['test', 'a', 0, 100, 10]));
print(ssdb.request('zdel', ['test', 'a']));
print(ssdb.request('zget', ['test', 'a']));
print "\n";
print(ssdb.request('hset', ['test', 'a', 20]));
print(ssdb.request('hget', ['test', 'a']));
print(ssdb.request('hincr', ['test', 'a', 20]));
print(ssdb.request('hdecr', ['test', 'a', 20]));
print(ssdb.request('hscan', ['test', '0', 'z', 10]));
print(ssdb.request('hrscan', ['test', 'z', '0', 10]));
print(ssdb.request('hkeys', ['test', '0', 'z', 10]));
print(ssdb.request('hdel', ['test', 'a']));
print(ssdb.request('hget', ['test', 'a']));
print "\n";
