<?php
/**
 * Copyright (c) 2012, ideawu
 * All rights reserved.
 * @author: ideawu
 * @link: http://www.ideawu.com/
 *
 * SSDB PHP client SDK.
 */

class SSDBException extends Exception
{
}

class SSDBTimeoutException extends SSDBException
{
}

/**
 * All methods(except *exists) returns false on error,
 * so one should use Identical(if($ret === false)) to test the return value.
 */
class SimpleSSDB extends SSDB
{
	function __construct($host, $port, $timeout_ms=2000){
		parent::__construct($host, $port, $timeout_ms);
		$this->easy();
	}
}

class SSDB_Response
{
	public $cmd;
	public $code;
	public $data = null;
	public $message;

	function __construct($code='ok', $data_or_message=null){
		$this->code = $code;
		if($code == 'ok'){
			$this->data = $data_or_message;
		}else{
			$this->message = $data_or_message;
		}
	}

	function __toString(){
		if($this->code == 'ok'){
			$s = $this->data === null? '' : json_encode($this->data);
		}else{
			$s = $this->message;
		}
		return sprintf('%-13s %12s %s', $this->cmd, $this->code, $s);
	}

	function ok(){
		return $this->code == 'ok';
	}

	function not_found(){
		return $this->code == 'not_found';
	}
}

class SSDB
{
	private $debug = false;
	public $sock = null;
	private $_closed = false;
	private $recv_buf = '';
	private $_easy = false;
	public $last_resp = null;

	function __construct($host, $port, $timeout_ms=2000){
		$timeout_f = (float)$timeout_ms/1000;
		$this->sock = @stream_socket_client("$host:$port", $errno, $errstr, $timeout_f);
		if(!$this->sock){
			throw new SSDBException("$errno: $errstr");
		}
		$timeout_sec = intval($timeout_ms/1000);
		$timeout_usec = ($timeout_ms - $timeout_sec * 1000) * 1000;
		@stream_set_timeout($this->sock, $timeout_sec, $timeout_usec);
		if(function_exists('stream_set_chunk_size')){
			@stream_set_chunk_size($this->sock, 1024 * 1024);
		}
	}
	
	function set_timeout($timeout_ms){
		$timeout_sec = intval($timeout_ms/1000);
		$timeout_usec = ($timeout_ms - $timeout_sec * 1000) * 1000;
		@stream_set_timeout($this->sock, $timeout_sec, $timeout_usec);
	}
	
	/**
	 * After this method invoked with yesno=true, all requesting methods
	 * will not return a SSDB_Response object.
	 * And some certain methods like get/zget will return false
	 * when response is not ok(not_found, etc)
	 */
	function easy(){
		$this->_easy = true;
	}

	function close(){
		if(!$this->_closed){
			@fclose($this->sock);
			$this->_closed = true;
			$this->sock = null;
		}
	}

	function closed(){
		return $this->_closed;
	}

	private $batch_mode = false;
	private $batch_cmds = array();

	function batch(){
		$this->batch_mode = true;
		$this->batch_cmds = array();
		return $this;
	}

	function multi(){
		return $this->batch();
	}

	function exec(){
		$ret = array();
		foreach($this->batch_cmds as $op){
			list($cmd, $params) = $op;
			$this->send_req($cmd, $params);
		}
		foreach($this->batch_cmds as $op){
			list($cmd, $params) = $op;
			$resp = $this->recv_resp($cmd, $params);
			$resp = $this->check_easy_resp($cmd, $resp);
			$ret[] = $resp;
		}
		$this->batch_mode = false;
		$this->batch_cmds = array();
		return $ret;
	}
	
	function request(){
		$args = func_get_args();
		$cmd = array_shift($args);
		return $this->__call($cmd, $args);
	}
	
	private $async_auth_password = null;
	
	function auth($password){
		$this->async_auth_password = $password;
		return null;
	}

	function __call($cmd, $params=array()){
		$cmd = strtolower($cmd);
		if($this->async_auth_password !== null){
			$pass = $this->async_auth_password;
			$this->async_auth_password = null;
			$auth = $this->__call('auth', array($pass));
			if($auth !== true){
				throw new Exception("Authentication failed");
			}
		}

		if($this->batch_mode){
			$this->batch_cmds[] = array($cmd, $params);
			return $this;
		}

		try{
			if($this->send_req($cmd, $params) === false){
				$resp = new SSDB_Response('error', 'send error');
			}else{
				$resp = $this->recv_resp($cmd, $params);
			}
		}catch(SSDBException $e){
			if($this->_easy){
				throw $e;
			}else{
				$resp = new SSDB_Response('error', $e->getMessage());
			}
		}

		if($resp->code == 'noauth'){
			$msg = $resp->message;
			throw new Exception($msg);
		}
		
		$resp = $this->check_easy_resp($cmd, $resp);
		return $resp;
	}

	private function check_easy_resp($cmd, $resp){
		$this->last_resp = $resp;
		if($this->_easy){
			if($resp->not_found()){
				return NULL;
			}else if(!$resp->ok() && !is_array($resp->data)){
				return false;
			}else{
				return $resp->data;
			}
		}else{
			$resp->cmd = $cmd;
			return $resp;
		}
	}

	function multi_set($kvs=array()){
		$args = array();
		foreach($kvs as $k=>$v){
			$args[] = $k;
			$args[] = $v;
		}
		return $this->__call(__FUNCTION__, $args);
	}

	function multi_hset($name, $kvs=array()){
		$args = array($name);
		foreach($kvs as $k=>$v){
			$args[] = $k;
			$args[] = $v;
		}
		return $this->__call(__FUNCTION__, $args);
	}

	function multi_zset($name, $kvs=array()){
		$args = array($name);
		foreach($kvs as $k=>$v){
			$args[] = $k;
			$args[] = $v;
		}
		return $this->__call(__FUNCTION__, $args);
	}

	function incr($key, $val=1){
		$args = func_get_args();
		return $this->__call(__FUNCTION__, $args);
	}

	function decr($key, $val=1){
		$args = func_get_args();
		return $this->__call(__FUNCTION__, $args);
	}

	function zincr($name, $key, $score=1){
		$args = func_get_args();
		return $this->__call(__FUNCTION__, $args);
	}

	function zdecr($name, $key, $score=1){
		$args = func_get_args();
		return $this->__call(__FUNCTION__, $args);
	}

	function zadd($key, $score, $value){
		$args = array($key, $value, $score);
		return $this->__call('zset', $args);
	}

	function zRevRank($name, $key){
		$args = func_get_args();
		return $this->__call("zrrank", $args);
	}

	function zRevRange($name, $offset, $limit){
		$args = func_get_args();
		return $this->__call("zrrange", $args);
	}

	function hincr($name, $key, $val=1){
		$args = func_get_args();
		return $this->__call(__FUNCTION__, $args);
	}

	function hdecr($name, $key, $val=1){
		$args = func_get_args();
		return $this->__call(__FUNCTION__, $args);
	}

	private function send_req($cmd, $params){
		$req = array($cmd);
		foreach($params as $p){
			if(is_array($p)){
				$req = array_merge($req, $p);
			}else{
				$req[] = $p;
			}
		}
		return $this->send($req);
	}

	private function recv_resp($cmd, $params){
		$resp = $this->recv();
		if($resp === false){
			return new SSDB_Response('error', 'Unknown error');
		}else if(!$resp){
			return new SSDB_Response('disconnected', 'Connection closed');
		}
		if($resp[0] == 'noauth'){
			$errmsg = isset($resp[1])? $resp[1] : '';
			return new SSDB_Response($resp[0], $errmsg);
		}
		switch($cmd){
			case 'dbsize':
			case 'ping':
			case 'qset':
			case 'getbit':
			case 'setbit':
			case 'countbit':
			case 'strlen':
			case 'set':
			case 'setx':
			case 'setnx':
			case 'zset':
			case 'hset':
			case 'qpush':
			case 'qpush_front':
			case 'qpush_back':
			case 'qtrim_front':
			case 'qtrim_back':
			case 'del':
			case 'zdel':
			case 'hdel':
			case 'hsize':
			case 'zsize':
			case 'qsize':
			case 'hclear':
			case 'zclear':
			case 'qclear':
			case 'multi_set':
			case 'multi_del':
			case 'multi_hset':
			case 'multi_hdel':
			case 'multi_zset':
			case 'multi_zdel':
			case 'incr':
			case 'decr':
			case 'zincr':
			case 'zdecr':
			case 'hincr':
			case 'hdecr':
			case 'zget':
			case 'zrank':
			case 'zrrank':
			case 'zcount':
			case 'zsum':
			case 'zremrangebyrank':
			case 'zremrangebyscore':
			case 'ttl':
			case 'expire':
				if($resp[0] == 'ok'){
					$val = isset($resp[1])? intval($resp[1]) : 0;
					return new SSDB_Response($resp[0], $val);
				}else{
					$errmsg = isset($resp[1])? $resp[1] : '';
					return new SSDB_Response($resp[0], $errmsg);
				}
			case 'zavg':
				if($resp[0] == 'ok'){
					$val = isset($resp[1])? floatval($resp[1]) : (float)0;
					return new SSDB_Response($resp[0], $val);
				}else{
					$errmsg = isset($resp[1])? $resp[1] : '';
					return new SSDB_Response($resp[0], $errmsg);
				}
			case 'get':
			case 'substr':
			case 'getset':
			case 'hget':
			case 'qget':
			case 'qfront':
			case 'qback':
				if($resp[0] == 'ok'){
					if(count($resp) == 2){
						return new SSDB_Response('ok', $resp[1]);
					}else{
						return new SSDB_Response('server_error', 'Invalid response');
					}
				}else{
					$errmsg = isset($resp[1])? $resp[1] : '';
					return new SSDB_Response($resp[0], $errmsg);
				}
				break;
			case 'qpop':
			case 'qpop_front':
			case 'qpop_back':
				if($resp[0] == 'ok'){
					$size = 1;
					if(isset($params[1])){
						$size = intval($params[1]);
					}
					if($size <= 1){
						if(count($resp) == 2){
							return new SSDB_Response('ok', $resp[1]);
						}else{
							return new SSDB_Response('server_error', 'Invalid response');
						}
					}else{
						$data = array_slice($resp, 1);
						return new SSDB_Response('ok', $data);
					}
				}else{
					$errmsg = isset($resp[1])? $resp[1] : '';
					return new SSDB_Response($resp[0], $errmsg);
				}
				break;
			case 'keys':
			case 'zkeys':
			case 'hkeys':
			case 'hlist':
			case 'zlist':
			case 'qslice':
				if($resp[0] == 'ok'){
					$data = array();
					if($resp[0] == 'ok'){
						$data = array_slice($resp, 1);
					}
					return new SSDB_Response($resp[0], $data);
				}else{
					$errmsg = isset($resp[1])? $resp[1] : '';
					return new SSDB_Response($resp[0], $errmsg);
				}
			case 'auth':
			case 'exists':
			case 'hexists':
			case 'zexists':
				if($resp[0] == 'ok'){
					if(count($resp) == 2){
						return new SSDB_Response('ok', (bool)$resp[1]);
					}else{
						return new SSDB_Response('server_error', 'Invalid response');
					}
				}else{
					$errmsg = isset($resp[1])? $resp[1] : '';
					return new SSDB_Response($resp[0], $errmsg);
				}
				break;
			case 'multi_exists':
			case 'multi_hexists':
			case 'multi_zexists':
				if($resp[0] == 'ok'){
					if(count($resp) % 2 == 1){
						$data = array();
						for($i=1; $i<count($resp); $i+=2){
							$data[$resp[$i]] = (bool)$resp[$i + 1];
						}
						return new SSDB_Response('ok', $data);
					}else{
						return new SSDB_Response('server_error', 'Invalid response');
					}
				}else{
					$errmsg = isset($resp[1])? $resp[1] : '';
					return new SSDB_Response($resp[0], $errmsg);
				}
				break;
			case 'scan':
			case 'rscan':
			case 'zscan':
			case 'zrscan':
			case 'zrange':
			case 'zrrange':
			case 'hscan':
			case 'hrscan':
			case 'hgetall':
			case 'multi_hsize':
			case 'multi_zsize':
			case 'multi_get':
			case 'multi_hget':
			case 'multi_zget':
			case 'zpop_front':
			case 'zpop_back':
				if($resp[0] == 'ok'){
					if(count($resp) % 2 == 1){
						$data = array();
						for($i=1; $i<count($resp); $i+=2){
							if($cmd[0] == 'z'){
								$data[$resp[$i]] = intval($resp[$i + 1]);
							}else{
								$data[$resp[$i]] = $resp[$i + 1];
							}
						}
						return new SSDB_Response('ok', $data);
					}else{
						return new SSDB_Response('server_error', 'Invalid response');
					}
				}else{
					$errmsg = isset($resp[1])? $resp[1] : '';
					return new SSDB_Response($resp[0], $errmsg);
				}
				break;
			default:
				return new SSDB_Response($resp[0], array_slice($resp, 1));
		}
		return new SSDB_Response('error', 'Unknown command: $cmd');
	}

	function send($data){
		$ps = array();
		foreach($data as $p){
			$ps[] = strlen($p);
			$ps[] = $p;
		}
		$s = join("\n", $ps) . "\n\n";
		if($this->debug){
			echo '> ' . str_replace(array("\r", "\n"), array('\r', '\n'), $s) . "\n";
		}
		try{
			while(true){
				$ret = @fwrite($this->sock, $s);
				if($ret === false || $ret === 0){
					$this->close();
					throw new SSDBException('Connection lost');
				}
				$s = substr($s, $ret);
				if(strlen($s) == 0){
					break;
				}
				@fflush($this->sock);
			}
		}catch(Exception $e){
			$this->close();
			throw new SSDBException($e->getMessage());
		}
		return $ret;
	}

	function recv(){
		$this->step = self::STEP_SIZE;
		while(true){
			$ret = $this->parse();
			if($ret === null){
				try{
					$data = @fread($this->sock, 1024 * 1024);
					if($this->debug){
						echo '< ' . str_replace(array("\r", "\n"), array('\r', '\n'), $data) . "\n";
					}
				}catch(Exception $e){
					$data = '';
				}
				if($data === false || $data === ''){
					if(feof($this->sock)){
						$this->close();
						throw new SSDBException('Connection lost');
					}else{
						throw new SSDBTimeoutException('Connection timeout');
					}
				}
				$this->recv_buf .= $data;
#				echo "read " . strlen($data) . " total: " . strlen($this->recv_buf) . "\n";
			}else{
				return $ret;
			}
		}
	}

	const STEP_SIZE = 0;
	const STEP_DATA = 1;
	public $resp = array();
	public $step;
	public $block_size;

	private function parse(){
		$spos = 0;
		$epos = 0;
		$buf_size = strlen($this->recv_buf);
		// performance issue for large reponse
		//$this->recv_buf = ltrim($this->recv_buf);
		while(true){
			$spos = $epos;
			if($this->step === self::STEP_SIZE){
				$epos = strpos($this->recv_buf, "\n", $spos);
				if($epos === false){
					break;
				}
				$epos += 1;
				$line = substr($this->recv_buf, $spos, $epos - $spos);
				$spos = $epos;

				$line = trim($line);
				if(strlen($line) == 0){ // head end
					$this->recv_buf = substr($this->recv_buf, $spos);
					$ret = $this->resp;
					$this->resp = array();
					return $ret;
				}
				$this->block_size = intval($line);
				$this->step = self::STEP_DATA;
			}
			if($this->step === self::STEP_DATA){
				$epos = $spos + $this->block_size;
				if($epos <= $buf_size){
					$n = strpos($this->recv_buf, "\n", $epos);
					if($n !== false){
						$data = substr($this->recv_buf, $spos, $epos - $spos);
						$this->resp[] = $data;
						$epos = $n + 1;
						$this->step = self::STEP_SIZE;
						continue;
					}
				}
				break;
			}
		}

		// packet not ready
		if($spos > 0){
			$this->recv_buf = substr($this->recv_buf, $spos);
		}
		return null;
	}
}
