<?php
/**
 * Copyright (c) 2012, ideawu
 * All rights reserved.
 * @author: ideawu
 * @link: http://www.ideawu.com/
 *
 * SSDB PHP API demo.
 */

include(dirname(__FILE__) . '/SSDB.php');
$host = '127.0.0.1';
$port = 8888;


try{
	$ssdb = new SimpleSSDB($host, $port);
	//$ssdb->easy();
}catch(Exception $e){
	die(__LINE__ . ' ' . $e->getMessage());
}

var_dump($ssdb->set('test', time()));
var_dump($ssdb->set('test', time()));
echo $ssdb->get('test') . "\n";
var_dump($ssdb->del('test'));
var_dump($ssdb->del('test'));
var_dump($ssdb->get('test'));
echo "\n";

var_dump($ssdb->hset('test', 'b', time()));
var_dump($ssdb->hset('test', 'b', time()));
echo $ssdb->hget('test', 'b') . "\n";
var_dump($ssdb->hdel('test', 'b'));
var_dump($ssdb->hdel('test', 'b'));
var_dump($ssdb->hget('test', 'b'));
echo "\n";

var_dump($ssdb->zset('test', 'a', time()));
var_dump($ssdb->zset('test', 'a', time()));
echo $ssdb->zget('test', 'a') . "\n";
var_dump($ssdb->zdel('test', 'a'));
var_dump($ssdb->zdel('test', 'a'));
var_dump($ssdb->zget('test', 'a'));
echo "\n";

$ssdb->close();

die();

/* a simple bench mark */

$data = array();
for($i=0; $i<1000; $i++){
	$k = '' . mt_rand(0, 100000);
	$v = mt_rand(100000, 100000 * 10 - 1) . '';
	$data[$k] = $v;
}

speed();
try{
	$ssdb = new SSDB($host, $port);
}catch(Exception $e){
	die(__LINE__ . ' ' . $e->getMessage());
}
foreach($data as $k=>$v){
	$ret = $ssdb->set($k, $v);
	if($ret === false){
		echo "error\n";
		break;
	}
}
$ssdb->close();
speed('set speed: ', count($data));


speed();
try{
	$ssdb = new SSDB($host, $port);
}catch(Exception $e){
	die(__LINE__ . ' ' . $e->getMessage());
}
foreach($data as $k=>$v){
	$ret = $ssdb->get($k);
	if($ret === false){
		echo "error\n";
		break;
	}
}
$ssdb->close();
speed('get speed: ', count($data));



function speed($msg=null, $count=0){
	static $stime;
	if(!$msg && !$count){
		$stime = microtime(1);
	}else{
		$etime = microtime(1);
		$ts = ($etime - $stime == 0)? 1 : $etime - $stime;
		$speed = $count / floatval($ts);
		$speed = sprintf('%.2f', $speed);
		echo "$msg: " . $speed . "\n";

		$stime = $etime;
	}
}
