<?php
require_once("SSDB.php");
$ssdb = new SimpleSSDB('127.0.0.1', 8888);

$DATA_LEN = 100 * 1024;

$str = str_pad('', $DATA_LEN);
$resp = $ssdb->set('key', $str);


$keys = array(
		'seq' => array(),
		);
for($i=0; $i<1000; $i++){
	$key = sprintf('%010s', $i);
	$keys['seq'][] = $key;
}

$REQUESTS = 1000;
$stime = 0;
$etime = 0;


start();
foreach($keys['seq'] as $key){
	$resp = $ssdb->set($key, $str);
}
output('writeseq');

$ks = $keys['seq'];
shuffle($ks);
start();
foreach($ks as $key){
	$resp = $ssdb->set($key, $str);
}
output('writerand');

start();
foreach($keys['seq'] as $key){
	$resp = $ssdb->get($key);
	if(strlen($resp) != $DATA_LEN){
		echo "$key ERROR!\n";
		die();
	}
}
output('readseq');


$ks = $keys['seq'];
shuffle($ks);
start();
foreach($ks as $key){
	$resp = $ssdb->get($key);
	if(strlen($resp) != $DATA_LEN){
		echo "$key ERROR!\n";
		die();
	}
}
output('readrand');




function start(){
	global $stime, $etime, $DATA_LEN, $REQUESTS;
	$stime = microtime(1);
}

function output($op){
	global $stime, $etime, $DATA_LEN, $REQUESTS;
	$etime = microtime(1);
	$time_consumed = $etime - $stime;
	$tpr = $time_consumed/$REQUESTS * 1000;
	$sps = ($REQUESTS * $DATA_LEN)/$time_consumed/1024/1024;
	printf("%-10s: %8s ms/op %10.1f MB/s\n", $op, number_format($tpr, 3), $sps);// . "ms/op\n";
}

