#!/bin/sh
BASE_DIR=`pwd`
JEMALLOC_PATH="$BASE_DIR/deps/jemalloc-4.1.0"
LEVELDB_PATH="$BASE_DIR/deps/leveldb-1.18"
SNAPPY_PATH="$BASE_DIR/deps/snappy-1.1.0"

# dependency check
true
if [ "$?" -ne 0 ]; then
	echo ""
	echo "ERROR! autoconf required! install autoconf first"
	echo ""
	exit 1
fi

if test -z "$TARGET_OS"; then
	TARGET_OS=`uname -s`
fi
if test -z "$MAKE"; then
	MAKE=make
fi
if test -z "$CC"; then
	CC=gcc
fi
if test -z "$CXX"; then
	CXX=g++
fi

case "$TARGET_OS" in
    Darwin)
        #PLATFORM_CLIBS="-pthread"
		#PLATFORM_CFLAGS=""
        ;;
    Linux)
        PLATFORM_CLIBS="-pthread -lrt"
        ;;
    OS_ANDROID_CROSSCOMPILE)
        PLATFORM_CLIBS="-pthread"
        SNAPPY_HOST="--host=i386-linux"
        ;;
    CYGWIN_*)
        PLATFORM_CLIBS="-lpthread"
        ;;
    SunOS)
        PLATFORM_CLIBS="-lpthread -lrt"
        ;;
    FreeBSD)
        PLATFORM_CLIBS="-lpthread"
		MAKE=gmake
        ;;
    NetBSD)
        PLATFORM_CLIBS="-lpthread -lgcc_s"
        ;;
    OpenBSD)
        PLATFORM_CLIBS="-pthread"
        ;;
    DragonFly)
        PLATFORM_CLIBS="-lpthread"
        ;;
    HP-UX)
        PLATFORM_CLIBS="-pthread"
        ;;
    *)
        echo "Unknown platform!" >&2
        exit 1
esac


DIR=`pwd`
cd $SNAPPY_PATH
if [ ! -f Makefile ]; then
	echo ""
	echo "##### building snappy... #####"
	./configure $SNAPPY_HOST
	# FUCK! snappy compilation doesn't work on some linux!
	find . | xargs touch
	make
	echo "##### building snappy finished #####"
	echo ""
fi
cd "$DIR"


case "$TARGET_OS" in
	CYGWIN*|FreeBSD|Linux|OS_ANDROID_CROSSCOMPILE)
		echo "not using jemalloc on $TARGET_OS"
	;;
	*)
		DIR=`pwd`
		cd $JEMALLOC_PATH
		if [ ! -f Makefile ]; then
			echo ""
			echo "##### building jemalloc... #####"
			sh ./autogen.sh
			./configure
			make
			echo "##### building jemalloc finished #####"
			echo ""
		fi
		cd "$DIR"
	;;
esac


rm -f src/version.h
echo "#ifndef SSDB_DEPS_H" >> src/version.h
echo "#ifndef SSDB_VERSION" >> src/version.h
echo "#define SSDB_VERSION \"`cat version`\"" >> src/version.h
echo "#endif" >> src/version.h
echo "#endif" >> src/version.h
case "$TARGET_OS" in
	CYGWIN*|FreeBSD|Linux)
	;;
	OS_ANDROID_CROSSCOMPILE)
        echo "#define OS_ANDROID 1" >> src/version.h
	;;
	*)
		echo "#ifndef IOS" >> src/version.h
		echo "#include <stdlib.h>" >> src/version.h
		echo "#include <jemalloc/jemalloc.h>" >> src/version.h
		echo "#endif" >> src/version.h
	;;
esac

rm -f build_config.mk
echo CC=$CC >> build_config.mk
echo CXX=$CXX >> build_config.mk
echo "MAKE=$MAKE" >> build_config.mk
echo "LEVELDB_PATH=$LEVELDB_PATH" >> build_config.mk
echo "JEMALLOC_PATH=$JEMALLOC_PATH" >> build_config.mk
echo "SNAPPY_PATH=$SNAPPY_PATH" >> build_config.mk

echo "CFLAGS=" >> build_config.mk
echo "CFLAGS = -DNDEBUG -D__STDC_FORMAT_MACROS -Wall -O2 -Wno-sign-compare" >> build_config.mk
echo "CFLAGS += ${PLATFORM_CFLAGS}" >> build_config.mk
echo "CFLAGS += -I \"$LEVELDB_PATH/include\"" >> build_config.mk

echo "CLIBS=" >> build_config.mk
echo "CLIBS += \"$LEVELDB_PATH/libleveldb.a\"" >> build_config.mk
echo "CLIBS += \"$SNAPPY_PATH/.libs/libsnappy.a\"" >> build_config.mk

case "$TARGET_OS" in
	CYGWIN*|FreeBSD|Linux|OS_ANDROID_CROSSCOMPILE)
	;;
	*)
		echo "CLIBS += \"$JEMALLOC_PATH/lib/libjemalloc.a\"" >> build_config.mk
		echo "CFLAGS += -I \"$JEMALLOC_PATH/include\"" >> build_config.mk
	;;
esac

echo "CLIBS += ${PLATFORM_CLIBS}" >> build_config.mk


if test -z "$TMPDIR"; then
    TMPDIR=/tmp
fi

g++ -x c++ - -o $TMPDIR/ssdb_build_test.$$ 2>/dev/null <<EOF
	#include <unordered_map>
	int main() {}
EOF
if [ "$?" = 0 ]; then
	echo "CFLAGS += -DNEW_MAC" >> build_config.mk
fi

