/********************************
 * Author: ideawu
 * Link: http://www.ideawu.net/
 ********************************/

tree grammar Eval;

options {
    language=Python;
    tokenVocab=Expr;
    ASTLabelType=CommonTree;
}

@header{
	from engine import CpyBuilder
}

@init{
}

prog[cpy]
	@init{
		self.cpy = cpy
	}
	@after{
		self.cpy.close()
	}
	: stmt*
	;

stmt
	: import_stmt
	| exec_stmt
	| print_stmt | printf_stmt
	| break_stmt
	| continue_stmt
	| return_stmt
	| if_stmt
	| while_stmt
	| do_while_stmt
	| switch_stmt
	| throw_stmt
	| try_stmt
	| func_decl
	| class_decl
	| for_stmt
	| foreach_stmt
	;

/***** statements *****/

block
	@init{
		self.cpy.block_enter()
	}
	@after{
		self.cpy.block_leave()
	}
	: ^(BLOCK stmt*)
	;

import_stmt
	: ^(IMPORT
		( a=module
			{self.cpy.op_import($a.text, None)}
		| b=module '.*'
			{self.cpy.op_import($b.text, '*')}
		)+
		)
	;

exec_stmt
	: ^(EXEC_STMT exec_list)
		{self.cpy.stmt($exec_list.text)}
	;
exec_expr returns[text]
	: member_expr
		{$text = $member_expr.text}
	| ^(ASSIGN member_expr op=('='|'+='|'-='|'*='|'/='|'%='|'&='|'^='|'|=') expr)
		{$text = self.cpy.op_assign($member_expr.text, $expr.text, $op.text)}
	| ^(POST_INC member_expr)
		{$text = self.cpy.op_inc($member_expr.text)}
	| ^(POST_DEC member_expr)
		{$text = self.cpy.op_dec($member_expr.text)}
	| ^(PRE_INC member_expr)
		{$text = self.cpy.op_inc($member_expr.text)}
	| ^(PRE_DEC member_expr)
		{$text = self.cpy.op_dec($member_expr.text)}
	;
exec_list returns[text]
	@init{ps = []}
	: ^(EXEC_LIST (exec_expr {ps.append($exec_expr.text)} ) +)
		{$text = ', '.join(ps)}
	;

printf_stmt
	: ^(PRINTF expr expr_list?)
		{self.cpy.op_printf($expr.text, $expr_list.text)}
	;
print_stmt
	: ^(PRINT expr_list)
		{self.cpy.op_print($expr_list.text)}
	//: ^(PRINT (expr {self.cpy.op_print($expr.text)} )+)
	//	{self.cpy.op_print_leave()}
	;

break_stmt
	: BREAK
		{self.cpy.op_break()}
	;
continue_stmt
	: CONTINUE
		{self.cpy.op_continue()}
	;
return_stmt
	: ^(RETURN expr?)
		{self.cpy.op_return($expr.text)}
	;


if_stmt
	@init{
		self.cpy.if_enter()
	}
	@after{
		self.cpy.if_leave()
	}
	: if_clause else_if_clause* else_clause?
	;
if_clause
	: ^(IF expr {self.cpy.op_if($expr.text)} block)
	;
else_if_clause
	: ^(ELSE_IF {self.cpy.op_else_if()} if_clause)
	;
else_clause
	: ^(ELSE {self.cpy.op_else()} block)
	;


while_stmt
	: ^(WHILE expr {self.cpy.op_while($expr.text)} block)
	;

do_while_stmt
	: ^(DO_WHILE {self.cpy.op_do_while_enter()}
		block
		expr {self.cpy.op_do_while_leave($expr.text)}
		)
	;


switch_stmt
	: ^(SWITCH expr {self.cpy.op_switch_enter($expr.text)} case_block)
		{self.cpy.op_switch_leave()}
	;
case_block
	: '{' (case_clause)+ (default_clause)? '}'
	;
case_clause
	@init{self.cpy.op_case_enter()}
	: ^(CASE case_test+ {self.cpy.op_case()} stmt* break_stmt)
		{self.cpy.op_case_leave()}
	;
case_test
	: ^(CASE expr)
		{self.cpy.op_case_test($expr.text)}
	;
default_clause
	@init{
		self.cpy.op_default_enter()
	}
	: ^(DEFAULT stmt*)
		{self.cpy.op_default_leave()}
	;


for_stmt
	: ^(FOR (a=exec_list {self.cpy.stmt($a.text)})?
		expr {self.cpy.op_while($expr.text)}
		block
		{self.cpy.block_enter()}
		(b=exec_list {self.cpy.stmt($b.text)})?
		{self.cpy.block_leave()}
		)
	;
// for in 是一种 trackback 结构, 而 foreach as 不是
foreach_stmt
	: ^(FOREACH expr
		( ^(EACH k=ID v=each_val)
			{self.cpy.op_foreach($expr.text, $k.text, $v.text)}
		| ^(EACH v=each_val)
			{self.cpy.op_foreach($expr.text, None, $v.text)}
		)
		block
		)
	;
each_val returns[text]
	@init{ps = []}
	: ^(EACH_VAL (ID {ps.append($ID.text)} )+)
		{$text = ','.join(ps)}
	;


throw_stmt
	: ^(THROW expr)
		{self.cpy.op_throw($expr.text)}
	;
try_stmt
	@init{self.cpy.op_try()}
	: ^(TRY block catch_clause+ finally_clause?)
	;
catch_clause
	: ^(CATCH module ID?
		{self.cpy.op_catch($module.text, $ID.text)}
		block)
	;
finally_clause
	@init{self.cpy.op_finally()}
	: ^(FINALLY block)
	;


func_decl
	: ^(FUNCTION ID params
		{self.cpy.op_function($ID.text, $params.text)}
		block
		)
	;
params returns[text]
	@init{ps = []}
	: ^(PARAMS (param_decl {ps.append($param_decl.text)} ) *)
		{$text = ', '.join(ps)}
	;
param_decl returns[text]
	: ID
		{$text = $ID.text}
		('=' atom
			{$text += ('=' + $atom.text)}
		)?
	;


class_decl
	@after{self.cpy.op_class_leave()}
	: ^(CLASS a=ID
			{self.cpy.op_class_enter($a.text, None)}
		class_element*)
	| ^(CLASS b=ID c=ID
			{self.cpy.op_class_enter($b.text, $c.text)}
		class_element*)
	;
class_element
	: var_def | constructor | func_decl
	;
var_def
	: ^(VAR ID expr?)
		{self.cpy.op_var_def(False, $ID.text, $expr.text)}
	| ^(VAR 'static' ID expr?)
		{self.cpy.op_var_def(True, $ID.text, $expr.text)}
	;
constructor
	: ^(CONSTRUCTOR params
		{self.cpy.op_construct($params.text)}
		block)
	;


/***** expressions *****/
module returns[text]
	@init{ps = []}
	: ^(MODULE (ID {ps.append($ID.text)} ) +)
		{$text = '.'.join(ps)}
	;

member_expr returns[text]
	@init{ps = []}
	: ^(MEMBER (primary {ps.append($primary.text)} ) +)
		{$text = '.'.join(ps)}
	;
primary returns[text]
	@init{a=''}
	: ID (index_expr{a += $index_expr.text})*
		call_expr?
		{
		b = $call_expr.text
		if b == None: b = ''
		$text = $ID.text + a + b
		}
	;
call_expr returns[text]
	: ^(CALL expr_list?)
		{
		s = $expr_list.text
		if s == None: s = ''
		$text = '(' + s + ')'
		}
	;
index_expr returns[text]
	: ^(INDEX expr)
		{$text = '[' + $expr.text + ']'}
	| ^(SLICE a=expr b=expr?)
		{
		s = $b.text
		if s == None: s = ''
		$text = '[\%s : \%s]' \%($a.text, s)
		}
	;


expr_list returns[text]
	@init{ps = []}
	: ^(EXPR_LIST (expr {ps.append($expr.text)} )+)
		{
		$text = ', '.join(ps)
		}
	;

expr returns[text]
	: a=relation_expr	{$text = $a.text}
	| a=logic_or_expr	{$text = $a.text}
	| a=logic_and_expr	{$text = $a.text}
	| a=bitwise_or_expr	{$text = $a.text}
	| a=bitwise_xor_expr	{$text = $a.text}
	| a=bitwise_and_expr	{$text = $a.text}
	| a=add_expr		{$text = $a.text}
	| a=mul_expr		{$text = $a.text}
	| a=not_expr		{$text = $a.text}
	| a=negative_expr	{$text = $a.text}
	| a=atom			{$text = $a.text}
	;
logic_or_expr returns[text]
	: ^('||' b=expr c=expr)
		{$text = '(' + $b.text + ' or ' + $c.text + ')'}
	;
logic_and_expr returns[text]
	: ^('&&' b=expr c=expr)
		{$text = $b.text + ' and ' + $c.text}
	;
bitwise_or_expr returns[text]
	: ^('|' b=expr c=expr)
		{$text = $b.text + ' | ' + $c.text}
	;
bitwise_xor_expr returns[text]
	: ^('^' b=expr c=expr)
		{$text = $b.text + ' ^ ' + $c.text}
	;
bitwise_and_expr returns[text]
	: ^('&' b=expr c=expr)
		{$text = $b.text + ' & ' + $c.text}
	;
relation_expr returns[text]
	: ^(op=('<'|'>'|'<='|'>='|'=='|'!=') b=expr c=expr)
		{$text = $b.text + $op.text + $c.text}
	;
add_expr returns[text]
	: ^(op=('+'|'-') b=expr c=expr)
		{$text = '(' + $b.text + ' ' + $op.text + ' ' + $c.text + ')'}
	;
mul_expr returns[text]
	: ^(op=('*'|'/'|'%') b=expr c=expr)
		{$text = $b.text + ' ' + $op.text + ' ' + $c.text}
	;
not_expr returns[text]
	: ^('!' a=expr)
		{$text = 'not (' + $a.text + ')'}
	;
negative_expr returns[text]
	: ^(NEGATIVE a=expr)
		{$text = '- (' + $a.text + ')'}
	;


sprintf returns[text]
	: ^(SPRINTF expr a=expr_list?)
		{
		s = $a.text
		if not s: s=''
		$text = $expr.text + '\%(' + s + ')'
		}
	;

new_clause returns[text]
	: ^(NEW module call_expr)
		{$text = $module.text + $call_expr.text}
	;

array_decl returns[text]
	: ^(ARRAY expr_list?)
		{
		s = $expr_list.text
		if s == None: s = ''
		$text = '[' + s + ']'
		}
	;
object_decl returns[text]
	@init{s = ''}
	: ^(OBJECT (property {s += $property.text} )*)
		{$text = '{' + s + '}'}
	;
property returns[text]
	: a=(ID | STRING | INT) ':' expr
		{$text = $a.text + ': ' + $expr.text + ','}
	;


atom returns[text]
	: a=literal		{$text = $a.text}
	| a=member_expr	{$text = $a.text}
	| a=new_clause	{$text = $a.text}
	| a=array_decl	{$text = $a.text}
	| a=object_decl	{$text = $a.text}
	| a=sprintf		{$text = $a.text}
	;
literal returns[text]
	: NULL {$text = 'None'}
	| BOOL {$text = $BOOL.text.capitalize()}
	| INT {$text = $INT.text}
	| FLOAT {$text = $FLOAT.text}
	| STRING {$text = $STRING.text}
	;
