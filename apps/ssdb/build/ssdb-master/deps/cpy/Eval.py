# $ANTLR 3.4 Eval.g 2012-12-09 16:07:29

import sys
from antlr3 import *
from antlr3.tree import *

from antlr3.compat import set, frozenset

       
from engine import CpyBuilder



# for convenience in actions
HIDDEN = BaseRecognizer.HIDDEN

# token types
EOF=-1
T__68=68
T__69=69
T__70=70
T__71=71
T__72=72
T__73=73
T__74=74
T__75=75
T__76=76
T__77=77
T__78=78
T__79=79
T__80=80
T__81=81
T__82=82
T__83=83
T__84=84
T__85=85
T__86=86
T__87=87
T__88=88
T__89=89
T__90=90
T__91=91
T__92=92
T__93=93
T__94=94
T__95=95
T__96=96
T__97=97
T__98=98
T__99=99
T__100=100
T__101=101
T__102=102
T__103=103
T__104=104
T__105=105
T__106=106
T__107=107
T__108=108
T__109=109
T__110=110
T__111=111
T__112=112
T__113=113
T__114=114
T__115=115
T__116=116
T__117=117
T__118=118
T__119=119
T__120=120
T__121=121
T__122=122
T__123=123
T__124=124
T__125=125
T__126=126
T__127=127
T__128=128
T__129=129
T__130=130
T__131=131
T__132=132
T__133=133
T__134=134
T__135=135
T__136=136
ALPHA=4
ARRAY=5
ASSIGN=6
BLOCK=7
BOOL=8
BREAK=9
CALL=10
CASE=11
CATCH=12
CLASS=13
COMMENT=14
CONSTRUCTOR=15
CONTINUE=16
DEFAULT=17
DIGIT=18
DOUBLE_QUOTE_CHARS=19
DO_WHILE=20
EACH=21
EACH_VAL=22
ELSE=23
ELSE_IF=24
EMPTY_LINE=25
EXEC_LIST=26
EXEC_STMT=27
EXPR_LIST=28
FINALLY=29
FLOAT=30
FOR=31
FOREACH=32
FUNCTION=33
ID=34
ID_LIST=35
IF=36
IMPORT=37
INDEX=38
INT=39
LINECOMMENT=40
MEMBER=41
MODULE=42
NEGATIVE=43
NEW=44
NEWLINE=45
NOP=46
NULL=47
OBJECT=48
OP_ASSIGN=49
PARAMS=50
POST_DEC=51
POST_INC=52
PRE_DEC=53
PRE_INC=54
PRINT=55
PRINTF=56
RETURN=57
SINGLE_QUOTE_CHARS=58
SLICE=59
SPRINTF=60
STRING=61
SWITCH=62
THROW=63
TRY=64
VAR=65
WHILE=66
WS=67

# token names
tokenNames = [
    "<invalid>", "<EOR>", "<DOWN>", "<UP>",
    "ALPHA", "ARRAY", "ASSIGN", "BLOCK", "BOOL", "BREAK", "CALL", "CASE", 
    "CATCH", "CLASS", "COMMENT", "CONSTRUCTOR", "CONTINUE", "DEFAULT", "DIGIT", 
    "DOUBLE_QUOTE_CHARS", "DO_WHILE", "EACH", "EACH_VAL", "ELSE", "ELSE_IF", 
    "EMPTY_LINE", "EXEC_LIST", "EXEC_STMT", "EXPR_LIST", "FINALLY", "FLOAT", 
    "FOR", "FOREACH", "FUNCTION", "ID", "ID_LIST", "IF", "IMPORT", "INDEX", 
    "INT", "LINECOMMENT", "MEMBER", "MODULE", "NEGATIVE", "NEW", "NEWLINE", 
    "NOP", "NULL", "OBJECT", "OP_ASSIGN", "PARAMS", "POST_DEC", "POST_INC", 
    "PRE_DEC", "PRE_INC", "PRINT", "PRINTF", "RETURN", "SINGLE_QUOTE_CHARS", 
    "SLICE", "SPRINTF", "STRING", "SWITCH", "THROW", "TRY", "VAR", "WHILE", 
    "WS", "'!'", "'!='", "'%'", "'%='", "'&&'", "'&'", "'&='", "'('", "')'", 
    "'*'", "'*='", "'+'", "'++'", "'+='", "','", "'-'", "'--'", "'-='", 
    "'.'", "'.*'", "'..'", "'/'", "'/='", "':'", "';'", "'<'", "'<='", "'='", 
    "'=='", "'=>'", "'>'", "'>='", "'['", "']'", "'^'", "'^='", "'as'", 
    "'break'", "'case'", "'catch'", "'class'", "'continue'", "'default'", 
    "'do'", "'else'", "'extends'", "'finally'", "'for'", "'foreach'", "'function'", 
    "'if'", "'import'", "'init'", "'new'", "'print'", "'printf'", "'public'", 
    "'return'", "'sprintf'", "'static'", "'switch'", "'throw'", "'try'", 
    "'while'", "'{'", "'|'", "'|='", "'||'", "'}'"
]




class Eval(TreeParser):
    grammarFileName = "Eval.g"
    api_version = 1
    tokenNames = tokenNames

    def __init__(self, input, state=None, *args, **kwargs):
        if state is None:
            state = RecognizerSharedState()

        super(Eval, self).__init__(input, state, *args, **kwargs)

        self.dfa4 = self.DFA4(
            self, 4,
            eot = self.DFA4_eot,
            eof = self.DFA4_eof,
            min = self.DFA4_min,
            max = self.DFA4_max,
            accept = self.DFA4_accept,
            special = self.DFA4_special,
            transition = self.DFA4_transition
            )



             


        self.delegates = []






    # $ANTLR start "prog"
    # Eval.g:21:1: prog[cpy] : ( stmt )* ;
    def prog(self, cpy):
              
        self.cpy = cpy
        	
        try:
            try:
                # Eval.g:28:2: ( ( stmt )* )
                # Eval.g:28:4: ( stmt )*
                pass 
                # Eval.g:28:4: ( stmt )*
                while True: #loop1
                    alt1 = 2
                    LA1_0 = self.input.LA(1)

                    if (LA1_0 == BREAK or LA1_0 == CLASS or LA1_0 == CONTINUE or LA1_0 == DO_WHILE or LA1_0 == EXEC_STMT or (FOR <= LA1_0 <= FUNCTION) or (IF <= LA1_0 <= IMPORT) or (PRINT <= LA1_0 <= RETURN) or (SWITCH <= LA1_0 <= TRY) or LA1_0 == WHILE) :
                        alt1 = 1


                    if alt1 == 1:
                        # Eval.g:28:4: stmt
                        pass 
                        self._state.following.append(self.FOLLOW_stmt_in_prog69)
                        self.stmt()

                        self._state.following.pop()


                    else:
                        break #loop1




                #action start
                       
                self.cpy.close()
                	
                #action end


            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)

        finally:
            pass
        return 

    # $ANTLR end "prog"



    # $ANTLR start "stmt"
    # Eval.g:31:1: stmt : ( import_stmt | exec_stmt | print_stmt | printf_stmt | break_stmt | continue_stmt | return_stmt | if_stmt | while_stmt | do_while_stmt | switch_stmt | throw_stmt | try_stmt | func_decl | class_decl | for_stmt | foreach_stmt );
    def stmt(self, ):
        try:
            try:
                # Eval.g:32:2: ( import_stmt | exec_stmt | print_stmt | printf_stmt | break_stmt | continue_stmt | return_stmt | if_stmt | while_stmt | do_while_stmt | switch_stmt | throw_stmt | try_stmt | func_decl | class_decl | for_stmt | foreach_stmt )
                alt2 = 17
                LA2 = self.input.LA(1)
                if LA2 == IMPORT:
                    alt2 = 1
                elif LA2 == EXEC_STMT:
                    alt2 = 2
                elif LA2 == PRINT:
                    alt2 = 3
                elif LA2 == PRINTF:
                    alt2 = 4
                elif LA2 == BREAK:
                    alt2 = 5
                elif LA2 == CONTINUE:
                    alt2 = 6
                elif LA2 == RETURN:
                    alt2 = 7
                elif LA2 == IF:
                    alt2 = 8
                elif LA2 == WHILE:
                    alt2 = 9
                elif LA2 == DO_WHILE:
                    alt2 = 10
                elif LA2 == SWITCH:
                    alt2 = 11
                elif LA2 == THROW:
                    alt2 = 12
                elif LA2 == TRY:
                    alt2 = 13
                elif LA2 == FUNCTION:
                    alt2 = 14
                elif LA2 == CLASS:
                    alt2 = 15
                elif LA2 == FOR:
                    alt2 = 16
                elif LA2 == FOREACH:
                    alt2 = 17
                else:
                    nvae = NoViableAltException("", 2, 0, self.input)

                    raise nvae


                if alt2 == 1:
                    # Eval.g:32:4: import_stmt
                    pass 
                    self._state.following.append(self.FOLLOW_import_stmt_in_stmt81)
                    self.import_stmt()

                    self._state.following.pop()


                elif alt2 == 2:
                    # Eval.g:33:4: exec_stmt
                    pass 
                    self._state.following.append(self.FOLLOW_exec_stmt_in_stmt86)
                    self.exec_stmt()

                    self._state.following.pop()


                elif alt2 == 3:
                    # Eval.g:34:4: print_stmt
                    pass 
                    self._state.following.append(self.FOLLOW_print_stmt_in_stmt91)
                    self.print_stmt()

                    self._state.following.pop()


                elif alt2 == 4:
                    # Eval.g:34:17: printf_stmt
                    pass 
                    self._state.following.append(self.FOLLOW_printf_stmt_in_stmt95)
                    self.printf_stmt()

                    self._state.following.pop()


                elif alt2 == 5:
                    # Eval.g:35:4: break_stmt
                    pass 
                    self._state.following.append(self.FOLLOW_break_stmt_in_stmt100)
                    self.break_stmt()

                    self._state.following.pop()


                elif alt2 == 6:
                    # Eval.g:36:4: continue_stmt
                    pass 
                    self._state.following.append(self.FOLLOW_continue_stmt_in_stmt105)
                    self.continue_stmt()

                    self._state.following.pop()


                elif alt2 == 7:
                    # Eval.g:37:4: return_stmt
                    pass 
                    self._state.following.append(self.FOLLOW_return_stmt_in_stmt110)
                    self.return_stmt()

                    self._state.following.pop()


                elif alt2 == 8:
                    # Eval.g:38:4: if_stmt
                    pass 
                    self._state.following.append(self.FOLLOW_if_stmt_in_stmt115)
                    self.if_stmt()

                    self._state.following.pop()


                elif alt2 == 9:
                    # Eval.g:39:4: while_stmt
                    pass 
                    self._state.following.append(self.FOLLOW_while_stmt_in_stmt120)
                    self.while_stmt()

                    self._state.following.pop()


                elif alt2 == 10:
                    # Eval.g:40:4: do_while_stmt
                    pass 
                    self._state.following.append(self.FOLLOW_do_while_stmt_in_stmt125)
                    self.do_while_stmt()

                    self._state.following.pop()


                elif alt2 == 11:
                    # Eval.g:41:4: switch_stmt
                    pass 
                    self._state.following.append(self.FOLLOW_switch_stmt_in_stmt130)
                    self.switch_stmt()

                    self._state.following.pop()


                elif alt2 == 12:
                    # Eval.g:42:4: throw_stmt
                    pass 
                    self._state.following.append(self.FOLLOW_throw_stmt_in_stmt135)
                    self.throw_stmt()

                    self._state.following.pop()


                elif alt2 == 13:
                    # Eval.g:43:4: try_stmt
                    pass 
                    self._state.following.append(self.FOLLOW_try_stmt_in_stmt140)
                    self.try_stmt()

                    self._state.following.pop()


                elif alt2 == 14:
                    # Eval.g:44:4: func_decl
                    pass 
                    self._state.following.append(self.FOLLOW_func_decl_in_stmt145)
                    self.func_decl()

                    self._state.following.pop()


                elif alt2 == 15:
                    # Eval.g:45:4: class_decl
                    pass 
                    self._state.following.append(self.FOLLOW_class_decl_in_stmt150)
                    self.class_decl()

                    self._state.following.pop()


                elif alt2 == 16:
                    # Eval.g:46:4: for_stmt
                    pass 
                    self._state.following.append(self.FOLLOW_for_stmt_in_stmt155)
                    self.for_stmt()

                    self._state.following.pop()


                elif alt2 == 17:
                    # Eval.g:47:4: foreach_stmt
                    pass 
                    self._state.following.append(self.FOLLOW_foreach_stmt_in_stmt160)
                    self.foreach_stmt()

                    self._state.following.pop()



            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)

        finally:
            pass
        return 

    # $ANTLR end "stmt"



    # $ANTLR start "block"
    # Eval.g:52:1: block : ^( BLOCK ( stmt )* ) ;
    def block(self, ):
              
        self.cpy.block_enter()
        	
        try:
            try:
                # Eval.g:59:2: ( ^( BLOCK ( stmt )* ) )
                # Eval.g:59:4: ^( BLOCK ( stmt )* )
                pass 
                self.match(self.input, BLOCK, self.FOLLOW_BLOCK_in_block185)

                if self.input.LA(1) == DOWN:
                    self.match(self.input, DOWN, None)
                    # Eval.g:59:12: ( stmt )*
                    while True: #loop3
                        alt3 = 2
                        LA3_0 = self.input.LA(1)

                        if (LA3_0 == BREAK or LA3_0 == CLASS or LA3_0 == CONTINUE or LA3_0 == DO_WHILE or LA3_0 == EXEC_STMT or (FOR <= LA3_0 <= FUNCTION) or (IF <= LA3_0 <= IMPORT) or (PRINT <= LA3_0 <= RETURN) or (SWITCH <= LA3_0 <= TRY) or LA3_0 == WHILE) :
                            alt3 = 1


                        if alt3 == 1:
                            # Eval.g:59:12: stmt
                            pass 
                            self._state.following.append(self.FOLLOW_stmt_in_block187)
                            self.stmt()

                            self._state.following.pop()


                        else:
                            break #loop3


                    self.match(self.input, UP, None)





                #action start
                       
                self.cpy.block_leave()
                	
                #action end


            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)

        finally:
            pass
        return 

    # $ANTLR end "block"



    # $ANTLR start "import_stmt"
    # Eval.g:62:1: import_stmt : ^( IMPORT (a= module |b= module '.*' )+ ) ;
    def import_stmt(self, ):
        a = None

        b = None


        try:
            try:
                # Eval.g:63:2: ( ^( IMPORT (a= module |b= module '.*' )+ ) )
                # Eval.g:63:4: ^( IMPORT (a= module |b= module '.*' )+ )
                pass 
                self.match(self.input, IMPORT, self.FOLLOW_IMPORT_in_import_stmt201)

                self.match(self.input, DOWN, None)
                # Eval.g:64:3: (a= module |b= module '.*' )+
                cnt4 = 0
                while True: #loop4
                    alt4 = 3
                    alt4 = self.dfa4.predict(self.input)
                    if alt4 == 1:
                        # Eval.g:64:5: a= module
                        pass 
                        self._state.following.append(self.FOLLOW_module_in_import_stmt209)
                        a = self.module()

                        self._state.following.pop()

                        #action start
                        self.cpy.op_import(a, None)
                        #action end



                    elif alt4 == 2:
                        # Eval.g:66:5: b= module '.*'
                        pass 
                        self._state.following.append(self.FOLLOW_module_in_import_stmt222)
                        b = self.module()

                        self._state.following.pop()

                        self.match(self.input, 87, self.FOLLOW_87_in_import_stmt224)

                        #action start
                        self.cpy.op_import(b, '*')
                        #action end



                    else:
                        if cnt4 >= 1:
                            break #loop4

                        eee = EarlyExitException(4, self.input)
                        raise eee

                    cnt4 += 1


                self.match(self.input, UP, None)





            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)

        finally:
            pass
        return 

    # $ANTLR end "import_stmt"



    # $ANTLR start "exec_stmt"
    # Eval.g:72:1: exec_stmt : ^( EXEC_STMT exec_list ) ;
    def exec_stmt(self, ):
        exec_list1 = None


        try:
            try:
                # Eval.g:73:2: ( ^( EXEC_STMT exec_list ) )
                # Eval.g:73:4: ^( EXEC_STMT exec_list )
                pass 
                self.match(self.input, EXEC_STMT, self.FOLLOW_EXEC_STMT_in_exec_stmt250)

                self.match(self.input, DOWN, None)
                self._state.following.append(self.FOLLOW_exec_list_in_exec_stmt252)
                exec_list1 = self.exec_list()

                self._state.following.pop()

                self.match(self.input, UP, None)


                #action start
                self.cpy.stmt(exec_list1)
                #action end





            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)

        finally:
            pass
        return 

    # $ANTLR end "exec_stmt"



    # $ANTLR start "exec_expr"
    # Eval.g:76:1: exec_expr returns [text] : ( member_expr | ^( ASSIGN member_expr op= ( '=' | '+=' | '-=' | '*=' | '/=' | '%=' | '&=' | '^=' | '|=' ) expr ) | ^( POST_INC member_expr ) | ^( POST_DEC member_expr ) | ^( PRE_INC member_expr ) | ^( PRE_DEC member_expr ) );
    def exec_expr(self, ):
        text = None


        op = None
        member_expr2 = None

        member_expr3 = None

        expr4 = None

        member_expr5 = None

        member_expr6 = None

        member_expr7 = None

        member_expr8 = None


        try:
            try:
                # Eval.g:77:2: ( member_expr | ^( ASSIGN member_expr op= ( '=' | '+=' | '-=' | '*=' | '/=' | '%=' | '&=' | '^=' | '|=' ) expr ) | ^( POST_INC member_expr ) | ^( POST_DEC member_expr ) | ^( PRE_INC member_expr ) | ^( PRE_DEC member_expr ) )
                alt5 = 6
                LA5 = self.input.LA(1)
                if LA5 == MEMBER:
                    alt5 = 1
                elif LA5 == ASSIGN:
                    alt5 = 2
                elif LA5 == POST_INC:
                    alt5 = 3
                elif LA5 == POST_DEC:
                    alt5 = 4
                elif LA5 == PRE_INC:
                    alt5 = 5
                elif LA5 == PRE_DEC:
                    alt5 = 6
                else:
                    nvae = NoViableAltException("", 5, 0, self.input)

                    raise nvae


                if alt5 == 1:
                    # Eval.g:77:4: member_expr
                    pass 
                    self._state.following.append(self.FOLLOW_member_expr_in_exec_expr270)
                    member_expr2 = self.member_expr()

                    self._state.following.pop()

                    #action start
                    text = member_expr2
                    #action end



                elif alt5 == 2:
                    # Eval.g:79:4: ^( ASSIGN member_expr op= ( '=' | '+=' | '-=' | '*=' | '/=' | '%=' | '&=' | '^=' | '|=' ) expr )
                    pass 
                    self.match(self.input, ASSIGN, self.FOLLOW_ASSIGN_in_exec_expr280)

                    self.match(self.input, DOWN, None)
                    self._state.following.append(self.FOLLOW_member_expr_in_exec_expr282)
                    member_expr3 = self.member_expr()

                    self._state.following.pop()

                    op = self.input.LT(1)

                    if self.input.LA(1) == 71 or self.input.LA(1) == 74 or self.input.LA(1) == 78 or self.input.LA(1) == 81 or self.input.LA(1) == 85 or self.input.LA(1) == 90 or self.input.LA(1) == 95 or self.input.LA(1) == 103 or self.input.LA(1) == 134:
                        self.input.consume()
                        self._state.errorRecovery = False


                    else:
                        mse = MismatchedSetException(None, self.input)
                        raise mse



                    self._state.following.append(self.FOLLOW_expr_in_exec_expr306)
                    expr4 = self.expr()

                    self._state.following.pop()

                    self.match(self.input, UP, None)


                    #action start
                    text = self.cpy.op_assign(member_expr3, expr4, op.text)
                    #action end



                elif alt5 == 3:
                    # Eval.g:81:4: ^( POST_INC member_expr )
                    pass 
                    self.match(self.input, POST_INC, self.FOLLOW_POST_INC_in_exec_expr317)

                    self.match(self.input, DOWN, None)
                    self._state.following.append(self.FOLLOW_member_expr_in_exec_expr319)
                    member_expr5 = self.member_expr()

                    self._state.following.pop()

                    self.match(self.input, UP, None)


                    #action start
                    text = self.cpy.op_inc(member_expr5)
                    #action end



                elif alt5 == 4:
                    # Eval.g:83:4: ^( POST_DEC member_expr )
                    pass 
                    self.match(self.input, POST_DEC, self.FOLLOW_POST_DEC_in_exec_expr330)

                    self.match(self.input, DOWN, None)
                    self._state.following.append(self.FOLLOW_member_expr_in_exec_expr332)
                    member_expr6 = self.member_expr()

                    self._state.following.pop()

                    self.match(self.input, UP, None)


                    #action start
                    text = self.cpy.op_dec(member_expr6)
                    #action end



                elif alt5 == 5:
                    # Eval.g:85:4: ^( PRE_INC member_expr )
                    pass 
                    self.match(self.input, PRE_INC, self.FOLLOW_PRE_INC_in_exec_expr343)

                    self.match(self.input, DOWN, None)
                    self._state.following.append(self.FOLLOW_member_expr_in_exec_expr345)
                    member_expr7 = self.member_expr()

                    self._state.following.pop()

                    self.match(self.input, UP, None)


                    #action start
                    text = self.cpy.op_inc(member_expr7)
                    #action end



                elif alt5 == 6:
                    # Eval.g:87:4: ^( PRE_DEC member_expr )
                    pass 
                    self.match(self.input, PRE_DEC, self.FOLLOW_PRE_DEC_in_exec_expr356)

                    self.match(self.input, DOWN, None)
                    self._state.following.append(self.FOLLOW_member_expr_in_exec_expr358)
                    member_expr8 = self.member_expr()

                    self._state.following.pop()

                    self.match(self.input, UP, None)


                    #action start
                    text = self.cpy.op_dec(member_expr8)
                    #action end




            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)

        finally:
            pass
        return text

    # $ANTLR end "exec_expr"



    # $ANTLR start "exec_list"
    # Eval.g:90:1: exec_list returns [text] : ^( EXEC_LIST ( exec_expr )+ ) ;
    def exec_list(self, ):
        text = None


        exec_expr9 = None


        ps = []
        try:
            try:
                # Eval.g:92:2: ( ^( EXEC_LIST ( exec_expr )+ ) )
                # Eval.g:92:4: ^( EXEC_LIST ( exec_expr )+ )
                pass 
                self.match(self.input, EXEC_LIST, self.FOLLOW_EXEC_LIST_in_exec_list382)

                self.match(self.input, DOWN, None)
                # Eval.g:92:16: ( exec_expr )+
                cnt6 = 0
                while True: #loop6
                    alt6 = 2
                    LA6_0 = self.input.LA(1)

                    if (LA6_0 == ASSIGN or LA6_0 == MEMBER or (POST_DEC <= LA6_0 <= PRE_INC)) :
                        alt6 = 1


                    if alt6 == 1:
                        # Eval.g:92:17: exec_expr
                        pass 
                        self._state.following.append(self.FOLLOW_exec_expr_in_exec_list385)
                        exec_expr9 = self.exec_expr()

                        self._state.following.pop()

                        #action start
                        ps.append(exec_expr9)
                        #action end



                    else:
                        if cnt6 >= 1:
                            break #loop6

                        eee = EarlyExitException(6, self.input)
                        raise eee

                    cnt6 += 1


                self.match(self.input, UP, None)


                #action start
                text = ', '.join(ps)
                #action end





            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)

        finally:
            pass
        return text

    # $ANTLR end "exec_list"



    # $ANTLR start "printf_stmt"
    # Eval.g:96:1: printf_stmt : ^( PRINTF expr ( expr_list )? ) ;
    def printf_stmt(self, ):
        expr10 = None

        expr_list11 = None


        try:
            try:
                # Eval.g:97:2: ( ^( PRINTF expr ( expr_list )? ) )
                # Eval.g:97:4: ^( PRINTF expr ( expr_list )? )
                pass 
                self.match(self.input, PRINTF, self.FOLLOW_PRINTF_in_printf_stmt408)

                self.match(self.input, DOWN, None)
                self._state.following.append(self.FOLLOW_expr_in_printf_stmt410)
                expr10 = self.expr()

                self._state.following.pop()

                # Eval.g:97:18: ( expr_list )?
                alt7 = 2
                LA7_0 = self.input.LA(1)

                if (LA7_0 == EXPR_LIST) :
                    alt7 = 1
                if alt7 == 1:
                    # Eval.g:97:18: expr_list
                    pass 
                    self._state.following.append(self.FOLLOW_expr_list_in_printf_stmt412)
                    expr_list11 = self.expr_list()

                    self._state.following.pop()




                self.match(self.input, UP, None)


                #action start
                self.cpy.op_printf(expr10, expr_list11)
                #action end





            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)

        finally:
            pass
        return 

    # $ANTLR end "printf_stmt"



    # $ANTLR start "print_stmt"
    # Eval.g:100:1: print_stmt : ^( PRINT expr_list ) ;
    def print_stmt(self, ):
        expr_list12 = None


        try:
            try:
                # Eval.g:101:2: ( ^( PRINT expr_list ) )
                # Eval.g:101:4: ^( PRINT expr_list )
                pass 
                self.match(self.input, PRINT, self.FOLLOW_PRINT_in_print_stmt429)

                self.match(self.input, DOWN, None)
                self._state.following.append(self.FOLLOW_expr_list_in_print_stmt431)
                expr_list12 = self.expr_list()

                self._state.following.pop()

                self.match(self.input, UP, None)


                #action start
                self.cpy.op_print(expr_list12)
                #action end





            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)

        finally:
            pass
        return 

    # $ANTLR end "print_stmt"



    # $ANTLR start "break_stmt"
    # Eval.g:107:1: break_stmt : BREAK ;
    def break_stmt(self, ):
        try:
            try:
                # Eval.g:108:2: ( BREAK )
                # Eval.g:108:4: BREAK
                pass 
                self.match(self.input, BREAK, self.FOLLOW_BREAK_in_break_stmt451)

                #action start
                self.cpy.op_break()
                #action end





            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)

        finally:
            pass
        return 

    # $ANTLR end "break_stmt"



    # $ANTLR start "continue_stmt"
    # Eval.g:111:1: continue_stmt : CONTINUE ;
    def continue_stmt(self, ):
        try:
            try:
                # Eval.g:112:2: ( CONTINUE )
                # Eval.g:112:4: CONTINUE
                pass 
                self.match(self.input, CONTINUE, self.FOLLOW_CONTINUE_in_continue_stmt465)

                #action start
                self.cpy.op_continue()
                #action end





            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)

        finally:
            pass
        return 

    # $ANTLR end "continue_stmt"



    # $ANTLR start "return_stmt"
    # Eval.g:115:1: return_stmt : ^( RETURN ( expr )? ) ;
    def return_stmt(self, ):
        expr13 = None


        try:
            try:
                # Eval.g:116:2: ( ^( RETURN ( expr )? ) )
                # Eval.g:116:4: ^( RETURN ( expr )? )
                pass 
                self.match(self.input, RETURN, self.FOLLOW_RETURN_in_return_stmt480)

                if self.input.LA(1) == DOWN:
                    self.match(self.input, DOWN, None)
                    # Eval.g:116:13: ( expr )?
                    alt8 = 2
                    LA8_0 = self.input.LA(1)

                    if (LA8_0 == ARRAY or LA8_0 == BOOL or LA8_0 == FLOAT or LA8_0 == INT or LA8_0 == MEMBER or (NEGATIVE <= LA8_0 <= NEW) or (NULL <= LA8_0 <= OBJECT) or (SPRINTF <= LA8_0 <= STRING) or (68 <= LA8_0 <= 70) or (72 <= LA8_0 <= 73) or LA8_0 == 77 or LA8_0 == 79 or LA8_0 == 83 or LA8_0 == 89 or (93 <= LA8_0 <= 94) or LA8_0 == 96 or (98 <= LA8_0 <= 99) or LA8_0 == 102 or LA8_0 == 133 or LA8_0 == 135) :
                        alt8 = 1
                    if alt8 == 1:
                        # Eval.g:116:13: expr
                        pass 
                        self._state.following.append(self.FOLLOW_expr_in_return_stmt482)
                        expr13 = self.expr()

                        self._state.following.pop()




                    self.match(self.input, UP, None)



                #action start
                self.cpy.op_return(expr13)
                #action end





            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)

        finally:
            pass
        return 

    # $ANTLR end "return_stmt"



    # $ANTLR start "if_stmt"
    # Eval.g:121:1: if_stmt : if_clause ( else_if_clause )* ( else_clause )? ;
    def if_stmt(self, ):
              
        self.cpy.if_enter()
        	
        try:
            try:
                # Eval.g:128:2: ( if_clause ( else_if_clause )* ( else_clause )? )
                # Eval.g:128:4: if_clause ( else_if_clause )* ( else_clause )?
                pass 
                self._state.following.append(self.FOLLOW_if_clause_in_if_stmt510)
                self.if_clause()

                self._state.following.pop()

                # Eval.g:128:14: ( else_if_clause )*
                while True: #loop9
                    alt9 = 2
                    LA9_0 = self.input.LA(1)

                    if (LA9_0 == ELSE_IF) :
                        alt9 = 1


                    if alt9 == 1:
                        # Eval.g:128:14: else_if_clause
                        pass 
                        self._state.following.append(self.FOLLOW_else_if_clause_in_if_stmt512)
                        self.else_if_clause()

                        self._state.following.pop()


                    else:
                        break #loop9


                # Eval.g:128:30: ( else_clause )?
                alt10 = 2
                LA10_0 = self.input.LA(1)

                if (LA10_0 == ELSE) :
                    alt10 = 1
                if alt10 == 1:
                    # Eval.g:128:30: else_clause
                    pass 
                    self._state.following.append(self.FOLLOW_else_clause_in_if_stmt515)
                    self.else_clause()

                    self._state.following.pop()






                #action start
                       
                self.cpy.if_leave()
                	
                #action end


            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)

        finally:
            pass
        return 

    # $ANTLR end "if_stmt"



    # $ANTLR start "if_clause"
    # Eval.g:130:1: if_clause : ^( IF expr block ) ;
    def if_clause(self, ):
        expr14 = None


        try:
            try:
                # Eval.g:131:2: ( ^( IF expr block ) )
                # Eval.g:131:4: ^( IF expr block )
                pass 
                self.match(self.input, IF, self.FOLLOW_IF_in_if_clause527)

                self.match(self.input, DOWN, None)
                self._state.following.append(self.FOLLOW_expr_in_if_clause529)
                expr14 = self.expr()

                self._state.following.pop()

                #action start
                self.cpy.op_if(expr14)
                #action end


                self._state.following.append(self.FOLLOW_block_in_if_clause533)
                self.block()

                self._state.following.pop()

                self.match(self.input, UP, None)





            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)

        finally:
            pass
        return 

    # $ANTLR end "if_clause"



    # $ANTLR start "else_if_clause"
    # Eval.g:133:1: else_if_clause : ^( ELSE_IF if_clause ) ;
    def else_if_clause(self, ):
        try:
            try:
                # Eval.g:134:2: ( ^( ELSE_IF if_clause ) )
                # Eval.g:134:4: ^( ELSE_IF if_clause )
                pass 
                self.match(self.input, ELSE_IF, self.FOLLOW_ELSE_IF_in_else_if_clause545)

                #action start
                self.cpy.op_else_if()
                #action end


                self.match(self.input, DOWN, None)
                self._state.following.append(self.FOLLOW_if_clause_in_else_if_clause549)
                self.if_clause()

                self._state.following.pop()

                self.match(self.input, UP, None)





            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)

        finally:
            pass
        return 

    # $ANTLR end "else_if_clause"



    # $ANTLR start "else_clause"
    # Eval.g:136:1: else_clause : ^( ELSE block ) ;
    def else_clause(self, ):
        try:
            try:
                # Eval.g:137:2: ( ^( ELSE block ) )
                # Eval.g:137:4: ^( ELSE block )
                pass 
                self.match(self.input, ELSE, self.FOLLOW_ELSE_in_else_clause561)

                #action start
                self.cpy.op_else()
                #action end


                self.match(self.input, DOWN, None)
                self._state.following.append(self.FOLLOW_block_in_else_clause565)
                self.block()

                self._state.following.pop()

                self.match(self.input, UP, None)





            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)

        finally:
            pass
        return 

    # $ANTLR end "else_clause"



    # $ANTLR start "while_stmt"
    # Eval.g:141:1: while_stmt : ^( WHILE expr block ) ;
    def while_stmt(self, ):
        expr15 = None


        try:
            try:
                # Eval.g:142:2: ( ^( WHILE expr block ) )
                # Eval.g:142:4: ^( WHILE expr block )
                pass 
                self.match(self.input, WHILE, self.FOLLOW_WHILE_in_while_stmt579)

                self.match(self.input, DOWN, None)
                self._state.following.append(self.FOLLOW_expr_in_while_stmt581)
                expr15 = self.expr()

                self._state.following.pop()

                #action start
                self.cpy.op_while(expr15)
                #action end


                self._state.following.append(self.FOLLOW_block_in_while_stmt585)
                self.block()

                self._state.following.pop()

                self.match(self.input, UP, None)





            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)

        finally:
            pass
        return 

    # $ANTLR end "while_stmt"



    # $ANTLR start "do_while_stmt"
    # Eval.g:145:1: do_while_stmt : ^( DO_WHILE block expr ) ;
    def do_while_stmt(self, ):
        expr16 = None


        try:
            try:
                # Eval.g:146:2: ( ^( DO_WHILE block expr ) )
                # Eval.g:146:4: ^( DO_WHILE block expr )
                pass 
                self.match(self.input, DO_WHILE, self.FOLLOW_DO_WHILE_in_do_while_stmt598)

                #action start
                self.cpy.op_do_while_enter()
                #action end


                self.match(self.input, DOWN, None)
                self._state.following.append(self.FOLLOW_block_in_do_while_stmt604)
                self.block()

                self._state.following.pop()

                self._state.following.append(self.FOLLOW_expr_in_do_while_stmt608)
                expr16 = self.expr()

                self._state.following.pop()

                #action start
                self.cpy.op_do_while_leave(expr16)
                #action end


                self.match(self.input, UP, None)





            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)

        finally:
            pass
        return 

    # $ANTLR end "do_while_stmt"



    # $ANTLR start "switch_stmt"
    # Eval.g:153:1: switch_stmt : ^( SWITCH expr case_block ) ;
    def switch_stmt(self, ):
        expr17 = None


        try:
            try:
                # Eval.g:154:2: ( ^( SWITCH expr case_block ) )
                # Eval.g:154:4: ^( SWITCH expr case_block )
                pass 
                self.match(self.input, SWITCH, self.FOLLOW_SWITCH_in_switch_stmt627)

                self.match(self.input, DOWN, None)
                self._state.following.append(self.FOLLOW_expr_in_switch_stmt629)
                expr17 = self.expr()

                self._state.following.pop()

                #action start
                self.cpy.op_switch_enter(expr17)
                #action end


                self._state.following.append(self.FOLLOW_case_block_in_switch_stmt633)
                self.case_block()

                self._state.following.pop()

                self.match(self.input, UP, None)


                #action start
                self.cpy.op_switch_leave()
                #action end





            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)

        finally:
            pass
        return 

    # $ANTLR end "switch_stmt"



    # $ANTLR start "case_block"
    # Eval.g:157:1: case_block : '{' ( case_clause )+ ( default_clause )? '}' ;
    def case_block(self, ):
        try:
            try:
                # Eval.g:158:2: ( '{' ( case_clause )+ ( default_clause )? '}' )
                # Eval.g:158:4: '{' ( case_clause )+ ( default_clause )? '}'
                pass 
                self.match(self.input, 132, self.FOLLOW_132_in_case_block648)

                # Eval.g:158:8: ( case_clause )+
                cnt11 = 0
                while True: #loop11
                    alt11 = 2
                    LA11_0 = self.input.LA(1)

                    if (LA11_0 == CASE) :
                        alt11 = 1


                    if alt11 == 1:
                        # Eval.g:158:9: case_clause
                        pass 
                        self._state.following.append(self.FOLLOW_case_clause_in_case_block651)
                        self.case_clause()

                        self._state.following.pop()


                    else:
                        if cnt11 >= 1:
                            break #loop11

                        eee = EarlyExitException(11, self.input)
                        raise eee

                    cnt11 += 1


                # Eval.g:158:23: ( default_clause )?
                alt12 = 2
                LA12_0 = self.input.LA(1)

                if (LA12_0 == DEFAULT) :
                    alt12 = 1
                if alt12 == 1:
                    # Eval.g:158:24: default_clause
                    pass 
                    self._state.following.append(self.FOLLOW_default_clause_in_case_block656)
                    self.default_clause()

                    self._state.following.pop()




                self.match(self.input, 136, self.FOLLOW_136_in_case_block660)




            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)

        finally:
            pass
        return 

    # $ANTLR end "case_block"



    # $ANTLR start "case_clause"
    # Eval.g:160:1: case_clause : ^( CASE ( case_test )+ ( stmt )* break_stmt ) ;
    def case_clause(self, ):
        self.cpy.op_case_enter()
        try:
            try:
                # Eval.g:162:2: ( ^( CASE ( case_test )+ ( stmt )* break_stmt ) )
                # Eval.g:162:4: ^( CASE ( case_test )+ ( stmt )* break_stmt )
                pass 
                self.match(self.input, CASE, self.FOLLOW_CASE_in_case_clause676)

                self.match(self.input, DOWN, None)
                # Eval.g:162:11: ( case_test )+
                cnt13 = 0
                while True: #loop13
                    alt13 = 2
                    LA13_0 = self.input.LA(1)

                    if (LA13_0 == CASE) :
                        alt13 = 1


                    if alt13 == 1:
                        # Eval.g:162:11: case_test
                        pass 
                        self._state.following.append(self.FOLLOW_case_test_in_case_clause678)
                        self.case_test()

                        self._state.following.pop()


                    else:
                        if cnt13 >= 1:
                            break #loop13

                        eee = EarlyExitException(13, self.input)
                        raise eee

                    cnt13 += 1


                #action start
                self.cpy.op_case()
                #action end


                # Eval.g:162:43: ( stmt )*
                while True: #loop14
                    alt14 = 2
                    LA14_0 = self.input.LA(1)

                    if (LA14_0 == BREAK) :
                        LA14_1 = self.input.LA(2)

                        if (LA14_1 == BREAK or LA14_1 == CLASS or LA14_1 == CONTINUE or LA14_1 == DO_WHILE or LA14_1 == EXEC_STMT or (FOR <= LA14_1 <= FUNCTION) or (IF <= LA14_1 <= IMPORT) or (PRINT <= LA14_1 <= RETURN) or (SWITCH <= LA14_1 <= TRY) or LA14_1 == WHILE) :
                            alt14 = 1


                    elif (LA14_0 == CLASS or LA14_0 == CONTINUE or LA14_0 == DO_WHILE or LA14_0 == EXEC_STMT or (FOR <= LA14_0 <= FUNCTION) or (IF <= LA14_0 <= IMPORT) or (PRINT <= LA14_0 <= RETURN) or (SWITCH <= LA14_0 <= TRY) or LA14_0 == WHILE) :
                        alt14 = 1


                    if alt14 == 1:
                        # Eval.g:162:43: stmt
                        pass 
                        self._state.following.append(self.FOLLOW_stmt_in_case_clause683)
                        self.stmt()

                        self._state.following.pop()


                    else:
                        break #loop14


                self._state.following.append(self.FOLLOW_break_stmt_in_case_clause686)
                self.break_stmt()

                self._state.following.pop()

                self.match(self.input, UP, None)


                #action start
                self.cpy.op_case_leave()
                #action end





            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)

        finally:
            pass
        return 

    # $ANTLR end "case_clause"



    # $ANTLR start "case_test"
    # Eval.g:165:1: case_test : ^( CASE expr ) ;
    def case_test(self, ):
        expr18 = None


        try:
            try:
                # Eval.g:166:2: ( ^( CASE expr ) )
                # Eval.g:166:4: ^( CASE expr )
                pass 
                self.match(self.input, CASE, self.FOLLOW_CASE_in_case_test702)

                self.match(self.input, DOWN, None)
                self._state.following.append(self.FOLLOW_expr_in_case_test704)
                expr18 = self.expr()

                self._state.following.pop()

                self.match(self.input, UP, None)


                #action start
                self.cpy.op_case_test(expr18)
                #action end





            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)

        finally:
            pass
        return 

    # $ANTLR end "case_test"



    # $ANTLR start "default_clause"
    # Eval.g:169:1: default_clause : ^( DEFAULT ( stmt )* ) ;
    def default_clause(self, ):
              
        self.cpy.op_default_enter()
        	
        try:
            try:
                # Eval.g:173:2: ( ^( DEFAULT ( stmt )* ) )
                # Eval.g:173:4: ^( DEFAULT ( stmt )* )
                pass 
                self.match(self.input, DEFAULT, self.FOLLOW_DEFAULT_in_default_clause725)

                if self.input.LA(1) == DOWN:
                    self.match(self.input, DOWN, None)
                    # Eval.g:173:14: ( stmt )*
                    while True: #loop15
                        alt15 = 2
                        LA15_0 = self.input.LA(1)

                        if (LA15_0 == BREAK or LA15_0 == CLASS or LA15_0 == CONTINUE or LA15_0 == DO_WHILE or LA15_0 == EXEC_STMT or (FOR <= LA15_0 <= FUNCTION) or (IF <= LA15_0 <= IMPORT) or (PRINT <= LA15_0 <= RETURN) or (SWITCH <= LA15_0 <= TRY) or LA15_0 == WHILE) :
                            alt15 = 1


                        if alt15 == 1:
                            # Eval.g:173:14: stmt
                            pass 
                            self._state.following.append(self.FOLLOW_stmt_in_default_clause727)
                            self.stmt()

                            self._state.following.pop()


                        else:
                            break #loop15


                    self.match(self.input, UP, None)



                #action start
                self.cpy.op_default_leave()
                #action end





            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)

        finally:
            pass
        return 

    # $ANTLR end "default_clause"



    # $ANTLR start "for_stmt"
    # Eval.g:178:1: for_stmt : ^( FOR (a= exec_list )? expr block (b= exec_list )? ) ;
    def for_stmt(self, ):
        a = None

        b = None

        expr19 = None


        try:
            try:
                # Eval.g:179:2: ( ^( FOR (a= exec_list )? expr block (b= exec_list )? ) )
                # Eval.g:179:4: ^( FOR (a= exec_list )? expr block (b= exec_list )? )
                pass 
                self.match(self.input, FOR, self.FOLLOW_FOR_in_for_stmt746)

                self.match(self.input, DOWN, None)
                # Eval.g:179:10: (a= exec_list )?
                alt16 = 2
                LA16_0 = self.input.LA(1)

                if (LA16_0 == EXEC_LIST) :
                    alt16 = 1
                if alt16 == 1:
                    # Eval.g:179:11: a= exec_list
                    pass 
                    self._state.following.append(self.FOLLOW_exec_list_in_for_stmt751)
                    a = self.exec_list()

                    self._state.following.pop()

                    #action start
                    self.cpy.stmt(a)
                    #action end





                self._state.following.append(self.FOLLOW_expr_in_for_stmt759)
                expr19 = self.expr()

                self._state.following.pop()

                #action start
                self.cpy.op_while(expr19)
                #action end


                self._state.following.append(self.FOLLOW_block_in_for_stmt765)
                self.block()

                self._state.following.pop()

                #action start
                self.cpy.block_enter()
                #action end


                # Eval.g:183:3: (b= exec_list )?
                alt17 = 2
                LA17_0 = self.input.LA(1)

                if (LA17_0 == EXEC_LIST) :
                    alt17 = 1
                if alt17 == 1:
                    # Eval.g:183:4: b= exec_list
                    pass 
                    self._state.following.append(self.FOLLOW_exec_list_in_for_stmt776)
                    b = self.exec_list()

                    self._state.following.pop()

                    #action start
                    self.cpy.stmt(b)
                    #action end





                #action start
                self.cpy.block_leave()
                #action end


                self.match(self.input, UP, None)





            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)

        finally:
            pass
        return 

    # $ANTLR end "for_stmt"



    # $ANTLR start "foreach_stmt"
    # Eval.g:188:1: foreach_stmt : ^( FOREACH expr ( ^( EACH k= ID v= each_val ) | ^( EACH v= each_val ) ) block ) ;
    def foreach_stmt(self, ):
        k = None
        v = None

        expr20 = None


        try:
            try:
                # Eval.g:189:2: ( ^( FOREACH expr ( ^( EACH k= ID v= each_val ) | ^( EACH v= each_val ) ) block ) )
                # Eval.g:189:4: ^( FOREACH expr ( ^( EACH k= ID v= each_val ) | ^( EACH v= each_val ) ) block )
                pass 
                self.match(self.input, FOREACH, self.FOLLOW_FOREACH_in_foreach_stmt800)

                self.match(self.input, DOWN, None)
                self._state.following.append(self.FOLLOW_expr_in_foreach_stmt802)
                expr20 = self.expr()

                self._state.following.pop()

                # Eval.g:190:3: ( ^( EACH k= ID v= each_val ) | ^( EACH v= each_val ) )
                alt18 = 2
                LA18_0 = self.input.LA(1)

                if (LA18_0 == EACH) :
                    LA18_1 = self.input.LA(2)

                    if (LA18_1 == 2) :
                        LA18_2 = self.input.LA(3)

                        if (LA18_2 == ID) :
                            alt18 = 1
                        elif (LA18_2 == EACH_VAL) :
                            alt18 = 2
                        else:
                            nvae = NoViableAltException("", 18, 2, self.input)

                            raise nvae


                    else:
                        nvae = NoViableAltException("", 18, 1, self.input)

                        raise nvae


                else:
                    nvae = NoViableAltException("", 18, 0, self.input)

                    raise nvae


                if alt18 == 1:
                    # Eval.g:190:5: ^( EACH k= ID v= each_val )
                    pass 
                    self.match(self.input, EACH, self.FOLLOW_EACH_in_foreach_stmt809)

                    self.match(self.input, DOWN, None)
                    k = self.match(self.input, ID, self.FOLLOW_ID_in_foreach_stmt813)

                    self._state.following.append(self.FOLLOW_each_val_in_foreach_stmt817)
                    v = self.each_val()

                    self._state.following.pop()

                    self.match(self.input, UP, None)


                    #action start
                    self.cpy.op_foreach(expr20, k.text, v)
                    #action end



                elif alt18 == 2:
                    # Eval.g:192:5: ^( EACH v= each_val )
                    pass 
                    self.match(self.input, EACH, self.FOLLOW_EACH_in_foreach_stmt830)

                    self.match(self.input, DOWN, None)
                    self._state.following.append(self.FOLLOW_each_val_in_foreach_stmt834)
                    v = self.each_val()

                    self._state.following.pop()

                    self.match(self.input, UP, None)


                    #action start
                    self.cpy.op_foreach(expr20, None, v)
                    #action end





                self._state.following.append(self.FOLLOW_block_in_foreach_stmt848)
                self.block()

                self._state.following.pop()

                self.match(self.input, UP, None)





            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)

        finally:
            pass
        return 

    # $ANTLR end "foreach_stmt"



    # $ANTLR start "each_val"
    # Eval.g:198:1: each_val returns [text] : ^( EACH_VAL ( ID )+ ) ;
    def each_val(self, ):
        text = None


        ID21 = None

        ps = []
        try:
            try:
                # Eval.g:200:2: ( ^( EACH_VAL ( ID )+ ) )
                # Eval.g:200:4: ^( EACH_VAL ( ID )+ )
                pass 
                self.match(self.input, EACH_VAL, self.FOLLOW_EACH_VAL_in_each_val871)

                self.match(self.input, DOWN, None)
                # Eval.g:200:15: ( ID )+
                cnt19 = 0
                while True: #loop19
                    alt19 = 2
                    LA19_0 = self.input.LA(1)

                    if (LA19_0 == ID) :
                        alt19 = 1


                    if alt19 == 1:
                        # Eval.g:200:16: ID
                        pass 
                        ID21 = self.match(self.input, ID, self.FOLLOW_ID_in_each_val874)

                        #action start
                        ps.append(ID21.text)
                        #action end



                    else:
                        if cnt19 >= 1:
                            break #loop19

                        eee = EarlyExitException(19, self.input)
                        raise eee

                    cnt19 += 1


                self.match(self.input, UP, None)


                #action start
                text = ','.join(ps)
                #action end





            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)

        finally:
            pass
        return text

    # $ANTLR end "each_val"



    # $ANTLR start "throw_stmt"
    # Eval.g:205:1: throw_stmt : ^( THROW expr ) ;
    def throw_stmt(self, ):
        expr22 = None


        try:
            try:
                # Eval.g:206:2: ( ^( THROW expr ) )
                # Eval.g:206:4: ^( THROW expr )
                pass 
                self.match(self.input, THROW, self.FOLLOW_THROW_in_throw_stmt897)

                self.match(self.input, DOWN, None)
                self._state.following.append(self.FOLLOW_expr_in_throw_stmt899)
                expr22 = self.expr()

                self._state.following.pop()

                self.match(self.input, UP, None)


                #action start
                self.cpy.op_throw(expr22)
                #action end





            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)

        finally:
            pass
        return 

    # $ANTLR end "throw_stmt"



    # $ANTLR start "try_stmt"
    # Eval.g:209:1: try_stmt : ^( TRY block ( catch_clause )+ ( finally_clause )? ) ;
    def try_stmt(self, ):
        self.cpy.op_try()
        try:
            try:
                # Eval.g:211:2: ( ^( TRY block ( catch_clause )+ ( finally_clause )? ) )
                # Eval.g:211:4: ^( TRY block ( catch_clause )+ ( finally_clause )? )
                pass 
                self.match(self.input, TRY, self.FOLLOW_TRY_in_try_stmt920)

                self.match(self.input, DOWN, None)
                self._state.following.append(self.FOLLOW_block_in_try_stmt922)
                self.block()

                self._state.following.pop()

                # Eval.g:211:16: ( catch_clause )+
                cnt20 = 0
                while True: #loop20
                    alt20 = 2
                    LA20_0 = self.input.LA(1)

                    if (LA20_0 == CATCH) :
                        alt20 = 1


                    if alt20 == 1:
                        # Eval.g:211:16: catch_clause
                        pass 
                        self._state.following.append(self.FOLLOW_catch_clause_in_try_stmt924)
                        self.catch_clause()

                        self._state.following.pop()


                    else:
                        if cnt20 >= 1:
                            break #loop20

                        eee = EarlyExitException(20, self.input)
                        raise eee

                    cnt20 += 1


                # Eval.g:211:30: ( finally_clause )?
                alt21 = 2
                LA21_0 = self.input.LA(1)

                if (LA21_0 == FINALLY) :
                    alt21 = 1
                if alt21 == 1:
                    # Eval.g:211:30: finally_clause
                    pass 
                    self._state.following.append(self.FOLLOW_finally_clause_in_try_stmt927)
                    self.finally_clause()

                    self._state.following.pop()




                self.match(self.input, UP, None)





            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)

        finally:
            pass
        return 

    # $ANTLR end "try_stmt"



    # $ANTLR start "catch_clause"
    # Eval.g:213:1: catch_clause : ^( CATCH module ( ID )? block ) ;
    def catch_clause(self, ):
        ID24 = None
        module23 = None


        try:
            try:
                # Eval.g:214:2: ( ^( CATCH module ( ID )? block ) )
                # Eval.g:214:4: ^( CATCH module ( ID )? block )
                pass 
                self.match(self.input, CATCH, self.FOLLOW_CATCH_in_catch_clause940)

                self.match(self.input, DOWN, None)
                self._state.following.append(self.FOLLOW_module_in_catch_clause942)
                module23 = self.module()

                self._state.following.pop()

                # Eval.g:214:19: ( ID )?
                alt22 = 2
                LA22_0 = self.input.LA(1)

                if (LA22_0 == ID) :
                    alt22 = 1
                if alt22 == 1:
                    # Eval.g:214:19: ID
                    pass 
                    ID24 = self.match(self.input, ID, self.FOLLOW_ID_in_catch_clause944)




                #action start
                self.cpy.op_catch(module23, ID24.text)
                #action end


                self._state.following.append(self.FOLLOW_block_in_catch_clause953)
                self.block()

                self._state.following.pop()

                self.match(self.input, UP, None)





            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)

        finally:
            pass
        return 

    # $ANTLR end "catch_clause"



    # $ANTLR start "finally_clause"
    # Eval.g:218:1: finally_clause : ^( FINALLY block ) ;
    def finally_clause(self, ):
        self.cpy.op_finally()
        try:
            try:
                # Eval.g:220:2: ( ^( FINALLY block ) )
                # Eval.g:220:4: ^( FINALLY block )
                pass 
                self.match(self.input, FINALLY, self.FOLLOW_FINALLY_in_finally_clause970)

                self.match(self.input, DOWN, None)
                self._state.following.append(self.FOLLOW_block_in_finally_clause972)
                self.block()

                self._state.following.pop()

                self.match(self.input, UP, None)





            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)

        finally:
            pass
        return 

    # $ANTLR end "finally_clause"



    # $ANTLR start "func_decl"
    # Eval.g:224:1: func_decl : ^( FUNCTION ID params block ) ;
    def func_decl(self, ):
        ID25 = None
        params26 = None


        try:
            try:
                # Eval.g:225:2: ( ^( FUNCTION ID params block ) )
                # Eval.g:225:4: ^( FUNCTION ID params block )
                pass 
                self.match(self.input, FUNCTION, self.FOLLOW_FUNCTION_in_func_decl986)

                self.match(self.input, DOWN, None)
                ID25 = self.match(self.input, ID, self.FOLLOW_ID_in_func_decl988)

                self._state.following.append(self.FOLLOW_params_in_func_decl990)
                params26 = self.params()

                self._state.following.pop()

                #action start
                self.cpy.op_function(ID25.text, params26)
                #action end


                self._state.following.append(self.FOLLOW_block_in_func_decl998)
                self.block()

                self._state.following.pop()

                self.match(self.input, UP, None)





            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)

        finally:
            pass
        return 

    # $ANTLR end "func_decl"



    # $ANTLR start "params"
    # Eval.g:230:1: params returns [text] : ^( PARAMS ( param_decl )* ) ;
    def params(self, ):
        text = None


        param_decl27 = None


        ps = []
        try:
            try:
                # Eval.g:232:2: ( ^( PARAMS ( param_decl )* ) )
                # Eval.g:232:4: ^( PARAMS ( param_decl )* )
                pass 
                self.match(self.input, PARAMS, self.FOLLOW_PARAMS_in_params1021)

                if self.input.LA(1) == DOWN:
                    self.match(self.input, DOWN, None)
                    # Eval.g:232:13: ( param_decl )*
                    while True: #loop23
                        alt23 = 2
                        LA23_0 = self.input.LA(1)

                        if (LA23_0 == ID) :
                            alt23 = 1


                        if alt23 == 1:
                            # Eval.g:232:14: param_decl
                            pass 
                            self._state.following.append(self.FOLLOW_param_decl_in_params1024)
                            param_decl27 = self.param_decl()

                            self._state.following.pop()

                            #action start
                            ps.append(param_decl27)
                            #action end



                        else:
                            break #loop23


                    self.match(self.input, UP, None)



                #action start
                text = ', '.join(ps)
                #action end





            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)

        finally:
            pass
        return text

    # $ANTLR end "params"



    # $ANTLR start "param_decl"
    # Eval.g:235:1: param_decl returns [text] : ID ( '=' atom )? ;
    def param_decl(self, ):
        text = None


        ID28 = None
        atom29 = None


        try:
            try:
                # Eval.g:236:2: ( ID ( '=' atom )? )
                # Eval.g:236:4: ID ( '=' atom )?
                pass 
                ID28 = self.match(self.input, ID, self.FOLLOW_ID_in_param_decl1048)

                #action start
                text = ID28.text
                #action end


                # Eval.g:238:3: ( '=' atom )?
                alt24 = 2
                LA24_0 = self.input.LA(1)

                if (LA24_0 == 95) :
                    alt24 = 1
                if alt24 == 1:
                    # Eval.g:238:4: '=' atom
                    pass 
                    self.match(self.input, 95, self.FOLLOW_95_in_param_decl1057)

                    self._state.following.append(self.FOLLOW_atom_in_param_decl1059)
                    atom29 = self.atom()

                    self._state.following.pop()

                    #action start
                    text += ('=' + atom29)
                    #action end








            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)

        finally:
            pass
        return text

    # $ANTLR end "param_decl"



    # $ANTLR start "class_decl"
    # Eval.g:244:1: class_decl : ( ^( CLASS a= ID ( class_element )* ) | ^( CLASS b= ID c= ID ( class_element )* ) );
    def class_decl(self, ):
        a = None
        b = None
        c = None

        try:
            try:
                # Eval.g:246:2: ( ^( CLASS a= ID ( class_element )* ) | ^( CLASS b= ID c= ID ( class_element )* ) )
                alt27 = 2
                LA27_0 = self.input.LA(1)

                if (LA27_0 == CLASS) :
                    LA27_1 = self.input.LA(2)

                    if (LA27_1 == 2) :
                        LA27_2 = self.input.LA(3)

                        if (LA27_2 == ID) :
                            LA27_3 = self.input.LA(4)

                            if (LA27_3 == ID) :
                                alt27 = 2
                            elif (LA27_3 == 3 or LA27_3 == CONSTRUCTOR or LA27_3 == FUNCTION or LA27_3 == VAR) :
                                alt27 = 1
                            else:
                                nvae = NoViableAltException("", 27, 3, self.input)

                                raise nvae


                        else:
                            nvae = NoViableAltException("", 27, 2, self.input)

                            raise nvae


                    else:
                        nvae = NoViableAltException("", 27, 1, self.input)

                        raise nvae


                else:
                    nvae = NoViableAltException("", 27, 0, self.input)

                    raise nvae


                if alt27 == 1:
                    # Eval.g:246:4: ^( CLASS a= ID ( class_element )* )
                    pass 
                    self.match(self.input, CLASS, self.FOLLOW_CLASS_in_class_decl1087)

                    self.match(self.input, DOWN, None)
                    a = self.match(self.input, ID, self.FOLLOW_ID_in_class_decl1091)

                    #action start
                    self.cpy.op_class_enter(a.text, None)
                    #action end


                    # Eval.g:248:3: ( class_element )*
                    while True: #loop25
                        alt25 = 2
                        LA25_0 = self.input.LA(1)

                        if (LA25_0 == CONSTRUCTOR or LA25_0 == FUNCTION or LA25_0 == VAR) :
                            alt25 = 1


                        if alt25 == 1:
                            # Eval.g:248:3: class_element
                            pass 
                            self._state.following.append(self.FOLLOW_class_element_in_class_decl1100)
                            self.class_element()

                            self._state.following.pop()


                        else:
                            break #loop25


                    self.match(self.input, UP, None)



                elif alt27 == 2:
                    # Eval.g:249:4: ^( CLASS b= ID c= ID ( class_element )* )
                    pass 
                    self.match(self.input, CLASS, self.FOLLOW_CLASS_in_class_decl1108)

                    self.match(self.input, DOWN, None)
                    b = self.match(self.input, ID, self.FOLLOW_ID_in_class_decl1112)

                    c = self.match(self.input, ID, self.FOLLOW_ID_in_class_decl1116)

                    #action start
                    self.cpy.op_class_enter(b.text, c.text)
                    #action end


                    # Eval.g:251:3: ( class_element )*
                    while True: #loop26
                        alt26 = 2
                        LA26_0 = self.input.LA(1)

                        if (LA26_0 == CONSTRUCTOR or LA26_0 == FUNCTION or LA26_0 == VAR) :
                            alt26 = 1


                        if alt26 == 1:
                            # Eval.g:251:3: class_element
                            pass 
                            self._state.following.append(self.FOLLOW_class_element_in_class_decl1125)
                            self.class_element()

                            self._state.following.pop()


                        else:
                            break #loop26


                    self.match(self.input, UP, None)



                #action start
                self.cpy.op_class_leave()
                #action end


            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)

        finally:
            pass
        return 

    # $ANTLR end "class_decl"



    # $ANTLR start "class_element"
    # Eval.g:253:1: class_element : ( var_def | constructor | func_decl );
    def class_element(self, ):
        try:
            try:
                # Eval.g:254:2: ( var_def | constructor | func_decl )
                alt28 = 3
                LA28 = self.input.LA(1)
                if LA28 == VAR:
                    alt28 = 1
                elif LA28 == CONSTRUCTOR:
                    alt28 = 2
                elif LA28 == FUNCTION:
                    alt28 = 3
                else:
                    nvae = NoViableAltException("", 28, 0, self.input)

                    raise nvae


                if alt28 == 1:
                    # Eval.g:254:4: var_def
                    pass 
                    self._state.following.append(self.FOLLOW_var_def_in_class_element1137)
                    self.var_def()

                    self._state.following.pop()


                elif alt28 == 2:
                    # Eval.g:254:14: constructor
                    pass 
                    self._state.following.append(self.FOLLOW_constructor_in_class_element1141)
                    self.constructor()

                    self._state.following.pop()


                elif alt28 == 3:
                    # Eval.g:254:28: func_decl
                    pass 
                    self._state.following.append(self.FOLLOW_func_decl_in_class_element1145)
                    self.func_decl()

                    self._state.following.pop()



            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)

        finally:
            pass
        return 

    # $ANTLR end "class_element"



    # $ANTLR start "var_def"
    # Eval.g:256:1: var_def : ( ^( VAR ID ( expr )? ) | ^( VAR 'static' ID ( expr )? ) );
    def var_def(self, ):
        ID30 = None
        ID32 = None
        expr31 = None

        expr33 = None


        try:
            try:
                # Eval.g:257:2: ( ^( VAR ID ( expr )? ) | ^( VAR 'static' ID ( expr )? ) )
                alt31 = 2
                LA31_0 = self.input.LA(1)

                if (LA31_0 == VAR) :
                    LA31_1 = self.input.LA(2)

                    if (LA31_1 == 2) :
                        LA31_2 = self.input.LA(3)

                        if (LA31_2 == ID) :
                            alt31 = 1
                        elif (LA31_2 == 127) :
                            alt31 = 2
                        else:
                            nvae = NoViableAltException("", 31, 2, self.input)

                            raise nvae


                    else:
                        nvae = NoViableAltException("", 31, 1, self.input)

                        raise nvae


                else:
                    nvae = NoViableAltException("", 31, 0, self.input)

                    raise nvae


                if alt31 == 1:
                    # Eval.g:257:4: ^( VAR ID ( expr )? )
                    pass 
                    self.match(self.input, VAR, self.FOLLOW_VAR_in_var_def1156)

                    self.match(self.input, DOWN, None)
                    ID30 = self.match(self.input, ID, self.FOLLOW_ID_in_var_def1158)

                    # Eval.g:257:13: ( expr )?
                    alt29 = 2
                    LA29_0 = self.input.LA(1)

                    if (LA29_0 == ARRAY or LA29_0 == BOOL or LA29_0 == FLOAT or LA29_0 == INT or LA29_0 == MEMBER or (NEGATIVE <= LA29_0 <= NEW) or (NULL <= LA29_0 <= OBJECT) or (SPRINTF <= LA29_0 <= STRING) or (68 <= LA29_0 <= 70) or (72 <= LA29_0 <= 73) or LA29_0 == 77 or LA29_0 == 79 or LA29_0 == 83 or LA29_0 == 89 or (93 <= LA29_0 <= 94) or LA29_0 == 96 or (98 <= LA29_0 <= 99) or LA29_0 == 102 or LA29_0 == 133 or LA29_0 == 135) :
                        alt29 = 1
                    if alt29 == 1:
                        # Eval.g:257:13: expr
                        pass 
                        self._state.following.append(self.FOLLOW_expr_in_var_def1160)
                        expr31 = self.expr()

                        self._state.following.pop()




                    self.match(self.input, UP, None)


                    #action start
                    self.cpy.op_var_def(False, ID30.text, expr31)
                    #action end



                elif alt31 == 2:
                    # Eval.g:259:4: ^( VAR 'static' ID ( expr )? )
                    pass 
                    self.match(self.input, VAR, self.FOLLOW_VAR_in_var_def1172)

                    self.match(self.input, DOWN, None)
                    self.match(self.input, 127, self.FOLLOW_127_in_var_def1174)

                    ID32 = self.match(self.input, ID, self.FOLLOW_ID_in_var_def1176)

                    # Eval.g:259:22: ( expr )?
                    alt30 = 2
                    LA30_0 = self.input.LA(1)

                    if (LA30_0 == ARRAY or LA30_0 == BOOL or LA30_0 == FLOAT or LA30_0 == INT or LA30_0 == MEMBER or (NEGATIVE <= LA30_0 <= NEW) or (NULL <= LA30_0 <= OBJECT) or (SPRINTF <= LA30_0 <= STRING) or (68 <= LA30_0 <= 70) or (72 <= LA30_0 <= 73) or LA30_0 == 77 or LA30_0 == 79 or LA30_0 == 83 or LA30_0 == 89 or (93 <= LA30_0 <= 94) or LA30_0 == 96 or (98 <= LA30_0 <= 99) or LA30_0 == 102 or LA30_0 == 133 or LA30_0 == 135) :
                        alt30 = 1
                    if alt30 == 1:
                        # Eval.g:259:22: expr
                        pass 
                        self._state.following.append(self.FOLLOW_expr_in_var_def1178)
                        expr33 = self.expr()

                        self._state.following.pop()




                    self.match(self.input, UP, None)


                    #action start
                    self.cpy.op_var_def(True, ID32.text, expr33)
                    #action end




            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)

        finally:
            pass
        return 

    # $ANTLR end "var_def"



    # $ANTLR start "constructor"
    # Eval.g:262:1: constructor : ^( CONSTRUCTOR params block ) ;
    def constructor(self, ):
        params34 = None


        try:
            try:
                # Eval.g:263:2: ( ^( CONSTRUCTOR params block ) )
                # Eval.g:263:4: ^( CONSTRUCTOR params block )
                pass 
                self.match(self.input, CONSTRUCTOR, self.FOLLOW_CONSTRUCTOR_in_constructor1195)

                self.match(self.input, DOWN, None)
                self._state.following.append(self.FOLLOW_params_in_constructor1197)
                params34 = self.params()

                self._state.following.pop()

                #action start
                self.cpy.op_construct(params34)
                #action end


                self._state.following.append(self.FOLLOW_block_in_constructor1205)
                self.block()

                self._state.following.pop()

                self.match(self.input, UP, None)





            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)

        finally:
            pass
        return 

    # $ANTLR end "constructor"



    # $ANTLR start "module"
    # Eval.g:270:1: module returns [text] : ^( MODULE ( ID )+ ) ;
    def module(self, ):
        text = None


        ID35 = None

        ps = []
        try:
            try:
                # Eval.g:272:2: ( ^( MODULE ( ID )+ ) )
                # Eval.g:272:4: ^( MODULE ( ID )+ )
                pass 
                self.match(self.input, MODULE, self.FOLLOW_MODULE_in_module1229)

                self.match(self.input, DOWN, None)
                # Eval.g:272:13: ( ID )+
                cnt32 = 0
                while True: #loop32
                    alt32 = 2
                    LA32_0 = self.input.LA(1)

                    if (LA32_0 == ID) :
                        alt32 = 1


                    if alt32 == 1:
                        # Eval.g:272:14: ID
                        pass 
                        ID35 = self.match(self.input, ID, self.FOLLOW_ID_in_module1232)

                        #action start
                        ps.append(ID35.text)
                        #action end



                    else:
                        if cnt32 >= 1:
                            break #loop32

                        eee = EarlyExitException(32, self.input)
                        raise eee

                    cnt32 += 1


                self.match(self.input, UP, None)


                #action start
                text = '.'.join(ps)
                #action end





            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)

        finally:
            pass
        return text

    # $ANTLR end "module"



    # $ANTLR start "member_expr"
    # Eval.g:276:1: member_expr returns [text] : ^( MEMBER ( primary )+ ) ;
    def member_expr(self, ):
        text = None


        primary36 = None


        ps = []
        try:
            try:
                # Eval.g:278:2: ( ^( MEMBER ( primary )+ ) )
                # Eval.g:278:4: ^( MEMBER ( primary )+ )
                pass 
                self.match(self.input, MEMBER, self.FOLLOW_MEMBER_in_member_expr1263)

                self.match(self.input, DOWN, None)
                # Eval.g:278:13: ( primary )+
                cnt33 = 0
                while True: #loop33
                    alt33 = 2
                    LA33_0 = self.input.LA(1)

                    if (LA33_0 == ID) :
                        alt33 = 1


                    if alt33 == 1:
                        # Eval.g:278:14: primary
                        pass 
                        self._state.following.append(self.FOLLOW_primary_in_member_expr1266)
                        primary36 = self.primary()

                        self._state.following.pop()

                        #action start
                        ps.append(primary36)
                        #action end



                    else:
                        if cnt33 >= 1:
                            break #loop33

                        eee = EarlyExitException(33, self.input)
                        raise eee

                    cnt33 += 1


                self.match(self.input, UP, None)


                #action start
                text = '.'.join(ps)
                #action end





            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)

        finally:
            pass
        return text

    # $ANTLR end "member_expr"



    # $ANTLR start "primary"
    # Eval.g:281:1: primary returns [text] : ID ( index_expr )* ( call_expr )? ;
    def primary(self, ):
        text = None


        ID39 = None
        index_expr37 = None

        call_expr38 = None


        a=''
        try:
            try:
                # Eval.g:283:2: ( ID ( index_expr )* ( call_expr )? )
                # Eval.g:283:4: ID ( index_expr )* ( call_expr )?
                pass 
                ID39 = self.match(self.input, ID, self.FOLLOW_ID_in_primary1295)

                # Eval.g:283:7: ( index_expr )*
                while True: #loop34
                    alt34 = 2
                    LA34_0 = self.input.LA(1)

                    if (LA34_0 == INDEX or LA34_0 == SLICE) :
                        alt34 = 1


                    if alt34 == 1:
                        # Eval.g:283:8: index_expr
                        pass 
                        self._state.following.append(self.FOLLOW_index_expr_in_primary1298)
                        index_expr37 = self.index_expr()

                        self._state.following.pop()

                        #action start
                        a += index_expr37
                        #action end



                    else:
                        break #loop34


                # Eval.g:284:3: ( call_expr )?
                alt35 = 2
                LA35_0 = self.input.LA(1)

                if (LA35_0 == CALL) :
                    alt35 = 1
                if alt35 == 1:
                    # Eval.g:284:3: call_expr
                    pass 
                    self._state.following.append(self.FOLLOW_call_expr_in_primary1305)
                    call_expr38 = self.call_expr()

                    self._state.following.pop()




                #action start
                  
                b = call_expr38
                if b == None: b = ''
                text = ID39.text + a + b
                		
                #action end





            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)

        finally:
            pass
        return text

    # $ANTLR end "primary"



    # $ANTLR start "call_expr"
    # Eval.g:291:1: call_expr returns [text] : ^( CALL ( expr_list )? ) ;
    def call_expr(self, ):
        text = None


        expr_list40 = None


        try:
            try:
                # Eval.g:292:2: ( ^( CALL ( expr_list )? ) )
                # Eval.g:292:4: ^( CALL ( expr_list )? )
                pass 
                self.match(self.input, CALL, self.FOLLOW_CALL_in_call_expr1324)

                if self.input.LA(1) == DOWN:
                    self.match(self.input, DOWN, None)
                    # Eval.g:292:11: ( expr_list )?
                    alt36 = 2
                    LA36_0 = self.input.LA(1)

                    if (LA36_0 == EXPR_LIST) :
                        alt36 = 1
                    if alt36 == 1:
                        # Eval.g:292:11: expr_list
                        pass 
                        self._state.following.append(self.FOLLOW_expr_list_in_call_expr1326)
                        expr_list40 = self.expr_list()

                        self._state.following.pop()




                    self.match(self.input, UP, None)



                #action start
                  
                s = expr_list40
                if s == None: s = ''
                text = '(' + s + ')'
                		
                #action end





            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)

        finally:
            pass
        return text

    # $ANTLR end "call_expr"



    # $ANTLR start "index_expr"
    # Eval.g:299:1: index_expr returns [text] : ( ^( INDEX expr ) | ^( SLICE a= expr (b= expr )? ) );
    def index_expr(self, ):
        text = None


        a = None

        b = None

        expr41 = None


        try:
            try:
                # Eval.g:300:2: ( ^( INDEX expr ) | ^( SLICE a= expr (b= expr )? ) )
                alt38 = 2
                LA38_0 = self.input.LA(1)

                if (LA38_0 == INDEX) :
                    alt38 = 1
                elif (LA38_0 == SLICE) :
                    alt38 = 2
                else:
                    nvae = NoViableAltException("", 38, 0, self.input)

                    raise nvae


                if alt38 == 1:
                    # Eval.g:300:4: ^( INDEX expr )
                    pass 
                    self.match(self.input, INDEX, self.FOLLOW_INDEX_in_index_expr1346)

                    self.match(self.input, DOWN, None)
                    self._state.following.append(self.FOLLOW_expr_in_index_expr1348)
                    expr41 = self.expr()

                    self._state.following.pop()

                    self.match(self.input, UP, None)


                    #action start
                    text = '[' + expr41 + ']'
                    #action end



                elif alt38 == 2:
                    # Eval.g:302:4: ^( SLICE a= expr (b= expr )? )
                    pass 
                    self.match(self.input, SLICE, self.FOLLOW_SLICE_in_index_expr1359)

                    self.match(self.input, DOWN, None)
                    self._state.following.append(self.FOLLOW_expr_in_index_expr1363)
                    a = self.expr()

                    self._state.following.pop()

                    # Eval.g:302:20: (b= expr )?
                    alt37 = 2
                    LA37_0 = self.input.LA(1)

                    if (LA37_0 == ARRAY or LA37_0 == BOOL or LA37_0 == FLOAT or LA37_0 == INT or LA37_0 == MEMBER or (NEGATIVE <= LA37_0 <= NEW) or (NULL <= LA37_0 <= OBJECT) or (SPRINTF <= LA37_0 <= STRING) or (68 <= LA37_0 <= 70) or (72 <= LA37_0 <= 73) or LA37_0 == 77 or LA37_0 == 79 or LA37_0 == 83 or LA37_0 == 89 or (93 <= LA37_0 <= 94) or LA37_0 == 96 or (98 <= LA37_0 <= 99) or LA37_0 == 102 or LA37_0 == 133 or LA37_0 == 135) :
                        alt37 = 1
                    if alt37 == 1:
                        # Eval.g:302:20: b= expr
                        pass 
                        self._state.following.append(self.FOLLOW_expr_in_index_expr1367)
                        b = self.expr()

                        self._state.following.pop()




                    self.match(self.input, UP, None)


                    #action start
                      
                    s = b
                    if s == None: s = ''
                    text = '[%s : %s]' %(a, s)
                    		
                    #action end




            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)

        finally:
            pass
        return text

    # $ANTLR end "index_expr"



    # $ANTLR start "expr_list"
    # Eval.g:311:1: expr_list returns [text] : ^( EXPR_LIST ( expr )+ ) ;
    def expr_list(self, ):
        text = None


        expr42 = None


        ps = []
        try:
            try:
                # Eval.g:313:2: ( ^( EXPR_LIST ( expr )+ ) )
                # Eval.g:313:4: ^( EXPR_LIST ( expr )+ )
                pass 
                self.match(self.input, EXPR_LIST, self.FOLLOW_EXPR_LIST_in_expr_list1394)

                self.match(self.input, DOWN, None)
                # Eval.g:313:16: ( expr )+
                cnt39 = 0
                while True: #loop39
                    alt39 = 2
                    LA39_0 = self.input.LA(1)

                    if (LA39_0 == ARRAY or LA39_0 == BOOL or LA39_0 == FLOAT or LA39_0 == INT or LA39_0 == MEMBER or (NEGATIVE <= LA39_0 <= NEW) or (NULL <= LA39_0 <= OBJECT) or (SPRINTF <= LA39_0 <= STRING) or (68 <= LA39_0 <= 70) or (72 <= LA39_0 <= 73) or LA39_0 == 77 or LA39_0 == 79 or LA39_0 == 83 or LA39_0 == 89 or (93 <= LA39_0 <= 94) or LA39_0 == 96 or (98 <= LA39_0 <= 99) or LA39_0 == 102 or LA39_0 == 133 or LA39_0 == 135) :
                        alt39 = 1


                    if alt39 == 1:
                        # Eval.g:313:17: expr
                        pass 
                        self._state.following.append(self.FOLLOW_expr_in_expr_list1397)
                        expr42 = self.expr()

                        self._state.following.pop()

                        #action start
                        ps.append(expr42)
                        #action end



                    else:
                        if cnt39 >= 1:
                            break #loop39

                        eee = EarlyExitException(39, self.input)
                        raise eee

                    cnt39 += 1


                self.match(self.input, UP, None)


                #action start
                  
                text = ', '.join(ps)
                		
                #action end





            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)

        finally:
            pass
        return text

    # $ANTLR end "expr_list"



    # $ANTLR start "expr"
    # Eval.g:319:1: expr returns [text] : (a= relation_expr |a= logic_or_expr |a= logic_and_expr |a= bitwise_or_expr |a= bitwise_xor_expr |a= bitwise_and_expr |a= add_expr |a= mul_expr |a= not_expr |a= negative_expr |a= atom );
    def expr(self, ):
        text = None


        a = None


        try:
            try:
                # Eval.g:320:2: (a= relation_expr |a= logic_or_expr |a= logic_and_expr |a= bitwise_or_expr |a= bitwise_xor_expr |a= bitwise_and_expr |a= add_expr |a= mul_expr |a= not_expr |a= negative_expr |a= atom )
                alt40 = 11
                LA40 = self.input.LA(1)
                if LA40 == 69 or LA40 == 93 or LA40 == 94 or LA40 == 96 or LA40 == 98 or LA40 == 99:
                    alt40 = 1
                elif LA40 == 135:
                    alt40 = 2
                elif LA40 == 72:
                    alt40 = 3
                elif LA40 == 133:
                    alt40 = 4
                elif LA40 == 102:
                    alt40 = 5
                elif LA40 == 73:
                    alt40 = 6
                elif LA40 == 79 or LA40 == 83:
                    alt40 = 7
                elif LA40 == 70 or LA40 == 77 or LA40 == 89:
                    alt40 = 8
                elif LA40 == 68:
                    alt40 = 9
                elif LA40 == NEGATIVE:
                    alt40 = 10
                elif LA40 == ARRAY or LA40 == BOOL or LA40 == FLOAT or LA40 == INT or LA40 == MEMBER or LA40 == NEW or LA40 == NULL or LA40 == OBJECT or LA40 == SPRINTF or LA40 == STRING:
                    alt40 = 11
                else:
                    nvae = NoViableAltException("", 40, 0, self.input)

                    raise nvae


                if alt40 == 1:
                    # Eval.g:320:4: a= relation_expr
                    pass 
                    self._state.following.append(self.FOLLOW_relation_expr_in_expr1423)
                    a = self.relation_expr()

                    self._state.following.pop()

                    #action start
                    text = a
                    #action end



                elif alt40 == 2:
                    # Eval.g:321:4: a= logic_or_expr
                    pass 
                    self._state.following.append(self.FOLLOW_logic_or_expr_in_expr1432)
                    a = self.logic_or_expr()

                    self._state.following.pop()

                    #action start
                    text = a
                    #action end



                elif alt40 == 3:
                    # Eval.g:322:4: a= logic_and_expr
                    pass 
                    self._state.following.append(self.FOLLOW_logic_and_expr_in_expr1441)
                    a = self.logic_and_expr()

                    self._state.following.pop()

                    #action start
                    text = a
                    #action end



                elif alt40 == 4:
                    # Eval.g:323:4: a= bitwise_or_expr
                    pass 
                    self._state.following.append(self.FOLLOW_bitwise_or_expr_in_expr1450)
                    a = self.bitwise_or_expr()

                    self._state.following.pop()

                    #action start
                    text = a
                    #action end



                elif alt40 == 5:
                    # Eval.g:324:4: a= bitwise_xor_expr
                    pass 
                    self._state.following.append(self.FOLLOW_bitwise_xor_expr_in_expr1459)
                    a = self.bitwise_xor_expr()

                    self._state.following.pop()

                    #action start
                    text = a
                    #action end



                elif alt40 == 6:
                    # Eval.g:325:4: a= bitwise_and_expr
                    pass 
                    self._state.following.append(self.FOLLOW_bitwise_and_expr_in_expr1468)
                    a = self.bitwise_and_expr()

                    self._state.following.pop()

                    #action start
                    text = a
                    #action end



                elif alt40 == 7:
                    # Eval.g:326:4: a= add_expr
                    pass 
                    self._state.following.append(self.FOLLOW_add_expr_in_expr1477)
                    a = self.add_expr()

                    self._state.following.pop()

                    #action start
                    text = a
                    #action end



                elif alt40 == 8:
                    # Eval.g:327:4: a= mul_expr
                    pass 
                    self._state.following.append(self.FOLLOW_mul_expr_in_expr1487)
                    a = self.mul_expr()

                    self._state.following.pop()

                    #action start
                    text = a
                    #action end



                elif alt40 == 9:
                    # Eval.g:328:4: a= not_expr
                    pass 
                    self._state.following.append(self.FOLLOW_not_expr_in_expr1497)
                    a = self.not_expr()

                    self._state.following.pop()

                    #action start
                    text = a
                    #action end



                elif alt40 == 10:
                    # Eval.g:329:4: a= negative_expr
                    pass 
                    self._state.following.append(self.FOLLOW_negative_expr_in_expr1507)
                    a = self.negative_expr()

                    self._state.following.pop()

                    #action start
                    text = a
                    #action end



                elif alt40 == 11:
                    # Eval.g:330:4: a= atom
                    pass 
                    self._state.following.append(self.FOLLOW_atom_in_expr1516)
                    a = self.atom()

                    self._state.following.pop()

                    #action start
                    text = a
                    #action end




            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)

        finally:
            pass
        return text

    # $ANTLR end "expr"



    # $ANTLR start "logic_or_expr"
    # Eval.g:332:1: logic_or_expr returns [text] : ^( '||' b= expr c= expr ) ;
    def logic_or_expr(self, ):
        text = None


        b = None

        c = None


        try:
            try:
                # Eval.g:333:2: ( ^( '||' b= expr c= expr ) )
                # Eval.g:333:4: ^( '||' b= expr c= expr )
                pass 
                self.match(self.input, 135, self.FOLLOW_135_in_logic_or_expr1534)

                self.match(self.input, DOWN, None)
                self._state.following.append(self.FOLLOW_expr_in_logic_or_expr1538)
                b = self.expr()

                self._state.following.pop()

                self._state.following.append(self.FOLLOW_expr_in_logic_or_expr1542)
                c = self.expr()

                self._state.following.pop()

                self.match(self.input, UP, None)


                #action start
                text = '(' + b + ' or ' + c + ')'
                #action end





            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)

        finally:
            pass
        return text

    # $ANTLR end "logic_or_expr"



    # $ANTLR start "logic_and_expr"
    # Eval.g:336:1: logic_and_expr returns [text] : ^( '&&' b= expr c= expr ) ;
    def logic_and_expr(self, ):
        text = None


        b = None

        c = None


        try:
            try:
                # Eval.g:337:2: ( ^( '&&' b= expr c= expr ) )
                # Eval.g:337:4: ^( '&&' b= expr c= expr )
                pass 
                self.match(self.input, 72, self.FOLLOW_72_in_logic_and_expr1561)

                self.match(self.input, DOWN, None)
                self._state.following.append(self.FOLLOW_expr_in_logic_and_expr1565)
                b = self.expr()

                self._state.following.pop()

                self._state.following.append(self.FOLLOW_expr_in_logic_and_expr1569)
                c = self.expr()

                self._state.following.pop()

                self.match(self.input, UP, None)


                #action start
                text = b + ' and ' + c
                #action end





            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)

        finally:
            pass
        return text

    # $ANTLR end "logic_and_expr"



    # $ANTLR start "bitwise_or_expr"
    # Eval.g:340:1: bitwise_or_expr returns [text] : ^( '|' b= expr c= expr ) ;
    def bitwise_or_expr(self, ):
        text = None


        b = None

        c = None


        try:
            try:
                # Eval.g:341:2: ( ^( '|' b= expr c= expr ) )
                # Eval.g:341:4: ^( '|' b= expr c= expr )
                pass 
                self.match(self.input, 133, self.FOLLOW_133_in_bitwise_or_expr1588)

                self.match(self.input, DOWN, None)
                self._state.following.append(self.FOLLOW_expr_in_bitwise_or_expr1592)
                b = self.expr()

                self._state.following.pop()

                self._state.following.append(self.FOLLOW_expr_in_bitwise_or_expr1596)
                c = self.expr()

                self._state.following.pop()

                self.match(self.input, UP, None)


                #action start
                text = b + ' | ' + c
                #action end





            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)

        finally:
            pass
        return text

    # $ANTLR end "bitwise_or_expr"



    # $ANTLR start "bitwise_xor_expr"
    # Eval.g:344:1: bitwise_xor_expr returns [text] : ^( '^' b= expr c= expr ) ;
    def bitwise_xor_expr(self, ):
        text = None


        b = None

        c = None


        try:
            try:
                # Eval.g:345:2: ( ^( '^' b= expr c= expr ) )
                # Eval.g:345:4: ^( '^' b= expr c= expr )
                pass 
                self.match(self.input, 102, self.FOLLOW_102_in_bitwise_xor_expr1615)

                self.match(self.input, DOWN, None)
                self._state.following.append(self.FOLLOW_expr_in_bitwise_xor_expr1619)
                b = self.expr()

                self._state.following.pop()

                self._state.following.append(self.FOLLOW_expr_in_bitwise_xor_expr1623)
                c = self.expr()

                self._state.following.pop()

                self.match(self.input, UP, None)


                #action start
                text = b + ' ^ ' + c
                #action end





            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)

        finally:
            pass
        return text

    # $ANTLR end "bitwise_xor_expr"



    # $ANTLR start "bitwise_and_expr"
    # Eval.g:348:1: bitwise_and_expr returns [text] : ^( '&' b= expr c= expr ) ;
    def bitwise_and_expr(self, ):
        text = None


        b = None

        c = None


        try:
            try:
                # Eval.g:349:2: ( ^( '&' b= expr c= expr ) )
                # Eval.g:349:4: ^( '&' b= expr c= expr )
                pass 
                self.match(self.input, 73, self.FOLLOW_73_in_bitwise_and_expr1642)

                self.match(self.input, DOWN, None)
                self._state.following.append(self.FOLLOW_expr_in_bitwise_and_expr1646)
                b = self.expr()

                self._state.following.pop()

                self._state.following.append(self.FOLLOW_expr_in_bitwise_and_expr1650)
                c = self.expr()

                self._state.following.pop()

                self.match(self.input, UP, None)


                #action start
                text = b + ' & ' + c
                #action end





            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)

        finally:
            pass
        return text

    # $ANTLR end "bitwise_and_expr"



    # $ANTLR start "relation_expr"
    # Eval.g:352:1: relation_expr returns [text] : ^(op= ( '<' | '>' | '<=' | '>=' | '==' | '!=' ) b= expr c= expr ) ;
    def relation_expr(self, ):
        text = None


        op = None
        b = None

        c = None


        try:
            try:
                # Eval.g:353:2: ( ^(op= ( '<' | '>' | '<=' | '>=' | '==' | '!=' ) b= expr c= expr ) )
                # Eval.g:353:4: ^(op= ( '<' | '>' | '<=' | '>=' | '==' | '!=' ) b= expr c= expr )
                pass 
                op = self.input.LT(1)

                if self.input.LA(1) == 69 or (93 <= self.input.LA(1) <= 94) or self.input.LA(1) == 96 or (98 <= self.input.LA(1) <= 99):
                    self.input.consume()
                    self._state.errorRecovery = False


                else:
                    mse = MismatchedSetException(None, self.input)
                    raise mse



                self.match(self.input, DOWN, None)
                self._state.following.append(self.FOLLOW_expr_in_relation_expr1687)
                b = self.expr()

                self._state.following.pop()

                self._state.following.append(self.FOLLOW_expr_in_relation_expr1691)
                c = self.expr()

                self._state.following.pop()

                self.match(self.input, UP, None)


                #action start
                text = b + op.text + c
                #action end





            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)

        finally:
            pass
        return text

    # $ANTLR end "relation_expr"



    # $ANTLR start "add_expr"
    # Eval.g:356:1: add_expr returns [text] : ^(op= ( '+' | '-' ) b= expr c= expr ) ;
    def add_expr(self, ):
        text = None


        op = None
        b = None

        c = None


        try:
            try:
                # Eval.g:357:2: ( ^(op= ( '+' | '-' ) b= expr c= expr ) )
                # Eval.g:357:4: ^(op= ( '+' | '-' ) b= expr c= expr )
                pass 
                op = self.input.LT(1)

                if self.input.LA(1) == 79 or self.input.LA(1) == 83:
                    self.input.consume()
                    self._state.errorRecovery = False


                else:
                    mse = MismatchedSetException(None, self.input)
                    raise mse



                self.match(self.input, DOWN, None)
                self._state.following.append(self.FOLLOW_expr_in_add_expr1720)
                b = self.expr()

                self._state.following.pop()

                self._state.following.append(self.FOLLOW_expr_in_add_expr1724)
                c = self.expr()

                self._state.following.pop()

                self.match(self.input, UP, None)


                #action start
                text = '(' + b + ' ' + op.text + ' ' + c + ')'
                #action end





            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)

        finally:
            pass
        return text

    # $ANTLR end "add_expr"



    # $ANTLR start "mul_expr"
    # Eval.g:360:1: mul_expr returns [text] : ^(op= ( '*' | '/' | '%' ) b= expr c= expr ) ;
    def mul_expr(self, ):
        text = None


        op = None
        b = None

        c = None


        try:
            try:
                # Eval.g:361:2: ( ^(op= ( '*' | '/' | '%' ) b= expr c= expr ) )
                # Eval.g:361:4: ^(op= ( '*' | '/' | '%' ) b= expr c= expr )
                pass 
                op = self.input.LT(1)

                if self.input.LA(1) == 70 or self.input.LA(1) == 77 or self.input.LA(1) == 89:
                    self.input.consume()
                    self._state.errorRecovery = False


                else:
                    mse = MismatchedSetException(None, self.input)
                    raise mse



                self.match(self.input, DOWN, None)
                self._state.following.append(self.FOLLOW_expr_in_mul_expr1755)
                b = self.expr()

                self._state.following.pop()

                self._state.following.append(self.FOLLOW_expr_in_mul_expr1759)
                c = self.expr()

                self._state.following.pop()

                self.match(self.input, UP, None)


                #action start
                text = b + ' ' + op.text + ' ' + c
                #action end





            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)

        finally:
            pass
        return text

    # $ANTLR end "mul_expr"



    # $ANTLR start "not_expr"
    # Eval.g:364:1: not_expr returns [text] : ^( '!' a= expr ) ;
    def not_expr(self, ):
        text = None


        a = None


        try:
            try:
                # Eval.g:365:2: ( ^( '!' a= expr ) )
                # Eval.g:365:4: ^( '!' a= expr )
                pass 
                self.match(self.input, 68, self.FOLLOW_68_in_not_expr1778)

                self.match(self.input, DOWN, None)
                self._state.following.append(self.FOLLOW_expr_in_not_expr1782)
                a = self.expr()

                self._state.following.pop()

                self.match(self.input, UP, None)


                #action start
                text = 'not (' + a + ')'
                #action end





            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)

        finally:
            pass
        return text

    # $ANTLR end "not_expr"



    # $ANTLR start "negative_expr"
    # Eval.g:368:1: negative_expr returns [text] : ^( NEGATIVE a= expr ) ;
    def negative_expr(self, ):
        text = None


        a = None


        try:
            try:
                # Eval.g:369:2: ( ^( NEGATIVE a= expr ) )
                # Eval.g:369:4: ^( NEGATIVE a= expr )
                pass 
                self.match(self.input, NEGATIVE, self.FOLLOW_NEGATIVE_in_negative_expr1801)

                self.match(self.input, DOWN, None)
                self._state.following.append(self.FOLLOW_expr_in_negative_expr1805)
                a = self.expr()

                self._state.following.pop()

                self.match(self.input, UP, None)


                #action start
                text = '- (' + a + ')'
                #action end





            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)

        finally:
            pass
        return text

    # $ANTLR end "negative_expr"



    # $ANTLR start "sprintf"
    # Eval.g:374:1: sprintf returns [text] : ^( SPRINTF expr (a= expr_list )? ) ;
    def sprintf(self, ):
        text = None


        a = None

        expr43 = None


        try:
            try:
                # Eval.g:375:2: ( ^( SPRINTF expr (a= expr_list )? ) )
                # Eval.g:375:4: ^( SPRINTF expr (a= expr_list )? )
                pass 
                self.match(self.input, SPRINTF, self.FOLLOW_SPRINTF_in_sprintf1826)

                self.match(self.input, DOWN, None)
                self._state.following.append(self.FOLLOW_expr_in_sprintf1828)
                expr43 = self.expr()

                self._state.following.pop()

                # Eval.g:375:20: (a= expr_list )?
                alt41 = 2
                LA41_0 = self.input.LA(1)

                if (LA41_0 == EXPR_LIST) :
                    alt41 = 1
                if alt41 == 1:
                    # Eval.g:375:20: a= expr_list
                    pass 
                    self._state.following.append(self.FOLLOW_expr_list_in_sprintf1832)
                    a = self.expr_list()

                    self._state.following.pop()




                self.match(self.input, UP, None)


                #action start
                  
                s = a
                if not s: s=''
                text = expr43 + '%(' + s + ')'
                		
                #action end





            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)

        finally:
            pass
        return text

    # $ANTLR end "sprintf"



    # $ANTLR start "new_clause"
    # Eval.g:383:1: new_clause returns [text] : ^( NEW module call_expr ) ;
    def new_clause(self, ):
        text = None


        module44 = None

        call_expr45 = None


        try:
            try:
                # Eval.g:384:2: ( ^( NEW module call_expr ) )
                # Eval.g:384:4: ^( NEW module call_expr )
                pass 
                self.match(self.input, NEW, self.FOLLOW_NEW_in_new_clause1853)

                self.match(self.input, DOWN, None)
                self._state.following.append(self.FOLLOW_module_in_new_clause1855)
                module44 = self.module()

                self._state.following.pop()

                self._state.following.append(self.FOLLOW_call_expr_in_new_clause1857)
                call_expr45 = self.call_expr()

                self._state.following.pop()

                self.match(self.input, UP, None)


                #action start
                text = module44 + call_expr45
                #action end





            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)

        finally:
            pass
        return text

    # $ANTLR end "new_clause"



    # $ANTLR start "array_decl"
    # Eval.g:388:1: array_decl returns [text] : ^( ARRAY ( expr_list )? ) ;
    def array_decl(self, ):
        text = None


        expr_list46 = None


        try:
            try:
                # Eval.g:389:2: ( ^( ARRAY ( expr_list )? ) )
                # Eval.g:389:4: ^( ARRAY ( expr_list )? )
                pass 
                self.match(self.input, ARRAY, self.FOLLOW_ARRAY_in_array_decl1877)

                if self.input.LA(1) == DOWN:
                    self.match(self.input, DOWN, None)
                    # Eval.g:389:12: ( expr_list )?
                    alt42 = 2
                    LA42_0 = self.input.LA(1)

                    if (LA42_0 == EXPR_LIST) :
                        alt42 = 1
                    if alt42 == 1:
                        # Eval.g:389:12: expr_list
                        pass 
                        self._state.following.append(self.FOLLOW_expr_list_in_array_decl1879)
                        expr_list46 = self.expr_list()

                        self._state.following.pop()




                    self.match(self.input, UP, None)



                #action start
                  
                s = expr_list46
                if s == None: s = ''
                text = '[' + s + ']'
                		
                #action end





            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)

        finally:
            pass
        return text

    # $ANTLR end "array_decl"



    # $ANTLR start "object_decl"
    # Eval.g:396:1: object_decl returns [text] : ^( OBJECT ( property )* ) ;
    def object_decl(self, ):
        text = None


        property47 = None


        s = ''
        try:
            try:
                # Eval.g:398:2: ( ^( OBJECT ( property )* ) )
                # Eval.g:398:4: ^( OBJECT ( property )* )
                pass 
                self.match(self.input, OBJECT, self.FOLLOW_OBJECT_in_object_decl1904)

                if self.input.LA(1) == DOWN:
                    self.match(self.input, DOWN, None)
                    # Eval.g:398:13: ( property )*
                    while True: #loop43
                        alt43 = 2
                        LA43_0 = self.input.LA(1)

                        if (LA43_0 == ID or LA43_0 == INT or LA43_0 == STRING) :
                            alt43 = 1


                        if alt43 == 1:
                            # Eval.g:398:14: property
                            pass 
                            self._state.following.append(self.FOLLOW_property_in_object_decl1907)
                            property47 = self.property()

                            self._state.following.pop()

                            #action start
                            s += property47
                            #action end



                        else:
                            break #loop43


                    self.match(self.input, UP, None)



                #action start
                text = '{' + s + '}'
                #action end





            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)

        finally:
            pass
        return text

    # $ANTLR end "object_decl"



    # $ANTLR start "property"
    # Eval.g:401:1: property returns [text] : a= ( ID | STRING | INT ) ':' expr ;
    def property(self, ):
        text = None


        a = None
        expr48 = None


        try:
            try:
                # Eval.g:402:2: (a= ( ID | STRING | INT ) ':' expr )
                # Eval.g:402:4: a= ( ID | STRING | INT ) ':' expr
                pass 
                a = self.input.LT(1)

                if self.input.LA(1) == ID or self.input.LA(1) == INT or self.input.LA(1) == STRING:
                    self.input.consume()
                    self._state.errorRecovery = False


                else:
                    mse = MismatchedSetException(None, self.input)
                    raise mse



                self.match(self.input, 91, self.FOLLOW_91_in_property1944)

                self._state.following.append(self.FOLLOW_expr_in_property1946)
                expr48 = self.expr()

                self._state.following.pop()

                #action start
                text = a.text + ': ' + expr48 + ','
                #action end





            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)

        finally:
            pass
        return text

    # $ANTLR end "property"



    # $ANTLR start "atom"
    # Eval.g:407:1: atom returns [text] : (a= literal |a= member_expr |a= new_clause |a= array_decl |a= object_decl |a= sprintf );
    def atom(self, ):
        text = None


        a = None


        try:
            try:
                # Eval.g:408:2: (a= literal |a= member_expr |a= new_clause |a= array_decl |a= object_decl |a= sprintf )
                alt44 = 6
                LA44 = self.input.LA(1)
                if LA44 == BOOL or LA44 == FLOAT or LA44 == INT or LA44 == NULL or LA44 == STRING:
                    alt44 = 1
                elif LA44 == MEMBER:
                    alt44 = 2
                elif LA44 == NEW:
                    alt44 = 3
                elif LA44 == ARRAY:
                    alt44 = 4
                elif LA44 == OBJECT:
                    alt44 = 5
                elif LA44 == SPRINTF:
                    alt44 = 6
                else:
                    nvae = NoViableAltException("", 44, 0, self.input)

                    raise nvae


                if alt44 == 1:
                    # Eval.g:408:4: a= literal
                    pass 
                    self._state.following.append(self.FOLLOW_literal_in_atom1967)
                    a = self.literal()

                    self._state.following.pop()

                    #action start
                    text = a
                    #action end



                elif alt44 == 2:
                    # Eval.g:409:4: a= member_expr
                    pass 
                    self._state.following.append(self.FOLLOW_member_expr_in_atom1977)
                    a = self.member_expr()

                    self._state.following.pop()

                    #action start
                    text = a
                    #action end



                elif alt44 == 3:
                    # Eval.g:410:4: a= new_clause
                    pass 
                    self._state.following.append(self.FOLLOW_new_clause_in_atom1986)
                    a = self.new_clause()

                    self._state.following.pop()

                    #action start
                    text = a
                    #action end



                elif alt44 == 4:
                    # Eval.g:411:4: a= array_decl
                    pass 
                    self._state.following.append(self.FOLLOW_array_decl_in_atom1995)
                    a = self.array_decl()

                    self._state.following.pop()

                    #action start
                    text = a
                    #action end



                elif alt44 == 5:
                    # Eval.g:412:4: a= object_decl
                    pass 
                    self._state.following.append(self.FOLLOW_object_decl_in_atom2004)
                    a = self.object_decl()

                    self._state.following.pop()

                    #action start
                    text = a
                    #action end



                elif alt44 == 6:
                    # Eval.g:413:4: a= sprintf
                    pass 
                    self._state.following.append(self.FOLLOW_sprintf_in_atom2013)
                    a = self.sprintf()

                    self._state.following.pop()

                    #action start
                    text = a
                    #action end




            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)

        finally:
            pass
        return text

    # $ANTLR end "atom"



    # $ANTLR start "literal"
    # Eval.g:415:1: literal returns [text] : ( NULL | BOOL | INT | FLOAT | STRING );
    def literal(self, ):
        text = None


        BOOL49 = None
        INT50 = None
        FLOAT51 = None
        STRING52 = None

        try:
            try:
                # Eval.g:416:2: ( NULL | BOOL | INT | FLOAT | STRING )
                alt45 = 5
                LA45 = self.input.LA(1)
                if LA45 == NULL:
                    alt45 = 1
                elif LA45 == BOOL:
                    alt45 = 2
                elif LA45 == INT:
                    alt45 = 3
                elif LA45 == FLOAT:
                    alt45 = 4
                elif LA45 == STRING:
                    alt45 = 5
                else:
                    nvae = NoViableAltException("", 45, 0, self.input)

                    raise nvae


                if alt45 == 1:
                    # Eval.g:416:4: NULL
                    pass 
                    self.match(self.input, NULL, self.FOLLOW_NULL_in_literal2029)

                    #action start
                    text = 'None'
                    #action end



                elif alt45 == 2:
                    # Eval.g:417:4: BOOL
                    pass 
                    BOOL49 = self.match(self.input, BOOL, self.FOLLOW_BOOL_in_literal2036)

                    #action start
                    text = BOOL49.text.capitalize()
                    #action end



                elif alt45 == 3:
                    # Eval.g:418:4: INT
                    pass 
                    INT50 = self.match(self.input, INT, self.FOLLOW_INT_in_literal2043)

                    #action start
                    text = INT50.text
                    #action end



                elif alt45 == 4:
                    # Eval.g:419:4: FLOAT
                    pass 
                    FLOAT51 = self.match(self.input, FLOAT, self.FOLLOW_FLOAT_in_literal2050)

                    #action start
                    text = FLOAT51.text
                    #action end



                elif alt45 == 5:
                    # Eval.g:420:4: STRING
                    pass 
                    STRING52 = self.match(self.input, STRING, self.FOLLOW_STRING_in_literal2057)

                    #action start
                    text = STRING52.text
                    #action end




            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)

        finally:
            pass
        return text

    # $ANTLR end "literal"



    # lookup tables for DFA #4

    DFA4_eot = DFA.unpack(
        u"\10\uffff"
        )

    DFA4_eof = DFA.unpack(
        u"\10\uffff"
        )

    DFA4_min = DFA.unpack(
        u"\1\3\1\uffff\1\2\1\42\2\3\2\uffff"
        )

    DFA4_max = DFA.unpack(
        u"\1\52\1\uffff\1\2\2\42\1\127\2\uffff"
        )

    DFA4_accept = DFA.unpack(
        u"\1\uffff\1\3\4\uffff\1\1\1\2"
        )

    DFA4_special = DFA.unpack(
        u"\10\uffff"
        )


    DFA4_transition = [
        DFA.unpack(u"\1\1\46\uffff\1\2"),
        DFA.unpack(u""),
        DFA.unpack(u"\1\3"),
        DFA.unpack(u"\1\4"),
        DFA.unpack(u"\1\5\36\uffff\1\4"),
        DFA.unpack(u"\1\6\46\uffff\1\6\54\uffff\1\7"),
        DFA.unpack(u""),
        DFA.unpack(u"")
    ]

    # class definition for DFA #4

    class DFA4(DFA):
        pass


 

    FOLLOW_stmt_in_prog69 = frozenset([1, 9, 13, 16, 20, 27, 31, 32, 33, 36, 37, 55, 56, 57, 62, 63, 64, 66])
    FOLLOW_import_stmt_in_stmt81 = frozenset([1])
    FOLLOW_exec_stmt_in_stmt86 = frozenset([1])
    FOLLOW_print_stmt_in_stmt91 = frozenset([1])
    FOLLOW_printf_stmt_in_stmt95 = frozenset([1])
    FOLLOW_break_stmt_in_stmt100 = frozenset([1])
    FOLLOW_continue_stmt_in_stmt105 = frozenset([1])
    FOLLOW_return_stmt_in_stmt110 = frozenset([1])
    FOLLOW_if_stmt_in_stmt115 = frozenset([1])
    FOLLOW_while_stmt_in_stmt120 = frozenset([1])
    FOLLOW_do_while_stmt_in_stmt125 = frozenset([1])
    FOLLOW_switch_stmt_in_stmt130 = frozenset([1])
    FOLLOW_throw_stmt_in_stmt135 = frozenset([1])
    FOLLOW_try_stmt_in_stmt140 = frozenset([1])
    FOLLOW_func_decl_in_stmt145 = frozenset([1])
    FOLLOW_class_decl_in_stmt150 = frozenset([1])
    FOLLOW_for_stmt_in_stmt155 = frozenset([1])
    FOLLOW_foreach_stmt_in_stmt160 = frozenset([1])
    FOLLOW_BLOCK_in_block185 = frozenset([2])
    FOLLOW_stmt_in_block187 = frozenset([3, 9, 13, 16, 20, 27, 31, 32, 33, 36, 37, 55, 56, 57, 62, 63, 64, 66])
    FOLLOW_IMPORT_in_import_stmt201 = frozenset([2])
    FOLLOW_module_in_import_stmt209 = frozenset([3, 42])
    FOLLOW_module_in_import_stmt222 = frozenset([87])
    FOLLOW_87_in_import_stmt224 = frozenset([3, 42])
    FOLLOW_EXEC_STMT_in_exec_stmt250 = frozenset([2])
    FOLLOW_exec_list_in_exec_stmt252 = frozenset([3])
    FOLLOW_member_expr_in_exec_expr270 = frozenset([1])
    FOLLOW_ASSIGN_in_exec_expr280 = frozenset([2])
    FOLLOW_member_expr_in_exec_expr282 = frozenset([71, 74, 78, 81, 85, 90, 95, 103, 134])
    FOLLOW_set_in_exec_expr286 = frozenset([5, 8, 30, 39, 41, 43, 44, 47, 48, 60, 61, 68, 69, 70, 72, 73, 77, 79, 83, 89, 93, 94, 96, 98, 99, 102, 133, 135])
    FOLLOW_expr_in_exec_expr306 = frozenset([3])
    FOLLOW_POST_INC_in_exec_expr317 = frozenset([2])
    FOLLOW_member_expr_in_exec_expr319 = frozenset([3])
    FOLLOW_POST_DEC_in_exec_expr330 = frozenset([2])
    FOLLOW_member_expr_in_exec_expr332 = frozenset([3])
    FOLLOW_PRE_INC_in_exec_expr343 = frozenset([2])
    FOLLOW_member_expr_in_exec_expr345 = frozenset([3])
    FOLLOW_PRE_DEC_in_exec_expr356 = frozenset([2])
    FOLLOW_member_expr_in_exec_expr358 = frozenset([3])
    FOLLOW_EXEC_LIST_in_exec_list382 = frozenset([2])
    FOLLOW_exec_expr_in_exec_list385 = frozenset([3, 6, 41, 51, 52, 53, 54])
    FOLLOW_PRINTF_in_printf_stmt408 = frozenset([2])
    FOLLOW_expr_in_printf_stmt410 = frozenset([3, 28])
    FOLLOW_expr_list_in_printf_stmt412 = frozenset([3])
    FOLLOW_PRINT_in_print_stmt429 = frozenset([2])
    FOLLOW_expr_list_in_print_stmt431 = frozenset([3])
    FOLLOW_BREAK_in_break_stmt451 = frozenset([1])
    FOLLOW_CONTINUE_in_continue_stmt465 = frozenset([1])
    FOLLOW_RETURN_in_return_stmt480 = frozenset([2])
    FOLLOW_expr_in_return_stmt482 = frozenset([3])
    FOLLOW_if_clause_in_if_stmt510 = frozenset([1, 23, 24])
    FOLLOW_else_if_clause_in_if_stmt512 = frozenset([1, 23, 24])
    FOLLOW_else_clause_in_if_stmt515 = frozenset([1])
    FOLLOW_IF_in_if_clause527 = frozenset([2])
    FOLLOW_expr_in_if_clause529 = frozenset([7])
    FOLLOW_block_in_if_clause533 = frozenset([3])
    FOLLOW_ELSE_IF_in_else_if_clause545 = frozenset([2])
    FOLLOW_if_clause_in_else_if_clause549 = frozenset([3])
    FOLLOW_ELSE_in_else_clause561 = frozenset([2])
    FOLLOW_block_in_else_clause565 = frozenset([3])
    FOLLOW_WHILE_in_while_stmt579 = frozenset([2])
    FOLLOW_expr_in_while_stmt581 = frozenset([7])
    FOLLOW_block_in_while_stmt585 = frozenset([3])
    FOLLOW_DO_WHILE_in_do_while_stmt598 = frozenset([2])
    FOLLOW_block_in_do_while_stmt604 = frozenset([5, 8, 30, 39, 41, 43, 44, 47, 48, 60, 61, 68, 69, 70, 72, 73, 77, 79, 83, 89, 93, 94, 96, 98, 99, 102, 133, 135])
    FOLLOW_expr_in_do_while_stmt608 = frozenset([3])
    FOLLOW_SWITCH_in_switch_stmt627 = frozenset([2])
    FOLLOW_expr_in_switch_stmt629 = frozenset([132])
    FOLLOW_case_block_in_switch_stmt633 = frozenset([3])
    FOLLOW_132_in_case_block648 = frozenset([11])
    FOLLOW_case_clause_in_case_block651 = frozenset([11, 17, 136])
    FOLLOW_default_clause_in_case_block656 = frozenset([136])
    FOLLOW_136_in_case_block660 = frozenset([1])
    FOLLOW_CASE_in_case_clause676 = frozenset([2])
    FOLLOW_case_test_in_case_clause678 = frozenset([9, 11, 13, 16, 20, 27, 31, 32, 33, 36, 37, 55, 56, 57, 62, 63, 64, 66])
    FOLLOW_stmt_in_case_clause683 = frozenset([9, 13, 16, 20, 27, 31, 32, 33, 36, 37, 55, 56, 57, 62, 63, 64, 66])
    FOLLOW_break_stmt_in_case_clause686 = frozenset([3])
    FOLLOW_CASE_in_case_test702 = frozenset([2])
    FOLLOW_expr_in_case_test704 = frozenset([3])
    FOLLOW_DEFAULT_in_default_clause725 = frozenset([2])
    FOLLOW_stmt_in_default_clause727 = frozenset([3, 9, 13, 16, 20, 27, 31, 32, 33, 36, 37, 55, 56, 57, 62, 63, 64, 66])
    FOLLOW_FOR_in_for_stmt746 = frozenset([2])
    FOLLOW_exec_list_in_for_stmt751 = frozenset([5, 8, 30, 39, 41, 43, 44, 47, 48, 60, 61, 68, 69, 70, 72, 73, 77, 79, 83, 89, 93, 94, 96, 98, 99, 102, 133, 135])
    FOLLOW_expr_in_for_stmt759 = frozenset([7])
    FOLLOW_block_in_for_stmt765 = frozenset([3, 26])
    FOLLOW_exec_list_in_for_stmt776 = frozenset([3])
    FOLLOW_FOREACH_in_foreach_stmt800 = frozenset([2])
    FOLLOW_expr_in_foreach_stmt802 = frozenset([21])
    FOLLOW_EACH_in_foreach_stmt809 = frozenset([2])
    FOLLOW_ID_in_foreach_stmt813 = frozenset([22])
    FOLLOW_each_val_in_foreach_stmt817 = frozenset([3])
    FOLLOW_EACH_in_foreach_stmt830 = frozenset([2])
    FOLLOW_each_val_in_foreach_stmt834 = frozenset([3])
    FOLLOW_block_in_foreach_stmt848 = frozenset([3])
    FOLLOW_EACH_VAL_in_each_val871 = frozenset([2])
    FOLLOW_ID_in_each_val874 = frozenset([3, 34])
    FOLLOW_THROW_in_throw_stmt897 = frozenset([2])
    FOLLOW_expr_in_throw_stmt899 = frozenset([3])
    FOLLOW_TRY_in_try_stmt920 = frozenset([2])
    FOLLOW_block_in_try_stmt922 = frozenset([12])
    FOLLOW_catch_clause_in_try_stmt924 = frozenset([3, 12, 29])
    FOLLOW_finally_clause_in_try_stmt927 = frozenset([3])
    FOLLOW_CATCH_in_catch_clause940 = frozenset([2])
    FOLLOW_module_in_catch_clause942 = frozenset([7, 34])
    FOLLOW_ID_in_catch_clause944 = frozenset([7])
    FOLLOW_block_in_catch_clause953 = frozenset([3])
    FOLLOW_FINALLY_in_finally_clause970 = frozenset([2])
    FOLLOW_block_in_finally_clause972 = frozenset([3])
    FOLLOW_FUNCTION_in_func_decl986 = frozenset([2])
    FOLLOW_ID_in_func_decl988 = frozenset([50])
    FOLLOW_params_in_func_decl990 = frozenset([7])
    FOLLOW_block_in_func_decl998 = frozenset([3])
    FOLLOW_PARAMS_in_params1021 = frozenset([2])
    FOLLOW_param_decl_in_params1024 = frozenset([3, 34])
    FOLLOW_ID_in_param_decl1048 = frozenset([1, 95])
    FOLLOW_95_in_param_decl1057 = frozenset([5, 8, 30, 39, 41, 44, 47, 48, 60, 61])
    FOLLOW_atom_in_param_decl1059 = frozenset([1])
    FOLLOW_CLASS_in_class_decl1087 = frozenset([2])
    FOLLOW_ID_in_class_decl1091 = frozenset([3, 15, 33, 65])
    FOLLOW_class_element_in_class_decl1100 = frozenset([3, 15, 33, 65])
    FOLLOW_CLASS_in_class_decl1108 = frozenset([2])
    FOLLOW_ID_in_class_decl1112 = frozenset([34])
    FOLLOW_ID_in_class_decl1116 = frozenset([3, 15, 33, 65])
    FOLLOW_class_element_in_class_decl1125 = frozenset([3, 15, 33, 65])
    FOLLOW_var_def_in_class_element1137 = frozenset([1])
    FOLLOW_constructor_in_class_element1141 = frozenset([1])
    FOLLOW_func_decl_in_class_element1145 = frozenset([1])
    FOLLOW_VAR_in_var_def1156 = frozenset([2])
    FOLLOW_ID_in_var_def1158 = frozenset([3, 5, 8, 30, 39, 41, 43, 44, 47, 48, 60, 61, 68, 69, 70, 72, 73, 77, 79, 83, 89, 93, 94, 96, 98, 99, 102, 133, 135])
    FOLLOW_expr_in_var_def1160 = frozenset([3])
    FOLLOW_VAR_in_var_def1172 = frozenset([2])
    FOLLOW_127_in_var_def1174 = frozenset([34])
    FOLLOW_ID_in_var_def1176 = frozenset([3, 5, 8, 30, 39, 41, 43, 44, 47, 48, 60, 61, 68, 69, 70, 72, 73, 77, 79, 83, 89, 93, 94, 96, 98, 99, 102, 133, 135])
    FOLLOW_expr_in_var_def1178 = frozenset([3])
    FOLLOW_CONSTRUCTOR_in_constructor1195 = frozenset([2])
    FOLLOW_params_in_constructor1197 = frozenset([7])
    FOLLOW_block_in_constructor1205 = frozenset([3])
    FOLLOW_MODULE_in_module1229 = frozenset([2])
    FOLLOW_ID_in_module1232 = frozenset([3, 34])
    FOLLOW_MEMBER_in_member_expr1263 = frozenset([2])
    FOLLOW_primary_in_member_expr1266 = frozenset([3, 34])
    FOLLOW_ID_in_primary1295 = frozenset([1, 10, 38, 59])
    FOLLOW_index_expr_in_primary1298 = frozenset([1, 10, 38, 59])
    FOLLOW_call_expr_in_primary1305 = frozenset([1])
    FOLLOW_CALL_in_call_expr1324 = frozenset([2])
    FOLLOW_expr_list_in_call_expr1326 = frozenset([3])
    FOLLOW_INDEX_in_index_expr1346 = frozenset([2])
    FOLLOW_expr_in_index_expr1348 = frozenset([3])
    FOLLOW_SLICE_in_index_expr1359 = frozenset([2])
    FOLLOW_expr_in_index_expr1363 = frozenset([3, 5, 8, 30, 39, 41, 43, 44, 47, 48, 60, 61, 68, 69, 70, 72, 73, 77, 79, 83, 89, 93, 94, 96, 98, 99, 102, 133, 135])
    FOLLOW_expr_in_index_expr1367 = frozenset([3])
    FOLLOW_EXPR_LIST_in_expr_list1394 = frozenset([2])
    FOLLOW_expr_in_expr_list1397 = frozenset([3, 5, 8, 30, 39, 41, 43, 44, 47, 48, 60, 61, 68, 69, 70, 72, 73, 77, 79, 83, 89, 93, 94, 96, 98, 99, 102, 133, 135])
    FOLLOW_relation_expr_in_expr1423 = frozenset([1])
    FOLLOW_logic_or_expr_in_expr1432 = frozenset([1])
    FOLLOW_logic_and_expr_in_expr1441 = frozenset([1])
    FOLLOW_bitwise_or_expr_in_expr1450 = frozenset([1])
    FOLLOW_bitwise_xor_expr_in_expr1459 = frozenset([1])
    FOLLOW_bitwise_and_expr_in_expr1468 = frozenset([1])
    FOLLOW_add_expr_in_expr1477 = frozenset([1])
    FOLLOW_mul_expr_in_expr1487 = frozenset([1])
    FOLLOW_not_expr_in_expr1497 = frozenset([1])
    FOLLOW_negative_expr_in_expr1507 = frozenset([1])
    FOLLOW_atom_in_expr1516 = frozenset([1])
    FOLLOW_135_in_logic_or_expr1534 = frozenset([2])
    FOLLOW_expr_in_logic_or_expr1538 = frozenset([5, 8, 30, 39, 41, 43, 44, 47, 48, 60, 61, 68, 69, 70, 72, 73, 77, 79, 83, 89, 93, 94, 96, 98, 99, 102, 133, 135])
    FOLLOW_expr_in_logic_or_expr1542 = frozenset([3])
    FOLLOW_72_in_logic_and_expr1561 = frozenset([2])
    FOLLOW_expr_in_logic_and_expr1565 = frozenset([5, 8, 30, 39, 41, 43, 44, 47, 48, 60, 61, 68, 69, 70, 72, 73, 77, 79, 83, 89, 93, 94, 96, 98, 99, 102, 133, 135])
    FOLLOW_expr_in_logic_and_expr1569 = frozenset([3])
    FOLLOW_133_in_bitwise_or_expr1588 = frozenset([2])
    FOLLOW_expr_in_bitwise_or_expr1592 = frozenset([5, 8, 30, 39, 41, 43, 44, 47, 48, 60, 61, 68, 69, 70, 72, 73, 77, 79, 83, 89, 93, 94, 96, 98, 99, 102, 133, 135])
    FOLLOW_expr_in_bitwise_or_expr1596 = frozenset([3])
    FOLLOW_102_in_bitwise_xor_expr1615 = frozenset([2])
    FOLLOW_expr_in_bitwise_xor_expr1619 = frozenset([5, 8, 30, 39, 41, 43, 44, 47, 48, 60, 61, 68, 69, 70, 72, 73, 77, 79, 83, 89, 93, 94, 96, 98, 99, 102, 133, 135])
    FOLLOW_expr_in_bitwise_xor_expr1623 = frozenset([3])
    FOLLOW_73_in_bitwise_and_expr1642 = frozenset([2])
    FOLLOW_expr_in_bitwise_and_expr1646 = frozenset([5, 8, 30, 39, 41, 43, 44, 47, 48, 60, 61, 68, 69, 70, 72, 73, 77, 79, 83, 89, 93, 94, 96, 98, 99, 102, 133, 135])
    FOLLOW_expr_in_bitwise_and_expr1650 = frozenset([3])
    FOLLOW_set_in_relation_expr1671 = frozenset([2])
    FOLLOW_expr_in_relation_expr1687 = frozenset([5, 8, 30, 39, 41, 43, 44, 47, 48, 60, 61, 68, 69, 70, 72, 73, 77, 79, 83, 89, 93, 94, 96, 98, 99, 102, 133, 135])
    FOLLOW_expr_in_relation_expr1691 = frozenset([3])
    FOLLOW_set_in_add_expr1712 = frozenset([2])
    FOLLOW_expr_in_add_expr1720 = frozenset([5, 8, 30, 39, 41, 43, 44, 47, 48, 60, 61, 68, 69, 70, 72, 73, 77, 79, 83, 89, 93, 94, 96, 98, 99, 102, 133, 135])
    FOLLOW_expr_in_add_expr1724 = frozenset([3])
    FOLLOW_set_in_mul_expr1745 = frozenset([2])
    FOLLOW_expr_in_mul_expr1755 = frozenset([5, 8, 30, 39, 41, 43, 44, 47, 48, 60, 61, 68, 69, 70, 72, 73, 77, 79, 83, 89, 93, 94, 96, 98, 99, 102, 133, 135])
    FOLLOW_expr_in_mul_expr1759 = frozenset([3])
    FOLLOW_68_in_not_expr1778 = frozenset([2])
    FOLLOW_expr_in_not_expr1782 = frozenset([3])
    FOLLOW_NEGATIVE_in_negative_expr1801 = frozenset([2])
    FOLLOW_expr_in_negative_expr1805 = frozenset([3])
    FOLLOW_SPRINTF_in_sprintf1826 = frozenset([2])
    FOLLOW_expr_in_sprintf1828 = frozenset([3, 28])
    FOLLOW_expr_list_in_sprintf1832 = frozenset([3])
    FOLLOW_NEW_in_new_clause1853 = frozenset([2])
    FOLLOW_module_in_new_clause1855 = frozenset([10])
    FOLLOW_call_expr_in_new_clause1857 = frozenset([3])
    FOLLOW_ARRAY_in_array_decl1877 = frozenset([2])
    FOLLOW_expr_list_in_array_decl1879 = frozenset([3])
    FOLLOW_OBJECT_in_object_decl1904 = frozenset([2])
    FOLLOW_property_in_object_decl1907 = frozenset([3, 34, 39, 61])
    FOLLOW_set_in_property1932 = frozenset([91])
    FOLLOW_91_in_property1944 = frozenset([5, 8, 30, 39, 41, 43, 44, 47, 48, 60, 61, 68, 69, 70, 72, 73, 77, 79, 83, 89, 93, 94, 96, 98, 99, 102, 133, 135])
    FOLLOW_expr_in_property1946 = frozenset([1])
    FOLLOW_literal_in_atom1967 = frozenset([1])
    FOLLOW_member_expr_in_atom1977 = frozenset([1])
    FOLLOW_new_clause_in_atom1986 = frozenset([1])
    FOLLOW_array_decl_in_atom1995 = frozenset([1])
    FOLLOW_object_decl_in_atom2004 = frozenset([1])
    FOLLOW_sprintf_in_atom2013 = frozenset([1])
    FOLLOW_NULL_in_literal2029 = frozenset([1])
    FOLLOW_BOOL_in_literal2036 = frozenset([1])
    FOLLOW_INT_in_literal2043 = frozenset([1])
    FOLLOW_FLOAT_in_literal2050 = frozenset([1])
    FOLLOW_STRING_in_literal2057 = frozenset([1])



def main(argv, stdin=sys.stdin, stdout=sys.stdout, stderr=sys.stderr):
    from antlr3.main import WalkerMain
    main = WalkerMain(Eval)

    main.stdin = stdin
    main.stdout = stdout
    main.stderr = stderr
    main.execute(argv)



if __name__ == '__main__':
    main(sys.argv)
