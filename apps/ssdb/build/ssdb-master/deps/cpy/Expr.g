/********************************
 * Author: ideawu
 * Link: http://www.ideawu.net/
 ********************************/

grammar Expr;

options {
	language=Python;
	output=AST;
	ASTLabelType=CommonTree;
}

tokens{
	NOP; EMPTY_LINE; ID_LIST;
	NEW; THROW; TRY; CATCH; FINALLY;
	PRINT; PRINTF; SPRINTF;
	CALL; BLOCK; EXPR_LIST;
	IMPORT; MEMBER; MODULE;
	ARRAY; INDEX; SLICE; OBJECT;
	CLASS; FUNCTION; PARAMS;
	PRE_INC; PRE_DEC; POST_INC; POST_DEC;
	IF; ELSE; ELSE_IF; WHILE; DO_WHILE;
	SWITCH; CASE; DEFAULT;
	BREAK; CONTINUE; RETURN;
	FOR; FOREACH; EACH; EACH_VAL;
	CLASS; VAR; CONSTRUCTOR;
	ASSIGN; OP_ASSIGN;
	EXEC_STMT; EXEC_LIST;
	NEGATIVE;
}

prog
	: EOF -> NOP
	| stmt*
	;

stmt
	: ';' ->
	| exec_stmt
	| import_stmt
	| print_stmt | printf_stmt
	| break_stmt
	| continue_stmt
	| return_stmt
	| if_stmt
	| while_stmt
	| do_while_stmt
	| switch_stmt
	| for_stmt
	| foreach_stmt
	| throw_stmt
	| try_stmt
	| func_decl
	| class_decl
	;

/***** statements *****/
block
	: '{' stmt* '}'
		-> ^(BLOCK stmt*)
	;

import_stmt
	: 'import' module_path (',' module_path)* ';'
		-> ^(IMPORT module_path+)
	;
module_path
	: module
	| module '.*'
	;

printf_stmt
	: 'printf' '(' expr (',' expr_list)? ')' ';'
		-> ^(PRINTF expr expr_list?)
	;
// echo: no newline
print_stmt
	//: ('print')  expr (',' expr)* ';'
	//	-> ^(PRINT expr+)
	: ('print') expr_list ';'
		-> ^(PRINT expr_list)
	;

break_stmt
	: 'break' ';'
		-> BREAK
	;
continue_stmt
	: 'continue' ';'
		-> CONTINUE
	;
return_stmt
	: 'return' expr? ';'
		-> ^(RETURN expr?)
	;

if_stmt
	: if_clause else_if_clause* else_clause?
	;
if_clause
	: 'if' '(' expr ')' block
		-> ^(IF expr block)
	;
else_if_clause
	: 'else' if_clause
		-> ^(ELSE_IF if_clause)
	;
else_clause
	: 'else' block
		-> ^(ELSE block)
	;

while_stmt
	: 'while' '(' expr ')' block
		-> ^(WHILE expr block)
	;

do_while_stmt
	: 'do' block 'while' '(' expr ')' ';'
		-> ^(DO_WHILE block expr)
	;

switch_stmt
	: 'switch' '(' expr ')' case_block
		-> ^(SWITCH expr case_block)
	;
case_block
	: '{' (case_clause)+ (default_clause)? '}'
	;
case_clause
	: case_test+ stmt* break_stmt
		-> ^(CASE case_test+ stmt* break_stmt)
	;
case_test
	: 'case' expr ':'
		-> ^(CASE expr)
	;
default_clause
	: 'default' ':' stmt*
		-> ^(DEFAULT stmt*)
	;

for_stmt
	: 'for' '(' a=exec_list? ';' expr ';' b=exec_list? ')' block
		-> ^(FOR $a? expr block $b?)
	;
// for in 是一种 trackback 结构, 而 foreach as 不是
foreach_stmt
	: 'foreach' '(' expr 'as' each ')' block
		-> ^(FOREACH expr each block)
	;
each
	: each_val
		-> ^(EACH each_val)
	| ID '=>' each_val
		-> ^(EACH ID each_val)
	;
each_val
	: ID (',' ID)*
		-> ^(EACH_VAL ID+)
	;


throw_stmt
	: 'throw' expr ';'
		-> ^(THROW expr)
	;
try_stmt
	: 'try' block catch_clause+ finally_clause?
		-> ^(TRY block catch_clause+ finally_clause?)
	;
catch_clause
	: 'catch' '(' module ID? ')' block
		-> ^(CATCH module ID? block)
	;
finally_clause
	: 'finally' block
		-> ^(FINALLY block)
	;


func_decl
	: 'function' ID params block
		-> ^(FUNCTION ID params block)
	;
params
	: '(' param_decl? (',' param_decl)* ')'
		-> ^(PARAMS param_decl*)
	;
param_decl
	: ID ('=' atom)?
	;

class_decl
	: 'class' ID ('extends' ID)?
		'{' class_element* '}'
		-> ^(CLASS ID ID? class_element*)
	;
class_element
	: var_def | constructor | func_decl
	;
var_def
	: 'public' ID ('=' expr)? ';'
		-> ^(VAR ID expr?)
	| 'public' 'static' ID ('=' expr)? ';'
		-> ^(VAR 'static' ID expr?)
	;
constructor
	: 'function' 'init' params block
		-> ^(CONSTRUCTOR params block)
	;



/***** expressions *****/
member_expr
	: primary ('.' primary)*
		-> ^(MEMBER primary+)
	;
primary
	: ID index_expr* call_expr?
	;
call_expr
	: '(' expr_list? ')'
		-> ^(CALL expr_list?)
	;
index_expr
	options{
		backtrack = true;
	}
	: '[' expr ']'
		-> ^(INDEX expr)
	| '[' expr '..' expr? ']'
		-> ^(SLICE expr expr?)
	;


exec_list
	: exec_expr (',' exec_expr)*
		-> ^(EXEC_LIST exec_expr+)
	;
member_list
	: member_expr (',' member_expr)*
	;
exec_expr
	: member_expr
		(assign_op expr
			-> ^(ASSIGN member_expr assign_op expr)
		| '++'
			-> ^(POST_INC member_expr)
		| '--'
			-> ^(POST_DEC member_expr)
		|
			-> member_expr
		)
	| '++' member_expr
		-> ^(PRE_INC member_expr)
	| '--' member_expr
		-> ^(PRE_DEC member_expr)
	;
assign_op
	: '='|'+='|'-='|'*='|'/='|'%='|'&='|'^='|'|='
	;
exec_stmt
	: exec_list ';'
		-> ^(EXEC_STMT exec_list)
	;



expr_list
	: expr (',' expr)* ','?
		-> ^(EXPR_LIST expr+)
	;
expr
	: logic_or_expr
	;
logic_or_expr
	: logic_and_expr ('||'^ logic_and_expr)*
	;
logic_and_expr
	: bitwise_or_expr ('&&'^ bitwise_or_expr)*
	;
bitwise_or_expr
	: bitwise_xor_expr ('|'^ bitwise_xor_expr)*
	;
bitwise_xor_expr
	: bitwise_and_expr ('^'^ bitwise_and_expr)*
	;
bitwise_and_expr
	: relation_expr ('&'^ relation_expr)*
	;
relation_expr
	: add_expr (('<'|'>'|'<='|'>='|'=='|'!=')^ add_expr)?
	;
add_expr
	: mul_expr (('+'|'-')^ mul_expr)*
	;
mul_expr
	: not_expr (('*'|'/'|'%')^ not_expr)*
	;
not_expr
	: op='!'? negative_expr
		-> {$op != None}?
			^('!' negative_expr)
			-> negative_expr
	;
negative_expr
	: (op='-')? atom
		-> {$op != None}?
			^(NEGATIVE atom)
			-> atom
	;

atom
	: literal
	| member_expr
	| array_decl
	| object_decl
	| new_clause
	| sprintf
	| '(' expr ')' -> expr
	;
literal
	: BOOL | NULL | INT | FLOAT | STRING
	;

new_clause
	: 'new' module call_expr
		-> ^(NEW module call_expr)
	;
module
	: ID ('.' ID)*
		-> ^(MODULE ID+)
	;


array_decl
	: '[' expr_list? ']'
		-> ^(ARRAY expr_list?)
	;

object_decl
	: '{' property? (',' property)* ','? '}'
		-> ^(OBJECT property*)
	;
property
	: (ID | STRING | INT) ':' expr
	;


sprintf
	: 'sprintf' '(' expr (',' expr_list)? ')'
		-> ^(SPRINTF expr expr_list?)
	;

/***** tokens *****/

NULL
	: 'null'
	;
BOOL
	: 'true' | 'false'
	;
ID
	: (ALPHA | '_' | '$') (ALPHA | '_' | DIGIT)*
	;

INT
	: DIGIT+
	;
FLOAT
	: INT '.' DIGIT*
	;
fragment ALPHA
	: 'a'..'z' |'A'..'Z'
	;
fragment DIGIT
	: '0'..'9'
	;
// TODO: 字符串拼接 "$a$b"
STRING
	: '"' DOUBLE_QUOTE_CHARS* '"'
	| '\'' SINGLE_QUOTE_CHARS* '\''
	;
fragment DOUBLE_QUOTE_CHARS
	: ~('"')
	// 应该是 '\\"' 吧?
	| '\\' '"'
	;
fragment SINGLE_QUOTE_CHARS
	: ~('\'')
	| '\\' '\''
	;

fragment NEWLINE
	: '\r'? '\n'
	;

WS
	: (' '|'\t'|'\r'|'\n')+ {$channel=HIDDEN;}
	;
COMMENT
	: '/*' (options {greedy=false;}:.)* '*/' {$channel=HIDDEN;}
	;
LINECOMMENT
	: ('//'|'#') ~('\r'|'\n')* NEWLINE {$channel=HIDDEN;}
	;

