# $ANTLR 3.5 Expr.g 2013-04-12 19:22:24

import sys
from antlr3 import *
from antlr3.compat import set, frozenset



# for convenience in actions
HIDDEN = BaseRecognizer.HIDDEN

# token types
EOF=-1
T__68=68
T__69=69
T__70=70
T__71=71
T__72=72
T__73=73
T__74=74
T__75=75
T__76=76
T__77=77
T__78=78
T__79=79
T__80=80
T__81=81
T__82=82
T__83=83
T__84=84
T__85=85
T__86=86
T__87=87
T__88=88
T__89=89
T__90=90
T__91=91
T__92=92
T__93=93
T__94=94
T__95=95
T__96=96
T__97=97
T__98=98
T__99=99
T__100=100
T__101=101
T__102=102
T__103=103
T__104=104
T__105=105
T__106=106
T__107=107
T__108=108
T__109=109
T__110=110
T__111=111
T__112=112
T__113=113
T__114=114
T__115=115
T__116=116
T__117=117
T__118=118
T__119=119
T__120=120
T__121=121
T__122=122
T__123=123
T__124=124
T__125=125
T__126=126
T__127=127
T__128=128
T__129=129
T__130=130
T__131=131
T__132=132
T__133=133
T__134=134
T__135=135
T__136=136
ALPHA=4
ARRAY=5
ASSIGN=6
BLOCK=7
BOOL=8
BREAK=9
CALL=10
CASE=11
CATCH=12
CLASS=13
COMMENT=14
CONSTRUCTOR=15
CONTINUE=16
DEFAULT=17
DIGIT=18
DOUBLE_QUOTE_CHARS=19
DO_WHILE=20
EACH=21
EACH_VAL=22
ELSE=23
ELSE_IF=24
EMPTY_LINE=25
EXEC_LIST=26
EXEC_STMT=27
EXPR_LIST=28
FINALLY=29
FLOAT=30
FOR=31
FOREACH=32
FUNCTION=33
ID=34
ID_LIST=35
IF=36
IMPORT=37
INDEX=38
INT=39
LINECOMMENT=40
MEMBER=41
MODULE=42
NEGATIVE=43
NEW=44
NEWLINE=45
NOP=46
NULL=47
OBJECT=48
OP_ASSIGN=49
PARAMS=50
POST_DEC=51
POST_INC=52
PRE_DEC=53
PRE_INC=54
PRINT=55
PRINTF=56
RETURN=57
SINGLE_QUOTE_CHARS=58
SLICE=59
SPRINTF=60
STRING=61
SWITCH=62
THROW=63
TRY=64
VAR=65
WHILE=66
WS=67


class ExprLexer(Lexer):

    grammarFileName = "Expr.g"
    api_version = 1

    def __init__(self, input=None, state=None):
        if state is None:
            state = RecognizerSharedState()
        super(ExprLexer, self).__init__(input, state)

        self.delegates = []

        self.dfa15 = self.DFA15(
            self, 15,
            eot = self.DFA15_eot,
            eof = self.DFA15_eof,
            min = self.DFA15_min,
            max = self.DFA15_max,
            accept = self.DFA15_accept,
            special = self.DFA15_special,
            transition = self.DFA15_transition
            )






    # $ANTLR start "T__68"
    def mT__68(self, ):
        try:
            _type = T__68
            _channel = DEFAULT_CHANNEL

            # Expr.g:7:7: ( '!' )
            # Expr.g:7:9: '!'
            pass 
            self.match(33)



            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "T__68"



    # $ANTLR start "T__69"
    def mT__69(self, ):
        try:
            _type = T__69
            _channel = DEFAULT_CHANNEL

            # Expr.g:8:7: ( '!=' )
            # Expr.g:8:9: '!='
            pass 
            self.match("!=")




            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "T__69"



    # $ANTLR start "T__70"
    def mT__70(self, ):
        try:
            _type = T__70
            _channel = DEFAULT_CHANNEL

            # Expr.g:9:7: ( '%' )
            # Expr.g:9:9: '%'
            pass 
            self.match(37)



            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "T__70"



    # $ANTLR start "T__71"
    def mT__71(self, ):
        try:
            _type = T__71
            _channel = DEFAULT_CHANNEL

            # Expr.g:10:7: ( '%=' )
            # Expr.g:10:9: '%='
            pass 
            self.match("%=")




            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "T__71"



    # $ANTLR start "T__72"
    def mT__72(self, ):
        try:
            _type = T__72
            _channel = DEFAULT_CHANNEL

            # Expr.g:11:7: ( '&&' )
            # Expr.g:11:9: '&&'
            pass 
            self.match("&&")




            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "T__72"



    # $ANTLR start "T__73"
    def mT__73(self, ):
        try:
            _type = T__73
            _channel = DEFAULT_CHANNEL

            # Expr.g:12:7: ( '&' )
            # Expr.g:12:9: '&'
            pass 
            self.match(38)



            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "T__73"



    # $ANTLR start "T__74"
    def mT__74(self, ):
        try:
            _type = T__74
            _channel = DEFAULT_CHANNEL

            # Expr.g:13:7: ( '&=' )
            # Expr.g:13:9: '&='
            pass 
            self.match("&=")




            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "T__74"



    # $ANTLR start "T__75"
    def mT__75(self, ):
        try:
            _type = T__75
            _channel = DEFAULT_CHANNEL

            # Expr.g:14:7: ( '(' )
            # Expr.g:14:9: '('
            pass 
            self.match(40)



            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "T__75"



    # $ANTLR start "T__76"
    def mT__76(self, ):
        try:
            _type = T__76
            _channel = DEFAULT_CHANNEL

            # Expr.g:15:7: ( ')' )
            # Expr.g:15:9: ')'
            pass 
            self.match(41)



            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "T__76"



    # $ANTLR start "T__77"
    def mT__77(self, ):
        try:
            _type = T__77
            _channel = DEFAULT_CHANNEL

            # Expr.g:16:7: ( '*' )
            # Expr.g:16:9: '*'
            pass 
            self.match(42)



            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "T__77"



    # $ANTLR start "T__78"
    def mT__78(self, ):
        try:
            _type = T__78
            _channel = DEFAULT_CHANNEL

            # Expr.g:17:7: ( '*=' )
            # Expr.g:17:9: '*='
            pass 
            self.match("*=")




            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "T__78"



    # $ANTLR start "T__79"
    def mT__79(self, ):
        try:
            _type = T__79
            _channel = DEFAULT_CHANNEL

            # Expr.g:18:7: ( '+' )
            # Expr.g:18:9: '+'
            pass 
            self.match(43)



            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "T__79"



    # $ANTLR start "T__80"
    def mT__80(self, ):
        try:
            _type = T__80
            _channel = DEFAULT_CHANNEL

            # Expr.g:19:7: ( '++' )
            # Expr.g:19:9: '++'
            pass 
            self.match("++")




            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "T__80"



    # $ANTLR start "T__81"
    def mT__81(self, ):
        try:
            _type = T__81
            _channel = DEFAULT_CHANNEL

            # Expr.g:20:7: ( '+=' )
            # Expr.g:20:9: '+='
            pass 
            self.match("+=")




            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "T__81"



    # $ANTLR start "T__82"
    def mT__82(self, ):
        try:
            _type = T__82
            _channel = DEFAULT_CHANNEL

            # Expr.g:21:7: ( ',' )
            # Expr.g:21:9: ','
            pass 
            self.match(44)



            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "T__82"



    # $ANTLR start "T__83"
    def mT__83(self, ):
        try:
            _type = T__83
            _channel = DEFAULT_CHANNEL

            # Expr.g:22:7: ( '-' )
            # Expr.g:22:9: '-'
            pass 
            self.match(45)



            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "T__83"



    # $ANTLR start "T__84"
    def mT__84(self, ):
        try:
            _type = T__84
            _channel = DEFAULT_CHANNEL

            # Expr.g:23:7: ( '--' )
            # Expr.g:23:9: '--'
            pass 
            self.match("--")




            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "T__84"



    # $ANTLR start "T__85"
    def mT__85(self, ):
        try:
            _type = T__85
            _channel = DEFAULT_CHANNEL

            # Expr.g:24:7: ( '-=' )
            # Expr.g:24:9: '-='
            pass 
            self.match("-=")




            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "T__85"



    # $ANTLR start "T__86"
    def mT__86(self, ):
        try:
            _type = T__86
            _channel = DEFAULT_CHANNEL

            # Expr.g:25:7: ( '.' )
            # Expr.g:25:9: '.'
            pass 
            self.match(46)



            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "T__86"



    # $ANTLR start "T__87"
    def mT__87(self, ):
        try:
            _type = T__87
            _channel = DEFAULT_CHANNEL

            # Expr.g:26:7: ( '.*' )
            # Expr.g:26:9: '.*'
            pass 
            self.match(".*")




            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "T__87"



    # $ANTLR start "T__88"
    def mT__88(self, ):
        try:
            _type = T__88
            _channel = DEFAULT_CHANNEL

            # Expr.g:27:7: ( '..' )
            # Expr.g:27:9: '..'
            pass 
            self.match("..")




            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "T__88"



    # $ANTLR start "T__89"
    def mT__89(self, ):
        try:
            _type = T__89
            _channel = DEFAULT_CHANNEL

            # Expr.g:28:7: ( '/' )
            # Expr.g:28:9: '/'
            pass 
            self.match(47)



            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "T__89"



    # $ANTLR start "T__90"
    def mT__90(self, ):
        try:
            _type = T__90
            _channel = DEFAULT_CHANNEL

            # Expr.g:29:7: ( '/=' )
            # Expr.g:29:9: '/='
            pass 
            self.match("/=")




            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "T__90"



    # $ANTLR start "T__91"
    def mT__91(self, ):
        try:
            _type = T__91
            _channel = DEFAULT_CHANNEL

            # Expr.g:30:7: ( ':' )
            # Expr.g:30:9: ':'
            pass 
            self.match(58)



            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "T__91"



    # $ANTLR start "T__92"
    def mT__92(self, ):
        try:
            _type = T__92
            _channel = DEFAULT_CHANNEL

            # Expr.g:31:7: ( ';' )
            # Expr.g:31:9: ';'
            pass 
            self.match(59)



            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "T__92"



    # $ANTLR start "T__93"
    def mT__93(self, ):
        try:
            _type = T__93
            _channel = DEFAULT_CHANNEL

            # Expr.g:32:7: ( '<' )
            # Expr.g:32:9: '<'
            pass 
            self.match(60)



            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "T__93"



    # $ANTLR start "T__94"
    def mT__94(self, ):
        try:
            _type = T__94
            _channel = DEFAULT_CHANNEL

            # Expr.g:33:7: ( '<=' )
            # Expr.g:33:9: '<='
            pass 
            self.match("<=")




            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "T__94"



    # $ANTLR start "T__95"
    def mT__95(self, ):
        try:
            _type = T__95
            _channel = DEFAULT_CHANNEL

            # Expr.g:34:7: ( '=' )
            # Expr.g:34:9: '='
            pass 
            self.match(61)



            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "T__95"



    # $ANTLR start "T__96"
    def mT__96(self, ):
        try:
            _type = T__96
            _channel = DEFAULT_CHANNEL

            # Expr.g:35:7: ( '==' )
            # Expr.g:35:9: '=='
            pass 
            self.match("==")




            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "T__96"



    # $ANTLR start "T__97"
    def mT__97(self, ):
        try:
            _type = T__97
            _channel = DEFAULT_CHANNEL

            # Expr.g:36:7: ( '=>' )
            # Expr.g:36:9: '=>'
            pass 
            self.match("=>")




            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "T__97"



    # $ANTLR start "T__98"
    def mT__98(self, ):
        try:
            _type = T__98
            _channel = DEFAULT_CHANNEL

            # Expr.g:37:7: ( '>' )
            # Expr.g:37:9: '>'
            pass 
            self.match(62)



            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "T__98"



    # $ANTLR start "T__99"
    def mT__99(self, ):
        try:
            _type = T__99
            _channel = DEFAULT_CHANNEL

            # Expr.g:38:7: ( '>=' )
            # Expr.g:38:9: '>='
            pass 
            self.match(">=")




            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "T__99"



    # $ANTLR start "T__100"
    def mT__100(self, ):
        try:
            _type = T__100
            _channel = DEFAULT_CHANNEL

            # Expr.g:39:8: ( '[' )
            # Expr.g:39:10: '['
            pass 
            self.match(91)



            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "T__100"



    # $ANTLR start "T__101"
    def mT__101(self, ):
        try:
            _type = T__101
            _channel = DEFAULT_CHANNEL

            # Expr.g:40:8: ( ']' )
            # Expr.g:40:10: ']'
            pass 
            self.match(93)



            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "T__101"



    # $ANTLR start "T__102"
    def mT__102(self, ):
        try:
            _type = T__102
            _channel = DEFAULT_CHANNEL

            # Expr.g:41:8: ( '^' )
            # Expr.g:41:10: '^'
            pass 
            self.match(94)



            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "T__102"



    # $ANTLR start "T__103"
    def mT__103(self, ):
        try:
            _type = T__103
            _channel = DEFAULT_CHANNEL

            # Expr.g:42:8: ( '^=' )
            # Expr.g:42:10: '^='
            pass 
            self.match("^=")




            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "T__103"



    # $ANTLR start "T__104"
    def mT__104(self, ):
        try:
            _type = T__104
            _channel = DEFAULT_CHANNEL

            # Expr.g:43:8: ( 'as' )
            # Expr.g:43:10: 'as'
            pass 
            self.match("as")




            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "T__104"



    # $ANTLR start "T__105"
    def mT__105(self, ):
        try:
            _type = T__105
            _channel = DEFAULT_CHANNEL

            # Expr.g:44:8: ( 'break' )
            # Expr.g:44:10: 'break'
            pass 
            self.match("break")




            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "T__105"



    # $ANTLR start "T__106"
    def mT__106(self, ):
        try:
            _type = T__106
            _channel = DEFAULT_CHANNEL

            # Expr.g:45:8: ( 'case' )
            # Expr.g:45:10: 'case'
            pass 
            self.match("case")




            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "T__106"



    # $ANTLR start "T__107"
    def mT__107(self, ):
        try:
            _type = T__107
            _channel = DEFAULT_CHANNEL

            # Expr.g:46:8: ( 'catch' )
            # Expr.g:46:10: 'catch'
            pass 
            self.match("catch")




            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "T__107"



    # $ANTLR start "T__108"
    def mT__108(self, ):
        try:
            _type = T__108
            _channel = DEFAULT_CHANNEL

            # Expr.g:47:8: ( 'class' )
            # Expr.g:47:10: 'class'
            pass 
            self.match("class")




            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "T__108"



    # $ANTLR start "T__109"
    def mT__109(self, ):
        try:
            _type = T__109
            _channel = DEFAULT_CHANNEL

            # Expr.g:48:8: ( 'continue' )
            # Expr.g:48:10: 'continue'
            pass 
            self.match("continue")




            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "T__109"



    # $ANTLR start "T__110"
    def mT__110(self, ):
        try:
            _type = T__110
            _channel = DEFAULT_CHANNEL

            # Expr.g:49:8: ( 'default' )
            # Expr.g:49:10: 'default'
            pass 
            self.match("default")




            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "T__110"



    # $ANTLR start "T__111"
    def mT__111(self, ):
        try:
            _type = T__111
            _channel = DEFAULT_CHANNEL

            # Expr.g:50:8: ( 'do' )
            # Expr.g:50:10: 'do'
            pass 
            self.match("do")




            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "T__111"



    # $ANTLR start "T__112"
    def mT__112(self, ):
        try:
            _type = T__112
            _channel = DEFAULT_CHANNEL

            # Expr.g:51:8: ( 'else' )
            # Expr.g:51:10: 'else'
            pass 
            self.match("else")




            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "T__112"



    # $ANTLR start "T__113"
    def mT__113(self, ):
        try:
            _type = T__113
            _channel = DEFAULT_CHANNEL

            # Expr.g:52:8: ( 'extends' )
            # Expr.g:52:10: 'extends'
            pass 
            self.match("extends")




            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "T__113"



    # $ANTLR start "T__114"
    def mT__114(self, ):
        try:
            _type = T__114
            _channel = DEFAULT_CHANNEL

            # Expr.g:53:8: ( 'finally' )
            # Expr.g:53:10: 'finally'
            pass 
            self.match("finally")




            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "T__114"



    # $ANTLR start "T__115"
    def mT__115(self, ):
        try:
            _type = T__115
            _channel = DEFAULT_CHANNEL

            # Expr.g:54:8: ( 'for' )
            # Expr.g:54:10: 'for'
            pass 
            self.match("for")




            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "T__115"



    # $ANTLR start "T__116"
    def mT__116(self, ):
        try:
            _type = T__116
            _channel = DEFAULT_CHANNEL

            # Expr.g:55:8: ( 'foreach' )
            # Expr.g:55:10: 'foreach'
            pass 
            self.match("foreach")




            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "T__116"



    # $ANTLR start "T__117"
    def mT__117(self, ):
        try:
            _type = T__117
            _channel = DEFAULT_CHANNEL

            # Expr.g:56:8: ( 'function' )
            # Expr.g:56:10: 'function'
            pass 
            self.match("function")




            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "T__117"



    # $ANTLR start "T__118"
    def mT__118(self, ):
        try:
            _type = T__118
            _channel = DEFAULT_CHANNEL

            # Expr.g:57:8: ( 'if' )
            # Expr.g:57:10: 'if'
            pass 
            self.match("if")




            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "T__118"



    # $ANTLR start "T__119"
    def mT__119(self, ):
        try:
            _type = T__119
            _channel = DEFAULT_CHANNEL

            # Expr.g:58:8: ( 'import' )
            # Expr.g:58:10: 'import'
            pass 
            self.match("import")




            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "T__119"



    # $ANTLR start "T__120"
    def mT__120(self, ):
        try:
            _type = T__120
            _channel = DEFAULT_CHANNEL

            # Expr.g:59:8: ( 'init' )
            # Expr.g:59:10: 'init'
            pass 
            self.match("init")




            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "T__120"



    # $ANTLR start "T__121"
    def mT__121(self, ):
        try:
            _type = T__121
            _channel = DEFAULT_CHANNEL

            # Expr.g:60:8: ( 'new' )
            # Expr.g:60:10: 'new'
            pass 
            self.match("new")




            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "T__121"



    # $ANTLR start "T__122"
    def mT__122(self, ):
        try:
            _type = T__122
            _channel = DEFAULT_CHANNEL

            # Expr.g:61:8: ( 'print' )
            # Expr.g:61:10: 'print'
            pass 
            self.match("print")




            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "T__122"



    # $ANTLR start "T__123"
    def mT__123(self, ):
        try:
            _type = T__123
            _channel = DEFAULT_CHANNEL

            # Expr.g:62:8: ( 'printf' )
            # Expr.g:62:10: 'printf'
            pass 
            self.match("printf")




            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "T__123"



    # $ANTLR start "T__124"
    def mT__124(self, ):
        try:
            _type = T__124
            _channel = DEFAULT_CHANNEL

            # Expr.g:63:8: ( 'public' )
            # Expr.g:63:10: 'public'
            pass 
            self.match("public")




            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "T__124"



    # $ANTLR start "T__125"
    def mT__125(self, ):
        try:
            _type = T__125
            _channel = DEFAULT_CHANNEL

            # Expr.g:64:8: ( 'return' )
            # Expr.g:64:10: 'return'
            pass 
            self.match("return")




            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "T__125"



    # $ANTLR start "T__126"
    def mT__126(self, ):
        try:
            _type = T__126
            _channel = DEFAULT_CHANNEL

            # Expr.g:65:8: ( 'sprintf' )
            # Expr.g:65:10: 'sprintf'
            pass 
            self.match("sprintf")




            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "T__126"



    # $ANTLR start "T__127"
    def mT__127(self, ):
        try:
            _type = T__127
            _channel = DEFAULT_CHANNEL

            # Expr.g:66:8: ( 'static' )
            # Expr.g:66:10: 'static'
            pass 
            self.match("static")




            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "T__127"



    # $ANTLR start "T__128"
    def mT__128(self, ):
        try:
            _type = T__128
            _channel = DEFAULT_CHANNEL

            # Expr.g:67:8: ( 'switch' )
            # Expr.g:67:10: 'switch'
            pass 
            self.match("switch")




            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "T__128"



    # $ANTLR start "T__129"
    def mT__129(self, ):
        try:
            _type = T__129
            _channel = DEFAULT_CHANNEL

            # Expr.g:68:8: ( 'throw' )
            # Expr.g:68:10: 'throw'
            pass 
            self.match("throw")




            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "T__129"



    # $ANTLR start "T__130"
    def mT__130(self, ):
        try:
            _type = T__130
            _channel = DEFAULT_CHANNEL

            # Expr.g:69:8: ( 'try' )
            # Expr.g:69:10: 'try'
            pass 
            self.match("try")




            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "T__130"



    # $ANTLR start "T__131"
    def mT__131(self, ):
        try:
            _type = T__131
            _channel = DEFAULT_CHANNEL

            # Expr.g:70:8: ( 'while' )
            # Expr.g:70:10: 'while'
            pass 
            self.match("while")




            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "T__131"



    # $ANTLR start "T__132"
    def mT__132(self, ):
        try:
            _type = T__132
            _channel = DEFAULT_CHANNEL

            # Expr.g:71:8: ( '{' )
            # Expr.g:71:10: '{'
            pass 
            self.match(123)



            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "T__132"



    # $ANTLR start "T__133"
    def mT__133(self, ):
        try:
            _type = T__133
            _channel = DEFAULT_CHANNEL

            # Expr.g:72:8: ( '|' )
            # Expr.g:72:10: '|'
            pass 
            self.match(124)



            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "T__133"



    # $ANTLR start "T__134"
    def mT__134(self, ):
        try:
            _type = T__134
            _channel = DEFAULT_CHANNEL

            # Expr.g:73:8: ( '|=' )
            # Expr.g:73:10: '|='
            pass 
            self.match("|=")




            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "T__134"



    # $ANTLR start "T__135"
    def mT__135(self, ):
        try:
            _type = T__135
            _channel = DEFAULT_CHANNEL

            # Expr.g:74:8: ( '||' )
            # Expr.g:74:10: '||'
            pass 
            self.match("||")




            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "T__135"



    # $ANTLR start "T__136"
    def mT__136(self, ):
        try:
            _type = T__136
            _channel = DEFAULT_CHANNEL

            # Expr.g:75:8: ( '}' )
            # Expr.g:75:10: '}'
            pass 
            self.match(125)



            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "T__136"



    # $ANTLR start "NULL"
    def mNULL(self, ):
        try:
            _type = NULL
            _channel = DEFAULT_CHANNEL

            # Expr.g:363:2: ( 'null' )
            # Expr.g:363:4: 'null'
            pass 
            self.match("null")




            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "NULL"



    # $ANTLR start "BOOL"
    def mBOOL(self, ):
        try:
            _type = BOOL
            _channel = DEFAULT_CHANNEL

            # Expr.g:364:2: ( 'true' | 'false' )
            alt1 = 2
            LA1_0 = self.input.LA(1)

            if (LA1_0 == 116) :
                alt1 = 1
            elif (LA1_0 == 102) :
                alt1 = 2
            else:
                nvae = NoViableAltException("", 1, 0, self.input)

                raise nvae


            if alt1 == 1:
                # Expr.g:364:4: 'true'
                pass 
                self.match("true")



            elif alt1 == 2:
                # Expr.g:364:13: 'false'
                pass 
                self.match("false")



            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "BOOL"



    # $ANTLR start "ID"
    def mID(self, ):
        try:
            _type = ID
            _channel = DEFAULT_CHANNEL

            # Expr.g:367:2: ( ( ALPHA | '_' | '$' ) ( ALPHA | '_' | DIGIT )* )
            # Expr.g:367:4: ( ALPHA | '_' | '$' ) ( ALPHA | '_' | DIGIT )*
            pass 
            if self.input.LA(1) == 36 or (65 <= self.input.LA(1) <= 90) or self.input.LA(1) == 95 or (97 <= self.input.LA(1) <= 122):
                self.input.consume()
            else:
                mse = MismatchedSetException(None, self.input)
                self.recover(mse)
                raise mse



            # Expr.g:367:24: ( ALPHA | '_' | DIGIT )*
            while True: #loop2
                alt2 = 2
                LA2_0 = self.input.LA(1)

                if ((48 <= LA2_0 <= 57) or (65 <= LA2_0 <= 90) or LA2_0 == 95 or (97 <= LA2_0 <= 122)) :
                    alt2 = 1


                if alt2 == 1:
                    # Expr.g:
                    pass 
                    if (48 <= self.input.LA(1) <= 57) or (65 <= self.input.LA(1) <= 90) or self.input.LA(1) == 95 or (97 <= self.input.LA(1) <= 122):
                        self.input.consume()
                    else:
                        mse = MismatchedSetException(None, self.input)
                        self.recover(mse)
                        raise mse




                else:
                    break #loop2




            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "ID"



    # $ANTLR start "INT"
    def mINT(self, ):
        try:
            _type = INT
            _channel = DEFAULT_CHANNEL

            # Expr.g:371:2: ( ( DIGIT )+ )
            # Expr.g:371:4: ( DIGIT )+
            pass 
            # Expr.g:371:4: ( DIGIT )+
            cnt3 = 0
            while True: #loop3
                alt3 = 2
                LA3_0 = self.input.LA(1)

                if ((48 <= LA3_0 <= 57)) :
                    alt3 = 1


                if alt3 == 1:
                    # Expr.g:
                    pass 
                    if (48 <= self.input.LA(1) <= 57):
                        self.input.consume()
                    else:
                        mse = MismatchedSetException(None, self.input)
                        self.recover(mse)
                        raise mse




                else:
                    if cnt3 >= 1:
                        break #loop3

                    eee = EarlyExitException(3, self.input)
                    raise eee

                cnt3 += 1




            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "INT"



    # $ANTLR start "FLOAT"
    def mFLOAT(self, ):
        try:
            _type = FLOAT
            _channel = DEFAULT_CHANNEL

            # Expr.g:374:2: ( INT '.' ( DIGIT )* )
            # Expr.g:374:4: INT '.' ( DIGIT )*
            pass 
            self.mINT()


            self.match(46)

            # Expr.g:374:12: ( DIGIT )*
            while True: #loop4
                alt4 = 2
                LA4_0 = self.input.LA(1)

                if ((48 <= LA4_0 <= 57)) :
                    alt4 = 1


                if alt4 == 1:
                    # Expr.g:
                    pass 
                    if (48 <= self.input.LA(1) <= 57):
                        self.input.consume()
                    else:
                        mse = MismatchedSetException(None, self.input)
                        self.recover(mse)
                        raise mse




                else:
                    break #loop4




            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "FLOAT"



    # $ANTLR start "ALPHA"
    def mALPHA(self, ):
        try:
            # Expr.g:377:2: ( 'a' .. 'z' | 'A' .. 'Z' )
            # Expr.g:
            pass 
            if (65 <= self.input.LA(1) <= 90) or (97 <= self.input.LA(1) <= 122):
                self.input.consume()
            else:
                mse = MismatchedSetException(None, self.input)
                self.recover(mse)
                raise mse






        finally:
            pass

    # $ANTLR end "ALPHA"



    # $ANTLR start "DIGIT"
    def mDIGIT(self, ):
        try:
            # Expr.g:380:2: ( '0' .. '9' )
            # Expr.g:
            pass 
            if (48 <= self.input.LA(1) <= 57):
                self.input.consume()
            else:
                mse = MismatchedSetException(None, self.input)
                self.recover(mse)
                raise mse






        finally:
            pass

    # $ANTLR end "DIGIT"



    # $ANTLR start "STRING"
    def mSTRING(self, ):
        try:
            _type = STRING
            _channel = DEFAULT_CHANNEL

            # Expr.g:384:2: ( '\"' ( DOUBLE_QUOTE_CHARS )* '\"' | '\\'' ( SINGLE_QUOTE_CHARS )* '\\'' )
            alt7 = 2
            LA7_0 = self.input.LA(1)

            if (LA7_0 == 34) :
                alt7 = 1
            elif (LA7_0 == 39) :
                alt7 = 2
            else:
                nvae = NoViableAltException("", 7, 0, self.input)

                raise nvae


            if alt7 == 1:
                # Expr.g:384:4: '\"' ( DOUBLE_QUOTE_CHARS )* '\"'
                pass 
                self.match(34)

                # Expr.g:384:8: ( DOUBLE_QUOTE_CHARS )*
                while True: #loop5
                    alt5 = 2
                    LA5_0 = self.input.LA(1)

                    if ((0 <= LA5_0 <= 33) or (35 <= LA5_0 <= 65535)) :
                        alt5 = 1


                    if alt5 == 1:
                        # Expr.g:384:8: DOUBLE_QUOTE_CHARS
                        pass 
                        self.mDOUBLE_QUOTE_CHARS()



                    else:
                        break #loop5


                self.match(34)


            elif alt7 == 2:
                # Expr.g:385:4: '\\'' ( SINGLE_QUOTE_CHARS )* '\\''
                pass 
                self.match(39)

                # Expr.g:385:9: ( SINGLE_QUOTE_CHARS )*
                while True: #loop6
                    alt6 = 2
                    LA6_0 = self.input.LA(1)

                    if ((0 <= LA6_0 <= 38) or (40 <= LA6_0 <= 65535)) :
                        alt6 = 1


                    if alt6 == 1:
                        # Expr.g:385:9: SINGLE_QUOTE_CHARS
                        pass 
                        self.mSINGLE_QUOTE_CHARS()



                    else:
                        break #loop6


                self.match(39)


            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "STRING"



    # $ANTLR start "DOUBLE_QUOTE_CHARS"
    def mDOUBLE_QUOTE_CHARS(self, ):
        try:
            # Expr.g:388:2: (~ ( '\"' ) | '\\\\' '\"' | '\\\\' '\\\\' )
            alt8 = 3
            LA8_0 = self.input.LA(1)

            if (LA8_0 == 92) :
                LA8 = self.input.LA(2)
                if LA8 == 34:
                    alt8 = 2
                elif LA8 == 92:
                    alt8 = 3
                else:
                    alt8 = 1

            elif ((0 <= LA8_0 <= 33) or (35 <= LA8_0 <= 91) or (93 <= LA8_0 <= 65535)) :
                alt8 = 1
            else:
                nvae = NoViableAltException("", 8, 0, self.input)

                raise nvae


            if alt8 == 1:
                # Expr.g:388:4: ~ ( '\"' )
                pass 
                if (0 <= self.input.LA(1) <= 33) or (35 <= self.input.LA(1) <= 65535):
                    self.input.consume()
                else:
                    mse = MismatchedSetException(None, self.input)
                    self.recover(mse)
                    raise mse




            elif alt8 == 2:
                # Expr.g:390:4: '\\\\' '\"'
                pass 
                self.match(92)

                self.match(34)


            elif alt8 == 3:
                # Expr.g:391:4: '\\\\' '\\\\'
                pass 
                self.match(92)

                self.match(92)



        finally:
            pass

    # $ANTLR end "DOUBLE_QUOTE_CHARS"



    # $ANTLR start "SINGLE_QUOTE_CHARS"
    def mSINGLE_QUOTE_CHARS(self, ):
        try:
            # Expr.g:394:2: (~ ( '\\'' ) | '\\\\' '\\'' | '\\\\' '\\\\' )
            alt9 = 3
            LA9_0 = self.input.LA(1)

            if (LA9_0 == 92) :
                LA9 = self.input.LA(2)
                if LA9 == 39:
                    alt9 = 2
                elif LA9 == 92:
                    alt9 = 3
                else:
                    alt9 = 1

            elif ((0 <= LA9_0 <= 38) or (40 <= LA9_0 <= 91) or (93 <= LA9_0 <= 65535)) :
                alt9 = 1
            else:
                nvae = NoViableAltException("", 9, 0, self.input)

                raise nvae


            if alt9 == 1:
                # Expr.g:394:4: ~ ( '\\'' )
                pass 
                if (0 <= self.input.LA(1) <= 38) or (40 <= self.input.LA(1) <= 65535):
                    self.input.consume()
                else:
                    mse = MismatchedSetException(None, self.input)
                    self.recover(mse)
                    raise mse




            elif alt9 == 2:
                # Expr.g:395:4: '\\\\' '\\''
                pass 
                self.match(92)

                self.match(39)


            elif alt9 == 3:
                # Expr.g:396:4: '\\\\' '\\\\'
                pass 
                self.match(92)

                self.match(92)



        finally:
            pass

    # $ANTLR end "SINGLE_QUOTE_CHARS"



    # $ANTLR start "NEWLINE"
    def mNEWLINE(self, ):
        try:
            # Expr.g:400:2: ( ( '\\r' )? '\\n' )
            # Expr.g:400:4: ( '\\r' )? '\\n'
            pass 
            # Expr.g:400:4: ( '\\r' )?
            alt10 = 2
            LA10_0 = self.input.LA(1)

            if (LA10_0 == 13) :
                alt10 = 1
            if alt10 == 1:
                # Expr.g:400:4: '\\r'
                pass 
                self.match(13)




            self.match(10)




        finally:
            pass

    # $ANTLR end "NEWLINE"



    # $ANTLR start "WS"
    def mWS(self, ):
        try:
            _type = WS
            _channel = DEFAULT_CHANNEL

            # Expr.g:404:2: ( ( ' ' | '\\t' | '\\r' | '\\n' )+ )
            # Expr.g:404:4: ( ' ' | '\\t' | '\\r' | '\\n' )+
            pass 
            # Expr.g:404:4: ( ' ' | '\\t' | '\\r' | '\\n' )+
            cnt11 = 0
            while True: #loop11
                alt11 = 2
                LA11_0 = self.input.LA(1)

                if ((9 <= LA11_0 <= 10) or LA11_0 == 13 or LA11_0 == 32) :
                    alt11 = 1


                if alt11 == 1:
                    # Expr.g:
                    pass 
                    if (9 <= self.input.LA(1) <= 10) or self.input.LA(1) == 13 or self.input.LA(1) == 32:
                        self.input.consume()
                    else:
                        mse = MismatchedSetException(None, self.input)
                        self.recover(mse)
                        raise mse




                else:
                    if cnt11 >= 1:
                        break #loop11

                    eee = EarlyExitException(11, self.input)
                    raise eee

                cnt11 += 1


            #action start
            _channel=HIDDEN;
            #action end




            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "WS"



    # $ANTLR start "COMMENT"
    def mCOMMENT(self, ):
        try:
            _type = COMMENT
            _channel = DEFAULT_CHANNEL

            # Expr.g:407:2: ( '/*' ( options {greedy=false; } : . )* '*/' )
            # Expr.g:407:4: '/*' ( options {greedy=false; } : . )* '*/'
            pass 
            self.match("/*")


            # Expr.g:407:9: ( options {greedy=false; } : . )*
            while True: #loop12
                alt12 = 2
                LA12_0 = self.input.LA(1)

                if (LA12_0 == 42) :
                    LA12_1 = self.input.LA(2)

                    if (LA12_1 == 47) :
                        alt12 = 2
                    elif ((0 <= LA12_1 <= 46) or (48 <= LA12_1 <= 65535)) :
                        alt12 = 1


                elif ((0 <= LA12_0 <= 41) or (43 <= LA12_0 <= 65535)) :
                    alt12 = 1


                if alt12 == 1:
                    # Expr.g:407:34: .
                    pass 
                    self.matchAny()


                else:
                    break #loop12


            self.match("*/")


            #action start
            _channel=HIDDEN;
            #action end




            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "COMMENT"



    # $ANTLR start "LINECOMMENT"
    def mLINECOMMENT(self, ):
        try:
            _type = LINECOMMENT
            _channel = DEFAULT_CHANNEL

            # Expr.g:410:2: ( ( '//' | '#' ) (~ ( '\\r' | '\\n' ) )* NEWLINE )
            # Expr.g:410:4: ( '//' | '#' ) (~ ( '\\r' | '\\n' ) )* NEWLINE
            pass 
            # Expr.g:410:4: ( '//' | '#' )
            alt13 = 2
            LA13_0 = self.input.LA(1)

            if (LA13_0 == 47) :
                alt13 = 1
            elif (LA13_0 == 35) :
                alt13 = 2
            else:
                nvae = NoViableAltException("", 13, 0, self.input)

                raise nvae


            if alt13 == 1:
                # Expr.g:410:5: '//'
                pass 
                self.match("//")



            elif alt13 == 2:
                # Expr.g:410:10: '#'
                pass 
                self.match(35)




            # Expr.g:410:15: (~ ( '\\r' | '\\n' ) )*
            while True: #loop14
                alt14 = 2
                LA14_0 = self.input.LA(1)

                if ((0 <= LA14_0 <= 9) or (11 <= LA14_0 <= 12) or (14 <= LA14_0 <= 65535)) :
                    alt14 = 1


                if alt14 == 1:
                    # Expr.g:
                    pass 
                    if (0 <= self.input.LA(1) <= 9) or (11 <= self.input.LA(1) <= 12) or (14 <= self.input.LA(1) <= 65535):
                        self.input.consume()
                    else:
                        mse = MismatchedSetException(None, self.input)
                        self.recover(mse)
                        raise mse




                else:
                    break #loop14


            self.mNEWLINE()


            #action start
            _channel=HIDDEN;
            #action end




            self._state.type = _type
            self._state.channel = _channel
        finally:
            pass

    # $ANTLR end "LINECOMMENT"



    def mTokens(self):
        # Expr.g:1:8: ( T__68 | T__69 | T__70 | T__71 | T__72 | T__73 | T__74 | T__75 | T__76 | T__77 | T__78 | T__79 | T__80 | T__81 | T__82 | T__83 | T__84 | T__85 | T__86 | T__87 | T__88 | T__89 | T__90 | T__91 | T__92 | T__93 | T__94 | T__95 | T__96 | T__97 | T__98 | T__99 | T__100 | T__101 | T__102 | T__103 | T__104 | T__105 | T__106 | T__107 | T__108 | T__109 | T__110 | T__111 | T__112 | T__113 | T__114 | T__115 | T__116 | T__117 | T__118 | T__119 | T__120 | T__121 | T__122 | T__123 | T__124 | T__125 | T__126 | T__127 | T__128 | T__129 | T__130 | T__131 | T__132 | T__133 | T__134 | T__135 | T__136 | NULL | BOOL | ID | INT | FLOAT | STRING | WS | COMMENT | LINECOMMENT )
        alt15 = 78
        alt15 = self.dfa15.predict(self.input)
        if alt15 == 1:
            # Expr.g:1:10: T__68
            pass 
            self.mT__68()



        elif alt15 == 2:
            # Expr.g:1:16: T__69
            pass 
            self.mT__69()



        elif alt15 == 3:
            # Expr.g:1:22: T__70
            pass 
            self.mT__70()



        elif alt15 == 4:
            # Expr.g:1:28: T__71
            pass 
            self.mT__71()



        elif alt15 == 5:
            # Expr.g:1:34: T__72
            pass 
            self.mT__72()



        elif alt15 == 6:
            # Expr.g:1:40: T__73
            pass 
            self.mT__73()



        elif alt15 == 7:
            # Expr.g:1:46: T__74
            pass 
            self.mT__74()



        elif alt15 == 8:
            # Expr.g:1:52: T__75
            pass 
            self.mT__75()



        elif alt15 == 9:
            # Expr.g:1:58: T__76
            pass 
            self.mT__76()



        elif alt15 == 10:
            # Expr.g:1:64: T__77
            pass 
            self.mT__77()



        elif alt15 == 11:
            # Expr.g:1:70: T__78
            pass 
            self.mT__78()



        elif alt15 == 12:
            # Expr.g:1:76: T__79
            pass 
            self.mT__79()



        elif alt15 == 13:
            # Expr.g:1:82: T__80
            pass 
            self.mT__80()



        elif alt15 == 14:
            # Expr.g:1:88: T__81
            pass 
            self.mT__81()



        elif alt15 == 15:
            # Expr.g:1:94: T__82
            pass 
            self.mT__82()



        elif alt15 == 16:
            # Expr.g:1:100: T__83
            pass 
            self.mT__83()



        elif alt15 == 17:
            # Expr.g:1:106: T__84
            pass 
            self.mT__84()



        elif alt15 == 18:
            # Expr.g:1:112: T__85
            pass 
            self.mT__85()



        elif alt15 == 19:
            # Expr.g:1:118: T__86
            pass 
            self.mT__86()



        elif alt15 == 20:
            # Expr.g:1:124: T__87
            pass 
            self.mT__87()



        elif alt15 == 21:
            # Expr.g:1:130: T__88
            pass 
            self.mT__88()



        elif alt15 == 22:
            # Expr.g:1:136: T__89
            pass 
            self.mT__89()



        elif alt15 == 23:
            # Expr.g:1:142: T__90
            pass 
            self.mT__90()



        elif alt15 == 24:
            # Expr.g:1:148: T__91
            pass 
            self.mT__91()



        elif alt15 == 25:
            # Expr.g:1:154: T__92
            pass 
            self.mT__92()



        elif alt15 == 26:
            # Expr.g:1:160: T__93
            pass 
            self.mT__93()



        elif alt15 == 27:
            # Expr.g:1:166: T__94
            pass 
            self.mT__94()



        elif alt15 == 28:
            # Expr.g:1:172: T__95
            pass 
            self.mT__95()



        elif alt15 == 29:
            # Expr.g:1:178: T__96
            pass 
            self.mT__96()



        elif alt15 == 30:
            # Expr.g:1:184: T__97
            pass 
            self.mT__97()



        elif alt15 == 31:
            # Expr.g:1:190: T__98
            pass 
            self.mT__98()



        elif alt15 == 32:
            # Expr.g:1:196: T__99
            pass 
            self.mT__99()



        elif alt15 == 33:
            # Expr.g:1:202: T__100
            pass 
            self.mT__100()



        elif alt15 == 34:
            # Expr.g:1:209: T__101
            pass 
            self.mT__101()



        elif alt15 == 35:
            # Expr.g:1:216: T__102
            pass 
            self.mT__102()



        elif alt15 == 36:
            # Expr.g:1:223: T__103
            pass 
            self.mT__103()



        elif alt15 == 37:
            # Expr.g:1:230: T__104
            pass 
            self.mT__104()



        elif alt15 == 38:
            # Expr.g:1:237: T__105
            pass 
            self.mT__105()



        elif alt15 == 39:
            # Expr.g:1:244: T__106
            pass 
            self.mT__106()



        elif alt15 == 40:
            # Expr.g:1:251: T__107
            pass 
            self.mT__107()



        elif alt15 == 41:
            # Expr.g:1:258: T__108
            pass 
            self.mT__108()



        elif alt15 == 42:
            # Expr.g:1:265: T__109
            pass 
            self.mT__109()



        elif alt15 == 43:
            # Expr.g:1:272: T__110
            pass 
            self.mT__110()



        elif alt15 == 44:
            # Expr.g:1:279: T__111
            pass 
            self.mT__111()



        elif alt15 == 45:
            # Expr.g:1:286: T__112
            pass 
            self.mT__112()



        elif alt15 == 46:
            # Expr.g:1:293: T__113
            pass 
            self.mT__113()



        elif alt15 == 47:
            # Expr.g:1:300: T__114
            pass 
            self.mT__114()



        elif alt15 == 48:
            # Expr.g:1:307: T__115
            pass 
            self.mT__115()



        elif alt15 == 49:
            # Expr.g:1:314: T__116
            pass 
            self.mT__116()



        elif alt15 == 50:
            # Expr.g:1:321: T__117
            pass 
            self.mT__117()



        elif alt15 == 51:
            # Expr.g:1:328: T__118
            pass 
            self.mT__118()



        elif alt15 == 52:
            # Expr.g:1:335: T__119
            pass 
            self.mT__119()



        elif alt15 == 53:
            # Expr.g:1:342: T__120
            pass 
            self.mT__120()



        elif alt15 == 54:
            # Expr.g:1:349: T__121
            pass 
            self.mT__121()



        elif alt15 == 55:
            # Expr.g:1:356: T__122
            pass 
            self.mT__122()



        elif alt15 == 56:
            # Expr.g:1:363: T__123
            pass 
            self.mT__123()



        elif alt15 == 57:
            # Expr.g:1:370: T__124
            pass 
            self.mT__124()



        elif alt15 == 58:
            # Expr.g:1:377: T__125
            pass 
            self.mT__125()



        elif alt15 == 59:
            # Expr.g:1:384: T__126
            pass 
            self.mT__126()



        elif alt15 == 60:
            # Expr.g:1:391: T__127
            pass 
            self.mT__127()



        elif alt15 == 61:
            # Expr.g:1:398: T__128
            pass 
            self.mT__128()



        elif alt15 == 62:
            # Expr.g:1:405: T__129
            pass 
            self.mT__129()



        elif alt15 == 63:
            # Expr.g:1:412: T__130
            pass 
            self.mT__130()



        elif alt15 == 64:
            # Expr.g:1:419: T__131
            pass 
            self.mT__131()



        elif alt15 == 65:
            # Expr.g:1:426: T__132
            pass 
            self.mT__132()



        elif alt15 == 66:
            # Expr.g:1:433: T__133
            pass 
            self.mT__133()



        elif alt15 == 67:
            # Expr.g:1:440: T__134
            pass 
            self.mT__134()



        elif alt15 == 68:
            # Expr.g:1:447: T__135
            pass 
            self.mT__135()



        elif alt15 == 69:
            # Expr.g:1:454: T__136
            pass 
            self.mT__136()



        elif alt15 == 70:
            # Expr.g:1:461: NULL
            pass 
            self.mNULL()



        elif alt15 == 71:
            # Expr.g:1:466: BOOL
            pass 
            self.mBOOL()



        elif alt15 == 72:
            # Expr.g:1:471: ID
            pass 
            self.mID()



        elif alt15 == 73:
            # Expr.g:1:474: INT
            pass 
            self.mINT()



        elif alt15 == 74:
            # Expr.g:1:478: FLOAT
            pass 
            self.mFLOAT()



        elif alt15 == 75:
            # Expr.g:1:484: STRING
            pass 
            self.mSTRING()



        elif alt15 == 76:
            # Expr.g:1:491: WS
            pass 
            self.mWS()



        elif alt15 == 77:
            # Expr.g:1:494: COMMENT
            pass 
            self.mCOMMENT()



        elif alt15 == 78:
            # Expr.g:1:502: LINECOMMENT
            pass 
            self.mLINECOMMENT()








    # lookup tables for DFA #15

    DFA15_eot = DFA.unpack(
        u"\1\uffff\1\52\1\54\1\57\2\uffff\1\61\1\64\1\uffff\1\67\1\72\1\75"
        u"\2\uffff\1\77\1\102\1\104\2\uffff\1\106\15\44\1\uffff\1\144\2\uffff"
        u"\1\145\41\uffff\1\147\5\44\1\156\6\44\1\165\15\44\6\uffff\6\44"
        u"\1\uffff\3\44\1\u008e\2\44\1\uffff\2\44\1\u0093\10\44\1\u009c\3"
        u"\44\1\u00a0\4\44\1\u00a5\3\44\1\uffff\3\44\1\u00ac\1\uffff\1\u00ad"
        u"\7\44\1\uffff\1\u00b5\1\44\1\u00b7\1\uffff\1\u00b8\1\u00b9\2\44"
        u"\1\uffff\4\44\1\u00b5\1\44\2\uffff\1\u00c2\5\44\1\u00c8\1\uffff"
        u"\1\u00c9\3\uffff\6\44\1\u00d0\1\u00d1\1\uffff\1\u00d2\1\u00d3\1"
        u"\44\1\u00d5\1\u00d6\2\uffff\1\44\1\u00d8\1\u00d9\1\u00da\1\u00db"
        u"\1\44\4\uffff\1\u00dd\2\uffff\1\u00de\4\uffff\1\u00df\3\uffff"
        )

    DFA15_eof = DFA.unpack(
        u"\u00e0\uffff"
        )

    DFA15_min = DFA.unpack(
        u"\1\11\2\75\1\46\2\uffff\1\75\1\53\1\uffff\1\55\2\52\2\uffff\3\75"
        u"\2\uffff\1\75\1\163\1\162\1\141\1\145\1\154\1\141\1\146\1\145\1"
        u"\162\1\145\1\160\2\150\1\uffff\1\75\2\uffff\1\56\41\uffff\1\60"
        u"\1\145\1\163\1\141\1\156\1\146\1\60\1\163\1\164\1\156\1\162\1\156"
        u"\1\154\1\60\1\160\1\151\1\167\1\154\1\151\1\142\1\164\1\162\1\141"
        u"\1\151\1\162\1\165\1\151\6\uffff\1\141\1\145\1\143\1\163\1\164"
        u"\1\141\1\uffff\2\145\1\141\1\60\1\143\1\163\1\uffff\1\157\1\164"
        u"\1\60\1\154\1\156\1\154\1\165\1\151\2\164\1\157\1\60\1\145\1\154"
        u"\1\153\1\60\1\150\1\163\1\151\1\165\1\60\1\156\1\154\1\141\1\uffff"
        u"\1\164\1\145\1\162\1\60\1\uffff\1\60\1\164\1\151\1\162\1\156\1"
        u"\151\1\143\1\167\1\uffff\1\60\1\145\1\60\1\uffff\2\60\1\156\1\154"
        u"\1\uffff\1\144\1\154\1\143\1\151\1\60\1\164\2\uffff\1\60\1\143"
        u"\1\156\1\164\1\143\1\150\1\60\1\uffff\1\60\3\uffff\1\165\1\164"
        u"\1\163\1\171\1\150\1\157\2\60\1\uffff\2\60\1\146\2\60\2\uffff\1"
        u"\145\4\60\1\156\4\uffff\1\60\2\uffff\1\60\4\uffff\1\60\3\uffff"
        )

    DFA15_max = DFA.unpack(
        u"\1\175\3\75\2\uffff\2\75\1\uffff\1\75\1\56\1\75\2\uffff\1\75\1"
        u"\76\1\75\2\uffff\1\75\1\163\1\162\2\157\1\170\1\165\1\156\2\165"
        u"\1\145\1\167\1\162\1\150\1\uffff\1\174\2\uffff\1\71\41\uffff\1"
        u"\172\1\145\1\164\1\141\1\156\1\146\1\172\1\163\1\164\1\156\1\162"
        u"\1\156\1\154\1\172\1\160\1\151\1\167\1\154\1\151\1\142\1\164\1"
        u"\162\1\141\1\151\1\162\1\171\1\151\6\uffff\1\141\1\145\1\143\1"
        u"\163\1\164\1\141\1\uffff\2\145\1\141\1\172\1\143\1\163\1\uffff"
        u"\1\157\1\164\1\172\1\154\1\156\1\154\1\165\1\151\2\164\1\157\1"
        u"\172\1\145\1\154\1\153\1\172\1\150\1\163\1\151\1\165\1\172\1\156"
        u"\1\154\1\141\1\uffff\1\164\1\145\1\162\1\172\1\uffff\1\172\1\164"
        u"\1\151\1\162\1\156\1\151\1\143\1\167\1\uffff\1\172\1\145\1\172"
        u"\1\uffff\2\172\1\156\1\154\1\uffff\1\144\1\154\1\143\1\151\1\172"
        u"\1\164\2\uffff\1\172\1\143\1\156\1\164\1\143\1\150\1\172\1\uffff"
        u"\1\172\3\uffff\1\165\1\164\1\163\1\171\1\150\1\157\2\172\1\uffff"
        u"\2\172\1\146\2\172\2\uffff\1\145\4\172\1\156\4\uffff\1\172\2\uffff"
        u"\1\172\4\uffff\1\172\3\uffff"
        )

    DFA15_accept = DFA.unpack(
        u"\4\uffff\1\10\1\11\2\uffff\1\17\3\uffff\1\30\1\31\3\uffff\1\41"
        u"\1\42\16\uffff\1\101\1\uffff\1\105\1\110\1\uffff\1\113\1\114\1"
        u"\116\1\2\1\1\1\4\1\3\1\5\1\7\1\6\1\13\1\12\1\15\1\16\1\14\1\21"
        u"\1\22\1\20\1\24\1\25\1\23\1\27\1\115\1\26\1\33\1\32\1\35\1\36\1"
        u"\34\1\40\1\37\1\44\1\43\33\uffff\1\103\1\104\1\102\1\111\1\112"
        u"\1\45\6\uffff\1\54\6\uffff\1\63\30\uffff\1\60\4\uffff\1\66\10\uffff"
        u"\1\77\3\uffff\1\47\4\uffff\1\55\6\uffff\1\65\1\106\7\uffff\1\107"
        u"\1\uffff\1\46\1\50\1\51\10\uffff\1\67\5\uffff\1\76\1\100\6\uffff"
        u"\1\64\1\70\1\71\1\72\1\uffff\1\74\1\75\1\uffff\1\53\1\56\1\57\1"
        u"\61\1\uffff\1\73\1\52\1\62"
        )

    DFA15_special = DFA.unpack(
        u"\u00e0\uffff"
        )


    DFA15_transition = [
        DFA.unpack(u"\2\47\2\uffff\1\47\22\uffff\1\47\1\1\1\46\1\50\1\44"
        u"\1\2\1\3\1\46\1\4\1\5\1\6\1\7\1\10\1\11\1\12\1\13\12\45\1\14\1"
        u"\15\1\16\1\17\1\20\2\uffff\32\44\1\21\1\uffff\1\22\1\23\1\44\1"
        u"\uffff\1\24\1\25\1\26\1\27\1\30\1\31\2\44\1\32\4\44\1\33\1\44\1"
        u"\34\1\44\1\35\1\36\1\37\2\44\1\40\3\44\1\41\1\42\1\43"),
        DFA.unpack(u"\1\51"),
        DFA.unpack(u"\1\53"),
        DFA.unpack(u"\1\55\26\uffff\1\56"),
        DFA.unpack(u""),
        DFA.unpack(u""),
        DFA.unpack(u"\1\60"),
        DFA.unpack(u"\1\62\21\uffff\1\63"),
        DFA.unpack(u""),
        DFA.unpack(u"\1\65\17\uffff\1\66"),
        DFA.unpack(u"\1\70\3\uffff\1\71"),
        DFA.unpack(u"\1\74\4\uffff\1\50\15\uffff\1\73"),
        DFA.unpack(u""),
        DFA.unpack(u""),
        DFA.unpack(u"\1\76"),
        DFA.unpack(u"\1\100\1\101"),
        DFA.unpack(u"\1\103"),
        DFA.unpack(u""),
        DFA.unpack(u""),
        DFA.unpack(u"\1\105"),
        DFA.unpack(u"\1\107"),
        DFA.unpack(u"\1\110"),
        DFA.unpack(u"\1\111\12\uffff\1\112\2\uffff\1\113"),
        DFA.unpack(u"\1\114\11\uffff\1\115"),
        DFA.unpack(u"\1\116\13\uffff\1\117"),
        DFA.unpack(u"\1\123\7\uffff\1\120\5\uffff\1\121\5\uffff\1\122"),
        DFA.unpack(u"\1\124\6\uffff\1\125\1\126"),
        DFA.unpack(u"\1\127\17\uffff\1\130"),
        DFA.unpack(u"\1\131\2\uffff\1\132"),
        DFA.unpack(u"\1\133"),
        DFA.unpack(u"\1\134\3\uffff\1\135\2\uffff\1\136"),
        DFA.unpack(u"\1\137\11\uffff\1\140"),
        DFA.unpack(u"\1\141"),
        DFA.unpack(u""),
        DFA.unpack(u"\1\142\76\uffff\1\143"),
        DFA.unpack(u""),
        DFA.unpack(u""),
        DFA.unpack(u"\1\146\1\uffff\12\45"),
        DFA.unpack(u""),
        DFA.unpack(u""),
        DFA.unpack(u""),
        DFA.unpack(u""),
        DFA.unpack(u""),
        DFA.unpack(u""),
        DFA.unpack(u""),
        DFA.unpack(u""),
        DFA.unpack(u""),
        DFA.unpack(u""),
        DFA.unpack(u""),
        DFA.unpack(u""),
        DFA.unpack(u""),
        DFA.unpack(u""),
        DFA.unpack(u""),
        DFA.unpack(u""),
        DFA.unpack(u""),
        DFA.unpack(u""),
        DFA.unpack(u""),
        DFA.unpack(u""),
        DFA.unpack(u""),
        DFA.unpack(u""),
        DFA.unpack(u""),
        DFA.unpack(u""),
        DFA.unpack(u""),
        DFA.unpack(u""),
        DFA.unpack(u""),
        DFA.unpack(u""),
        DFA.unpack(u""),
        DFA.unpack(u""),
        DFA.unpack(u""),
        DFA.unpack(u""),
        DFA.unpack(u""),
        DFA.unpack(u"\12\44\7\uffff\32\44\4\uffff\1\44\1\uffff\32\44"),
        DFA.unpack(u"\1\150"),
        DFA.unpack(u"\1\151\1\152"),
        DFA.unpack(u"\1\153"),
        DFA.unpack(u"\1\154"),
        DFA.unpack(u"\1\155"),
        DFA.unpack(u"\12\44\7\uffff\32\44\4\uffff\1\44\1\uffff\32\44"),
        DFA.unpack(u"\1\157"),
        DFA.unpack(u"\1\160"),
        DFA.unpack(u"\1\161"),
        DFA.unpack(u"\1\162"),
        DFA.unpack(u"\1\163"),
        DFA.unpack(u"\1\164"),
        DFA.unpack(u"\12\44\7\uffff\32\44\4\uffff\1\44\1\uffff\32\44"),
        DFA.unpack(u"\1\166"),
        DFA.unpack(u"\1\167"),
        DFA.unpack(u"\1\170"),
        DFA.unpack(u"\1\171"),
        DFA.unpack(u"\1\172"),
        DFA.unpack(u"\1\173"),
        DFA.unpack(u"\1\174"),
        DFA.unpack(u"\1\175"),
        DFA.unpack(u"\1\176"),
        DFA.unpack(u"\1\177"),
        DFA.unpack(u"\1\u0080"),
        DFA.unpack(u"\1\u0082\3\uffff\1\u0081"),
        DFA.unpack(u"\1\u0083"),
        DFA.unpack(u""),
        DFA.unpack(u""),
        DFA.unpack(u""),
        DFA.unpack(u""),
        DFA.unpack(u""),
        DFA.unpack(u""),
        DFA.unpack(u"\1\u0084"),
        DFA.unpack(u"\1\u0085"),
        DFA.unpack(u"\1\u0086"),
        DFA.unpack(u"\1\u0087"),
        DFA.unpack(u"\1\u0088"),
        DFA.unpack(u"\1\u0089"),
        DFA.unpack(u""),
        DFA.unpack(u"\1\u008a"),
        DFA.unpack(u"\1\u008b"),
        DFA.unpack(u"\1\u008c"),
        DFA.unpack(u"\12\44\7\uffff\32\44\4\uffff\1\44\1\uffff\4\44\1\u008d"
        u"\25\44"),
        DFA.unpack(u"\1\u008f"),
        DFA.unpack(u"\1\u0090"),
        DFA.unpack(u""),
        DFA.unpack(u"\1\u0091"),
        DFA.unpack(u"\1\u0092"),
        DFA.unpack(u"\12\44\7\uffff\32\44\4\uffff\1\44\1\uffff\32\44"),
        DFA.unpack(u"\1\u0094"),
        DFA.unpack(u"\1\u0095"),
        DFA.unpack(u"\1\u0096"),
        DFA.unpack(u"\1\u0097"),
        DFA.unpack(u"\1\u0098"),
        DFA.unpack(u"\1\u0099"),
        DFA.unpack(u"\1\u009a"),
        DFA.unpack(u"\1\u009b"),
        DFA.unpack(u"\12\44\7\uffff\32\44\4\uffff\1\44\1\uffff\32\44"),
        DFA.unpack(u"\1\u009d"),
        DFA.unpack(u"\1\u009e"),
        DFA.unpack(u"\1\u009f"),
        DFA.unpack(u"\12\44\7\uffff\32\44\4\uffff\1\44\1\uffff\32\44"),
        DFA.unpack(u"\1\u00a1"),
        DFA.unpack(u"\1\u00a2"),
        DFA.unpack(u"\1\u00a3"),
        DFA.unpack(u"\1\u00a4"),
        DFA.unpack(u"\12\44\7\uffff\32\44\4\uffff\1\44\1\uffff\32\44"),
        DFA.unpack(u"\1\u00a6"),
        DFA.unpack(u"\1\u00a7"),
        DFA.unpack(u"\1\u00a8"),
        DFA.unpack(u""),
        DFA.unpack(u"\1\u00a9"),
        DFA.unpack(u"\1\u00aa"),
        DFA.unpack(u"\1\u00ab"),
        DFA.unpack(u"\12\44\7\uffff\32\44\4\uffff\1\44\1\uffff\32\44"),
        DFA.unpack(u""),
        DFA.unpack(u"\12\44\7\uffff\32\44\4\uffff\1\44\1\uffff\32\44"),
        DFA.unpack(u"\1\u00ae"),
        DFA.unpack(u"\1\u00af"),
        DFA.unpack(u"\1\u00b0"),
        DFA.unpack(u"\1\u00b1"),
        DFA.unpack(u"\1\u00b2"),
        DFA.unpack(u"\1\u00b3"),
        DFA.unpack(u"\1\u00b4"),
        DFA.unpack(u""),
        DFA.unpack(u"\12\44\7\uffff\32\44\4\uffff\1\44\1\uffff\32\44"),
        DFA.unpack(u"\1\u00b6"),
        DFA.unpack(u"\12\44\7\uffff\32\44\4\uffff\1\44\1\uffff\32\44"),
        DFA.unpack(u""),
        DFA.unpack(u"\12\44\7\uffff\32\44\4\uffff\1\44\1\uffff\32\44"),
        DFA.unpack(u"\12\44\7\uffff\32\44\4\uffff\1\44\1\uffff\32\44"),
        DFA.unpack(u"\1\u00ba"),
        DFA.unpack(u"\1\u00bb"),
        DFA.unpack(u""),
        DFA.unpack(u"\1\u00bc"),
        DFA.unpack(u"\1\u00bd"),
        DFA.unpack(u"\1\u00be"),
        DFA.unpack(u"\1\u00bf"),
        DFA.unpack(u"\12\44\7\uffff\32\44\4\uffff\1\44\1\uffff\32\44"),
        DFA.unpack(u"\1\u00c0"),
        DFA.unpack(u""),
        DFA.unpack(u""),
        DFA.unpack(u"\12\44\7\uffff\32\44\4\uffff\1\44\1\uffff\5\44\1\u00c1"
        u"\24\44"),
        DFA.unpack(u"\1\u00c3"),
        DFA.unpack(u"\1\u00c4"),
        DFA.unpack(u"\1\u00c5"),
        DFA.unpack(u"\1\u00c6"),
        DFA.unpack(u"\1\u00c7"),
        DFA.unpack(u"\12\44\7\uffff\32\44\4\uffff\1\44\1\uffff\32\44"),
        DFA.unpack(u""),
        DFA.unpack(u"\12\44\7\uffff\32\44\4\uffff\1\44\1\uffff\32\44"),
        DFA.unpack(u""),
        DFA.unpack(u""),
        DFA.unpack(u""),
        DFA.unpack(u"\1\u00ca"),
        DFA.unpack(u"\1\u00cb"),
        DFA.unpack(u"\1\u00cc"),
        DFA.unpack(u"\1\u00cd"),
        DFA.unpack(u"\1\u00ce"),
        DFA.unpack(u"\1\u00cf"),
        DFA.unpack(u"\12\44\7\uffff\32\44\4\uffff\1\44\1\uffff\32\44"),
        DFA.unpack(u"\12\44\7\uffff\32\44\4\uffff\1\44\1\uffff\32\44"),
        DFA.unpack(u""),
        DFA.unpack(u"\12\44\7\uffff\32\44\4\uffff\1\44\1\uffff\32\44"),
        DFA.unpack(u"\12\44\7\uffff\32\44\4\uffff\1\44\1\uffff\32\44"),
        DFA.unpack(u"\1\u00d4"),
        DFA.unpack(u"\12\44\7\uffff\32\44\4\uffff\1\44\1\uffff\32\44"),
        DFA.unpack(u"\12\44\7\uffff\32\44\4\uffff\1\44\1\uffff\32\44"),
        DFA.unpack(u""),
        DFA.unpack(u""),
        DFA.unpack(u"\1\u00d7"),
        DFA.unpack(u"\12\44\7\uffff\32\44\4\uffff\1\44\1\uffff\32\44"),
        DFA.unpack(u"\12\44\7\uffff\32\44\4\uffff\1\44\1\uffff\32\44"),
        DFA.unpack(u"\12\44\7\uffff\32\44\4\uffff\1\44\1\uffff\32\44"),
        DFA.unpack(u"\12\44\7\uffff\32\44\4\uffff\1\44\1\uffff\32\44"),
        DFA.unpack(u"\1\u00dc"),
        DFA.unpack(u""),
        DFA.unpack(u""),
        DFA.unpack(u""),
        DFA.unpack(u""),
        DFA.unpack(u"\12\44\7\uffff\32\44\4\uffff\1\44\1\uffff\32\44"),
        DFA.unpack(u""),
        DFA.unpack(u""),
        DFA.unpack(u"\12\44\7\uffff\32\44\4\uffff\1\44\1\uffff\32\44"),
        DFA.unpack(u""),
        DFA.unpack(u""),
        DFA.unpack(u""),
        DFA.unpack(u""),
        DFA.unpack(u"\12\44\7\uffff\32\44\4\uffff\1\44\1\uffff\32\44"),
        DFA.unpack(u""),
        DFA.unpack(u""),
        DFA.unpack(u"")
    ]

    # class definition for DFA #15

    class DFA15(DFA):
        pass


 



def main(argv, stdin=sys.stdin, stdout=sys.stdout, stderr=sys.stderr):
    from antlr3.main import LexerMain
    main = LexerMain(ExprLexer)

    main.stdin = stdin
    main.stdout = stdout
    main.stderr = stderr
    main.execute(argv)



if __name__ == '__main__':
    main(sys.argv)
