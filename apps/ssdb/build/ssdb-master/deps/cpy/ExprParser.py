# $ANTLR 3.5 Expr.g 2013-04-12 19:22:24

import sys
from antlr3 import *
from antlr3.compat import set, frozenset

from antlr3.tree import *




# for convenience in actions
HIDDEN = BaseRecognizer.HIDDEN

# token types
EOF=-1
T__68=68
T__69=69
T__70=70
T__71=71
T__72=72
T__73=73
T__74=74
T__75=75
T__76=76
T__77=77
T__78=78
T__79=79
T__80=80
T__81=81
T__82=82
T__83=83
T__84=84
T__85=85
T__86=86
T__87=87
T__88=88
T__89=89
T__90=90
T__91=91
T__92=92
T__93=93
T__94=94
T__95=95
T__96=96
T__97=97
T__98=98
T__99=99
T__100=100
T__101=101
T__102=102
T__103=103
T__104=104
T__105=105
T__106=106
T__107=107
T__108=108
T__109=109
T__110=110
T__111=111
T__112=112
T__113=113
T__114=114
T__115=115
T__116=116
T__117=117
T__118=118
T__119=119
T__120=120
T__121=121
T__122=122
T__123=123
T__124=124
T__125=125
T__126=126
T__127=127
T__128=128
T__129=129
T__130=130
T__131=131
T__132=132
T__133=133
T__134=134
T__135=135
T__136=136
ALPHA=4
ARRAY=5
ASSIGN=6
BLOCK=7
BOOL=8
BREAK=9
CALL=10
CASE=11
CATCH=12
CLASS=13
COMMENT=14
CONSTRUCTOR=15
CONTINUE=16
DEFAULT=17
DIGIT=18
DOUBLE_QUOTE_CHARS=19
DO_WHILE=20
EACH=21
EACH_VAL=22
ELSE=23
ELSE_IF=24
EMPTY_LINE=25
EXEC_LIST=26
EXEC_STMT=27
EXPR_LIST=28
FINALLY=29
FLOAT=30
FOR=31
FOREACH=32
FUNCTION=33
ID=34
ID_LIST=35
IF=36
IMPORT=37
INDEX=38
INT=39
LINECOMMENT=40
MEMBER=41
MODULE=42
NEGATIVE=43
NEW=44
NEWLINE=45
NOP=46
NULL=47
OBJECT=48
OP_ASSIGN=49
PARAMS=50
POST_DEC=51
POST_INC=52
PRE_DEC=53
PRE_INC=54
PRINT=55
PRINTF=56
RETURN=57
SINGLE_QUOTE_CHARS=58
SLICE=59
SPRINTF=60
STRING=61
SWITCH=62
THROW=63
TRY=64
VAR=65
WHILE=66
WS=67

# token names
tokenNames = [
    "<invalid>", "<EOR>", "<DOWN>", "<UP>",
    "ALPHA", "ARRAY", "ASSIGN", "BLOCK", "BOOL", "BREAK", "CALL", "CASE", 
    "CATCH", "CLASS", "COMMENT", "CONSTRUCTOR", "CONTINUE", "DEFAULT", "DIGIT", 
    "DOUBLE_QUOTE_CHARS", "DO_WHILE", "EACH", "EACH_VAL", "ELSE", "ELSE_IF", 
    "EMPTY_LINE", "EXEC_LIST", "EXEC_STMT", "EXPR_LIST", "FINALLY", "FLOAT", 
    "FOR", "FOREACH", "FUNCTION", "ID", "ID_LIST", "IF", "IMPORT", "INDEX", 
    "INT", "LINECOMMENT", "MEMBER", "MODULE", "NEGATIVE", "NEW", "NEWLINE", 
    "NOP", "NULL", "OBJECT", "OP_ASSIGN", "PARAMS", "POST_DEC", "POST_INC", 
    "PRE_DEC", "PRE_INC", "PRINT", "PRINTF", "RETURN", "SINGLE_QUOTE_CHARS", 
    "SLICE", "SPRINTF", "STRING", "SWITCH", "THROW", "TRY", "VAR", "WHILE", 
    "WS", "'!'", "'!='", "'%'", "'%='", "'&&'", "'&'", "'&='", "'('", "')'", 
    "'*'", "'*='", "'+'", "'++'", "'+='", "','", "'-'", "'--'", "'-='", 
    "'.'", "'.*'", "'..'", "'/'", "'/='", "':'", "';'", "'<'", "'<='", "'='", 
    "'=='", "'=>'", "'>'", "'>='", "'['", "']'", "'^'", "'^='", "'as'", 
    "'break'", "'case'", "'catch'", "'class'", "'continue'", "'default'", 
    "'do'", "'else'", "'extends'", "'finally'", "'for'", "'foreach'", "'function'", 
    "'if'", "'import'", "'init'", "'new'", "'print'", "'printf'", "'public'", 
    "'return'", "'sprintf'", "'static'", "'switch'", "'throw'", "'try'", 
    "'while'", "'{'", "'|'", "'|='", "'||'", "'}'"
]




class ExprParser(Parser):
    grammarFileName = "Expr.g"
    api_version = 1
    tokenNames = tokenNames

    def __init__(self, input, state=None, *args, **kwargs):
        if state is None:
            state = RecognizerSharedState()

        super(ExprParser, self).__init__(input, state, *args, **kwargs)

        self.dfa6 = self.DFA6(
            self, 6,
            eot = self.DFA6_eot,
            eof = self.DFA6_eof,
            min = self.DFA6_min,
            max = self.DFA6_max,
            accept = self.DFA6_accept,
            special = self.DFA6_special,
            transition = self.DFA6_transition
            )




        self.delegates = []

	self._adaptor = None
	self.adaptor = CommonTreeAdaptor()



    def getTreeAdaptor(self):
        return self._adaptor

    def setTreeAdaptor(self, adaptor):
        self._adaptor = adaptor

    adaptor = property(getTreeAdaptor, setTreeAdaptor)


    class prog_return(ParserRuleReturnScope):
        def __init__(self):
            super(ExprParser.prog_return, self).__init__()

            self.tree = None





    # $ANTLR start "prog"
    # Expr.g:33:1: prog : ( EOF -> NOP | ( stmt )* );
    def prog(self, ):
        retval = self.prog_return()
        retval.start = self.input.LT(1)


        root_0 = None

        EOF1 = None
        stmt2 = None

        EOF1_tree = None
        stream_EOF = RewriteRuleTokenStream(self._adaptor, "token EOF")

        try:
            try:
                # Expr.g:34:2: ( EOF -> NOP | ( stmt )* )
                alt2 = 2
                LA2_0 = self.input.LA(1)

                if (LA2_0 == EOF) :
                    LA2_1 = self.input.LA(2)

                    if (LA2_1 == EOF) :
                        alt2 = 1
                    else:
                        if self._state.backtracking > 0:
                            raise BacktrackingFailed


                        nvae = NoViableAltException("", 2, 1, self.input)

                        raise nvae


                elif (LA2_0 == ID or LA2_0 == 80 or LA2_0 == 84 or LA2_0 == 92 or LA2_0 == 105 or (108 <= LA2_0 <= 109) or LA2_0 == 111 or (115 <= LA2_0 <= 119) or (122 <= LA2_0 <= 123) or LA2_0 == 125 or (128 <= LA2_0 <= 131)) :
                    alt2 = 2
                else:
                    if self._state.backtracking > 0:
                        raise BacktrackingFailed


                    nvae = NoViableAltException("", 2, 0, self.input)

                    raise nvae


                if alt2 == 1:
                    # Expr.g:34:4: EOF
                    pass 
                    EOF1 = self.match(self.input, EOF, self.FOLLOW_EOF_in_prog211) 
                    if self._state.backtracking == 0:
                        stream_EOF.add(EOF1)


                    # AST Rewrite
                    # elements: 
                    # token labels: 
                    # rule labels: retval
                    # token list labels: 
                    # rule list labels: 
                    # wildcard labels: 
                    if self._state.backtracking == 0:
                        retval.tree = root_0
                        if retval is not None:
                            stream_retval = RewriteRuleSubtreeStream(self._adaptor, "rule retval", retval.tree)
                        else:
                            stream_retval = RewriteRuleSubtreeStream(self._adaptor, "token retval", None)


                        root_0 = self._adaptor.nil()
                        # 34:8: -> NOP
                        self._adaptor.addChild(root_0, 
                        self._adaptor.createFromType(NOP, "NOP")
                        )




                        retval.tree = root_0




                elif alt2 == 2:
                    # Expr.g:35:4: ( stmt )*
                    pass 
                    root_0 = self._adaptor.nil()


                    # Expr.g:35:4: ( stmt )*
                    while True: #loop1
                        alt1 = 2
                        LA1_0 = self.input.LA(1)

                        if (LA1_0 == ID or LA1_0 == 80 or LA1_0 == 84 or LA1_0 == 92 or LA1_0 == 105 or (108 <= LA1_0 <= 109) or LA1_0 == 111 or (115 <= LA1_0 <= 119) or (122 <= LA1_0 <= 123) or LA1_0 == 125 or (128 <= LA1_0 <= 131)) :
                            alt1 = 1


                        if alt1 == 1:
                            # Expr.g:35:4: stmt
                            pass 
                            self._state.following.append(self.FOLLOW_stmt_in_prog220)
                            stmt2 = self.stmt()

                            self._state.following.pop()
                            if self._state.backtracking == 0:
                                self._adaptor.addChild(root_0, stmt2.tree)



                        else:
                            break #loop1



                retval.stop = self.input.LT(-1)


                if self._state.backtracking == 0:
                    retval.tree = self._adaptor.rulePostProcessing(root_0)
                    self._adaptor.setTokenBoundaries(retval.tree, retval.start, retval.stop)



            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)
                retval.tree = self._adaptor.errorNode(self.input, retval.start, self.input.LT(-1), re)

        finally:
            pass
        return retval

    # $ANTLR end "prog"


    class stmt_return(ParserRuleReturnScope):
        def __init__(self):
            super(ExprParser.stmt_return, self).__init__()

            self.tree = None





    # $ANTLR start "stmt"
    # Expr.g:38:1: stmt : ( ';' ->| exec_stmt | import_stmt | print_stmt | printf_stmt | break_stmt | continue_stmt | return_stmt | if_stmt | while_stmt | do_while_stmt | switch_stmt | for_stmt | foreach_stmt | throw_stmt | try_stmt | func_decl | class_decl );
    def stmt(self, ):
        retval = self.stmt_return()
        retval.start = self.input.LT(1)


        root_0 = None

        char_literal3 = None
        exec_stmt4 = None
        import_stmt5 = None
        print_stmt6 = None
        printf_stmt7 = None
        break_stmt8 = None
        continue_stmt9 = None
        return_stmt10 = None
        if_stmt11 = None
        while_stmt12 = None
        do_while_stmt13 = None
        switch_stmt14 = None
        for_stmt15 = None
        foreach_stmt16 = None
        throw_stmt17 = None
        try_stmt18 = None
        func_decl19 = None
        class_decl20 = None

        char_literal3_tree = None
        stream_92 = RewriteRuleTokenStream(self._adaptor, "token 92")

        try:
            try:
                # Expr.g:39:2: ( ';' ->| exec_stmt | import_stmt | print_stmt | printf_stmt | break_stmt | continue_stmt | return_stmt | if_stmt | while_stmt | do_while_stmt | switch_stmt | for_stmt | foreach_stmt | throw_stmt | try_stmt | func_decl | class_decl )
                alt3 = 18
                LA3 = self.input.LA(1)
                if LA3 == 92:
                    alt3 = 1
                elif LA3 == ID or LA3 == 80 or LA3 == 84:
                    alt3 = 2
                elif LA3 == 119:
                    alt3 = 3
                elif LA3 == 122:
                    alt3 = 4
                elif LA3 == 123:
                    alt3 = 5
                elif LA3 == 105:
                    alt3 = 6
                elif LA3 == 109:
                    alt3 = 7
                elif LA3 == 125:
                    alt3 = 8
                elif LA3 == 118:
                    alt3 = 9
                elif LA3 == 131:
                    alt3 = 10
                elif LA3 == 111:
                    alt3 = 11
                elif LA3 == 128:
                    alt3 = 12
                elif LA3 == 115:
                    alt3 = 13
                elif LA3 == 116:
                    alt3 = 14
                elif LA3 == 129:
                    alt3 = 15
                elif LA3 == 130:
                    alt3 = 16
                elif LA3 == 117:
                    alt3 = 17
                elif LA3 == 108:
                    alt3 = 18
                else:
                    if self._state.backtracking > 0:
                        raise BacktrackingFailed


                    nvae = NoViableAltException("", 3, 0, self.input)

                    raise nvae


                if alt3 == 1:
                    # Expr.g:39:4: ';'
                    pass 
                    char_literal3 = self.match(self.input, 92, self.FOLLOW_92_in_stmt232) 
                    if self._state.backtracking == 0:
                        stream_92.add(char_literal3)


                    # AST Rewrite
                    # elements: 
                    # token labels: 
                    # rule labels: retval
                    # token list labels: 
                    # rule list labels: 
                    # wildcard labels: 
                    if self._state.backtracking == 0:
                        retval.tree = root_0
                        if retval is not None:
                            stream_retval = RewriteRuleSubtreeStream(self._adaptor, "rule retval", retval.tree)
                        else:
                            stream_retval = RewriteRuleSubtreeStream(self._adaptor, "token retval", None)


                        root_0 = self._adaptor.nil()
                        # 39:8: ->
                        root_0 = None



                        retval.tree = root_0




                elif alt3 == 2:
                    # Expr.g:40:4: exec_stmt
                    pass 
                    root_0 = self._adaptor.nil()


                    self._state.following.append(self.FOLLOW_exec_stmt_in_stmt239)
                    exec_stmt4 = self.exec_stmt()

                    self._state.following.pop()
                    if self._state.backtracking == 0:
                        self._adaptor.addChild(root_0, exec_stmt4.tree)



                elif alt3 == 3:
                    # Expr.g:41:4: import_stmt
                    pass 
                    root_0 = self._adaptor.nil()


                    self._state.following.append(self.FOLLOW_import_stmt_in_stmt244)
                    import_stmt5 = self.import_stmt()

                    self._state.following.pop()
                    if self._state.backtracking == 0:
                        self._adaptor.addChild(root_0, import_stmt5.tree)



                elif alt3 == 4:
                    # Expr.g:42:4: print_stmt
                    pass 
                    root_0 = self._adaptor.nil()


                    self._state.following.append(self.FOLLOW_print_stmt_in_stmt249)
                    print_stmt6 = self.print_stmt()

                    self._state.following.pop()
                    if self._state.backtracking == 0:
                        self._adaptor.addChild(root_0, print_stmt6.tree)



                elif alt3 == 5:
                    # Expr.g:42:17: printf_stmt
                    pass 
                    root_0 = self._adaptor.nil()


                    self._state.following.append(self.FOLLOW_printf_stmt_in_stmt253)
                    printf_stmt7 = self.printf_stmt()

                    self._state.following.pop()
                    if self._state.backtracking == 0:
                        self._adaptor.addChild(root_0, printf_stmt7.tree)



                elif alt3 == 6:
                    # Expr.g:43:4: break_stmt
                    pass 
                    root_0 = self._adaptor.nil()


                    self._state.following.append(self.FOLLOW_break_stmt_in_stmt258)
                    break_stmt8 = self.break_stmt()

                    self._state.following.pop()
                    if self._state.backtracking == 0:
                        self._adaptor.addChild(root_0, break_stmt8.tree)



                elif alt3 == 7:
                    # Expr.g:44:4: continue_stmt
                    pass 
                    root_0 = self._adaptor.nil()


                    self._state.following.append(self.FOLLOW_continue_stmt_in_stmt263)
                    continue_stmt9 = self.continue_stmt()

                    self._state.following.pop()
                    if self._state.backtracking == 0:
                        self._adaptor.addChild(root_0, continue_stmt9.tree)



                elif alt3 == 8:
                    # Expr.g:45:4: return_stmt
                    pass 
                    root_0 = self._adaptor.nil()


                    self._state.following.append(self.FOLLOW_return_stmt_in_stmt268)
                    return_stmt10 = self.return_stmt()

                    self._state.following.pop()
                    if self._state.backtracking == 0:
                        self._adaptor.addChild(root_0, return_stmt10.tree)



                elif alt3 == 9:
                    # Expr.g:46:4: if_stmt
                    pass 
                    root_0 = self._adaptor.nil()


                    self._state.following.append(self.FOLLOW_if_stmt_in_stmt273)
                    if_stmt11 = self.if_stmt()

                    self._state.following.pop()
                    if self._state.backtracking == 0:
                        self._adaptor.addChild(root_0, if_stmt11.tree)



                elif alt3 == 10:
                    # Expr.g:47:4: while_stmt
                    pass 
                    root_0 = self._adaptor.nil()


                    self._state.following.append(self.FOLLOW_while_stmt_in_stmt278)
                    while_stmt12 = self.while_stmt()

                    self._state.following.pop()
                    if self._state.backtracking == 0:
                        self._adaptor.addChild(root_0, while_stmt12.tree)



                elif alt3 == 11:
                    # Expr.g:48:4: do_while_stmt
                    pass 
                    root_0 = self._adaptor.nil()


                    self._state.following.append(self.FOLLOW_do_while_stmt_in_stmt283)
                    do_while_stmt13 = self.do_while_stmt()

                    self._state.following.pop()
                    if self._state.backtracking == 0:
                        self._adaptor.addChild(root_0, do_while_stmt13.tree)



                elif alt3 == 12:
                    # Expr.g:49:4: switch_stmt
                    pass 
                    root_0 = self._adaptor.nil()


                    self._state.following.append(self.FOLLOW_switch_stmt_in_stmt288)
                    switch_stmt14 = self.switch_stmt()

                    self._state.following.pop()
                    if self._state.backtracking == 0:
                        self._adaptor.addChild(root_0, switch_stmt14.tree)



                elif alt3 == 13:
                    # Expr.g:50:4: for_stmt
                    pass 
                    root_0 = self._adaptor.nil()


                    self._state.following.append(self.FOLLOW_for_stmt_in_stmt293)
                    for_stmt15 = self.for_stmt()

                    self._state.following.pop()
                    if self._state.backtracking == 0:
                        self._adaptor.addChild(root_0, for_stmt15.tree)



                elif alt3 == 14:
                    # Expr.g:51:4: foreach_stmt
                    pass 
                    root_0 = self._adaptor.nil()


                    self._state.following.append(self.FOLLOW_foreach_stmt_in_stmt298)
                    foreach_stmt16 = self.foreach_stmt()

                    self._state.following.pop()
                    if self._state.backtracking == 0:
                        self._adaptor.addChild(root_0, foreach_stmt16.tree)



                elif alt3 == 15:
                    # Expr.g:52:4: throw_stmt
                    pass 
                    root_0 = self._adaptor.nil()


                    self._state.following.append(self.FOLLOW_throw_stmt_in_stmt303)
                    throw_stmt17 = self.throw_stmt()

                    self._state.following.pop()
                    if self._state.backtracking == 0:
                        self._adaptor.addChild(root_0, throw_stmt17.tree)



                elif alt3 == 16:
                    # Expr.g:53:4: try_stmt
                    pass 
                    root_0 = self._adaptor.nil()


                    self._state.following.append(self.FOLLOW_try_stmt_in_stmt308)
                    try_stmt18 = self.try_stmt()

                    self._state.following.pop()
                    if self._state.backtracking == 0:
                        self._adaptor.addChild(root_0, try_stmt18.tree)



                elif alt3 == 17:
                    # Expr.g:54:4: func_decl
                    pass 
                    root_0 = self._adaptor.nil()


                    self._state.following.append(self.FOLLOW_func_decl_in_stmt313)
                    func_decl19 = self.func_decl()

                    self._state.following.pop()
                    if self._state.backtracking == 0:
                        self._adaptor.addChild(root_0, func_decl19.tree)



                elif alt3 == 18:
                    # Expr.g:55:4: class_decl
                    pass 
                    root_0 = self._adaptor.nil()


                    self._state.following.append(self.FOLLOW_class_decl_in_stmt318)
                    class_decl20 = self.class_decl()

                    self._state.following.pop()
                    if self._state.backtracking == 0:
                        self._adaptor.addChild(root_0, class_decl20.tree)



                retval.stop = self.input.LT(-1)


                if self._state.backtracking == 0:
                    retval.tree = self._adaptor.rulePostProcessing(root_0)
                    self._adaptor.setTokenBoundaries(retval.tree, retval.start, retval.stop)



            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)
                retval.tree = self._adaptor.errorNode(self.input, retval.start, self.input.LT(-1), re)

        finally:
            pass
        return retval

    # $ANTLR end "stmt"


    class block_return(ParserRuleReturnScope):
        def __init__(self):
            super(ExprParser.block_return, self).__init__()

            self.tree = None





    # $ANTLR start "block"
    # Expr.g:59:1: block : '{' ( stmt )* '}' -> ^( BLOCK ( stmt )* ) ;
    def block(self, ):
        retval = self.block_return()
        retval.start = self.input.LT(1)


        root_0 = None

        char_literal21 = None
        char_literal23 = None
        stmt22 = None

        char_literal21_tree = None
        char_literal23_tree = None
        stream_132 = RewriteRuleTokenStream(self._adaptor, "token 132")
        stream_136 = RewriteRuleTokenStream(self._adaptor, "token 136")
        stream_stmt = RewriteRuleSubtreeStream(self._adaptor, "rule stmt")
        try:
            try:
                # Expr.g:60:2: ( '{' ( stmt )* '}' -> ^( BLOCK ( stmt )* ) )
                # Expr.g:60:4: '{' ( stmt )* '}'
                pass 
                char_literal21 = self.match(self.input, 132, self.FOLLOW_132_in_block331) 
                if self._state.backtracking == 0:
                    stream_132.add(char_literal21)


                # Expr.g:60:8: ( stmt )*
                while True: #loop4
                    alt4 = 2
                    LA4_0 = self.input.LA(1)

                    if (LA4_0 == ID or LA4_0 == 80 or LA4_0 == 84 or LA4_0 == 92 or LA4_0 == 105 or (108 <= LA4_0 <= 109) or LA4_0 == 111 or (115 <= LA4_0 <= 119) or (122 <= LA4_0 <= 123) or LA4_0 == 125 or (128 <= LA4_0 <= 131)) :
                        alt4 = 1


                    if alt4 == 1:
                        # Expr.g:60:8: stmt
                        pass 
                        self._state.following.append(self.FOLLOW_stmt_in_block333)
                        stmt22 = self.stmt()

                        self._state.following.pop()
                        if self._state.backtracking == 0:
                            stream_stmt.add(stmt22.tree)



                    else:
                        break #loop4


                char_literal23 = self.match(self.input, 136, self.FOLLOW_136_in_block336) 
                if self._state.backtracking == 0:
                    stream_136.add(char_literal23)


                # AST Rewrite
                # elements: stmt
                # token labels: 
                # rule labels: retval
                # token list labels: 
                # rule list labels: 
                # wildcard labels: 
                if self._state.backtracking == 0:
                    retval.tree = root_0
                    if retval is not None:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "rule retval", retval.tree)
                    else:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "token retval", None)


                    root_0 = self._adaptor.nil()
                    # 61:3: -> ^( BLOCK ( stmt )* )
                    # Expr.g:61:6: ^( BLOCK ( stmt )* )
                    root_1 = self._adaptor.nil()
                    root_1 = self._adaptor.becomeRoot(
                    self._adaptor.createFromType(BLOCK, "BLOCK")
                    , root_1)

                    # Expr.g:61:14: ( stmt )*
                    while stream_stmt.hasNext():
                        self._adaptor.addChild(root_1, stream_stmt.nextTree())


                    stream_stmt.reset();

                    self._adaptor.addChild(root_0, root_1)




                    retval.tree = root_0





                retval.stop = self.input.LT(-1)


                if self._state.backtracking == 0:
                    retval.tree = self._adaptor.rulePostProcessing(root_0)
                    self._adaptor.setTokenBoundaries(retval.tree, retval.start, retval.stop)



            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)
                retval.tree = self._adaptor.errorNode(self.input, retval.start, self.input.LT(-1), re)

        finally:
            pass
        return retval

    # $ANTLR end "block"


    class import_stmt_return(ParserRuleReturnScope):
        def __init__(self):
            super(ExprParser.import_stmt_return, self).__init__()

            self.tree = None





    # $ANTLR start "import_stmt"
    # Expr.g:64:1: import_stmt : 'import' module_path ( ',' module_path )* ';' -> ^( IMPORT ( module_path )+ ) ;
    def import_stmt(self, ):
        retval = self.import_stmt_return()
        retval.start = self.input.LT(1)


        root_0 = None

        string_literal24 = None
        char_literal26 = None
        char_literal28 = None
        module_path25 = None
        module_path27 = None

        string_literal24_tree = None
        char_literal26_tree = None
        char_literal28_tree = None
        stream_92 = RewriteRuleTokenStream(self._adaptor, "token 92")
        stream_82 = RewriteRuleTokenStream(self._adaptor, "token 82")
        stream_119 = RewriteRuleTokenStream(self._adaptor, "token 119")
        stream_module_path = RewriteRuleSubtreeStream(self._adaptor, "rule module_path")
        try:
            try:
                # Expr.g:65:2: ( 'import' module_path ( ',' module_path )* ';' -> ^( IMPORT ( module_path )+ ) )
                # Expr.g:65:4: 'import' module_path ( ',' module_path )* ';'
                pass 
                string_literal24 = self.match(self.input, 119, self.FOLLOW_119_in_import_stmt358) 
                if self._state.backtracking == 0:
                    stream_119.add(string_literal24)


                self._state.following.append(self.FOLLOW_module_path_in_import_stmt360)
                module_path25 = self.module_path()

                self._state.following.pop()
                if self._state.backtracking == 0:
                    stream_module_path.add(module_path25.tree)


                # Expr.g:65:25: ( ',' module_path )*
                while True: #loop5
                    alt5 = 2
                    LA5_0 = self.input.LA(1)

                    if (LA5_0 == 82) :
                        alt5 = 1


                    if alt5 == 1:
                        # Expr.g:65:26: ',' module_path
                        pass 
                        char_literal26 = self.match(self.input, 82, self.FOLLOW_82_in_import_stmt363) 
                        if self._state.backtracking == 0:
                            stream_82.add(char_literal26)


                        self._state.following.append(self.FOLLOW_module_path_in_import_stmt365)
                        module_path27 = self.module_path()

                        self._state.following.pop()
                        if self._state.backtracking == 0:
                            stream_module_path.add(module_path27.tree)



                    else:
                        break #loop5


                char_literal28 = self.match(self.input, 92, self.FOLLOW_92_in_import_stmt369) 
                if self._state.backtracking == 0:
                    stream_92.add(char_literal28)


                # AST Rewrite
                # elements: module_path
                # token labels: 
                # rule labels: retval
                # token list labels: 
                # rule list labels: 
                # wildcard labels: 
                if self._state.backtracking == 0:
                    retval.tree = root_0
                    if retval is not None:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "rule retval", retval.tree)
                    else:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "token retval", None)


                    root_0 = self._adaptor.nil()
                    # 66:3: -> ^( IMPORT ( module_path )+ )
                    # Expr.g:66:6: ^( IMPORT ( module_path )+ )
                    root_1 = self._adaptor.nil()
                    root_1 = self._adaptor.becomeRoot(
                    self._adaptor.createFromType(IMPORT, "IMPORT")
                    , root_1)

                    # Expr.g:66:15: ( module_path )+
                    if not (stream_module_path.hasNext()):
                        raise RewriteEarlyExitException()

                    while stream_module_path.hasNext():
                        self._adaptor.addChild(root_1, stream_module_path.nextTree())


                    stream_module_path.reset()

                    self._adaptor.addChild(root_0, root_1)




                    retval.tree = root_0





                retval.stop = self.input.LT(-1)


                if self._state.backtracking == 0:
                    retval.tree = self._adaptor.rulePostProcessing(root_0)
                    self._adaptor.setTokenBoundaries(retval.tree, retval.start, retval.stop)



            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)
                retval.tree = self._adaptor.errorNode(self.input, retval.start, self.input.LT(-1), re)

        finally:
            pass
        return retval

    # $ANTLR end "import_stmt"


    class module_path_return(ParserRuleReturnScope):
        def __init__(self):
            super(ExprParser.module_path_return, self).__init__()

            self.tree = None





    # $ANTLR start "module_path"
    # Expr.g:68:1: module_path : ( module | module '.*' );
    def module_path(self, ):
        retval = self.module_path_return()
        retval.start = self.input.LT(1)


        root_0 = None

        string_literal31 = None
        module29 = None
        module30 = None

        string_literal31_tree = None

        try:
            try:
                # Expr.g:69:2: ( module | module '.*' )
                alt6 = 2
                alt6 = self.dfa6.predict(self.input)
                if alt6 == 1:
                    # Expr.g:69:4: module
                    pass 
                    root_0 = self._adaptor.nil()


                    self._state.following.append(self.FOLLOW_module_in_module_path390)
                    module29 = self.module()

                    self._state.following.pop()
                    if self._state.backtracking == 0:
                        self._adaptor.addChild(root_0, module29.tree)



                elif alt6 == 2:
                    # Expr.g:70:4: module '.*'
                    pass 
                    root_0 = self._adaptor.nil()


                    self._state.following.append(self.FOLLOW_module_in_module_path395)
                    module30 = self.module()

                    self._state.following.pop()
                    if self._state.backtracking == 0:
                        self._adaptor.addChild(root_0, module30.tree)


                    string_literal31 = self.match(self.input, 87, self.FOLLOW_87_in_module_path397)
                    if self._state.backtracking == 0:
                        string_literal31_tree = self._adaptor.createWithPayload(string_literal31)
                        self._adaptor.addChild(root_0, string_literal31_tree)




                retval.stop = self.input.LT(-1)


                if self._state.backtracking == 0:
                    retval.tree = self._adaptor.rulePostProcessing(root_0)
                    self._adaptor.setTokenBoundaries(retval.tree, retval.start, retval.stop)



            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)
                retval.tree = self._adaptor.errorNode(self.input, retval.start, self.input.LT(-1), re)

        finally:
            pass
        return retval

    # $ANTLR end "module_path"


    class printf_stmt_return(ParserRuleReturnScope):
        def __init__(self):
            super(ExprParser.printf_stmt_return, self).__init__()

            self.tree = None





    # $ANTLR start "printf_stmt"
    # Expr.g:73:1: printf_stmt : 'printf' '(' expr ( ',' expr_list )? ')' ';' -> ^( PRINTF expr ( expr_list )? ) ;
    def printf_stmt(self, ):
        retval = self.printf_stmt_return()
        retval.start = self.input.LT(1)


        root_0 = None

        string_literal32 = None
        char_literal33 = None
        char_literal35 = None
        char_literal37 = None
        char_literal38 = None
        expr34 = None
        expr_list36 = None

        string_literal32_tree = None
        char_literal33_tree = None
        char_literal35_tree = None
        char_literal37_tree = None
        char_literal38_tree = None
        stream_92 = RewriteRuleTokenStream(self._adaptor, "token 92")
        stream_123 = RewriteRuleTokenStream(self._adaptor, "token 123")
        stream_82 = RewriteRuleTokenStream(self._adaptor, "token 82")
        stream_75 = RewriteRuleTokenStream(self._adaptor, "token 75")
        stream_76 = RewriteRuleTokenStream(self._adaptor, "token 76")
        stream_expr = RewriteRuleSubtreeStream(self._adaptor, "rule expr")
        stream_expr_list = RewriteRuleSubtreeStream(self._adaptor, "rule expr_list")
        try:
            try:
                # Expr.g:74:2: ( 'printf' '(' expr ( ',' expr_list )? ')' ';' -> ^( PRINTF expr ( expr_list )? ) )
                # Expr.g:74:4: 'printf' '(' expr ( ',' expr_list )? ')' ';'
                pass 
                string_literal32 = self.match(self.input, 123, self.FOLLOW_123_in_printf_stmt408) 
                if self._state.backtracking == 0:
                    stream_123.add(string_literal32)


                char_literal33 = self.match(self.input, 75, self.FOLLOW_75_in_printf_stmt410) 
                if self._state.backtracking == 0:
                    stream_75.add(char_literal33)


                self._state.following.append(self.FOLLOW_expr_in_printf_stmt412)
                expr34 = self.expr()

                self._state.following.pop()
                if self._state.backtracking == 0:
                    stream_expr.add(expr34.tree)


                # Expr.g:74:22: ( ',' expr_list )?
                alt7 = 2
                LA7_0 = self.input.LA(1)

                if (LA7_0 == 82) :
                    alt7 = 1
                if alt7 == 1:
                    # Expr.g:74:23: ',' expr_list
                    pass 
                    char_literal35 = self.match(self.input, 82, self.FOLLOW_82_in_printf_stmt415) 
                    if self._state.backtracking == 0:
                        stream_82.add(char_literal35)


                    self._state.following.append(self.FOLLOW_expr_list_in_printf_stmt417)
                    expr_list36 = self.expr_list()

                    self._state.following.pop()
                    if self._state.backtracking == 0:
                        stream_expr_list.add(expr_list36.tree)





                char_literal37 = self.match(self.input, 76, self.FOLLOW_76_in_printf_stmt421) 
                if self._state.backtracking == 0:
                    stream_76.add(char_literal37)


                char_literal38 = self.match(self.input, 92, self.FOLLOW_92_in_printf_stmt423) 
                if self._state.backtracking == 0:
                    stream_92.add(char_literal38)


                # AST Rewrite
                # elements: expr_list, expr
                # token labels: 
                # rule labels: retval
                # token list labels: 
                # rule list labels: 
                # wildcard labels: 
                if self._state.backtracking == 0:
                    retval.tree = root_0
                    if retval is not None:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "rule retval", retval.tree)
                    else:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "token retval", None)


                    root_0 = self._adaptor.nil()
                    # 75:3: -> ^( PRINTF expr ( expr_list )? )
                    # Expr.g:75:6: ^( PRINTF expr ( expr_list )? )
                    root_1 = self._adaptor.nil()
                    root_1 = self._adaptor.becomeRoot(
                    self._adaptor.createFromType(PRINTF, "PRINTF")
                    , root_1)

                    self._adaptor.addChild(root_1, stream_expr.nextTree())

                    # Expr.g:75:20: ( expr_list )?
                    if stream_expr_list.hasNext():
                        self._adaptor.addChild(root_1, stream_expr_list.nextTree())


                    stream_expr_list.reset();

                    self._adaptor.addChild(root_0, root_1)




                    retval.tree = root_0





                retval.stop = self.input.LT(-1)


                if self._state.backtracking == 0:
                    retval.tree = self._adaptor.rulePostProcessing(root_0)
                    self._adaptor.setTokenBoundaries(retval.tree, retval.start, retval.stop)



            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)
                retval.tree = self._adaptor.errorNode(self.input, retval.start, self.input.LT(-1), re)

        finally:
            pass
        return retval

    # $ANTLR end "printf_stmt"


    class print_stmt_return(ParserRuleReturnScope):
        def __init__(self):
            super(ExprParser.print_stmt_return, self).__init__()

            self.tree = None





    # $ANTLR start "print_stmt"
    # Expr.g:78:1: print_stmt : ( 'print' ) expr_list ';' -> ^( PRINT expr_list ) ;
    def print_stmt(self, ):
        retval = self.print_stmt_return()
        retval.start = self.input.LT(1)


        root_0 = None

        string_literal39 = None
        char_literal41 = None
        expr_list40 = None

        string_literal39_tree = None
        char_literal41_tree = None
        stream_122 = RewriteRuleTokenStream(self._adaptor, "token 122")
        stream_92 = RewriteRuleTokenStream(self._adaptor, "token 92")
        stream_expr_list = RewriteRuleSubtreeStream(self._adaptor, "rule expr_list")
        try:
            try:
                # Expr.g:81:2: ( ( 'print' ) expr_list ';' -> ^( PRINT expr_list ) )
                # Expr.g:81:4: ( 'print' ) expr_list ';'
                pass 
                # Expr.g:81:4: ( 'print' )
                # Expr.g:81:5: 'print'
                pass 
                string_literal39 = self.match(self.input, 122, self.FOLLOW_122_in_print_stmt452) 
                if self._state.backtracking == 0:
                    stream_122.add(string_literal39)





                self._state.following.append(self.FOLLOW_expr_list_in_print_stmt455)
                expr_list40 = self.expr_list()

                self._state.following.pop()
                if self._state.backtracking == 0:
                    stream_expr_list.add(expr_list40.tree)


                char_literal41 = self.match(self.input, 92, self.FOLLOW_92_in_print_stmt457) 
                if self._state.backtracking == 0:
                    stream_92.add(char_literal41)


                # AST Rewrite
                # elements: expr_list
                # token labels: 
                # rule labels: retval
                # token list labels: 
                # rule list labels: 
                # wildcard labels: 
                if self._state.backtracking == 0:
                    retval.tree = root_0
                    if retval is not None:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "rule retval", retval.tree)
                    else:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "token retval", None)


                    root_0 = self._adaptor.nil()
                    # 82:3: -> ^( PRINT expr_list )
                    # Expr.g:82:6: ^( PRINT expr_list )
                    root_1 = self._adaptor.nil()
                    root_1 = self._adaptor.becomeRoot(
                    self._adaptor.createFromType(PRINT, "PRINT")
                    , root_1)

                    self._adaptor.addChild(root_1, stream_expr_list.nextTree())

                    self._adaptor.addChild(root_0, root_1)




                    retval.tree = root_0





                retval.stop = self.input.LT(-1)


                if self._state.backtracking == 0:
                    retval.tree = self._adaptor.rulePostProcessing(root_0)
                    self._adaptor.setTokenBoundaries(retval.tree, retval.start, retval.stop)



            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)
                retval.tree = self._adaptor.errorNode(self.input, retval.start, self.input.LT(-1), re)

        finally:
            pass
        return retval

    # $ANTLR end "print_stmt"


    class break_stmt_return(ParserRuleReturnScope):
        def __init__(self):
            super(ExprParser.break_stmt_return, self).__init__()

            self.tree = None





    # $ANTLR start "break_stmt"
    # Expr.g:85:1: break_stmt : 'break' ';' -> BREAK ;
    def break_stmt(self, ):
        retval = self.break_stmt_return()
        retval.start = self.input.LT(1)


        root_0 = None

        string_literal42 = None
        char_literal43 = None

        string_literal42_tree = None
        char_literal43_tree = None
        stream_92 = RewriteRuleTokenStream(self._adaptor, "token 92")
        stream_105 = RewriteRuleTokenStream(self._adaptor, "token 105")

        try:
            try:
                # Expr.g:86:2: ( 'break' ';' -> BREAK )
                # Expr.g:86:4: 'break' ';'
                pass 
                string_literal42 = self.match(self.input, 105, self.FOLLOW_105_in_break_stmt478) 
                if self._state.backtracking == 0:
                    stream_105.add(string_literal42)


                char_literal43 = self.match(self.input, 92, self.FOLLOW_92_in_break_stmt480) 
                if self._state.backtracking == 0:
                    stream_92.add(char_literal43)


                # AST Rewrite
                # elements: 
                # token labels: 
                # rule labels: retval
                # token list labels: 
                # rule list labels: 
                # wildcard labels: 
                if self._state.backtracking == 0:
                    retval.tree = root_0
                    if retval is not None:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "rule retval", retval.tree)
                    else:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "token retval", None)


                    root_0 = self._adaptor.nil()
                    # 87:3: -> BREAK
                    self._adaptor.addChild(root_0, 
                    self._adaptor.createFromType(BREAK, "BREAK")
                    )




                    retval.tree = root_0





                retval.stop = self.input.LT(-1)


                if self._state.backtracking == 0:
                    retval.tree = self._adaptor.rulePostProcessing(root_0)
                    self._adaptor.setTokenBoundaries(retval.tree, retval.start, retval.stop)



            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)
                retval.tree = self._adaptor.errorNode(self.input, retval.start, self.input.LT(-1), re)

        finally:
            pass
        return retval

    # $ANTLR end "break_stmt"


    class continue_stmt_return(ParserRuleReturnScope):
        def __init__(self):
            super(ExprParser.continue_stmt_return, self).__init__()

            self.tree = None





    # $ANTLR start "continue_stmt"
    # Expr.g:89:1: continue_stmt : 'continue' ';' -> CONTINUE ;
    def continue_stmt(self, ):
        retval = self.continue_stmt_return()
        retval.start = self.input.LT(1)


        root_0 = None

        string_literal44 = None
        char_literal45 = None

        string_literal44_tree = None
        char_literal45_tree = None
        stream_109 = RewriteRuleTokenStream(self._adaptor, "token 109")
        stream_92 = RewriteRuleTokenStream(self._adaptor, "token 92")

        try:
            try:
                # Expr.g:90:2: ( 'continue' ';' -> CONTINUE )
                # Expr.g:90:4: 'continue' ';'
                pass 
                string_literal44 = self.match(self.input, 109, self.FOLLOW_109_in_continue_stmt496) 
                if self._state.backtracking == 0:
                    stream_109.add(string_literal44)


                char_literal45 = self.match(self.input, 92, self.FOLLOW_92_in_continue_stmt498) 
                if self._state.backtracking == 0:
                    stream_92.add(char_literal45)


                # AST Rewrite
                # elements: 
                # token labels: 
                # rule labels: retval
                # token list labels: 
                # rule list labels: 
                # wildcard labels: 
                if self._state.backtracking == 0:
                    retval.tree = root_0
                    if retval is not None:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "rule retval", retval.tree)
                    else:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "token retval", None)


                    root_0 = self._adaptor.nil()
                    # 91:3: -> CONTINUE
                    self._adaptor.addChild(root_0, 
                    self._adaptor.createFromType(CONTINUE, "CONTINUE")
                    )




                    retval.tree = root_0





                retval.stop = self.input.LT(-1)


                if self._state.backtracking == 0:
                    retval.tree = self._adaptor.rulePostProcessing(root_0)
                    self._adaptor.setTokenBoundaries(retval.tree, retval.start, retval.stop)



            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)
                retval.tree = self._adaptor.errorNode(self.input, retval.start, self.input.LT(-1), re)

        finally:
            pass
        return retval

    # $ANTLR end "continue_stmt"


    class return_stmt_return(ParserRuleReturnScope):
        def __init__(self):
            super(ExprParser.return_stmt_return, self).__init__()

            self.tree = None





    # $ANTLR start "return_stmt"
    # Expr.g:93:1: return_stmt : 'return' ( expr )? ';' -> ^( RETURN ( expr )? ) ;
    def return_stmt(self, ):
        retval = self.return_stmt_return()
        retval.start = self.input.LT(1)


        root_0 = None

        string_literal46 = None
        char_literal48 = None
        expr47 = None

        string_literal46_tree = None
        char_literal48_tree = None
        stream_125 = RewriteRuleTokenStream(self._adaptor, "token 125")
        stream_92 = RewriteRuleTokenStream(self._adaptor, "token 92")
        stream_expr = RewriteRuleSubtreeStream(self._adaptor, "rule expr")
        try:
            try:
                # Expr.g:94:2: ( 'return' ( expr )? ';' -> ^( RETURN ( expr )? ) )
                # Expr.g:94:4: 'return' ( expr )? ';'
                pass 
                string_literal46 = self.match(self.input, 125, self.FOLLOW_125_in_return_stmt514) 
                if self._state.backtracking == 0:
                    stream_125.add(string_literal46)


                # Expr.g:94:13: ( expr )?
                alt8 = 2
                LA8_0 = self.input.LA(1)

                if (LA8_0 == BOOL or LA8_0 == FLOAT or LA8_0 == ID or LA8_0 == INT or LA8_0 == NULL or LA8_0 == STRING or LA8_0 == 68 or LA8_0 == 75 or LA8_0 == 83 or LA8_0 == 100 or LA8_0 == 121 or LA8_0 == 126 or LA8_0 == 132) :
                    alt8 = 1
                if alt8 == 1:
                    # Expr.g:94:13: expr
                    pass 
                    self._state.following.append(self.FOLLOW_expr_in_return_stmt516)
                    expr47 = self.expr()

                    self._state.following.pop()
                    if self._state.backtracking == 0:
                        stream_expr.add(expr47.tree)





                char_literal48 = self.match(self.input, 92, self.FOLLOW_92_in_return_stmt519) 
                if self._state.backtracking == 0:
                    stream_92.add(char_literal48)


                # AST Rewrite
                # elements: expr
                # token labels: 
                # rule labels: retval
                # token list labels: 
                # rule list labels: 
                # wildcard labels: 
                if self._state.backtracking == 0:
                    retval.tree = root_0
                    if retval is not None:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "rule retval", retval.tree)
                    else:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "token retval", None)


                    root_0 = self._adaptor.nil()
                    # 95:3: -> ^( RETURN ( expr )? )
                    # Expr.g:95:6: ^( RETURN ( expr )? )
                    root_1 = self._adaptor.nil()
                    root_1 = self._adaptor.becomeRoot(
                    self._adaptor.createFromType(RETURN, "RETURN")
                    , root_1)

                    # Expr.g:95:15: ( expr )?
                    if stream_expr.hasNext():
                        self._adaptor.addChild(root_1, stream_expr.nextTree())


                    stream_expr.reset();

                    self._adaptor.addChild(root_0, root_1)




                    retval.tree = root_0





                retval.stop = self.input.LT(-1)


                if self._state.backtracking == 0:
                    retval.tree = self._adaptor.rulePostProcessing(root_0)
                    self._adaptor.setTokenBoundaries(retval.tree, retval.start, retval.stop)



            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)
                retval.tree = self._adaptor.errorNode(self.input, retval.start, self.input.LT(-1), re)

        finally:
            pass
        return retval

    # $ANTLR end "return_stmt"


    class if_stmt_return(ParserRuleReturnScope):
        def __init__(self):
            super(ExprParser.if_stmt_return, self).__init__()

            self.tree = None





    # $ANTLR start "if_stmt"
    # Expr.g:98:1: if_stmt : if_clause ( else_if_clause )* ( else_clause )? ;
    def if_stmt(self, ):
        retval = self.if_stmt_return()
        retval.start = self.input.LT(1)


        root_0 = None

        if_clause49 = None
        else_if_clause50 = None
        else_clause51 = None


        try:
            try:
                # Expr.g:99:2: ( if_clause ( else_if_clause )* ( else_clause )? )
                # Expr.g:99:4: if_clause ( else_if_clause )* ( else_clause )?
                pass 
                root_0 = self._adaptor.nil()


                self._state.following.append(self.FOLLOW_if_clause_in_if_stmt541)
                if_clause49 = self.if_clause()

                self._state.following.pop()
                if self._state.backtracking == 0:
                    self._adaptor.addChild(root_0, if_clause49.tree)


                # Expr.g:99:14: ( else_if_clause )*
                while True: #loop9
                    alt9 = 2
                    LA9_0 = self.input.LA(1)

                    if (LA9_0 == 112) :
                        LA9_1 = self.input.LA(2)

                        if (LA9_1 == 118) :
                            alt9 = 1




                    if alt9 == 1:
                        # Expr.g:99:14: else_if_clause
                        pass 
                        self._state.following.append(self.FOLLOW_else_if_clause_in_if_stmt543)
                        else_if_clause50 = self.else_if_clause()

                        self._state.following.pop()
                        if self._state.backtracking == 0:
                            self._adaptor.addChild(root_0, else_if_clause50.tree)



                    else:
                        break #loop9


                # Expr.g:99:30: ( else_clause )?
                alt10 = 2
                LA10_0 = self.input.LA(1)

                if (LA10_0 == 112) :
                    alt10 = 1
                if alt10 == 1:
                    # Expr.g:99:30: else_clause
                    pass 
                    self._state.following.append(self.FOLLOW_else_clause_in_if_stmt546)
                    else_clause51 = self.else_clause()

                    self._state.following.pop()
                    if self._state.backtracking == 0:
                        self._adaptor.addChild(root_0, else_clause51.tree)







                retval.stop = self.input.LT(-1)


                if self._state.backtracking == 0:
                    retval.tree = self._adaptor.rulePostProcessing(root_0)
                    self._adaptor.setTokenBoundaries(retval.tree, retval.start, retval.stop)



            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)
                retval.tree = self._adaptor.errorNode(self.input, retval.start, self.input.LT(-1), re)

        finally:
            pass
        return retval

    # $ANTLR end "if_stmt"


    class if_clause_return(ParserRuleReturnScope):
        def __init__(self):
            super(ExprParser.if_clause_return, self).__init__()

            self.tree = None





    # $ANTLR start "if_clause"
    # Expr.g:101:1: if_clause : 'if' '(' expr ')' block -> ^( IF expr block ) ;
    def if_clause(self, ):
        retval = self.if_clause_return()
        retval.start = self.input.LT(1)


        root_0 = None

        string_literal52 = None
        char_literal53 = None
        char_literal55 = None
        expr54 = None
        block56 = None

        string_literal52_tree = None
        char_literal53_tree = None
        char_literal55_tree = None
        stream_75 = RewriteRuleTokenStream(self._adaptor, "token 75")
        stream_118 = RewriteRuleTokenStream(self._adaptor, "token 118")
        stream_76 = RewriteRuleTokenStream(self._adaptor, "token 76")
        stream_block = RewriteRuleSubtreeStream(self._adaptor, "rule block")
        stream_expr = RewriteRuleSubtreeStream(self._adaptor, "rule expr")
        try:
            try:
                # Expr.g:102:2: ( 'if' '(' expr ')' block -> ^( IF expr block ) )
                # Expr.g:102:4: 'if' '(' expr ')' block
                pass 
                string_literal52 = self.match(self.input, 118, self.FOLLOW_118_in_if_clause557) 
                if self._state.backtracking == 0:
                    stream_118.add(string_literal52)


                char_literal53 = self.match(self.input, 75, self.FOLLOW_75_in_if_clause559) 
                if self._state.backtracking == 0:
                    stream_75.add(char_literal53)


                self._state.following.append(self.FOLLOW_expr_in_if_clause561)
                expr54 = self.expr()

                self._state.following.pop()
                if self._state.backtracking == 0:
                    stream_expr.add(expr54.tree)


                char_literal55 = self.match(self.input, 76, self.FOLLOW_76_in_if_clause563) 
                if self._state.backtracking == 0:
                    stream_76.add(char_literal55)


                self._state.following.append(self.FOLLOW_block_in_if_clause565)
                block56 = self.block()

                self._state.following.pop()
                if self._state.backtracking == 0:
                    stream_block.add(block56.tree)


                # AST Rewrite
                # elements: block, expr
                # token labels: 
                # rule labels: retval
                # token list labels: 
                # rule list labels: 
                # wildcard labels: 
                if self._state.backtracking == 0:
                    retval.tree = root_0
                    if retval is not None:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "rule retval", retval.tree)
                    else:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "token retval", None)


                    root_0 = self._adaptor.nil()
                    # 103:3: -> ^( IF expr block )
                    # Expr.g:103:6: ^( IF expr block )
                    root_1 = self._adaptor.nil()
                    root_1 = self._adaptor.becomeRoot(
                    self._adaptor.createFromType(IF, "IF")
                    , root_1)

                    self._adaptor.addChild(root_1, stream_expr.nextTree())

                    self._adaptor.addChild(root_1, stream_block.nextTree())

                    self._adaptor.addChild(root_0, root_1)




                    retval.tree = root_0





                retval.stop = self.input.LT(-1)


                if self._state.backtracking == 0:
                    retval.tree = self._adaptor.rulePostProcessing(root_0)
                    self._adaptor.setTokenBoundaries(retval.tree, retval.start, retval.stop)



            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)
                retval.tree = self._adaptor.errorNode(self.input, retval.start, self.input.LT(-1), re)

        finally:
            pass
        return retval

    # $ANTLR end "if_clause"


    class else_if_clause_return(ParserRuleReturnScope):
        def __init__(self):
            super(ExprParser.else_if_clause_return, self).__init__()

            self.tree = None





    # $ANTLR start "else_if_clause"
    # Expr.g:105:1: else_if_clause : 'else' if_clause -> ^( ELSE_IF if_clause ) ;
    def else_if_clause(self, ):
        retval = self.else_if_clause_return()
        retval.start = self.input.LT(1)


        root_0 = None

        string_literal57 = None
        if_clause58 = None

        string_literal57_tree = None
        stream_112 = RewriteRuleTokenStream(self._adaptor, "token 112")
        stream_if_clause = RewriteRuleSubtreeStream(self._adaptor, "rule if_clause")
        try:
            try:
                # Expr.g:106:2: ( 'else' if_clause -> ^( ELSE_IF if_clause ) )
                # Expr.g:106:4: 'else' if_clause
                pass 
                string_literal57 = self.match(self.input, 112, self.FOLLOW_112_in_else_if_clause587) 
                if self._state.backtracking == 0:
                    stream_112.add(string_literal57)


                self._state.following.append(self.FOLLOW_if_clause_in_else_if_clause589)
                if_clause58 = self.if_clause()

                self._state.following.pop()
                if self._state.backtracking == 0:
                    stream_if_clause.add(if_clause58.tree)


                # AST Rewrite
                # elements: if_clause
                # token labels: 
                # rule labels: retval
                # token list labels: 
                # rule list labels: 
                # wildcard labels: 
                if self._state.backtracking == 0:
                    retval.tree = root_0
                    if retval is not None:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "rule retval", retval.tree)
                    else:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "token retval", None)


                    root_0 = self._adaptor.nil()
                    # 107:3: -> ^( ELSE_IF if_clause )
                    # Expr.g:107:6: ^( ELSE_IF if_clause )
                    root_1 = self._adaptor.nil()
                    root_1 = self._adaptor.becomeRoot(
                    self._adaptor.createFromType(ELSE_IF, "ELSE_IF")
                    , root_1)

                    self._adaptor.addChild(root_1, stream_if_clause.nextTree())

                    self._adaptor.addChild(root_0, root_1)




                    retval.tree = root_0





                retval.stop = self.input.LT(-1)


                if self._state.backtracking == 0:
                    retval.tree = self._adaptor.rulePostProcessing(root_0)
                    self._adaptor.setTokenBoundaries(retval.tree, retval.start, retval.stop)



            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)
                retval.tree = self._adaptor.errorNode(self.input, retval.start, self.input.LT(-1), re)

        finally:
            pass
        return retval

    # $ANTLR end "else_if_clause"


    class else_clause_return(ParserRuleReturnScope):
        def __init__(self):
            super(ExprParser.else_clause_return, self).__init__()

            self.tree = None





    # $ANTLR start "else_clause"
    # Expr.g:109:1: else_clause : 'else' block -> ^( ELSE block ) ;
    def else_clause(self, ):
        retval = self.else_clause_return()
        retval.start = self.input.LT(1)


        root_0 = None

        string_literal59 = None
        block60 = None

        string_literal59_tree = None
        stream_112 = RewriteRuleTokenStream(self._adaptor, "token 112")
        stream_block = RewriteRuleSubtreeStream(self._adaptor, "rule block")
        try:
            try:
                # Expr.g:110:2: ( 'else' block -> ^( ELSE block ) )
                # Expr.g:110:4: 'else' block
                pass 
                string_literal59 = self.match(self.input, 112, self.FOLLOW_112_in_else_clause609) 
                if self._state.backtracking == 0:
                    stream_112.add(string_literal59)


                self._state.following.append(self.FOLLOW_block_in_else_clause611)
                block60 = self.block()

                self._state.following.pop()
                if self._state.backtracking == 0:
                    stream_block.add(block60.tree)


                # AST Rewrite
                # elements: block
                # token labels: 
                # rule labels: retval
                # token list labels: 
                # rule list labels: 
                # wildcard labels: 
                if self._state.backtracking == 0:
                    retval.tree = root_0
                    if retval is not None:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "rule retval", retval.tree)
                    else:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "token retval", None)


                    root_0 = self._adaptor.nil()
                    # 111:3: -> ^( ELSE block )
                    # Expr.g:111:6: ^( ELSE block )
                    root_1 = self._adaptor.nil()
                    root_1 = self._adaptor.becomeRoot(
                    self._adaptor.createFromType(ELSE, "ELSE")
                    , root_1)

                    self._adaptor.addChild(root_1, stream_block.nextTree())

                    self._adaptor.addChild(root_0, root_1)




                    retval.tree = root_0





                retval.stop = self.input.LT(-1)


                if self._state.backtracking == 0:
                    retval.tree = self._adaptor.rulePostProcessing(root_0)
                    self._adaptor.setTokenBoundaries(retval.tree, retval.start, retval.stop)



            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)
                retval.tree = self._adaptor.errorNode(self.input, retval.start, self.input.LT(-1), re)

        finally:
            pass
        return retval

    # $ANTLR end "else_clause"


    class while_stmt_return(ParserRuleReturnScope):
        def __init__(self):
            super(ExprParser.while_stmt_return, self).__init__()

            self.tree = None





    # $ANTLR start "while_stmt"
    # Expr.g:114:1: while_stmt : 'while' '(' expr ')' block -> ^( WHILE expr block ) ;
    def while_stmt(self, ):
        retval = self.while_stmt_return()
        retval.start = self.input.LT(1)


        root_0 = None

        string_literal61 = None
        char_literal62 = None
        char_literal64 = None
        expr63 = None
        block65 = None

        string_literal61_tree = None
        char_literal62_tree = None
        char_literal64_tree = None
        stream_131 = RewriteRuleTokenStream(self._adaptor, "token 131")
        stream_75 = RewriteRuleTokenStream(self._adaptor, "token 75")
        stream_76 = RewriteRuleTokenStream(self._adaptor, "token 76")
        stream_block = RewriteRuleSubtreeStream(self._adaptor, "rule block")
        stream_expr = RewriteRuleSubtreeStream(self._adaptor, "rule expr")
        try:
            try:
                # Expr.g:115:2: ( 'while' '(' expr ')' block -> ^( WHILE expr block ) )
                # Expr.g:115:4: 'while' '(' expr ')' block
                pass 
                string_literal61 = self.match(self.input, 131, self.FOLLOW_131_in_while_stmt632) 
                if self._state.backtracking == 0:
                    stream_131.add(string_literal61)


                char_literal62 = self.match(self.input, 75, self.FOLLOW_75_in_while_stmt634) 
                if self._state.backtracking == 0:
                    stream_75.add(char_literal62)


                self._state.following.append(self.FOLLOW_expr_in_while_stmt636)
                expr63 = self.expr()

                self._state.following.pop()
                if self._state.backtracking == 0:
                    stream_expr.add(expr63.tree)


                char_literal64 = self.match(self.input, 76, self.FOLLOW_76_in_while_stmt638) 
                if self._state.backtracking == 0:
                    stream_76.add(char_literal64)


                self._state.following.append(self.FOLLOW_block_in_while_stmt640)
                block65 = self.block()

                self._state.following.pop()
                if self._state.backtracking == 0:
                    stream_block.add(block65.tree)


                # AST Rewrite
                # elements: expr, block
                # token labels: 
                # rule labels: retval
                # token list labels: 
                # rule list labels: 
                # wildcard labels: 
                if self._state.backtracking == 0:
                    retval.tree = root_0
                    if retval is not None:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "rule retval", retval.tree)
                    else:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "token retval", None)


                    root_0 = self._adaptor.nil()
                    # 116:3: -> ^( WHILE expr block )
                    # Expr.g:116:6: ^( WHILE expr block )
                    root_1 = self._adaptor.nil()
                    root_1 = self._adaptor.becomeRoot(
                    self._adaptor.createFromType(WHILE, "WHILE")
                    , root_1)

                    self._adaptor.addChild(root_1, stream_expr.nextTree())

                    self._adaptor.addChild(root_1, stream_block.nextTree())

                    self._adaptor.addChild(root_0, root_1)




                    retval.tree = root_0





                retval.stop = self.input.LT(-1)


                if self._state.backtracking == 0:
                    retval.tree = self._adaptor.rulePostProcessing(root_0)
                    self._adaptor.setTokenBoundaries(retval.tree, retval.start, retval.stop)



            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)
                retval.tree = self._adaptor.errorNode(self.input, retval.start, self.input.LT(-1), re)

        finally:
            pass
        return retval

    # $ANTLR end "while_stmt"


    class do_while_stmt_return(ParserRuleReturnScope):
        def __init__(self):
            super(ExprParser.do_while_stmt_return, self).__init__()

            self.tree = None





    # $ANTLR start "do_while_stmt"
    # Expr.g:119:1: do_while_stmt : 'do' block 'while' '(' expr ')' ';' -> ^( DO_WHILE block expr ) ;
    def do_while_stmt(self, ):
        retval = self.do_while_stmt_return()
        retval.start = self.input.LT(1)


        root_0 = None

        string_literal66 = None
        string_literal68 = None
        char_literal69 = None
        char_literal71 = None
        char_literal72 = None
        block67 = None
        expr70 = None

        string_literal66_tree = None
        string_literal68_tree = None
        char_literal69_tree = None
        char_literal71_tree = None
        char_literal72_tree = None
        stream_92 = RewriteRuleTokenStream(self._adaptor, "token 92")
        stream_111 = RewriteRuleTokenStream(self._adaptor, "token 111")
        stream_131 = RewriteRuleTokenStream(self._adaptor, "token 131")
        stream_75 = RewriteRuleTokenStream(self._adaptor, "token 75")
        stream_76 = RewriteRuleTokenStream(self._adaptor, "token 76")
        stream_block = RewriteRuleSubtreeStream(self._adaptor, "rule block")
        stream_expr = RewriteRuleSubtreeStream(self._adaptor, "rule expr")
        try:
            try:
                # Expr.g:120:2: ( 'do' block 'while' '(' expr ')' ';' -> ^( DO_WHILE block expr ) )
                # Expr.g:120:4: 'do' block 'while' '(' expr ')' ';'
                pass 
                string_literal66 = self.match(self.input, 111, self.FOLLOW_111_in_do_while_stmt663) 
                if self._state.backtracking == 0:
                    stream_111.add(string_literal66)


                self._state.following.append(self.FOLLOW_block_in_do_while_stmt665)
                block67 = self.block()

                self._state.following.pop()
                if self._state.backtracking == 0:
                    stream_block.add(block67.tree)


                string_literal68 = self.match(self.input, 131, self.FOLLOW_131_in_do_while_stmt667) 
                if self._state.backtracking == 0:
                    stream_131.add(string_literal68)


                char_literal69 = self.match(self.input, 75, self.FOLLOW_75_in_do_while_stmt669) 
                if self._state.backtracking == 0:
                    stream_75.add(char_literal69)


                self._state.following.append(self.FOLLOW_expr_in_do_while_stmt671)
                expr70 = self.expr()

                self._state.following.pop()
                if self._state.backtracking == 0:
                    stream_expr.add(expr70.tree)


                char_literal71 = self.match(self.input, 76, self.FOLLOW_76_in_do_while_stmt673) 
                if self._state.backtracking == 0:
                    stream_76.add(char_literal71)


                char_literal72 = self.match(self.input, 92, self.FOLLOW_92_in_do_while_stmt675) 
                if self._state.backtracking == 0:
                    stream_92.add(char_literal72)


                # AST Rewrite
                # elements: expr, block
                # token labels: 
                # rule labels: retval
                # token list labels: 
                # rule list labels: 
                # wildcard labels: 
                if self._state.backtracking == 0:
                    retval.tree = root_0
                    if retval is not None:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "rule retval", retval.tree)
                    else:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "token retval", None)


                    root_0 = self._adaptor.nil()
                    # 121:3: -> ^( DO_WHILE block expr )
                    # Expr.g:121:6: ^( DO_WHILE block expr )
                    root_1 = self._adaptor.nil()
                    root_1 = self._adaptor.becomeRoot(
                    self._adaptor.createFromType(DO_WHILE, "DO_WHILE")
                    , root_1)

                    self._adaptor.addChild(root_1, stream_block.nextTree())

                    self._adaptor.addChild(root_1, stream_expr.nextTree())

                    self._adaptor.addChild(root_0, root_1)




                    retval.tree = root_0





                retval.stop = self.input.LT(-1)


                if self._state.backtracking == 0:
                    retval.tree = self._adaptor.rulePostProcessing(root_0)
                    self._adaptor.setTokenBoundaries(retval.tree, retval.start, retval.stop)



            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)
                retval.tree = self._adaptor.errorNode(self.input, retval.start, self.input.LT(-1), re)

        finally:
            pass
        return retval

    # $ANTLR end "do_while_stmt"


    class switch_stmt_return(ParserRuleReturnScope):
        def __init__(self):
            super(ExprParser.switch_stmt_return, self).__init__()

            self.tree = None





    # $ANTLR start "switch_stmt"
    # Expr.g:124:1: switch_stmt : 'switch' '(' expr ')' case_block -> ^( SWITCH expr case_block ) ;
    def switch_stmt(self, ):
        retval = self.switch_stmt_return()
        retval.start = self.input.LT(1)


        root_0 = None

        string_literal73 = None
        char_literal74 = None
        char_literal76 = None
        expr75 = None
        case_block77 = None

        string_literal73_tree = None
        char_literal74_tree = None
        char_literal76_tree = None
        stream_128 = RewriteRuleTokenStream(self._adaptor, "token 128")
        stream_75 = RewriteRuleTokenStream(self._adaptor, "token 75")
        stream_76 = RewriteRuleTokenStream(self._adaptor, "token 76")
        stream_case_block = RewriteRuleSubtreeStream(self._adaptor, "rule case_block")
        stream_expr = RewriteRuleSubtreeStream(self._adaptor, "rule expr")
        try:
            try:
                # Expr.g:125:2: ( 'switch' '(' expr ')' case_block -> ^( SWITCH expr case_block ) )
                # Expr.g:125:4: 'switch' '(' expr ')' case_block
                pass 
                string_literal73 = self.match(self.input, 128, self.FOLLOW_128_in_switch_stmt698) 
                if self._state.backtracking == 0:
                    stream_128.add(string_literal73)


                char_literal74 = self.match(self.input, 75, self.FOLLOW_75_in_switch_stmt700) 
                if self._state.backtracking == 0:
                    stream_75.add(char_literal74)


                self._state.following.append(self.FOLLOW_expr_in_switch_stmt702)
                expr75 = self.expr()

                self._state.following.pop()
                if self._state.backtracking == 0:
                    stream_expr.add(expr75.tree)


                char_literal76 = self.match(self.input, 76, self.FOLLOW_76_in_switch_stmt704) 
                if self._state.backtracking == 0:
                    stream_76.add(char_literal76)


                self._state.following.append(self.FOLLOW_case_block_in_switch_stmt706)
                case_block77 = self.case_block()

                self._state.following.pop()
                if self._state.backtracking == 0:
                    stream_case_block.add(case_block77.tree)


                # AST Rewrite
                # elements: case_block, expr
                # token labels: 
                # rule labels: retval
                # token list labels: 
                # rule list labels: 
                # wildcard labels: 
                if self._state.backtracking == 0:
                    retval.tree = root_0
                    if retval is not None:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "rule retval", retval.tree)
                    else:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "token retval", None)


                    root_0 = self._adaptor.nil()
                    # 126:3: -> ^( SWITCH expr case_block )
                    # Expr.g:126:6: ^( SWITCH expr case_block )
                    root_1 = self._adaptor.nil()
                    root_1 = self._adaptor.becomeRoot(
                    self._adaptor.createFromType(SWITCH, "SWITCH")
                    , root_1)

                    self._adaptor.addChild(root_1, stream_expr.nextTree())

                    self._adaptor.addChild(root_1, stream_case_block.nextTree())

                    self._adaptor.addChild(root_0, root_1)




                    retval.tree = root_0





                retval.stop = self.input.LT(-1)


                if self._state.backtracking == 0:
                    retval.tree = self._adaptor.rulePostProcessing(root_0)
                    self._adaptor.setTokenBoundaries(retval.tree, retval.start, retval.stop)



            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)
                retval.tree = self._adaptor.errorNode(self.input, retval.start, self.input.LT(-1), re)

        finally:
            pass
        return retval

    # $ANTLR end "switch_stmt"


    class case_block_return(ParserRuleReturnScope):
        def __init__(self):
            super(ExprParser.case_block_return, self).__init__()

            self.tree = None





    # $ANTLR start "case_block"
    # Expr.g:128:1: case_block : '{' ( case_clause )+ ( default_clause )? '}' ;
    def case_block(self, ):
        retval = self.case_block_return()
        retval.start = self.input.LT(1)


        root_0 = None

        char_literal78 = None
        char_literal81 = None
        case_clause79 = None
        default_clause80 = None

        char_literal78_tree = None
        char_literal81_tree = None

        try:
            try:
                # Expr.g:129:2: ( '{' ( case_clause )+ ( default_clause )? '}' )
                # Expr.g:129:4: '{' ( case_clause )+ ( default_clause )? '}'
                pass 
                root_0 = self._adaptor.nil()


                char_literal78 = self.match(self.input, 132, self.FOLLOW_132_in_case_block728)
                if self._state.backtracking == 0:
                    char_literal78_tree = self._adaptor.createWithPayload(char_literal78)
                    self._adaptor.addChild(root_0, char_literal78_tree)



                # Expr.g:129:8: ( case_clause )+
                cnt11 = 0
                while True: #loop11
                    alt11 = 2
                    LA11_0 = self.input.LA(1)

                    if (LA11_0 == 106) :
                        alt11 = 1


                    if alt11 == 1:
                        # Expr.g:129:9: case_clause
                        pass 
                        self._state.following.append(self.FOLLOW_case_clause_in_case_block731)
                        case_clause79 = self.case_clause()

                        self._state.following.pop()
                        if self._state.backtracking == 0:
                            self._adaptor.addChild(root_0, case_clause79.tree)



                    else:
                        if cnt11 >= 1:
                            break #loop11

                        if self._state.backtracking > 0:
                            raise BacktrackingFailed


                        eee = EarlyExitException(11, self.input)
                        raise eee

                    cnt11 += 1


                # Expr.g:129:23: ( default_clause )?
                alt12 = 2
                LA12_0 = self.input.LA(1)

                if (LA12_0 == 110) :
                    alt12 = 1
                if alt12 == 1:
                    # Expr.g:129:24: default_clause
                    pass 
                    self._state.following.append(self.FOLLOW_default_clause_in_case_block736)
                    default_clause80 = self.default_clause()

                    self._state.following.pop()
                    if self._state.backtracking == 0:
                        self._adaptor.addChild(root_0, default_clause80.tree)





                char_literal81 = self.match(self.input, 136, self.FOLLOW_136_in_case_block740)
                if self._state.backtracking == 0:
                    char_literal81_tree = self._adaptor.createWithPayload(char_literal81)
                    self._adaptor.addChild(root_0, char_literal81_tree)





                retval.stop = self.input.LT(-1)


                if self._state.backtracking == 0:
                    retval.tree = self._adaptor.rulePostProcessing(root_0)
                    self._adaptor.setTokenBoundaries(retval.tree, retval.start, retval.stop)



            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)
                retval.tree = self._adaptor.errorNode(self.input, retval.start, self.input.LT(-1), re)

        finally:
            pass
        return retval

    # $ANTLR end "case_block"


    class case_clause_return(ParserRuleReturnScope):
        def __init__(self):
            super(ExprParser.case_clause_return, self).__init__()

            self.tree = None





    # $ANTLR start "case_clause"
    # Expr.g:131:1: case_clause : ( case_test )+ ( stmt )* break_stmt -> ^( CASE ( case_test )+ ( stmt )* break_stmt ) ;
    def case_clause(self, ):
        retval = self.case_clause_return()
        retval.start = self.input.LT(1)


        root_0 = None

        case_test82 = None
        stmt83 = None
        break_stmt84 = None

        stream_case_test = RewriteRuleSubtreeStream(self._adaptor, "rule case_test")
        stream_stmt = RewriteRuleSubtreeStream(self._adaptor, "rule stmt")
        stream_break_stmt = RewriteRuleSubtreeStream(self._adaptor, "rule break_stmt")
        try:
            try:
                # Expr.g:132:2: ( ( case_test )+ ( stmt )* break_stmt -> ^( CASE ( case_test )+ ( stmt )* break_stmt ) )
                # Expr.g:132:4: ( case_test )+ ( stmt )* break_stmt
                pass 
                # Expr.g:132:4: ( case_test )+
                cnt13 = 0
                while True: #loop13
                    alt13 = 2
                    LA13_0 = self.input.LA(1)

                    if (LA13_0 == 106) :
                        alt13 = 1


                    if alt13 == 1:
                        # Expr.g:132:4: case_test
                        pass 
                        self._state.following.append(self.FOLLOW_case_test_in_case_clause750)
                        case_test82 = self.case_test()

                        self._state.following.pop()
                        if self._state.backtracking == 0:
                            stream_case_test.add(case_test82.tree)



                    else:
                        if cnt13 >= 1:
                            break #loop13

                        if self._state.backtracking > 0:
                            raise BacktrackingFailed


                        eee = EarlyExitException(13, self.input)
                        raise eee

                    cnt13 += 1


                # Expr.g:132:15: ( stmt )*
                while True: #loop14
                    alt14 = 2
                    LA14_0 = self.input.LA(1)

                    if (LA14_0 == 105) :
                        LA14_1 = self.input.LA(2)

                        if (LA14_1 == 92) :
                            LA14_3 = self.input.LA(3)

                            if (LA14_3 == ID or LA14_3 == 80 or LA14_3 == 84 or LA14_3 == 92 or LA14_3 == 105 or (108 <= LA14_3 <= 109) or LA14_3 == 111 or (115 <= LA14_3 <= 119) or (122 <= LA14_3 <= 123) or LA14_3 == 125 or (128 <= LA14_3 <= 131)) :
                                alt14 = 1




                    elif (LA14_0 == ID or LA14_0 == 80 or LA14_0 == 84 or LA14_0 == 92 or (108 <= LA14_0 <= 109) or LA14_0 == 111 or (115 <= LA14_0 <= 119) or (122 <= LA14_0 <= 123) or LA14_0 == 125 or (128 <= LA14_0 <= 131)) :
                        alt14 = 1


                    if alt14 == 1:
                        # Expr.g:132:15: stmt
                        pass 
                        self._state.following.append(self.FOLLOW_stmt_in_case_clause753)
                        stmt83 = self.stmt()

                        self._state.following.pop()
                        if self._state.backtracking == 0:
                            stream_stmt.add(stmt83.tree)



                    else:
                        break #loop14


                self._state.following.append(self.FOLLOW_break_stmt_in_case_clause756)
                break_stmt84 = self.break_stmt()

                self._state.following.pop()
                if self._state.backtracking == 0:
                    stream_break_stmt.add(break_stmt84.tree)


                # AST Rewrite
                # elements: stmt, case_test, break_stmt
                # token labels: 
                # rule labels: retval
                # token list labels: 
                # rule list labels: 
                # wildcard labels: 
                if self._state.backtracking == 0:
                    retval.tree = root_0
                    if retval is not None:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "rule retval", retval.tree)
                    else:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "token retval", None)


                    root_0 = self._adaptor.nil()
                    # 133:3: -> ^( CASE ( case_test )+ ( stmt )* break_stmt )
                    # Expr.g:133:6: ^( CASE ( case_test )+ ( stmt )* break_stmt )
                    root_1 = self._adaptor.nil()
                    root_1 = self._adaptor.becomeRoot(
                    self._adaptor.createFromType(CASE, "CASE")
                    , root_1)

                    # Expr.g:133:13: ( case_test )+
                    if not (stream_case_test.hasNext()):
                        raise RewriteEarlyExitException()

                    while stream_case_test.hasNext():
                        self._adaptor.addChild(root_1, stream_case_test.nextTree())


                    stream_case_test.reset()

                    # Expr.g:133:24: ( stmt )*
                    while stream_stmt.hasNext():
                        self._adaptor.addChild(root_1, stream_stmt.nextTree())


                    stream_stmt.reset();

                    self._adaptor.addChild(root_1, stream_break_stmt.nextTree())

                    self._adaptor.addChild(root_0, root_1)




                    retval.tree = root_0





                retval.stop = self.input.LT(-1)


                if self._state.backtracking == 0:
                    retval.tree = self._adaptor.rulePostProcessing(root_0)
                    self._adaptor.setTokenBoundaries(retval.tree, retval.start, retval.stop)



            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)
                retval.tree = self._adaptor.errorNode(self.input, retval.start, self.input.LT(-1), re)

        finally:
            pass
        return retval

    # $ANTLR end "case_clause"


    class case_test_return(ParserRuleReturnScope):
        def __init__(self):
            super(ExprParser.case_test_return, self).__init__()

            self.tree = None





    # $ANTLR start "case_test"
    # Expr.g:135:1: case_test : 'case' expr ':' -> ^( CASE expr ) ;
    def case_test(self, ):
        retval = self.case_test_return()
        retval.start = self.input.LT(1)


        root_0 = None

        string_literal85 = None
        char_literal87 = None
        expr86 = None

        string_literal85_tree = None
        char_literal87_tree = None
        stream_91 = RewriteRuleTokenStream(self._adaptor, "token 91")
        stream_106 = RewriteRuleTokenStream(self._adaptor, "token 106")
        stream_expr = RewriteRuleSubtreeStream(self._adaptor, "rule expr")
        try:
            try:
                # Expr.g:136:2: ( 'case' expr ':' -> ^( CASE expr ) )
                # Expr.g:136:4: 'case' expr ':'
                pass 
                string_literal85 = self.match(self.input, 106, self.FOLLOW_106_in_case_test782) 
                if self._state.backtracking == 0:
                    stream_106.add(string_literal85)


                self._state.following.append(self.FOLLOW_expr_in_case_test784)
                expr86 = self.expr()

                self._state.following.pop()
                if self._state.backtracking == 0:
                    stream_expr.add(expr86.tree)


                char_literal87 = self.match(self.input, 91, self.FOLLOW_91_in_case_test786) 
                if self._state.backtracking == 0:
                    stream_91.add(char_literal87)


                # AST Rewrite
                # elements: expr
                # token labels: 
                # rule labels: retval
                # token list labels: 
                # rule list labels: 
                # wildcard labels: 
                if self._state.backtracking == 0:
                    retval.tree = root_0
                    if retval is not None:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "rule retval", retval.tree)
                    else:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "token retval", None)


                    root_0 = self._adaptor.nil()
                    # 137:3: -> ^( CASE expr )
                    # Expr.g:137:6: ^( CASE expr )
                    root_1 = self._adaptor.nil()
                    root_1 = self._adaptor.becomeRoot(
                    self._adaptor.createFromType(CASE, "CASE")
                    , root_1)

                    self._adaptor.addChild(root_1, stream_expr.nextTree())

                    self._adaptor.addChild(root_0, root_1)




                    retval.tree = root_0





                retval.stop = self.input.LT(-1)


                if self._state.backtracking == 0:
                    retval.tree = self._adaptor.rulePostProcessing(root_0)
                    self._adaptor.setTokenBoundaries(retval.tree, retval.start, retval.stop)



            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)
                retval.tree = self._adaptor.errorNode(self.input, retval.start, self.input.LT(-1), re)

        finally:
            pass
        return retval

    # $ANTLR end "case_test"


    class default_clause_return(ParserRuleReturnScope):
        def __init__(self):
            super(ExprParser.default_clause_return, self).__init__()

            self.tree = None





    # $ANTLR start "default_clause"
    # Expr.g:139:1: default_clause : 'default' ':' ( stmt )* -> ^( DEFAULT ( stmt )* ) ;
    def default_clause(self, ):
        retval = self.default_clause_return()
        retval.start = self.input.LT(1)


        root_0 = None

        string_literal88 = None
        char_literal89 = None
        stmt90 = None

        string_literal88_tree = None
        char_literal89_tree = None
        stream_110 = RewriteRuleTokenStream(self._adaptor, "token 110")
        stream_91 = RewriteRuleTokenStream(self._adaptor, "token 91")
        stream_stmt = RewriteRuleSubtreeStream(self._adaptor, "rule stmt")
        try:
            try:
                # Expr.g:140:2: ( 'default' ':' ( stmt )* -> ^( DEFAULT ( stmt )* ) )
                # Expr.g:140:4: 'default' ':' ( stmt )*
                pass 
                string_literal88 = self.match(self.input, 110, self.FOLLOW_110_in_default_clause806) 
                if self._state.backtracking == 0:
                    stream_110.add(string_literal88)


                char_literal89 = self.match(self.input, 91, self.FOLLOW_91_in_default_clause808) 
                if self._state.backtracking == 0:
                    stream_91.add(char_literal89)


                # Expr.g:140:18: ( stmt )*
                while True: #loop15
                    alt15 = 2
                    LA15_0 = self.input.LA(1)

                    if (LA15_0 == ID or LA15_0 == 80 or LA15_0 == 84 or LA15_0 == 92 or LA15_0 == 105 or (108 <= LA15_0 <= 109) or LA15_0 == 111 or (115 <= LA15_0 <= 119) or (122 <= LA15_0 <= 123) or LA15_0 == 125 or (128 <= LA15_0 <= 131)) :
                        alt15 = 1


                    if alt15 == 1:
                        # Expr.g:140:18: stmt
                        pass 
                        self._state.following.append(self.FOLLOW_stmt_in_default_clause810)
                        stmt90 = self.stmt()

                        self._state.following.pop()
                        if self._state.backtracking == 0:
                            stream_stmt.add(stmt90.tree)



                    else:
                        break #loop15


                # AST Rewrite
                # elements: stmt
                # token labels: 
                # rule labels: retval
                # token list labels: 
                # rule list labels: 
                # wildcard labels: 
                if self._state.backtracking == 0:
                    retval.tree = root_0
                    if retval is not None:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "rule retval", retval.tree)
                    else:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "token retval", None)


                    root_0 = self._adaptor.nil()
                    # 141:3: -> ^( DEFAULT ( stmt )* )
                    # Expr.g:141:6: ^( DEFAULT ( stmt )* )
                    root_1 = self._adaptor.nil()
                    root_1 = self._adaptor.becomeRoot(
                    self._adaptor.createFromType(DEFAULT, "DEFAULT")
                    , root_1)

                    # Expr.g:141:16: ( stmt )*
                    while stream_stmt.hasNext():
                        self._adaptor.addChild(root_1, stream_stmt.nextTree())


                    stream_stmt.reset();

                    self._adaptor.addChild(root_0, root_1)




                    retval.tree = root_0





                retval.stop = self.input.LT(-1)


                if self._state.backtracking == 0:
                    retval.tree = self._adaptor.rulePostProcessing(root_0)
                    self._adaptor.setTokenBoundaries(retval.tree, retval.start, retval.stop)



            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)
                retval.tree = self._adaptor.errorNode(self.input, retval.start, self.input.LT(-1), re)

        finally:
            pass
        return retval

    # $ANTLR end "default_clause"


    class for_stmt_return(ParserRuleReturnScope):
        def __init__(self):
            super(ExprParser.for_stmt_return, self).__init__()

            self.tree = None





    # $ANTLR start "for_stmt"
    # Expr.g:144:1: for_stmt : 'for' '(' (a= exec_list )? ';' expr ';' (b= exec_list )? ')' block -> ^( FOR ( $a)? expr block ( $b)? ) ;
    def for_stmt(self, ):
        retval = self.for_stmt_return()
        retval.start = self.input.LT(1)


        root_0 = None

        string_literal91 = None
        char_literal92 = None
        char_literal93 = None
        char_literal95 = None
        char_literal96 = None
        a = None
        b = None
        expr94 = None
        block97 = None

        string_literal91_tree = None
        char_literal92_tree = None
        char_literal93_tree = None
        char_literal95_tree = None
        char_literal96_tree = None
        stream_115 = RewriteRuleTokenStream(self._adaptor, "token 115")
        stream_92 = RewriteRuleTokenStream(self._adaptor, "token 92")
        stream_75 = RewriteRuleTokenStream(self._adaptor, "token 75")
        stream_76 = RewriteRuleTokenStream(self._adaptor, "token 76")
        stream_block = RewriteRuleSubtreeStream(self._adaptor, "rule block")
        stream_expr = RewriteRuleSubtreeStream(self._adaptor, "rule expr")
        stream_exec_list = RewriteRuleSubtreeStream(self._adaptor, "rule exec_list")
        try:
            try:
                # Expr.g:145:2: ( 'for' '(' (a= exec_list )? ';' expr ';' (b= exec_list )? ')' block -> ^( FOR ( $a)? expr block ( $b)? ) )
                # Expr.g:145:4: 'for' '(' (a= exec_list )? ';' expr ';' (b= exec_list )? ')' block
                pass 
                string_literal91 = self.match(self.input, 115, self.FOLLOW_115_in_for_stmt833) 
                if self._state.backtracking == 0:
                    stream_115.add(string_literal91)


                char_literal92 = self.match(self.input, 75, self.FOLLOW_75_in_for_stmt835) 
                if self._state.backtracking == 0:
                    stream_75.add(char_literal92)


                # Expr.g:145:15: (a= exec_list )?
                alt16 = 2
                LA16_0 = self.input.LA(1)

                if (LA16_0 == ID or LA16_0 == 80 or LA16_0 == 84) :
                    alt16 = 1
                if alt16 == 1:
                    # Expr.g:145:15: a= exec_list
                    pass 
                    self._state.following.append(self.FOLLOW_exec_list_in_for_stmt839)
                    a = self.exec_list()

                    self._state.following.pop()
                    if self._state.backtracking == 0:
                        stream_exec_list.add(a.tree)





                char_literal93 = self.match(self.input, 92, self.FOLLOW_92_in_for_stmt842) 
                if self._state.backtracking == 0:
                    stream_92.add(char_literal93)


                self._state.following.append(self.FOLLOW_expr_in_for_stmt844)
                expr94 = self.expr()

                self._state.following.pop()
                if self._state.backtracking == 0:
                    stream_expr.add(expr94.tree)


                char_literal95 = self.match(self.input, 92, self.FOLLOW_92_in_for_stmt846) 
                if self._state.backtracking == 0:
                    stream_92.add(char_literal95)


                # Expr.g:145:41: (b= exec_list )?
                alt17 = 2
                LA17_0 = self.input.LA(1)

                if (LA17_0 == ID or LA17_0 == 80 or LA17_0 == 84) :
                    alt17 = 1
                if alt17 == 1:
                    # Expr.g:145:41: b= exec_list
                    pass 
                    self._state.following.append(self.FOLLOW_exec_list_in_for_stmt850)
                    b = self.exec_list()

                    self._state.following.pop()
                    if self._state.backtracking == 0:
                        stream_exec_list.add(b.tree)





                char_literal96 = self.match(self.input, 76, self.FOLLOW_76_in_for_stmt853) 
                if self._state.backtracking == 0:
                    stream_76.add(char_literal96)


                self._state.following.append(self.FOLLOW_block_in_for_stmt855)
                block97 = self.block()

                self._state.following.pop()
                if self._state.backtracking == 0:
                    stream_block.add(block97.tree)


                # AST Rewrite
                # elements: expr, block, a, b
                # token labels: 
                # rule labels: retval, b, a
                # token list labels: 
                # rule list labels: 
                # wildcard labels: 
                if self._state.backtracking == 0:
                    retval.tree = root_0
                    if retval is not None:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "rule retval", retval.tree)
                    else:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "token retval", None)

                    if b is not None:
                        stream_b = RewriteRuleSubtreeStream(self._adaptor, "rule b", b.tree)
                    else:
                        stream_b = RewriteRuleSubtreeStream(self._adaptor, "token b", None)

                    if a is not None:
                        stream_a = RewriteRuleSubtreeStream(self._adaptor, "rule a", a.tree)
                    else:
                        stream_a = RewriteRuleSubtreeStream(self._adaptor, "token a", None)


                    root_0 = self._adaptor.nil()
                    # 146:3: -> ^( FOR ( $a)? expr block ( $b)? )
                    # Expr.g:146:6: ^( FOR ( $a)? expr block ( $b)? )
                    root_1 = self._adaptor.nil()
                    root_1 = self._adaptor.becomeRoot(
                    self._adaptor.createFromType(FOR, "FOR")
                    , root_1)

                    # Expr.g:146:13: ( $a)?
                    if stream_a.hasNext():
                        self._adaptor.addChild(root_1, stream_a.nextTree())


                    stream_a.reset();

                    self._adaptor.addChild(root_1, stream_expr.nextTree())

                    self._adaptor.addChild(root_1, stream_block.nextTree())

                    # Expr.g:146:28: ( $b)?
                    if stream_b.hasNext():
                        self._adaptor.addChild(root_1, stream_b.nextTree())


                    stream_b.reset();

                    self._adaptor.addChild(root_0, root_1)




                    retval.tree = root_0





                retval.stop = self.input.LT(-1)


                if self._state.backtracking == 0:
                    retval.tree = self._adaptor.rulePostProcessing(root_0)
                    self._adaptor.setTokenBoundaries(retval.tree, retval.start, retval.stop)



            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)
                retval.tree = self._adaptor.errorNode(self.input, retval.start, self.input.LT(-1), re)

        finally:
            pass
        return retval

    # $ANTLR end "for_stmt"


    class foreach_stmt_return(ParserRuleReturnScope):
        def __init__(self):
            super(ExprParser.foreach_stmt_return, self).__init__()

            self.tree = None





    # $ANTLR start "foreach_stmt"
    # Expr.g:149:1: foreach_stmt : 'foreach' '(' expr 'as' each ')' block -> ^( FOREACH expr each block ) ;
    def foreach_stmt(self, ):
        retval = self.foreach_stmt_return()
        retval.start = self.input.LT(1)


        root_0 = None

        string_literal98 = None
        char_literal99 = None
        string_literal101 = None
        char_literal103 = None
        expr100 = None
        each102 = None
        block104 = None

        string_literal98_tree = None
        char_literal99_tree = None
        string_literal101_tree = None
        char_literal103_tree = None
        stream_116 = RewriteRuleTokenStream(self._adaptor, "token 116")
        stream_104 = RewriteRuleTokenStream(self._adaptor, "token 104")
        stream_75 = RewriteRuleTokenStream(self._adaptor, "token 75")
        stream_76 = RewriteRuleTokenStream(self._adaptor, "token 76")
        stream_block = RewriteRuleSubtreeStream(self._adaptor, "rule block")
        stream_expr = RewriteRuleSubtreeStream(self._adaptor, "rule expr")
        stream_each = RewriteRuleSubtreeStream(self._adaptor, "rule each")
        try:
            try:
                # Expr.g:150:2: ( 'foreach' '(' expr 'as' each ')' block -> ^( FOREACH expr each block ) )
                # Expr.g:150:4: 'foreach' '(' expr 'as' each ')' block
                pass 
                string_literal98 = self.match(self.input, 116, self.FOLLOW_116_in_foreach_stmt886) 
                if self._state.backtracking == 0:
                    stream_116.add(string_literal98)


                char_literal99 = self.match(self.input, 75, self.FOLLOW_75_in_foreach_stmt888) 
                if self._state.backtracking == 0:
                    stream_75.add(char_literal99)


                self._state.following.append(self.FOLLOW_expr_in_foreach_stmt890)
                expr100 = self.expr()

                self._state.following.pop()
                if self._state.backtracking == 0:
                    stream_expr.add(expr100.tree)


                string_literal101 = self.match(self.input, 104, self.FOLLOW_104_in_foreach_stmt892) 
                if self._state.backtracking == 0:
                    stream_104.add(string_literal101)


                self._state.following.append(self.FOLLOW_each_in_foreach_stmt894)
                each102 = self.each()

                self._state.following.pop()
                if self._state.backtracking == 0:
                    stream_each.add(each102.tree)


                char_literal103 = self.match(self.input, 76, self.FOLLOW_76_in_foreach_stmt896) 
                if self._state.backtracking == 0:
                    stream_76.add(char_literal103)


                self._state.following.append(self.FOLLOW_block_in_foreach_stmt898)
                block104 = self.block()

                self._state.following.pop()
                if self._state.backtracking == 0:
                    stream_block.add(block104.tree)


                # AST Rewrite
                # elements: expr, block, each
                # token labels: 
                # rule labels: retval
                # token list labels: 
                # rule list labels: 
                # wildcard labels: 
                if self._state.backtracking == 0:
                    retval.tree = root_0
                    if retval is not None:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "rule retval", retval.tree)
                    else:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "token retval", None)


                    root_0 = self._adaptor.nil()
                    # 151:3: -> ^( FOREACH expr each block )
                    # Expr.g:151:6: ^( FOREACH expr each block )
                    root_1 = self._adaptor.nil()
                    root_1 = self._adaptor.becomeRoot(
                    self._adaptor.createFromType(FOREACH, "FOREACH")
                    , root_1)

                    self._adaptor.addChild(root_1, stream_expr.nextTree())

                    self._adaptor.addChild(root_1, stream_each.nextTree())

                    self._adaptor.addChild(root_1, stream_block.nextTree())

                    self._adaptor.addChild(root_0, root_1)




                    retval.tree = root_0





                retval.stop = self.input.LT(-1)


                if self._state.backtracking == 0:
                    retval.tree = self._adaptor.rulePostProcessing(root_0)
                    self._adaptor.setTokenBoundaries(retval.tree, retval.start, retval.stop)



            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)
                retval.tree = self._adaptor.errorNode(self.input, retval.start, self.input.LT(-1), re)

        finally:
            pass
        return retval

    # $ANTLR end "foreach_stmt"


    class each_return(ParserRuleReturnScope):
        def __init__(self):
            super(ExprParser.each_return, self).__init__()

            self.tree = None





    # $ANTLR start "each"
    # Expr.g:153:1: each : ( each_val -> ^( EACH each_val ) | ID '=>' each_val -> ^( EACH ID each_val ) );
    def each(self, ):
        retval = self.each_return()
        retval.start = self.input.LT(1)


        root_0 = None

        ID106 = None
        string_literal107 = None
        each_val105 = None
        each_val108 = None

        ID106_tree = None
        string_literal107_tree = None
        stream_97 = RewriteRuleTokenStream(self._adaptor, "token 97")
        stream_ID = RewriteRuleTokenStream(self._adaptor, "token ID")
        stream_each_val = RewriteRuleSubtreeStream(self._adaptor, "rule each_val")
        try:
            try:
                # Expr.g:154:2: ( each_val -> ^( EACH each_val ) | ID '=>' each_val -> ^( EACH ID each_val ) )
                alt18 = 2
                LA18_0 = self.input.LA(1)

                if (LA18_0 == ID) :
                    LA18_1 = self.input.LA(2)

                    if (LA18_1 == 97) :
                        alt18 = 2
                    elif (LA18_1 == 76 or LA18_1 == 82) :
                        alt18 = 1
                    else:
                        if self._state.backtracking > 0:
                            raise BacktrackingFailed


                        nvae = NoViableAltException("", 18, 1, self.input)

                        raise nvae


                else:
                    if self._state.backtracking > 0:
                        raise BacktrackingFailed


                    nvae = NoViableAltException("", 18, 0, self.input)

                    raise nvae


                if alt18 == 1:
                    # Expr.g:154:4: each_val
                    pass 
                    self._state.following.append(self.FOLLOW_each_val_in_each922)
                    each_val105 = self.each_val()

                    self._state.following.pop()
                    if self._state.backtracking == 0:
                        stream_each_val.add(each_val105.tree)


                    # AST Rewrite
                    # elements: each_val
                    # token labels: 
                    # rule labels: retval
                    # token list labels: 
                    # rule list labels: 
                    # wildcard labels: 
                    if self._state.backtracking == 0:
                        retval.tree = root_0
                        if retval is not None:
                            stream_retval = RewriteRuleSubtreeStream(self._adaptor, "rule retval", retval.tree)
                        else:
                            stream_retval = RewriteRuleSubtreeStream(self._adaptor, "token retval", None)


                        root_0 = self._adaptor.nil()
                        # 155:3: -> ^( EACH each_val )
                        # Expr.g:155:6: ^( EACH each_val )
                        root_1 = self._adaptor.nil()
                        root_1 = self._adaptor.becomeRoot(
                        self._adaptor.createFromType(EACH, "EACH")
                        , root_1)

                        self._adaptor.addChild(root_1, stream_each_val.nextTree())

                        self._adaptor.addChild(root_0, root_1)




                        retval.tree = root_0




                elif alt18 == 2:
                    # Expr.g:156:4: ID '=>' each_val
                    pass 
                    ID106 = self.match(self.input, ID, self.FOLLOW_ID_in_each937) 
                    if self._state.backtracking == 0:
                        stream_ID.add(ID106)


                    string_literal107 = self.match(self.input, 97, self.FOLLOW_97_in_each939) 
                    if self._state.backtracking == 0:
                        stream_97.add(string_literal107)


                    self._state.following.append(self.FOLLOW_each_val_in_each941)
                    each_val108 = self.each_val()

                    self._state.following.pop()
                    if self._state.backtracking == 0:
                        stream_each_val.add(each_val108.tree)


                    # AST Rewrite
                    # elements: each_val, ID
                    # token labels: 
                    # rule labels: retval
                    # token list labels: 
                    # rule list labels: 
                    # wildcard labels: 
                    if self._state.backtracking == 0:
                        retval.tree = root_0
                        if retval is not None:
                            stream_retval = RewriteRuleSubtreeStream(self._adaptor, "rule retval", retval.tree)
                        else:
                            stream_retval = RewriteRuleSubtreeStream(self._adaptor, "token retval", None)


                        root_0 = self._adaptor.nil()
                        # 157:3: -> ^( EACH ID each_val )
                        # Expr.g:157:6: ^( EACH ID each_val )
                        root_1 = self._adaptor.nil()
                        root_1 = self._adaptor.becomeRoot(
                        self._adaptor.createFromType(EACH, "EACH")
                        , root_1)

                        self._adaptor.addChild(root_1, 
                        stream_ID.nextNode()
                        )

                        self._adaptor.addChild(root_1, stream_each_val.nextTree())

                        self._adaptor.addChild(root_0, root_1)




                        retval.tree = root_0




                retval.stop = self.input.LT(-1)


                if self._state.backtracking == 0:
                    retval.tree = self._adaptor.rulePostProcessing(root_0)
                    self._adaptor.setTokenBoundaries(retval.tree, retval.start, retval.stop)



            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)
                retval.tree = self._adaptor.errorNode(self.input, retval.start, self.input.LT(-1), re)

        finally:
            pass
        return retval

    # $ANTLR end "each"


    class each_val_return(ParserRuleReturnScope):
        def __init__(self):
            super(ExprParser.each_val_return, self).__init__()

            self.tree = None





    # $ANTLR start "each_val"
    # Expr.g:159:1: each_val : ID ( ',' ID )* -> ^( EACH_VAL ( ID )+ ) ;
    def each_val(self, ):
        retval = self.each_val_return()
        retval.start = self.input.LT(1)


        root_0 = None

        ID109 = None
        char_literal110 = None
        ID111 = None

        ID109_tree = None
        char_literal110_tree = None
        ID111_tree = None
        stream_82 = RewriteRuleTokenStream(self._adaptor, "token 82")
        stream_ID = RewriteRuleTokenStream(self._adaptor, "token ID")

        try:
            try:
                # Expr.g:160:2: ( ID ( ',' ID )* -> ^( EACH_VAL ( ID )+ ) )
                # Expr.g:160:4: ID ( ',' ID )*
                pass 
                ID109 = self.match(self.input, ID, self.FOLLOW_ID_in_each_val963) 
                if self._state.backtracking == 0:
                    stream_ID.add(ID109)


                # Expr.g:160:7: ( ',' ID )*
                while True: #loop19
                    alt19 = 2
                    LA19_0 = self.input.LA(1)

                    if (LA19_0 == 82) :
                        alt19 = 1


                    if alt19 == 1:
                        # Expr.g:160:8: ',' ID
                        pass 
                        char_literal110 = self.match(self.input, 82, self.FOLLOW_82_in_each_val966) 
                        if self._state.backtracking == 0:
                            stream_82.add(char_literal110)


                        ID111 = self.match(self.input, ID, self.FOLLOW_ID_in_each_val968) 
                        if self._state.backtracking == 0:
                            stream_ID.add(ID111)



                    else:
                        break #loop19


                # AST Rewrite
                # elements: ID
                # token labels: 
                # rule labels: retval
                # token list labels: 
                # rule list labels: 
                # wildcard labels: 
                if self._state.backtracking == 0:
                    retval.tree = root_0
                    if retval is not None:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "rule retval", retval.tree)
                    else:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "token retval", None)


                    root_0 = self._adaptor.nil()
                    # 161:3: -> ^( EACH_VAL ( ID )+ )
                    # Expr.g:161:6: ^( EACH_VAL ( ID )+ )
                    root_1 = self._adaptor.nil()
                    root_1 = self._adaptor.becomeRoot(
                    self._adaptor.createFromType(EACH_VAL, "EACH_VAL")
                    , root_1)

                    # Expr.g:161:17: ( ID )+
                    if not (stream_ID.hasNext()):
                        raise RewriteEarlyExitException()

                    while stream_ID.hasNext():
                        self._adaptor.addChild(root_1, 
                        stream_ID.nextNode()
                        )


                    stream_ID.reset()

                    self._adaptor.addChild(root_0, root_1)




                    retval.tree = root_0





                retval.stop = self.input.LT(-1)


                if self._state.backtracking == 0:
                    retval.tree = self._adaptor.rulePostProcessing(root_0)
                    self._adaptor.setTokenBoundaries(retval.tree, retval.start, retval.stop)



            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)
                retval.tree = self._adaptor.errorNode(self.input, retval.start, self.input.LT(-1), re)

        finally:
            pass
        return retval

    # $ANTLR end "each_val"


    class throw_stmt_return(ParserRuleReturnScope):
        def __init__(self):
            super(ExprParser.throw_stmt_return, self).__init__()

            self.tree = None





    # $ANTLR start "throw_stmt"
    # Expr.g:165:1: throw_stmt : 'throw' expr ';' -> ^( THROW expr ) ;
    def throw_stmt(self, ):
        retval = self.throw_stmt_return()
        retval.start = self.input.LT(1)


        root_0 = None

        string_literal112 = None
        char_literal114 = None
        expr113 = None

        string_literal112_tree = None
        char_literal114_tree = None
        stream_92 = RewriteRuleTokenStream(self._adaptor, "token 92")
        stream_129 = RewriteRuleTokenStream(self._adaptor, "token 129")
        stream_expr = RewriteRuleSubtreeStream(self._adaptor, "rule expr")
        try:
            try:
                # Expr.g:166:2: ( 'throw' expr ';' -> ^( THROW expr ) )
                # Expr.g:166:4: 'throw' expr ';'
                pass 
                string_literal112 = self.match(self.input, 129, self.FOLLOW_129_in_throw_stmt993) 
                if self._state.backtracking == 0:
                    stream_129.add(string_literal112)


                self._state.following.append(self.FOLLOW_expr_in_throw_stmt995)
                expr113 = self.expr()

                self._state.following.pop()
                if self._state.backtracking == 0:
                    stream_expr.add(expr113.tree)


                char_literal114 = self.match(self.input, 92, self.FOLLOW_92_in_throw_stmt997) 
                if self._state.backtracking == 0:
                    stream_92.add(char_literal114)


                # AST Rewrite
                # elements: expr
                # token labels: 
                # rule labels: retval
                # token list labels: 
                # rule list labels: 
                # wildcard labels: 
                if self._state.backtracking == 0:
                    retval.tree = root_0
                    if retval is not None:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "rule retval", retval.tree)
                    else:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "token retval", None)


                    root_0 = self._adaptor.nil()
                    # 167:3: -> ^( THROW expr )
                    # Expr.g:167:6: ^( THROW expr )
                    root_1 = self._adaptor.nil()
                    root_1 = self._adaptor.becomeRoot(
                    self._adaptor.createFromType(THROW, "THROW")
                    , root_1)

                    self._adaptor.addChild(root_1, stream_expr.nextTree())

                    self._adaptor.addChild(root_0, root_1)




                    retval.tree = root_0





                retval.stop = self.input.LT(-1)


                if self._state.backtracking == 0:
                    retval.tree = self._adaptor.rulePostProcessing(root_0)
                    self._adaptor.setTokenBoundaries(retval.tree, retval.start, retval.stop)



            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)
                retval.tree = self._adaptor.errorNode(self.input, retval.start, self.input.LT(-1), re)

        finally:
            pass
        return retval

    # $ANTLR end "throw_stmt"


    class try_stmt_return(ParserRuleReturnScope):
        def __init__(self):
            super(ExprParser.try_stmt_return, self).__init__()

            self.tree = None





    # $ANTLR start "try_stmt"
    # Expr.g:169:1: try_stmt : 'try' block ( catch_clause )+ ( finally_clause )? -> ^( TRY block ( catch_clause )+ ( finally_clause )? ) ;
    def try_stmt(self, ):
        retval = self.try_stmt_return()
        retval.start = self.input.LT(1)


        root_0 = None

        string_literal115 = None
        block116 = None
        catch_clause117 = None
        finally_clause118 = None

        string_literal115_tree = None
        stream_130 = RewriteRuleTokenStream(self._adaptor, "token 130")
        stream_catch_clause = RewriteRuleSubtreeStream(self._adaptor, "rule catch_clause")
        stream_block = RewriteRuleSubtreeStream(self._adaptor, "rule block")
        stream_finally_clause = RewriteRuleSubtreeStream(self._adaptor, "rule finally_clause")
        try:
            try:
                # Expr.g:170:2: ( 'try' block ( catch_clause )+ ( finally_clause )? -> ^( TRY block ( catch_clause )+ ( finally_clause )? ) )
                # Expr.g:170:4: 'try' block ( catch_clause )+ ( finally_clause )?
                pass 
                string_literal115 = self.match(self.input, 130, self.FOLLOW_130_in_try_stmt1017) 
                if self._state.backtracking == 0:
                    stream_130.add(string_literal115)


                self._state.following.append(self.FOLLOW_block_in_try_stmt1019)
                block116 = self.block()

                self._state.following.pop()
                if self._state.backtracking == 0:
                    stream_block.add(block116.tree)


                # Expr.g:170:16: ( catch_clause )+
                cnt20 = 0
                while True: #loop20
                    alt20 = 2
                    LA20_0 = self.input.LA(1)

                    if (LA20_0 == 107) :
                        alt20 = 1


                    if alt20 == 1:
                        # Expr.g:170:16: catch_clause
                        pass 
                        self._state.following.append(self.FOLLOW_catch_clause_in_try_stmt1021)
                        catch_clause117 = self.catch_clause()

                        self._state.following.pop()
                        if self._state.backtracking == 0:
                            stream_catch_clause.add(catch_clause117.tree)



                    else:
                        if cnt20 >= 1:
                            break #loop20

                        if self._state.backtracking > 0:
                            raise BacktrackingFailed


                        eee = EarlyExitException(20, self.input)
                        raise eee

                    cnt20 += 1


                # Expr.g:170:30: ( finally_clause )?
                alt21 = 2
                LA21_0 = self.input.LA(1)

                if (LA21_0 == 114) :
                    alt21 = 1
                if alt21 == 1:
                    # Expr.g:170:30: finally_clause
                    pass 
                    self._state.following.append(self.FOLLOW_finally_clause_in_try_stmt1024)
                    finally_clause118 = self.finally_clause()

                    self._state.following.pop()
                    if self._state.backtracking == 0:
                        stream_finally_clause.add(finally_clause118.tree)





                # AST Rewrite
                # elements: finally_clause, catch_clause, block
                # token labels: 
                # rule labels: retval
                # token list labels: 
                # rule list labels: 
                # wildcard labels: 
                if self._state.backtracking == 0:
                    retval.tree = root_0
                    if retval is not None:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "rule retval", retval.tree)
                    else:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "token retval", None)


                    root_0 = self._adaptor.nil()
                    # 171:3: -> ^( TRY block ( catch_clause )+ ( finally_clause )? )
                    # Expr.g:171:6: ^( TRY block ( catch_clause )+ ( finally_clause )? )
                    root_1 = self._adaptor.nil()
                    root_1 = self._adaptor.becomeRoot(
                    self._adaptor.createFromType(TRY, "TRY")
                    , root_1)

                    self._adaptor.addChild(root_1, stream_block.nextTree())

                    # Expr.g:171:18: ( catch_clause )+
                    if not (stream_catch_clause.hasNext()):
                        raise RewriteEarlyExitException()

                    while stream_catch_clause.hasNext():
                        self._adaptor.addChild(root_1, stream_catch_clause.nextTree())


                    stream_catch_clause.reset()

                    # Expr.g:171:32: ( finally_clause )?
                    if stream_finally_clause.hasNext():
                        self._adaptor.addChild(root_1, stream_finally_clause.nextTree())


                    stream_finally_clause.reset();

                    self._adaptor.addChild(root_0, root_1)




                    retval.tree = root_0





                retval.stop = self.input.LT(-1)


                if self._state.backtracking == 0:
                    retval.tree = self._adaptor.rulePostProcessing(root_0)
                    self._adaptor.setTokenBoundaries(retval.tree, retval.start, retval.stop)



            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)
                retval.tree = self._adaptor.errorNode(self.input, retval.start, self.input.LT(-1), re)

        finally:
            pass
        return retval

    # $ANTLR end "try_stmt"


    class catch_clause_return(ParserRuleReturnScope):
        def __init__(self):
            super(ExprParser.catch_clause_return, self).__init__()

            self.tree = None





    # $ANTLR start "catch_clause"
    # Expr.g:173:1: catch_clause : 'catch' '(' module ( ID )? ')' block -> ^( CATCH module ( ID )? block ) ;
    def catch_clause(self, ):
        retval = self.catch_clause_return()
        retval.start = self.input.LT(1)


        root_0 = None

        string_literal119 = None
        char_literal120 = None
        ID122 = None
        char_literal123 = None
        module121 = None
        block124 = None

        string_literal119_tree = None
        char_literal120_tree = None
        ID122_tree = None
        char_literal123_tree = None
        stream_107 = RewriteRuleTokenStream(self._adaptor, "token 107")
        stream_ID = RewriteRuleTokenStream(self._adaptor, "token ID")
        stream_75 = RewriteRuleTokenStream(self._adaptor, "token 75")
        stream_76 = RewriteRuleTokenStream(self._adaptor, "token 76")
        stream_module = RewriteRuleSubtreeStream(self._adaptor, "rule module")
        stream_block = RewriteRuleSubtreeStream(self._adaptor, "rule block")
        try:
            try:
                # Expr.g:174:2: ( 'catch' '(' module ( ID )? ')' block -> ^( CATCH module ( ID )? block ) )
                # Expr.g:174:4: 'catch' '(' module ( ID )? ')' block
                pass 
                string_literal119 = self.match(self.input, 107, self.FOLLOW_107_in_catch_clause1051) 
                if self._state.backtracking == 0:
                    stream_107.add(string_literal119)


                char_literal120 = self.match(self.input, 75, self.FOLLOW_75_in_catch_clause1053) 
                if self._state.backtracking == 0:
                    stream_75.add(char_literal120)


                self._state.following.append(self.FOLLOW_module_in_catch_clause1055)
                module121 = self.module()

                self._state.following.pop()
                if self._state.backtracking == 0:
                    stream_module.add(module121.tree)


                # Expr.g:174:23: ( ID )?
                alt22 = 2
                LA22_0 = self.input.LA(1)

                if (LA22_0 == ID) :
                    alt22 = 1
                if alt22 == 1:
                    # Expr.g:174:23: ID
                    pass 
                    ID122 = self.match(self.input, ID, self.FOLLOW_ID_in_catch_clause1057) 
                    if self._state.backtracking == 0:
                        stream_ID.add(ID122)





                char_literal123 = self.match(self.input, 76, self.FOLLOW_76_in_catch_clause1060) 
                if self._state.backtracking == 0:
                    stream_76.add(char_literal123)


                self._state.following.append(self.FOLLOW_block_in_catch_clause1062)
                block124 = self.block()

                self._state.following.pop()
                if self._state.backtracking == 0:
                    stream_block.add(block124.tree)


                # AST Rewrite
                # elements: block, ID, module
                # token labels: 
                # rule labels: retval
                # token list labels: 
                # rule list labels: 
                # wildcard labels: 
                if self._state.backtracking == 0:
                    retval.tree = root_0
                    if retval is not None:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "rule retval", retval.tree)
                    else:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "token retval", None)


                    root_0 = self._adaptor.nil()
                    # 175:3: -> ^( CATCH module ( ID )? block )
                    # Expr.g:175:6: ^( CATCH module ( ID )? block )
                    root_1 = self._adaptor.nil()
                    root_1 = self._adaptor.becomeRoot(
                    self._adaptor.createFromType(CATCH, "CATCH")
                    , root_1)

                    self._adaptor.addChild(root_1, stream_module.nextTree())

                    # Expr.g:175:21: ( ID )?
                    if stream_ID.hasNext():
                        self._adaptor.addChild(root_1, 
                        stream_ID.nextNode()
                        )


                    stream_ID.reset();

                    self._adaptor.addChild(root_1, stream_block.nextTree())

                    self._adaptor.addChild(root_0, root_1)




                    retval.tree = root_0





                retval.stop = self.input.LT(-1)


                if self._state.backtracking == 0:
                    retval.tree = self._adaptor.rulePostProcessing(root_0)
                    self._adaptor.setTokenBoundaries(retval.tree, retval.start, retval.stop)



            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)
                retval.tree = self._adaptor.errorNode(self.input, retval.start, self.input.LT(-1), re)

        finally:
            pass
        return retval

    # $ANTLR end "catch_clause"


    class finally_clause_return(ParserRuleReturnScope):
        def __init__(self):
            super(ExprParser.finally_clause_return, self).__init__()

            self.tree = None





    # $ANTLR start "finally_clause"
    # Expr.g:177:1: finally_clause : 'finally' block -> ^( FINALLY block ) ;
    def finally_clause(self, ):
        retval = self.finally_clause_return()
        retval.start = self.input.LT(1)


        root_0 = None

        string_literal125 = None
        block126 = None

        string_literal125_tree = None
        stream_114 = RewriteRuleTokenStream(self._adaptor, "token 114")
        stream_block = RewriteRuleSubtreeStream(self._adaptor, "rule block")
        try:
            try:
                # Expr.g:178:2: ( 'finally' block -> ^( FINALLY block ) )
                # Expr.g:178:4: 'finally' block
                pass 
                string_literal125 = self.match(self.input, 114, self.FOLLOW_114_in_finally_clause1087) 
                if self._state.backtracking == 0:
                    stream_114.add(string_literal125)


                self._state.following.append(self.FOLLOW_block_in_finally_clause1089)
                block126 = self.block()

                self._state.following.pop()
                if self._state.backtracking == 0:
                    stream_block.add(block126.tree)


                # AST Rewrite
                # elements: block
                # token labels: 
                # rule labels: retval
                # token list labels: 
                # rule list labels: 
                # wildcard labels: 
                if self._state.backtracking == 0:
                    retval.tree = root_0
                    if retval is not None:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "rule retval", retval.tree)
                    else:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "token retval", None)


                    root_0 = self._adaptor.nil()
                    # 179:3: -> ^( FINALLY block )
                    # Expr.g:179:6: ^( FINALLY block )
                    root_1 = self._adaptor.nil()
                    root_1 = self._adaptor.becomeRoot(
                    self._adaptor.createFromType(FINALLY, "FINALLY")
                    , root_1)

                    self._adaptor.addChild(root_1, stream_block.nextTree())

                    self._adaptor.addChild(root_0, root_1)




                    retval.tree = root_0





                retval.stop = self.input.LT(-1)


                if self._state.backtracking == 0:
                    retval.tree = self._adaptor.rulePostProcessing(root_0)
                    self._adaptor.setTokenBoundaries(retval.tree, retval.start, retval.stop)



            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)
                retval.tree = self._adaptor.errorNode(self.input, retval.start, self.input.LT(-1), re)

        finally:
            pass
        return retval

    # $ANTLR end "finally_clause"


    class func_decl_return(ParserRuleReturnScope):
        def __init__(self):
            super(ExprParser.func_decl_return, self).__init__()

            self.tree = None





    # $ANTLR start "func_decl"
    # Expr.g:183:1: func_decl : 'function' ID params block -> ^( FUNCTION ID params block ) ;
    def func_decl(self, ):
        retval = self.func_decl_return()
        retval.start = self.input.LT(1)


        root_0 = None

        string_literal127 = None
        ID128 = None
        params129 = None
        block130 = None

        string_literal127_tree = None
        ID128_tree = None
        stream_117 = RewriteRuleTokenStream(self._adaptor, "token 117")
        stream_ID = RewriteRuleTokenStream(self._adaptor, "token ID")
        stream_block = RewriteRuleSubtreeStream(self._adaptor, "rule block")
        stream_params = RewriteRuleSubtreeStream(self._adaptor, "rule params")
        try:
            try:
                # Expr.g:184:2: ( 'function' ID params block -> ^( FUNCTION ID params block ) )
                # Expr.g:184:4: 'function' ID params block
                pass 
                string_literal127 = self.match(self.input, 117, self.FOLLOW_117_in_func_decl1111) 
                if self._state.backtracking == 0:
                    stream_117.add(string_literal127)


                ID128 = self.match(self.input, ID, self.FOLLOW_ID_in_func_decl1113) 
                if self._state.backtracking == 0:
                    stream_ID.add(ID128)


                self._state.following.append(self.FOLLOW_params_in_func_decl1115)
                params129 = self.params()

                self._state.following.pop()
                if self._state.backtracking == 0:
                    stream_params.add(params129.tree)


                self._state.following.append(self.FOLLOW_block_in_func_decl1117)
                block130 = self.block()

                self._state.following.pop()
                if self._state.backtracking == 0:
                    stream_block.add(block130.tree)


                # AST Rewrite
                # elements: block, ID, params
                # token labels: 
                # rule labels: retval
                # token list labels: 
                # rule list labels: 
                # wildcard labels: 
                if self._state.backtracking == 0:
                    retval.tree = root_0
                    if retval is not None:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "rule retval", retval.tree)
                    else:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "token retval", None)


                    root_0 = self._adaptor.nil()
                    # 185:3: -> ^( FUNCTION ID params block )
                    # Expr.g:185:6: ^( FUNCTION ID params block )
                    root_1 = self._adaptor.nil()
                    root_1 = self._adaptor.becomeRoot(
                    self._adaptor.createFromType(FUNCTION, "FUNCTION")
                    , root_1)

                    self._adaptor.addChild(root_1, 
                    stream_ID.nextNode()
                    )

                    self._adaptor.addChild(root_1, stream_params.nextTree())

                    self._adaptor.addChild(root_1, stream_block.nextTree())

                    self._adaptor.addChild(root_0, root_1)




                    retval.tree = root_0





                retval.stop = self.input.LT(-1)


                if self._state.backtracking == 0:
                    retval.tree = self._adaptor.rulePostProcessing(root_0)
                    self._adaptor.setTokenBoundaries(retval.tree, retval.start, retval.stop)



            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)
                retval.tree = self._adaptor.errorNode(self.input, retval.start, self.input.LT(-1), re)

        finally:
            pass
        return retval

    # $ANTLR end "func_decl"


    class params_return(ParserRuleReturnScope):
        def __init__(self):
            super(ExprParser.params_return, self).__init__()

            self.tree = None





    # $ANTLR start "params"
    # Expr.g:187:1: params : '(' ( param_decl )? ( ',' param_decl )* ')' -> ^( PARAMS ( param_decl )* ) ;
    def params(self, ):
        retval = self.params_return()
        retval.start = self.input.LT(1)


        root_0 = None

        char_literal131 = None
        char_literal133 = None
        char_literal135 = None
        param_decl132 = None
        param_decl134 = None

        char_literal131_tree = None
        char_literal133_tree = None
        char_literal135_tree = None
        stream_82 = RewriteRuleTokenStream(self._adaptor, "token 82")
        stream_75 = RewriteRuleTokenStream(self._adaptor, "token 75")
        stream_76 = RewriteRuleTokenStream(self._adaptor, "token 76")
        stream_param_decl = RewriteRuleSubtreeStream(self._adaptor, "rule param_decl")
        try:
            try:
                # Expr.g:188:2: ( '(' ( param_decl )? ( ',' param_decl )* ')' -> ^( PARAMS ( param_decl )* ) )
                # Expr.g:188:4: '(' ( param_decl )? ( ',' param_decl )* ')'
                pass 
                char_literal131 = self.match(self.input, 75, self.FOLLOW_75_in_params1141) 
                if self._state.backtracking == 0:
                    stream_75.add(char_literal131)


                # Expr.g:188:8: ( param_decl )?
                alt23 = 2
                LA23_0 = self.input.LA(1)

                if (LA23_0 == ID) :
                    alt23 = 1
                if alt23 == 1:
                    # Expr.g:188:8: param_decl
                    pass 
                    self._state.following.append(self.FOLLOW_param_decl_in_params1143)
                    param_decl132 = self.param_decl()

                    self._state.following.pop()
                    if self._state.backtracking == 0:
                        stream_param_decl.add(param_decl132.tree)





                # Expr.g:188:20: ( ',' param_decl )*
                while True: #loop24
                    alt24 = 2
                    LA24_0 = self.input.LA(1)

                    if (LA24_0 == 82) :
                        alt24 = 1


                    if alt24 == 1:
                        # Expr.g:188:21: ',' param_decl
                        pass 
                        char_literal133 = self.match(self.input, 82, self.FOLLOW_82_in_params1147) 
                        if self._state.backtracking == 0:
                            stream_82.add(char_literal133)


                        self._state.following.append(self.FOLLOW_param_decl_in_params1149)
                        param_decl134 = self.param_decl()

                        self._state.following.pop()
                        if self._state.backtracking == 0:
                            stream_param_decl.add(param_decl134.tree)



                    else:
                        break #loop24


                char_literal135 = self.match(self.input, 76, self.FOLLOW_76_in_params1153) 
                if self._state.backtracking == 0:
                    stream_76.add(char_literal135)


                # AST Rewrite
                # elements: param_decl
                # token labels: 
                # rule labels: retval
                # token list labels: 
                # rule list labels: 
                # wildcard labels: 
                if self._state.backtracking == 0:
                    retval.tree = root_0
                    if retval is not None:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "rule retval", retval.tree)
                    else:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "token retval", None)


                    root_0 = self._adaptor.nil()
                    # 189:3: -> ^( PARAMS ( param_decl )* )
                    # Expr.g:189:6: ^( PARAMS ( param_decl )* )
                    root_1 = self._adaptor.nil()
                    root_1 = self._adaptor.becomeRoot(
                    self._adaptor.createFromType(PARAMS, "PARAMS")
                    , root_1)

                    # Expr.g:189:15: ( param_decl )*
                    while stream_param_decl.hasNext():
                        self._adaptor.addChild(root_1, stream_param_decl.nextTree())


                    stream_param_decl.reset();

                    self._adaptor.addChild(root_0, root_1)




                    retval.tree = root_0





                retval.stop = self.input.LT(-1)


                if self._state.backtracking == 0:
                    retval.tree = self._adaptor.rulePostProcessing(root_0)
                    self._adaptor.setTokenBoundaries(retval.tree, retval.start, retval.stop)



            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)
                retval.tree = self._adaptor.errorNode(self.input, retval.start, self.input.LT(-1), re)

        finally:
            pass
        return retval

    # $ANTLR end "params"


    class param_decl_return(ParserRuleReturnScope):
        def __init__(self):
            super(ExprParser.param_decl_return, self).__init__()

            self.tree = None





    # $ANTLR start "param_decl"
    # Expr.g:191:1: param_decl : ID ( '=' atom )? ;
    def param_decl(self, ):
        retval = self.param_decl_return()
        retval.start = self.input.LT(1)


        root_0 = None

        ID136 = None
        char_literal137 = None
        atom138 = None

        ID136_tree = None
        char_literal137_tree = None

        try:
            try:
                # Expr.g:192:2: ( ID ( '=' atom )? )
                # Expr.g:192:4: ID ( '=' atom )?
                pass 
                root_0 = self._adaptor.nil()


                ID136 = self.match(self.input, ID, self.FOLLOW_ID_in_param_decl1174)
                if self._state.backtracking == 0:
                    ID136_tree = self._adaptor.createWithPayload(ID136)
                    self._adaptor.addChild(root_0, ID136_tree)



                # Expr.g:192:7: ( '=' atom )?
                alt25 = 2
                LA25_0 = self.input.LA(1)

                if (LA25_0 == 95) :
                    alt25 = 1
                if alt25 == 1:
                    # Expr.g:192:8: '=' atom
                    pass 
                    char_literal137 = self.match(self.input, 95, self.FOLLOW_95_in_param_decl1177)
                    if self._state.backtracking == 0:
                        char_literal137_tree = self._adaptor.createWithPayload(char_literal137)
                        self._adaptor.addChild(root_0, char_literal137_tree)



                    self._state.following.append(self.FOLLOW_atom_in_param_decl1179)
                    atom138 = self.atom()

                    self._state.following.pop()
                    if self._state.backtracking == 0:
                        self._adaptor.addChild(root_0, atom138.tree)







                retval.stop = self.input.LT(-1)


                if self._state.backtracking == 0:
                    retval.tree = self._adaptor.rulePostProcessing(root_0)
                    self._adaptor.setTokenBoundaries(retval.tree, retval.start, retval.stop)



            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)
                retval.tree = self._adaptor.errorNode(self.input, retval.start, self.input.LT(-1), re)

        finally:
            pass
        return retval

    # $ANTLR end "param_decl"


    class class_decl_return(ParserRuleReturnScope):
        def __init__(self):
            super(ExprParser.class_decl_return, self).__init__()

            self.tree = None





    # $ANTLR start "class_decl"
    # Expr.g:195:1: class_decl : 'class' ID ( 'extends' ID )? '{' ( class_element )* '}' -> ^( CLASS ID ( ID )? ( class_element )* ) ;
    def class_decl(self, ):
        retval = self.class_decl_return()
        retval.start = self.input.LT(1)


        root_0 = None

        string_literal139 = None
        ID140 = None
        string_literal141 = None
        ID142 = None
        char_literal143 = None
        char_literal145 = None
        class_element144 = None

        string_literal139_tree = None
        ID140_tree = None
        string_literal141_tree = None
        ID142_tree = None
        char_literal143_tree = None
        char_literal145_tree = None
        stream_132 = RewriteRuleTokenStream(self._adaptor, "token 132")
        stream_113 = RewriteRuleTokenStream(self._adaptor, "token 113")
        stream_108 = RewriteRuleTokenStream(self._adaptor, "token 108")
        stream_136 = RewriteRuleTokenStream(self._adaptor, "token 136")
        stream_ID = RewriteRuleTokenStream(self._adaptor, "token ID")
        stream_class_element = RewriteRuleSubtreeStream(self._adaptor, "rule class_element")
        try:
            try:
                # Expr.g:196:2: ( 'class' ID ( 'extends' ID )? '{' ( class_element )* '}' -> ^( CLASS ID ( ID )? ( class_element )* ) )
                # Expr.g:196:4: 'class' ID ( 'extends' ID )? '{' ( class_element )* '}'
                pass 
                string_literal139 = self.match(self.input, 108, self.FOLLOW_108_in_class_decl1192) 
                if self._state.backtracking == 0:
                    stream_108.add(string_literal139)


                ID140 = self.match(self.input, ID, self.FOLLOW_ID_in_class_decl1194) 
                if self._state.backtracking == 0:
                    stream_ID.add(ID140)


                # Expr.g:196:15: ( 'extends' ID )?
                alt26 = 2
                LA26_0 = self.input.LA(1)

                if (LA26_0 == 113) :
                    alt26 = 1
                if alt26 == 1:
                    # Expr.g:196:16: 'extends' ID
                    pass 
                    string_literal141 = self.match(self.input, 113, self.FOLLOW_113_in_class_decl1197) 
                    if self._state.backtracking == 0:
                        stream_113.add(string_literal141)


                    ID142 = self.match(self.input, ID, self.FOLLOW_ID_in_class_decl1199) 
                    if self._state.backtracking == 0:
                        stream_ID.add(ID142)





                char_literal143 = self.match(self.input, 132, self.FOLLOW_132_in_class_decl1205) 
                if self._state.backtracking == 0:
                    stream_132.add(char_literal143)


                # Expr.g:197:7: ( class_element )*
                while True: #loop27
                    alt27 = 2
                    LA27_0 = self.input.LA(1)

                    if (LA27_0 == 117 or LA27_0 == 124) :
                        alt27 = 1


                    if alt27 == 1:
                        # Expr.g:197:7: class_element
                        pass 
                        self._state.following.append(self.FOLLOW_class_element_in_class_decl1207)
                        class_element144 = self.class_element()

                        self._state.following.pop()
                        if self._state.backtracking == 0:
                            stream_class_element.add(class_element144.tree)



                    else:
                        break #loop27


                char_literal145 = self.match(self.input, 136, self.FOLLOW_136_in_class_decl1210) 
                if self._state.backtracking == 0:
                    stream_136.add(char_literal145)


                # AST Rewrite
                # elements: ID, ID, class_element
                # token labels: 
                # rule labels: retval
                # token list labels: 
                # rule list labels: 
                # wildcard labels: 
                if self._state.backtracking == 0:
                    retval.tree = root_0
                    if retval is not None:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "rule retval", retval.tree)
                    else:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "token retval", None)


                    root_0 = self._adaptor.nil()
                    # 198:3: -> ^( CLASS ID ( ID )? ( class_element )* )
                    # Expr.g:198:6: ^( CLASS ID ( ID )? ( class_element )* )
                    root_1 = self._adaptor.nil()
                    root_1 = self._adaptor.becomeRoot(
                    self._adaptor.createFromType(CLASS, "CLASS")
                    , root_1)

                    self._adaptor.addChild(root_1, 
                    stream_ID.nextNode()
                    )

                    # Expr.g:198:17: ( ID )?
                    if stream_ID.hasNext():
                        self._adaptor.addChild(root_1, 
                        stream_ID.nextNode()
                        )


                    stream_ID.reset();

                    # Expr.g:198:21: ( class_element )*
                    while stream_class_element.hasNext():
                        self._adaptor.addChild(root_1, stream_class_element.nextTree())


                    stream_class_element.reset();

                    self._adaptor.addChild(root_0, root_1)




                    retval.tree = root_0





                retval.stop = self.input.LT(-1)


                if self._state.backtracking == 0:
                    retval.tree = self._adaptor.rulePostProcessing(root_0)
                    self._adaptor.setTokenBoundaries(retval.tree, retval.start, retval.stop)



            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)
                retval.tree = self._adaptor.errorNode(self.input, retval.start, self.input.LT(-1), re)

        finally:
            pass
        return retval

    # $ANTLR end "class_decl"


    class class_element_return(ParserRuleReturnScope):
        def __init__(self):
            super(ExprParser.class_element_return, self).__init__()

            self.tree = None





    # $ANTLR start "class_element"
    # Expr.g:200:1: class_element : ( var_def | constructor | func_decl );
    def class_element(self, ):
        retval = self.class_element_return()
        retval.start = self.input.LT(1)


        root_0 = None

        var_def146 = None
        constructor147 = None
        func_decl148 = None


        try:
            try:
                # Expr.g:201:2: ( var_def | constructor | func_decl )
                alt28 = 3
                LA28_0 = self.input.LA(1)

                if (LA28_0 == 124) :
                    alt28 = 1
                elif (LA28_0 == 117) :
                    LA28_2 = self.input.LA(2)

                    if (LA28_2 == 120) :
                        alt28 = 2
                    elif (LA28_2 == ID) :
                        alt28 = 3
                    else:
                        if self._state.backtracking > 0:
                            raise BacktrackingFailed


                        nvae = NoViableAltException("", 28, 2, self.input)

                        raise nvae


                else:
                    if self._state.backtracking > 0:
                        raise BacktrackingFailed


                    nvae = NoViableAltException("", 28, 0, self.input)

                    raise nvae


                if alt28 == 1:
                    # Expr.g:201:4: var_def
                    pass 
                    root_0 = self._adaptor.nil()


                    self._state.following.append(self.FOLLOW_var_def_in_class_element1236)
                    var_def146 = self.var_def()

                    self._state.following.pop()
                    if self._state.backtracking == 0:
                        self._adaptor.addChild(root_0, var_def146.tree)



                elif alt28 == 2:
                    # Expr.g:201:14: constructor
                    pass 
                    root_0 = self._adaptor.nil()


                    self._state.following.append(self.FOLLOW_constructor_in_class_element1240)
                    constructor147 = self.constructor()

                    self._state.following.pop()
                    if self._state.backtracking == 0:
                        self._adaptor.addChild(root_0, constructor147.tree)



                elif alt28 == 3:
                    # Expr.g:201:28: func_decl
                    pass 
                    root_0 = self._adaptor.nil()


                    self._state.following.append(self.FOLLOW_func_decl_in_class_element1244)
                    func_decl148 = self.func_decl()

                    self._state.following.pop()
                    if self._state.backtracking == 0:
                        self._adaptor.addChild(root_0, func_decl148.tree)



                retval.stop = self.input.LT(-1)


                if self._state.backtracking == 0:
                    retval.tree = self._adaptor.rulePostProcessing(root_0)
                    self._adaptor.setTokenBoundaries(retval.tree, retval.start, retval.stop)



            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)
                retval.tree = self._adaptor.errorNode(self.input, retval.start, self.input.LT(-1), re)

        finally:
            pass
        return retval

    # $ANTLR end "class_element"


    class var_def_return(ParserRuleReturnScope):
        def __init__(self):
            super(ExprParser.var_def_return, self).__init__()

            self.tree = None





    # $ANTLR start "var_def"
    # Expr.g:203:1: var_def : ( 'public' ID ( '=' expr )? ';' -> ^( VAR ID ( expr )? ) | 'public' 'static' ID ( '=' expr )? ';' -> ^( VAR 'static' ID ( expr )? ) );
    def var_def(self, ):
        retval = self.var_def_return()
        retval.start = self.input.LT(1)


        root_0 = None

        string_literal149 = None
        ID150 = None
        char_literal151 = None
        char_literal153 = None
        string_literal154 = None
        string_literal155 = None
        ID156 = None
        char_literal157 = None
        char_literal159 = None
        expr152 = None
        expr158 = None

        string_literal149_tree = None
        ID150_tree = None
        char_literal151_tree = None
        char_literal153_tree = None
        string_literal154_tree = None
        string_literal155_tree = None
        ID156_tree = None
        char_literal157_tree = None
        char_literal159_tree = None
        stream_127 = RewriteRuleTokenStream(self._adaptor, "token 127")
        stream_95 = RewriteRuleTokenStream(self._adaptor, "token 95")
        stream_92 = RewriteRuleTokenStream(self._adaptor, "token 92")
        stream_124 = RewriteRuleTokenStream(self._adaptor, "token 124")
        stream_ID = RewriteRuleTokenStream(self._adaptor, "token ID")
        stream_expr = RewriteRuleSubtreeStream(self._adaptor, "rule expr")
        try:
            try:
                # Expr.g:204:2: ( 'public' ID ( '=' expr )? ';' -> ^( VAR ID ( expr )? ) | 'public' 'static' ID ( '=' expr )? ';' -> ^( VAR 'static' ID ( expr )? ) )
                alt31 = 2
                LA31_0 = self.input.LA(1)

                if (LA31_0 == 124) :
                    LA31_1 = self.input.LA(2)

                    if (LA31_1 == ID) :
                        alt31 = 1
                    elif (LA31_1 == 127) :
                        alt31 = 2
                    else:
                        if self._state.backtracking > 0:
                            raise BacktrackingFailed


                        nvae = NoViableAltException("", 31, 1, self.input)

                        raise nvae


                else:
                    if self._state.backtracking > 0:
                        raise BacktrackingFailed


                    nvae = NoViableAltException("", 31, 0, self.input)

                    raise nvae


                if alt31 == 1:
                    # Expr.g:204:4: 'public' ID ( '=' expr )? ';'
                    pass 
                    string_literal149 = self.match(self.input, 124, self.FOLLOW_124_in_var_def1254) 
                    if self._state.backtracking == 0:
                        stream_124.add(string_literal149)


                    ID150 = self.match(self.input, ID, self.FOLLOW_ID_in_var_def1256) 
                    if self._state.backtracking == 0:
                        stream_ID.add(ID150)


                    # Expr.g:204:16: ( '=' expr )?
                    alt29 = 2
                    LA29_0 = self.input.LA(1)

                    if (LA29_0 == 95) :
                        alt29 = 1
                    if alt29 == 1:
                        # Expr.g:204:17: '=' expr
                        pass 
                        char_literal151 = self.match(self.input, 95, self.FOLLOW_95_in_var_def1259) 
                        if self._state.backtracking == 0:
                            stream_95.add(char_literal151)


                        self._state.following.append(self.FOLLOW_expr_in_var_def1261)
                        expr152 = self.expr()

                        self._state.following.pop()
                        if self._state.backtracking == 0:
                            stream_expr.add(expr152.tree)





                    char_literal153 = self.match(self.input, 92, self.FOLLOW_92_in_var_def1265) 
                    if self._state.backtracking == 0:
                        stream_92.add(char_literal153)


                    # AST Rewrite
                    # elements: expr, ID
                    # token labels: 
                    # rule labels: retval
                    # token list labels: 
                    # rule list labels: 
                    # wildcard labels: 
                    if self._state.backtracking == 0:
                        retval.tree = root_0
                        if retval is not None:
                            stream_retval = RewriteRuleSubtreeStream(self._adaptor, "rule retval", retval.tree)
                        else:
                            stream_retval = RewriteRuleSubtreeStream(self._adaptor, "token retval", None)


                        root_0 = self._adaptor.nil()
                        # 205:3: -> ^( VAR ID ( expr )? )
                        # Expr.g:205:6: ^( VAR ID ( expr )? )
                        root_1 = self._adaptor.nil()
                        root_1 = self._adaptor.becomeRoot(
                        self._adaptor.createFromType(VAR, "VAR")
                        , root_1)

                        self._adaptor.addChild(root_1, 
                        stream_ID.nextNode()
                        )

                        # Expr.g:205:15: ( expr )?
                        if stream_expr.hasNext():
                            self._adaptor.addChild(root_1, stream_expr.nextTree())


                        stream_expr.reset();

                        self._adaptor.addChild(root_0, root_1)




                        retval.tree = root_0




                elif alt31 == 2:
                    # Expr.g:206:4: 'public' 'static' ID ( '=' expr )? ';'
                    pass 
                    string_literal154 = self.match(self.input, 124, self.FOLLOW_124_in_var_def1283) 
                    if self._state.backtracking == 0:
                        stream_124.add(string_literal154)


                    string_literal155 = self.match(self.input, 127, self.FOLLOW_127_in_var_def1285) 
                    if self._state.backtracking == 0:
                        stream_127.add(string_literal155)


                    ID156 = self.match(self.input, ID, self.FOLLOW_ID_in_var_def1287) 
                    if self._state.backtracking == 0:
                        stream_ID.add(ID156)


                    # Expr.g:206:25: ( '=' expr )?
                    alt30 = 2
                    LA30_0 = self.input.LA(1)

                    if (LA30_0 == 95) :
                        alt30 = 1
                    if alt30 == 1:
                        # Expr.g:206:26: '=' expr
                        pass 
                        char_literal157 = self.match(self.input, 95, self.FOLLOW_95_in_var_def1290) 
                        if self._state.backtracking == 0:
                            stream_95.add(char_literal157)


                        self._state.following.append(self.FOLLOW_expr_in_var_def1292)
                        expr158 = self.expr()

                        self._state.following.pop()
                        if self._state.backtracking == 0:
                            stream_expr.add(expr158.tree)





                    char_literal159 = self.match(self.input, 92, self.FOLLOW_92_in_var_def1296) 
                    if self._state.backtracking == 0:
                        stream_92.add(char_literal159)


                    # AST Rewrite
                    # elements: expr, ID, 127
                    # token labels: 
                    # rule labels: retval
                    # token list labels: 
                    # rule list labels: 
                    # wildcard labels: 
                    if self._state.backtracking == 0:
                        retval.tree = root_0
                        if retval is not None:
                            stream_retval = RewriteRuleSubtreeStream(self._adaptor, "rule retval", retval.tree)
                        else:
                            stream_retval = RewriteRuleSubtreeStream(self._adaptor, "token retval", None)


                        root_0 = self._adaptor.nil()
                        # 207:3: -> ^( VAR 'static' ID ( expr )? )
                        # Expr.g:207:6: ^( VAR 'static' ID ( expr )? )
                        root_1 = self._adaptor.nil()
                        root_1 = self._adaptor.becomeRoot(
                        self._adaptor.createFromType(VAR, "VAR")
                        , root_1)

                        self._adaptor.addChild(root_1, 
                        stream_127.nextNode()
                        )

                        self._adaptor.addChild(root_1, 
                        stream_ID.nextNode()
                        )

                        # Expr.g:207:24: ( expr )?
                        if stream_expr.hasNext():
                            self._adaptor.addChild(root_1, stream_expr.nextTree())


                        stream_expr.reset();

                        self._adaptor.addChild(root_0, root_1)




                        retval.tree = root_0




                retval.stop = self.input.LT(-1)


                if self._state.backtracking == 0:
                    retval.tree = self._adaptor.rulePostProcessing(root_0)
                    self._adaptor.setTokenBoundaries(retval.tree, retval.start, retval.stop)



            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)
                retval.tree = self._adaptor.errorNode(self.input, retval.start, self.input.LT(-1), re)

        finally:
            pass
        return retval

    # $ANTLR end "var_def"


    class constructor_return(ParserRuleReturnScope):
        def __init__(self):
            super(ExprParser.constructor_return, self).__init__()

            self.tree = None





    # $ANTLR start "constructor"
    # Expr.g:209:1: constructor : 'function' 'init' params block -> ^( CONSTRUCTOR params block ) ;
    def constructor(self, ):
        retval = self.constructor_return()
        retval.start = self.input.LT(1)


        root_0 = None

        string_literal160 = None
        string_literal161 = None
        params162 = None
        block163 = None

        string_literal160_tree = None
        string_literal161_tree = None
        stream_117 = RewriteRuleTokenStream(self._adaptor, "token 117")
        stream_120 = RewriteRuleTokenStream(self._adaptor, "token 120")
        stream_block = RewriteRuleSubtreeStream(self._adaptor, "rule block")
        stream_params = RewriteRuleSubtreeStream(self._adaptor, "rule params")
        try:
            try:
                # Expr.g:210:2: ( 'function' 'init' params block -> ^( CONSTRUCTOR params block ) )
                # Expr.g:210:4: 'function' 'init' params block
                pass 
                string_literal160 = self.match(self.input, 117, self.FOLLOW_117_in_constructor1321) 
                if self._state.backtracking == 0:
                    stream_117.add(string_literal160)


                string_literal161 = self.match(self.input, 120, self.FOLLOW_120_in_constructor1323) 
                if self._state.backtracking == 0:
                    stream_120.add(string_literal161)


                self._state.following.append(self.FOLLOW_params_in_constructor1325)
                params162 = self.params()

                self._state.following.pop()
                if self._state.backtracking == 0:
                    stream_params.add(params162.tree)


                self._state.following.append(self.FOLLOW_block_in_constructor1327)
                block163 = self.block()

                self._state.following.pop()
                if self._state.backtracking == 0:
                    stream_block.add(block163.tree)


                # AST Rewrite
                # elements: params, block
                # token labels: 
                # rule labels: retval
                # token list labels: 
                # rule list labels: 
                # wildcard labels: 
                if self._state.backtracking == 0:
                    retval.tree = root_0
                    if retval is not None:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "rule retval", retval.tree)
                    else:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "token retval", None)


                    root_0 = self._adaptor.nil()
                    # 211:3: -> ^( CONSTRUCTOR params block )
                    # Expr.g:211:6: ^( CONSTRUCTOR params block )
                    root_1 = self._adaptor.nil()
                    root_1 = self._adaptor.becomeRoot(
                    self._adaptor.createFromType(CONSTRUCTOR, "CONSTRUCTOR")
                    , root_1)

                    self._adaptor.addChild(root_1, stream_params.nextTree())

                    self._adaptor.addChild(root_1, stream_block.nextTree())

                    self._adaptor.addChild(root_0, root_1)




                    retval.tree = root_0





                retval.stop = self.input.LT(-1)


                if self._state.backtracking == 0:
                    retval.tree = self._adaptor.rulePostProcessing(root_0)
                    self._adaptor.setTokenBoundaries(retval.tree, retval.start, retval.stop)



            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)
                retval.tree = self._adaptor.errorNode(self.input, retval.start, self.input.LT(-1), re)

        finally:
            pass
        return retval

    # $ANTLR end "constructor"


    class member_expr_return(ParserRuleReturnScope):
        def __init__(self):
            super(ExprParser.member_expr_return, self).__init__()

            self.tree = None





    # $ANTLR start "member_expr"
    # Expr.g:217:1: member_expr : primary ( '.' primary )* -> ^( MEMBER ( primary )+ ) ;
    def member_expr(self, ):
        retval = self.member_expr_return()
        retval.start = self.input.LT(1)


        root_0 = None

        char_literal165 = None
        primary164 = None
        primary166 = None

        char_literal165_tree = None
        stream_86 = RewriteRuleTokenStream(self._adaptor, "token 86")
        stream_primary = RewriteRuleSubtreeStream(self._adaptor, "rule primary")
        try:
            try:
                # Expr.g:218:2: ( primary ( '.' primary )* -> ^( MEMBER ( primary )+ ) )
                # Expr.g:218:4: primary ( '.' primary )*
                pass 
                self._state.following.append(self.FOLLOW_primary_in_member_expr1354)
                primary164 = self.primary()

                self._state.following.pop()
                if self._state.backtracking == 0:
                    stream_primary.add(primary164.tree)


                # Expr.g:218:12: ( '.' primary )*
                while True: #loop32
                    alt32 = 2
                    LA32_0 = self.input.LA(1)

                    if (LA32_0 == 86) :
                        alt32 = 1


                    if alt32 == 1:
                        # Expr.g:218:13: '.' primary
                        pass 
                        char_literal165 = self.match(self.input, 86, self.FOLLOW_86_in_member_expr1357) 
                        if self._state.backtracking == 0:
                            stream_86.add(char_literal165)


                        self._state.following.append(self.FOLLOW_primary_in_member_expr1359)
                        primary166 = self.primary()

                        self._state.following.pop()
                        if self._state.backtracking == 0:
                            stream_primary.add(primary166.tree)



                    else:
                        break #loop32


                # AST Rewrite
                # elements: primary
                # token labels: 
                # rule labels: retval
                # token list labels: 
                # rule list labels: 
                # wildcard labels: 
                if self._state.backtracking == 0:
                    retval.tree = root_0
                    if retval is not None:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "rule retval", retval.tree)
                    else:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "token retval", None)


                    root_0 = self._adaptor.nil()
                    # 219:3: -> ^( MEMBER ( primary )+ )
                    # Expr.g:219:6: ^( MEMBER ( primary )+ )
                    root_1 = self._adaptor.nil()
                    root_1 = self._adaptor.becomeRoot(
                    self._adaptor.createFromType(MEMBER, "MEMBER")
                    , root_1)

                    # Expr.g:219:15: ( primary )+
                    if not (stream_primary.hasNext()):
                        raise RewriteEarlyExitException()

                    while stream_primary.hasNext():
                        self._adaptor.addChild(root_1, stream_primary.nextTree())


                    stream_primary.reset()

                    self._adaptor.addChild(root_0, root_1)




                    retval.tree = root_0





                retval.stop = self.input.LT(-1)


                if self._state.backtracking == 0:
                    retval.tree = self._adaptor.rulePostProcessing(root_0)
                    self._adaptor.setTokenBoundaries(retval.tree, retval.start, retval.stop)



            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)
                retval.tree = self._adaptor.errorNode(self.input, retval.start, self.input.LT(-1), re)

        finally:
            pass
        return retval

    # $ANTLR end "member_expr"


    class primary_return(ParserRuleReturnScope):
        def __init__(self):
            super(ExprParser.primary_return, self).__init__()

            self.tree = None





    # $ANTLR start "primary"
    # Expr.g:221:1: primary : ID ( index_expr )* ( call_expr )? ;
    def primary(self, ):
        retval = self.primary_return()
        retval.start = self.input.LT(1)


        root_0 = None

        ID167 = None
        index_expr168 = None
        call_expr169 = None

        ID167_tree = None

        try:
            try:
                # Expr.g:222:2: ( ID ( index_expr )* ( call_expr )? )
                # Expr.g:222:4: ID ( index_expr )* ( call_expr )?
                pass 
                root_0 = self._adaptor.nil()


                ID167 = self.match(self.input, ID, self.FOLLOW_ID_in_primary1382)
                if self._state.backtracking == 0:
                    ID167_tree = self._adaptor.createWithPayload(ID167)
                    self._adaptor.addChild(root_0, ID167_tree)



                # Expr.g:222:7: ( index_expr )*
                while True: #loop33
                    alt33 = 2
                    LA33_0 = self.input.LA(1)

                    if (LA33_0 == 100) :
                        alt33 = 1


                    if alt33 == 1:
                        # Expr.g:222:7: index_expr
                        pass 
                        self._state.following.append(self.FOLLOW_index_expr_in_primary1384)
                        index_expr168 = self.index_expr()

                        self._state.following.pop()
                        if self._state.backtracking == 0:
                            self._adaptor.addChild(root_0, index_expr168.tree)



                    else:
                        break #loop33


                # Expr.g:222:19: ( call_expr )?
                alt34 = 2
                LA34_0 = self.input.LA(1)

                if (LA34_0 == 75) :
                    alt34 = 1
                if alt34 == 1:
                    # Expr.g:222:19: call_expr
                    pass 
                    self._state.following.append(self.FOLLOW_call_expr_in_primary1387)
                    call_expr169 = self.call_expr()

                    self._state.following.pop()
                    if self._state.backtracking == 0:
                        self._adaptor.addChild(root_0, call_expr169.tree)







                retval.stop = self.input.LT(-1)


                if self._state.backtracking == 0:
                    retval.tree = self._adaptor.rulePostProcessing(root_0)
                    self._adaptor.setTokenBoundaries(retval.tree, retval.start, retval.stop)



            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)
                retval.tree = self._adaptor.errorNode(self.input, retval.start, self.input.LT(-1), re)

        finally:
            pass
        return retval

    # $ANTLR end "primary"


    class call_expr_return(ParserRuleReturnScope):
        def __init__(self):
            super(ExprParser.call_expr_return, self).__init__()

            self.tree = None





    # $ANTLR start "call_expr"
    # Expr.g:224:1: call_expr : '(' ( expr_list )? ')' -> ^( CALL ( expr_list )? ) ;
    def call_expr(self, ):
        retval = self.call_expr_return()
        retval.start = self.input.LT(1)


        root_0 = None

        char_literal170 = None
        char_literal172 = None
        expr_list171 = None

        char_literal170_tree = None
        char_literal172_tree = None
        stream_75 = RewriteRuleTokenStream(self._adaptor, "token 75")
        stream_76 = RewriteRuleTokenStream(self._adaptor, "token 76")
        stream_expr_list = RewriteRuleSubtreeStream(self._adaptor, "rule expr_list")
        try:
            try:
                # Expr.g:225:2: ( '(' ( expr_list )? ')' -> ^( CALL ( expr_list )? ) )
                # Expr.g:225:4: '(' ( expr_list )? ')'
                pass 
                char_literal170 = self.match(self.input, 75, self.FOLLOW_75_in_call_expr1398) 
                if self._state.backtracking == 0:
                    stream_75.add(char_literal170)


                # Expr.g:225:8: ( expr_list )?
                alt35 = 2
                LA35_0 = self.input.LA(1)

                if (LA35_0 == BOOL or LA35_0 == FLOAT or LA35_0 == ID or LA35_0 == INT or LA35_0 == NULL or LA35_0 == STRING or LA35_0 == 68 or LA35_0 == 75 or LA35_0 == 83 or LA35_0 == 100 or LA35_0 == 121 or LA35_0 == 126 or LA35_0 == 132) :
                    alt35 = 1
                if alt35 == 1:
                    # Expr.g:225:8: expr_list
                    pass 
                    self._state.following.append(self.FOLLOW_expr_list_in_call_expr1400)
                    expr_list171 = self.expr_list()

                    self._state.following.pop()
                    if self._state.backtracking == 0:
                        stream_expr_list.add(expr_list171.tree)





                char_literal172 = self.match(self.input, 76, self.FOLLOW_76_in_call_expr1403) 
                if self._state.backtracking == 0:
                    stream_76.add(char_literal172)


                # AST Rewrite
                # elements: expr_list
                # token labels: 
                # rule labels: retval
                # token list labels: 
                # rule list labels: 
                # wildcard labels: 
                if self._state.backtracking == 0:
                    retval.tree = root_0
                    if retval is not None:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "rule retval", retval.tree)
                    else:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "token retval", None)


                    root_0 = self._adaptor.nil()
                    # 226:3: -> ^( CALL ( expr_list )? )
                    # Expr.g:226:6: ^( CALL ( expr_list )? )
                    root_1 = self._adaptor.nil()
                    root_1 = self._adaptor.becomeRoot(
                    self._adaptor.createFromType(CALL, "CALL")
                    , root_1)

                    # Expr.g:226:13: ( expr_list )?
                    if stream_expr_list.hasNext():
                        self._adaptor.addChild(root_1, stream_expr_list.nextTree())


                    stream_expr_list.reset();

                    self._adaptor.addChild(root_0, root_1)




                    retval.tree = root_0





                retval.stop = self.input.LT(-1)


                if self._state.backtracking == 0:
                    retval.tree = self._adaptor.rulePostProcessing(root_0)
                    self._adaptor.setTokenBoundaries(retval.tree, retval.start, retval.stop)



            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)
                retval.tree = self._adaptor.errorNode(self.input, retval.start, self.input.LT(-1), re)

        finally:
            pass
        return retval

    # $ANTLR end "call_expr"


    class index_expr_return(ParserRuleReturnScope):
        def __init__(self):
            super(ExprParser.index_expr_return, self).__init__()

            self.tree = None





    # $ANTLR start "index_expr"
    # Expr.g:228:1: index_expr options {backtrack=true; } : ( '[' expr ']' -> ^( INDEX expr ) | '[' expr '..' ( expr )? ']' -> ^( SLICE expr ( expr )? ) );
    def index_expr(self, ):
        retval = self.index_expr_return()
        retval.start = self.input.LT(1)


        root_0 = None

        char_literal173 = None
        char_literal175 = None
        char_literal176 = None
        string_literal178 = None
        char_literal180 = None
        expr174 = None
        expr177 = None
        expr179 = None

        char_literal173_tree = None
        char_literal175_tree = None
        char_literal176_tree = None
        string_literal178_tree = None
        char_literal180_tree = None
        stream_101 = RewriteRuleTokenStream(self._adaptor, "token 101")
        stream_88 = RewriteRuleTokenStream(self._adaptor, "token 88")
        stream_100 = RewriteRuleTokenStream(self._adaptor, "token 100")
        stream_expr = RewriteRuleSubtreeStream(self._adaptor, "rule expr")
        try:
            try:
                # Expr.g:232:2: ( '[' expr ']' -> ^( INDEX expr ) | '[' expr '..' ( expr )? ']' -> ^( SLICE expr ( expr )? ) )
                alt37 = 2
                LA37_0 = self.input.LA(1)

                if (LA37_0 == 100) :
                    LA37_1 = self.input.LA(2)

                    if (self.synpred1_Expr()) :
                        alt37 = 1
                    elif (True) :
                        alt37 = 2
                    else:
                        if self._state.backtracking > 0:
                            raise BacktrackingFailed


                        nvae = NoViableAltException("", 37, 1, self.input)

                        raise nvae


                else:
                    if self._state.backtracking > 0:
                        raise BacktrackingFailed


                    nvae = NoViableAltException("", 37, 0, self.input)

                    raise nvae


                if alt37 == 1:
                    # Expr.g:232:4: '[' expr ']'
                    pass 
                    char_literal173 = self.match(self.input, 100, self.FOLLOW_100_in_index_expr1439) 
                    if self._state.backtracking == 0:
                        stream_100.add(char_literal173)


                    self._state.following.append(self.FOLLOW_expr_in_index_expr1441)
                    expr174 = self.expr()

                    self._state.following.pop()
                    if self._state.backtracking == 0:
                        stream_expr.add(expr174.tree)


                    char_literal175 = self.match(self.input, 101, self.FOLLOW_101_in_index_expr1443) 
                    if self._state.backtracking == 0:
                        stream_101.add(char_literal175)


                    # AST Rewrite
                    # elements: expr
                    # token labels: 
                    # rule labels: retval
                    # token list labels: 
                    # rule list labels: 
                    # wildcard labels: 
                    if self._state.backtracking == 0:
                        retval.tree = root_0
                        if retval is not None:
                            stream_retval = RewriteRuleSubtreeStream(self._adaptor, "rule retval", retval.tree)
                        else:
                            stream_retval = RewriteRuleSubtreeStream(self._adaptor, "token retval", None)


                        root_0 = self._adaptor.nil()
                        # 233:3: -> ^( INDEX expr )
                        # Expr.g:233:6: ^( INDEX expr )
                        root_1 = self._adaptor.nil()
                        root_1 = self._adaptor.becomeRoot(
                        self._adaptor.createFromType(INDEX, "INDEX")
                        , root_1)

                        self._adaptor.addChild(root_1, stream_expr.nextTree())

                        self._adaptor.addChild(root_0, root_1)




                        retval.tree = root_0




                elif alt37 == 2:
                    # Expr.g:234:4: '[' expr '..' ( expr )? ']'
                    pass 
                    char_literal176 = self.match(self.input, 100, self.FOLLOW_100_in_index_expr1458) 
                    if self._state.backtracking == 0:
                        stream_100.add(char_literal176)


                    self._state.following.append(self.FOLLOW_expr_in_index_expr1460)
                    expr177 = self.expr()

                    self._state.following.pop()
                    if self._state.backtracking == 0:
                        stream_expr.add(expr177.tree)


                    string_literal178 = self.match(self.input, 88, self.FOLLOW_88_in_index_expr1462) 
                    if self._state.backtracking == 0:
                        stream_88.add(string_literal178)


                    # Expr.g:234:18: ( expr )?
                    alt36 = 2
                    LA36_0 = self.input.LA(1)

                    if (LA36_0 == BOOL or LA36_0 == FLOAT or LA36_0 == ID or LA36_0 == INT or LA36_0 == NULL or LA36_0 == STRING or LA36_0 == 68 or LA36_0 == 75 or LA36_0 == 83 or LA36_0 == 100 or LA36_0 == 121 or LA36_0 == 126 or LA36_0 == 132) :
                        alt36 = 1
                    if alt36 == 1:
                        # Expr.g:234:18: expr
                        pass 
                        self._state.following.append(self.FOLLOW_expr_in_index_expr1464)
                        expr179 = self.expr()

                        self._state.following.pop()
                        if self._state.backtracking == 0:
                            stream_expr.add(expr179.tree)





                    char_literal180 = self.match(self.input, 101, self.FOLLOW_101_in_index_expr1467) 
                    if self._state.backtracking == 0:
                        stream_101.add(char_literal180)


                    # AST Rewrite
                    # elements: expr, expr
                    # token labels: 
                    # rule labels: retval
                    # token list labels: 
                    # rule list labels: 
                    # wildcard labels: 
                    if self._state.backtracking == 0:
                        retval.tree = root_0
                        if retval is not None:
                            stream_retval = RewriteRuleSubtreeStream(self._adaptor, "rule retval", retval.tree)
                        else:
                            stream_retval = RewriteRuleSubtreeStream(self._adaptor, "token retval", None)


                        root_0 = self._adaptor.nil()
                        # 235:3: -> ^( SLICE expr ( expr )? )
                        # Expr.g:235:6: ^( SLICE expr ( expr )? )
                        root_1 = self._adaptor.nil()
                        root_1 = self._adaptor.becomeRoot(
                        self._adaptor.createFromType(SLICE, "SLICE")
                        , root_1)

                        self._adaptor.addChild(root_1, stream_expr.nextTree())

                        # Expr.g:235:19: ( expr )?
                        if stream_expr.hasNext():
                            self._adaptor.addChild(root_1, stream_expr.nextTree())


                        stream_expr.reset();

                        self._adaptor.addChild(root_0, root_1)




                        retval.tree = root_0




                retval.stop = self.input.LT(-1)


                if self._state.backtracking == 0:
                    retval.tree = self._adaptor.rulePostProcessing(root_0)
                    self._adaptor.setTokenBoundaries(retval.tree, retval.start, retval.stop)



            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)
                retval.tree = self._adaptor.errorNode(self.input, retval.start, self.input.LT(-1), re)

        finally:
            pass
        return retval

    # $ANTLR end "index_expr"


    class exec_list_return(ParserRuleReturnScope):
        def __init__(self):
            super(ExprParser.exec_list_return, self).__init__()

            self.tree = None





    # $ANTLR start "exec_list"
    # Expr.g:239:1: exec_list : exec_expr ( ',' exec_expr )* -> ^( EXEC_LIST ( exec_expr )+ ) ;
    def exec_list(self, ):
        retval = self.exec_list_return()
        retval.start = self.input.LT(1)


        root_0 = None

        char_literal182 = None
        exec_expr181 = None
        exec_expr183 = None

        char_literal182_tree = None
        stream_82 = RewriteRuleTokenStream(self._adaptor, "token 82")
        stream_exec_expr = RewriteRuleSubtreeStream(self._adaptor, "rule exec_expr")
        try:
            try:
                # Expr.g:240:2: ( exec_expr ( ',' exec_expr )* -> ^( EXEC_LIST ( exec_expr )+ ) )
                # Expr.g:240:4: exec_expr ( ',' exec_expr )*
                pass 
                self._state.following.append(self.FOLLOW_exec_expr_in_exec_list1492)
                exec_expr181 = self.exec_expr()

                self._state.following.pop()
                if self._state.backtracking == 0:
                    stream_exec_expr.add(exec_expr181.tree)


                # Expr.g:240:14: ( ',' exec_expr )*
                while True: #loop38
                    alt38 = 2
                    LA38_0 = self.input.LA(1)

                    if (LA38_0 == 82) :
                        alt38 = 1


                    if alt38 == 1:
                        # Expr.g:240:15: ',' exec_expr
                        pass 
                        char_literal182 = self.match(self.input, 82, self.FOLLOW_82_in_exec_list1495) 
                        if self._state.backtracking == 0:
                            stream_82.add(char_literal182)


                        self._state.following.append(self.FOLLOW_exec_expr_in_exec_list1497)
                        exec_expr183 = self.exec_expr()

                        self._state.following.pop()
                        if self._state.backtracking == 0:
                            stream_exec_expr.add(exec_expr183.tree)



                    else:
                        break #loop38


                # AST Rewrite
                # elements: exec_expr
                # token labels: 
                # rule labels: retval
                # token list labels: 
                # rule list labels: 
                # wildcard labels: 
                if self._state.backtracking == 0:
                    retval.tree = root_0
                    if retval is not None:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "rule retval", retval.tree)
                    else:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "token retval", None)


                    root_0 = self._adaptor.nil()
                    # 241:3: -> ^( EXEC_LIST ( exec_expr )+ )
                    # Expr.g:241:6: ^( EXEC_LIST ( exec_expr )+ )
                    root_1 = self._adaptor.nil()
                    root_1 = self._adaptor.becomeRoot(
                    self._adaptor.createFromType(EXEC_LIST, "EXEC_LIST")
                    , root_1)

                    # Expr.g:241:18: ( exec_expr )+
                    if not (stream_exec_expr.hasNext()):
                        raise RewriteEarlyExitException()

                    while stream_exec_expr.hasNext():
                        self._adaptor.addChild(root_1, stream_exec_expr.nextTree())


                    stream_exec_expr.reset()

                    self._adaptor.addChild(root_0, root_1)




                    retval.tree = root_0





                retval.stop = self.input.LT(-1)


                if self._state.backtracking == 0:
                    retval.tree = self._adaptor.rulePostProcessing(root_0)
                    self._adaptor.setTokenBoundaries(retval.tree, retval.start, retval.stop)



            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)
                retval.tree = self._adaptor.errorNode(self.input, retval.start, self.input.LT(-1), re)

        finally:
            pass
        return retval

    # $ANTLR end "exec_list"


    class member_list_return(ParserRuleReturnScope):
        def __init__(self):
            super(ExprParser.member_list_return, self).__init__()

            self.tree = None





    # $ANTLR start "member_list"
    # Expr.g:243:1: member_list : member_expr ( ',' member_expr )* ;
    def member_list(self, ):
        retval = self.member_list_return()
        retval.start = self.input.LT(1)


        root_0 = None

        char_literal185 = None
        member_expr184 = None
        member_expr186 = None

        char_literal185_tree = None

        try:
            try:
                # Expr.g:244:2: ( member_expr ( ',' member_expr )* )
                # Expr.g:244:4: member_expr ( ',' member_expr )*
                pass 
                root_0 = self._adaptor.nil()


                self._state.following.append(self.FOLLOW_member_expr_in_member_list1520)
                member_expr184 = self.member_expr()

                self._state.following.pop()
                if self._state.backtracking == 0:
                    self._adaptor.addChild(root_0, member_expr184.tree)


                # Expr.g:244:16: ( ',' member_expr )*
                while True: #loop39
                    alt39 = 2
                    LA39_0 = self.input.LA(1)

                    if (LA39_0 == 82) :
                        alt39 = 1


                    if alt39 == 1:
                        # Expr.g:244:17: ',' member_expr
                        pass 
                        char_literal185 = self.match(self.input, 82, self.FOLLOW_82_in_member_list1523)
                        if self._state.backtracking == 0:
                            char_literal185_tree = self._adaptor.createWithPayload(char_literal185)
                            self._adaptor.addChild(root_0, char_literal185_tree)



                        self._state.following.append(self.FOLLOW_member_expr_in_member_list1525)
                        member_expr186 = self.member_expr()

                        self._state.following.pop()
                        if self._state.backtracking == 0:
                            self._adaptor.addChild(root_0, member_expr186.tree)



                    else:
                        break #loop39




                retval.stop = self.input.LT(-1)


                if self._state.backtracking == 0:
                    retval.tree = self._adaptor.rulePostProcessing(root_0)
                    self._adaptor.setTokenBoundaries(retval.tree, retval.start, retval.stop)



            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)
                retval.tree = self._adaptor.errorNode(self.input, retval.start, self.input.LT(-1), re)

        finally:
            pass
        return retval

    # $ANTLR end "member_list"


    class exec_expr_return(ParserRuleReturnScope):
        def __init__(self):
            super(ExprParser.exec_expr_return, self).__init__()

            self.tree = None





    # $ANTLR start "exec_expr"
    # Expr.g:246:1: exec_expr : ( member_expr ( assign_op expr -> ^( ASSIGN member_expr assign_op expr ) | '++' -> ^( POST_INC member_expr ) | '--' -> ^( POST_DEC member_expr ) | -> member_expr ) | '++' member_expr -> ^( PRE_INC member_expr ) | '--' member_expr -> ^( PRE_DEC member_expr ) );
    def exec_expr(self, ):
        retval = self.exec_expr_return()
        retval.start = self.input.LT(1)


        root_0 = None

        string_literal190 = None
        string_literal191 = None
        string_literal192 = None
        string_literal194 = None
        member_expr187 = None
        assign_op188 = None
        expr189 = None
        member_expr193 = None
        member_expr195 = None

        string_literal190_tree = None
        string_literal191_tree = None
        string_literal192_tree = None
        string_literal194_tree = None
        stream_80 = RewriteRuleTokenStream(self._adaptor, "token 80")
        stream_84 = RewriteRuleTokenStream(self._adaptor, "token 84")
        stream_member_expr = RewriteRuleSubtreeStream(self._adaptor, "rule member_expr")
        stream_expr = RewriteRuleSubtreeStream(self._adaptor, "rule expr")
        stream_assign_op = RewriteRuleSubtreeStream(self._adaptor, "rule assign_op")
        try:
            try:
                # Expr.g:247:2: ( member_expr ( assign_op expr -> ^( ASSIGN member_expr assign_op expr ) | '++' -> ^( POST_INC member_expr ) | '--' -> ^( POST_DEC member_expr ) | -> member_expr ) | '++' member_expr -> ^( PRE_INC member_expr ) | '--' member_expr -> ^( PRE_DEC member_expr ) )
                alt41 = 3
                LA41 = self.input.LA(1)
                if LA41 == ID:
                    alt41 = 1
                elif LA41 == 80:
                    alt41 = 2
                elif LA41 == 84:
                    alt41 = 3
                else:
                    if self._state.backtracking > 0:
                        raise BacktrackingFailed


                    nvae = NoViableAltException("", 41, 0, self.input)

                    raise nvae


                if alt41 == 1:
                    # Expr.g:247:4: member_expr ( assign_op expr -> ^( ASSIGN member_expr assign_op expr ) | '++' -> ^( POST_INC member_expr ) | '--' -> ^( POST_DEC member_expr ) | -> member_expr )
                    pass 
                    self._state.following.append(self.FOLLOW_member_expr_in_exec_expr1537)
                    member_expr187 = self.member_expr()

                    self._state.following.pop()
                    if self._state.backtracking == 0:
                        stream_member_expr.add(member_expr187.tree)


                    # Expr.g:248:3: ( assign_op expr -> ^( ASSIGN member_expr assign_op expr ) | '++' -> ^( POST_INC member_expr ) | '--' -> ^( POST_DEC member_expr ) | -> member_expr )
                    alt40 = 4
                    LA40 = self.input.LA(1)
                    if LA40 == 71 or LA40 == 74 or LA40 == 78 or LA40 == 81 or LA40 == 85 or LA40 == 90 or LA40 == 95 or LA40 == 103 or LA40 == 134:
                        alt40 = 1
                    elif LA40 == 80:
                        alt40 = 2
                    elif LA40 == 84:
                        alt40 = 3
                    elif LA40 == 76 or LA40 == 82 or LA40 == 92:
                        alt40 = 4
                    else:
                        if self._state.backtracking > 0:
                            raise BacktrackingFailed


                        nvae = NoViableAltException("", 40, 0, self.input)

                        raise nvae


                    if alt40 == 1:
                        # Expr.g:248:4: assign_op expr
                        pass 
                        self._state.following.append(self.FOLLOW_assign_op_in_exec_expr1542)
                        assign_op188 = self.assign_op()

                        self._state.following.pop()
                        if self._state.backtracking == 0:
                            stream_assign_op.add(assign_op188.tree)


                        self._state.following.append(self.FOLLOW_expr_in_exec_expr1544)
                        expr189 = self.expr()

                        self._state.following.pop()
                        if self._state.backtracking == 0:
                            stream_expr.add(expr189.tree)


                        # AST Rewrite
                        # elements: member_expr, expr, assign_op
                        # token labels: 
                        # rule labels: retval
                        # token list labels: 
                        # rule list labels: 
                        # wildcard labels: 
                        if self._state.backtracking == 0:
                            retval.tree = root_0
                            if retval is not None:
                                stream_retval = RewriteRuleSubtreeStream(self._adaptor, "rule retval", retval.tree)
                            else:
                                stream_retval = RewriteRuleSubtreeStream(self._adaptor, "token retval", None)


                            root_0 = self._adaptor.nil()
                            # 249:4: -> ^( ASSIGN member_expr assign_op expr )
                            # Expr.g:249:7: ^( ASSIGN member_expr assign_op expr )
                            root_1 = self._adaptor.nil()
                            root_1 = self._adaptor.becomeRoot(
                            self._adaptor.createFromType(ASSIGN, "ASSIGN")
                            , root_1)

                            self._adaptor.addChild(root_1, stream_member_expr.nextTree())

                            self._adaptor.addChild(root_1, stream_assign_op.nextTree())

                            self._adaptor.addChild(root_1, stream_expr.nextTree())

                            self._adaptor.addChild(root_0, root_1)




                            retval.tree = root_0




                    elif alt40 == 2:
                        # Expr.g:250:5: '++'
                        pass 
                        string_literal190 = self.match(self.input, 80, self.FOLLOW_80_in_exec_expr1565) 
                        if self._state.backtracking == 0:
                            stream_80.add(string_literal190)


                        # AST Rewrite
                        # elements: member_expr
                        # token labels: 
                        # rule labels: retval
                        # token list labels: 
                        # rule list labels: 
                        # wildcard labels: 
                        if self._state.backtracking == 0:
                            retval.tree = root_0
                            if retval is not None:
                                stream_retval = RewriteRuleSubtreeStream(self._adaptor, "rule retval", retval.tree)
                            else:
                                stream_retval = RewriteRuleSubtreeStream(self._adaptor, "token retval", None)


                            root_0 = self._adaptor.nil()
                            # 251:4: -> ^( POST_INC member_expr )
                            # Expr.g:251:7: ^( POST_INC member_expr )
                            root_1 = self._adaptor.nil()
                            root_1 = self._adaptor.becomeRoot(
                            self._adaptor.createFromType(POST_INC, "POST_INC")
                            , root_1)

                            self._adaptor.addChild(root_1, stream_member_expr.nextTree())

                            self._adaptor.addChild(root_0, root_1)




                            retval.tree = root_0




                    elif alt40 == 3:
                        # Expr.g:252:5: '--'
                        pass 
                        string_literal191 = self.match(self.input, 84, self.FOLLOW_84_in_exec_expr1582) 
                        if self._state.backtracking == 0:
                            stream_84.add(string_literal191)


                        # AST Rewrite
                        # elements: member_expr
                        # token labels: 
                        # rule labels: retval
                        # token list labels: 
                        # rule list labels: 
                        # wildcard labels: 
                        if self._state.backtracking == 0:
                            retval.tree = root_0
                            if retval is not None:
                                stream_retval = RewriteRuleSubtreeStream(self._adaptor, "rule retval", retval.tree)
                            else:
                                stream_retval = RewriteRuleSubtreeStream(self._adaptor, "token retval", None)


                            root_0 = self._adaptor.nil()
                            # 253:4: -> ^( POST_DEC member_expr )
                            # Expr.g:253:7: ^( POST_DEC member_expr )
                            root_1 = self._adaptor.nil()
                            root_1 = self._adaptor.becomeRoot(
                            self._adaptor.createFromType(POST_DEC, "POST_DEC")
                            , root_1)

                            self._adaptor.addChild(root_1, stream_member_expr.nextTree())

                            self._adaptor.addChild(root_0, root_1)




                            retval.tree = root_0




                    elif alt40 == 4:
                        # Expr.g:255:4: 
                        pass 
                        # AST Rewrite
                        # elements: member_expr
                        # token labels: 
                        # rule labels: retval
                        # token list labels: 
                        # rule list labels: 
                        # wildcard labels: 
                        if self._state.backtracking == 0:
                            retval.tree = root_0
                            if retval is not None:
                                stream_retval = RewriteRuleSubtreeStream(self._adaptor, "rule retval", retval.tree)
                            else:
                                stream_retval = RewriteRuleSubtreeStream(self._adaptor, "token retval", None)


                            root_0 = self._adaptor.nil()
                            # 255:4: -> member_expr
                            self._adaptor.addChild(root_0, stream_member_expr.nextTree())




                            retval.tree = root_0







                elif alt41 == 2:
                    # Expr.g:257:4: '++' member_expr
                    pass 
                    string_literal192 = self.match(self.input, 80, self.FOLLOW_80_in_exec_expr1613) 
                    if self._state.backtracking == 0:
                        stream_80.add(string_literal192)


                    self._state.following.append(self.FOLLOW_member_expr_in_exec_expr1615)
                    member_expr193 = self.member_expr()

                    self._state.following.pop()
                    if self._state.backtracking == 0:
                        stream_member_expr.add(member_expr193.tree)


                    # AST Rewrite
                    # elements: member_expr
                    # token labels: 
                    # rule labels: retval
                    # token list labels: 
                    # rule list labels: 
                    # wildcard labels: 
                    if self._state.backtracking == 0:
                        retval.tree = root_0
                        if retval is not None:
                            stream_retval = RewriteRuleSubtreeStream(self._adaptor, "rule retval", retval.tree)
                        else:
                            stream_retval = RewriteRuleSubtreeStream(self._adaptor, "token retval", None)


                        root_0 = self._adaptor.nil()
                        # 258:3: -> ^( PRE_INC member_expr )
                        # Expr.g:258:6: ^( PRE_INC member_expr )
                        root_1 = self._adaptor.nil()
                        root_1 = self._adaptor.becomeRoot(
                        self._adaptor.createFromType(PRE_INC, "PRE_INC")
                        , root_1)

                        self._adaptor.addChild(root_1, stream_member_expr.nextTree())

                        self._adaptor.addChild(root_0, root_1)




                        retval.tree = root_0




                elif alt41 == 3:
                    # Expr.g:259:4: '--' member_expr
                    pass 
                    string_literal194 = self.match(self.input, 84, self.FOLLOW_84_in_exec_expr1630) 
                    if self._state.backtracking == 0:
                        stream_84.add(string_literal194)


                    self._state.following.append(self.FOLLOW_member_expr_in_exec_expr1632)
                    member_expr195 = self.member_expr()

                    self._state.following.pop()
                    if self._state.backtracking == 0:
                        stream_member_expr.add(member_expr195.tree)


                    # AST Rewrite
                    # elements: member_expr
                    # token labels: 
                    # rule labels: retval
                    # token list labels: 
                    # rule list labels: 
                    # wildcard labels: 
                    if self._state.backtracking == 0:
                        retval.tree = root_0
                        if retval is not None:
                            stream_retval = RewriteRuleSubtreeStream(self._adaptor, "rule retval", retval.tree)
                        else:
                            stream_retval = RewriteRuleSubtreeStream(self._adaptor, "token retval", None)


                        root_0 = self._adaptor.nil()
                        # 260:3: -> ^( PRE_DEC member_expr )
                        # Expr.g:260:6: ^( PRE_DEC member_expr )
                        root_1 = self._adaptor.nil()
                        root_1 = self._adaptor.becomeRoot(
                        self._adaptor.createFromType(PRE_DEC, "PRE_DEC")
                        , root_1)

                        self._adaptor.addChild(root_1, stream_member_expr.nextTree())

                        self._adaptor.addChild(root_0, root_1)




                        retval.tree = root_0




                retval.stop = self.input.LT(-1)


                if self._state.backtracking == 0:
                    retval.tree = self._adaptor.rulePostProcessing(root_0)
                    self._adaptor.setTokenBoundaries(retval.tree, retval.start, retval.stop)



            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)
                retval.tree = self._adaptor.errorNode(self.input, retval.start, self.input.LT(-1), re)

        finally:
            pass
        return retval

    # $ANTLR end "exec_expr"


    class assign_op_return(ParserRuleReturnScope):
        def __init__(self):
            super(ExprParser.assign_op_return, self).__init__()

            self.tree = None





    # $ANTLR start "assign_op"
    # Expr.g:262:1: assign_op : ( '=' | '+=' | '-=' | '*=' | '/=' | '%=' | '&=' | '^=' | '|=' );
    def assign_op(self, ):
        retval = self.assign_op_return()
        retval.start = self.input.LT(1)


        root_0 = None

        set196 = None

        set196_tree = None

        try:
            try:
                # Expr.g:263:2: ( '=' | '+=' | '-=' | '*=' | '/=' | '%=' | '&=' | '^=' | '|=' )
                # Expr.g:
                pass 
                root_0 = self._adaptor.nil()


                set196 = self.input.LT(1)

                if self.input.LA(1) == 71 or self.input.LA(1) == 74 or self.input.LA(1) == 78 or self.input.LA(1) == 81 or self.input.LA(1) == 85 or self.input.LA(1) == 90 or self.input.LA(1) == 95 or self.input.LA(1) == 103 or self.input.LA(1) == 134:
                    self.input.consume()
                    if self._state.backtracking == 0:
                        self._adaptor.addChild(root_0, self._adaptor.createWithPayload(set196))

                    self._state.errorRecovery = False


                else:
                    if self._state.backtracking > 0:
                        raise BacktrackingFailed


                    mse = MismatchedSetException(None, self.input)
                    raise mse





                retval.stop = self.input.LT(-1)


                if self._state.backtracking == 0:
                    retval.tree = self._adaptor.rulePostProcessing(root_0)
                    self._adaptor.setTokenBoundaries(retval.tree, retval.start, retval.stop)



            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)
                retval.tree = self._adaptor.errorNode(self.input, retval.start, self.input.LT(-1), re)

        finally:
            pass
        return retval

    # $ANTLR end "assign_op"


    class exec_stmt_return(ParserRuleReturnScope):
        def __init__(self):
            super(ExprParser.exec_stmt_return, self).__init__()

            self.tree = None





    # $ANTLR start "exec_stmt"
    # Expr.g:265:1: exec_stmt : exec_list ';' -> ^( EXEC_STMT exec_list ) ;
    def exec_stmt(self, ):
        retval = self.exec_stmt_return()
        retval.start = self.input.LT(1)


        root_0 = None

        char_literal198 = None
        exec_list197 = None

        char_literal198_tree = None
        stream_92 = RewriteRuleTokenStream(self._adaptor, "token 92")
        stream_exec_list = RewriteRuleSubtreeStream(self._adaptor, "rule exec_list")
        try:
            try:
                # Expr.g:266:2: ( exec_list ';' -> ^( EXEC_STMT exec_list ) )
                # Expr.g:266:4: exec_list ';'
                pass 
                self._state.following.append(self.FOLLOW_exec_list_in_exec_stmt1678)
                exec_list197 = self.exec_list()

                self._state.following.pop()
                if self._state.backtracking == 0:
                    stream_exec_list.add(exec_list197.tree)


                char_literal198 = self.match(self.input, 92, self.FOLLOW_92_in_exec_stmt1680) 
                if self._state.backtracking == 0:
                    stream_92.add(char_literal198)


                # AST Rewrite
                # elements: exec_list
                # token labels: 
                # rule labels: retval
                # token list labels: 
                # rule list labels: 
                # wildcard labels: 
                if self._state.backtracking == 0:
                    retval.tree = root_0
                    if retval is not None:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "rule retval", retval.tree)
                    else:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "token retval", None)


                    root_0 = self._adaptor.nil()
                    # 267:3: -> ^( EXEC_STMT exec_list )
                    # Expr.g:267:6: ^( EXEC_STMT exec_list )
                    root_1 = self._adaptor.nil()
                    root_1 = self._adaptor.becomeRoot(
                    self._adaptor.createFromType(EXEC_STMT, "EXEC_STMT")
                    , root_1)

                    self._adaptor.addChild(root_1, stream_exec_list.nextTree())

                    self._adaptor.addChild(root_0, root_1)




                    retval.tree = root_0





                retval.stop = self.input.LT(-1)


                if self._state.backtracking == 0:
                    retval.tree = self._adaptor.rulePostProcessing(root_0)
                    self._adaptor.setTokenBoundaries(retval.tree, retval.start, retval.stop)



            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)
                retval.tree = self._adaptor.errorNode(self.input, retval.start, self.input.LT(-1), re)

        finally:
            pass
        return retval

    # $ANTLR end "exec_stmt"


    class expr_list_return(ParserRuleReturnScope):
        def __init__(self):
            super(ExprParser.expr_list_return, self).__init__()

            self.tree = None





    # $ANTLR start "expr_list"
    # Expr.g:272:1: expr_list : expr ( ',' expr )* ( ',' )? -> ^( EXPR_LIST ( expr )+ ) ;
    def expr_list(self, ):
        retval = self.expr_list_return()
        retval.start = self.input.LT(1)


        root_0 = None

        char_literal200 = None
        char_literal202 = None
        expr199 = None
        expr201 = None

        char_literal200_tree = None
        char_literal202_tree = None
        stream_82 = RewriteRuleTokenStream(self._adaptor, "token 82")
        stream_expr = RewriteRuleSubtreeStream(self._adaptor, "rule expr")
        try:
            try:
                # Expr.g:273:2: ( expr ( ',' expr )* ( ',' )? -> ^( EXPR_LIST ( expr )+ ) )
                # Expr.g:273:4: expr ( ',' expr )* ( ',' )?
                pass 
                self._state.following.append(self.FOLLOW_expr_in_expr_list1703)
                expr199 = self.expr()

                self._state.following.pop()
                if self._state.backtracking == 0:
                    stream_expr.add(expr199.tree)


                # Expr.g:273:9: ( ',' expr )*
                while True: #loop42
                    alt42 = 2
                    LA42_0 = self.input.LA(1)

                    if (LA42_0 == 82) :
                        LA42_1 = self.input.LA(2)

                        if (LA42_1 == BOOL or LA42_1 == FLOAT or LA42_1 == ID or LA42_1 == INT or LA42_1 == NULL or LA42_1 == STRING or LA42_1 == 68 or LA42_1 == 75 or LA42_1 == 83 or LA42_1 == 100 or LA42_1 == 121 or LA42_1 == 126 or LA42_1 == 132) :
                            alt42 = 1




                    if alt42 == 1:
                        # Expr.g:273:10: ',' expr
                        pass 
                        char_literal200 = self.match(self.input, 82, self.FOLLOW_82_in_expr_list1706) 
                        if self._state.backtracking == 0:
                            stream_82.add(char_literal200)


                        self._state.following.append(self.FOLLOW_expr_in_expr_list1708)
                        expr201 = self.expr()

                        self._state.following.pop()
                        if self._state.backtracking == 0:
                            stream_expr.add(expr201.tree)



                    else:
                        break #loop42


                # Expr.g:273:21: ( ',' )?
                alt43 = 2
                LA43_0 = self.input.LA(1)

                if (LA43_0 == 82) :
                    alt43 = 1
                if alt43 == 1:
                    # Expr.g:273:21: ','
                    pass 
                    char_literal202 = self.match(self.input, 82, self.FOLLOW_82_in_expr_list1712) 
                    if self._state.backtracking == 0:
                        stream_82.add(char_literal202)





                # AST Rewrite
                # elements: expr
                # token labels: 
                # rule labels: retval
                # token list labels: 
                # rule list labels: 
                # wildcard labels: 
                if self._state.backtracking == 0:
                    retval.tree = root_0
                    if retval is not None:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "rule retval", retval.tree)
                    else:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "token retval", None)


                    root_0 = self._adaptor.nil()
                    # 274:3: -> ^( EXPR_LIST ( expr )+ )
                    # Expr.g:274:6: ^( EXPR_LIST ( expr )+ )
                    root_1 = self._adaptor.nil()
                    root_1 = self._adaptor.becomeRoot(
                    self._adaptor.createFromType(EXPR_LIST, "EXPR_LIST")
                    , root_1)

                    # Expr.g:274:18: ( expr )+
                    if not (stream_expr.hasNext()):
                        raise RewriteEarlyExitException()

                    while stream_expr.hasNext():
                        self._adaptor.addChild(root_1, stream_expr.nextTree())


                    stream_expr.reset()

                    self._adaptor.addChild(root_0, root_1)




                    retval.tree = root_0





                retval.stop = self.input.LT(-1)


                if self._state.backtracking == 0:
                    retval.tree = self._adaptor.rulePostProcessing(root_0)
                    self._adaptor.setTokenBoundaries(retval.tree, retval.start, retval.stop)



            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)
                retval.tree = self._adaptor.errorNode(self.input, retval.start, self.input.LT(-1), re)

        finally:
            pass
        return retval

    # $ANTLR end "expr_list"


    class expr_return(ParserRuleReturnScope):
        def __init__(self):
            super(ExprParser.expr_return, self).__init__()

            self.tree = None





    # $ANTLR start "expr"
    # Expr.g:276:1: expr : logic_or_expr ;
    def expr(self, ):
        retval = self.expr_return()
        retval.start = self.input.LT(1)


        root_0 = None

        logic_or_expr203 = None


        try:
            try:
                # Expr.g:277:2: ( logic_or_expr )
                # Expr.g:277:4: logic_or_expr
                pass 
                root_0 = self._adaptor.nil()


                self._state.following.append(self.FOLLOW_logic_or_expr_in_expr1734)
                logic_or_expr203 = self.logic_or_expr()

                self._state.following.pop()
                if self._state.backtracking == 0:
                    self._adaptor.addChild(root_0, logic_or_expr203.tree)




                retval.stop = self.input.LT(-1)


                if self._state.backtracking == 0:
                    retval.tree = self._adaptor.rulePostProcessing(root_0)
                    self._adaptor.setTokenBoundaries(retval.tree, retval.start, retval.stop)



            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)
                retval.tree = self._adaptor.errorNode(self.input, retval.start, self.input.LT(-1), re)

        finally:
            pass
        return retval

    # $ANTLR end "expr"


    class logic_or_expr_return(ParserRuleReturnScope):
        def __init__(self):
            super(ExprParser.logic_or_expr_return, self).__init__()

            self.tree = None





    # $ANTLR start "logic_or_expr"
    # Expr.g:279:1: logic_or_expr : logic_and_expr ( '||' ^ logic_and_expr )* ;
    def logic_or_expr(self, ):
        retval = self.logic_or_expr_return()
        retval.start = self.input.LT(1)


        root_0 = None

        string_literal205 = None
        logic_and_expr204 = None
        logic_and_expr206 = None

        string_literal205_tree = None

        try:
            try:
                # Expr.g:280:2: ( logic_and_expr ( '||' ^ logic_and_expr )* )
                # Expr.g:280:4: logic_and_expr ( '||' ^ logic_and_expr )*
                pass 
                root_0 = self._adaptor.nil()


                self._state.following.append(self.FOLLOW_logic_and_expr_in_logic_or_expr1744)
                logic_and_expr204 = self.logic_and_expr()

                self._state.following.pop()
                if self._state.backtracking == 0:
                    self._adaptor.addChild(root_0, logic_and_expr204.tree)


                # Expr.g:280:19: ( '||' ^ logic_and_expr )*
                while True: #loop44
                    alt44 = 2
                    LA44_0 = self.input.LA(1)

                    if (LA44_0 == 135) :
                        alt44 = 1


                    if alt44 == 1:
                        # Expr.g:280:20: '||' ^ logic_and_expr
                        pass 
                        string_literal205 = self.match(self.input, 135, self.FOLLOW_135_in_logic_or_expr1747)
                        if self._state.backtracking == 0:
                            string_literal205_tree = self._adaptor.createWithPayload(string_literal205)
                            root_0 = self._adaptor.becomeRoot(string_literal205_tree, root_0)



                        self._state.following.append(self.FOLLOW_logic_and_expr_in_logic_or_expr1750)
                        logic_and_expr206 = self.logic_and_expr()

                        self._state.following.pop()
                        if self._state.backtracking == 0:
                            self._adaptor.addChild(root_0, logic_and_expr206.tree)



                    else:
                        break #loop44




                retval.stop = self.input.LT(-1)


                if self._state.backtracking == 0:
                    retval.tree = self._adaptor.rulePostProcessing(root_0)
                    self._adaptor.setTokenBoundaries(retval.tree, retval.start, retval.stop)



            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)
                retval.tree = self._adaptor.errorNode(self.input, retval.start, self.input.LT(-1), re)

        finally:
            pass
        return retval

    # $ANTLR end "logic_or_expr"


    class logic_and_expr_return(ParserRuleReturnScope):
        def __init__(self):
            super(ExprParser.logic_and_expr_return, self).__init__()

            self.tree = None





    # $ANTLR start "logic_and_expr"
    # Expr.g:282:1: logic_and_expr : bitwise_or_expr ( '&&' ^ bitwise_or_expr )* ;
    def logic_and_expr(self, ):
        retval = self.logic_and_expr_return()
        retval.start = self.input.LT(1)


        root_0 = None

        string_literal208 = None
        bitwise_or_expr207 = None
        bitwise_or_expr209 = None

        string_literal208_tree = None

        try:
            try:
                # Expr.g:283:2: ( bitwise_or_expr ( '&&' ^ bitwise_or_expr )* )
                # Expr.g:283:4: bitwise_or_expr ( '&&' ^ bitwise_or_expr )*
                pass 
                root_0 = self._adaptor.nil()


                self._state.following.append(self.FOLLOW_bitwise_or_expr_in_logic_and_expr1762)
                bitwise_or_expr207 = self.bitwise_or_expr()

                self._state.following.pop()
                if self._state.backtracking == 0:
                    self._adaptor.addChild(root_0, bitwise_or_expr207.tree)


                # Expr.g:283:20: ( '&&' ^ bitwise_or_expr )*
                while True: #loop45
                    alt45 = 2
                    LA45_0 = self.input.LA(1)

                    if (LA45_0 == 72) :
                        alt45 = 1


                    if alt45 == 1:
                        # Expr.g:283:21: '&&' ^ bitwise_or_expr
                        pass 
                        string_literal208 = self.match(self.input, 72, self.FOLLOW_72_in_logic_and_expr1765)
                        if self._state.backtracking == 0:
                            string_literal208_tree = self._adaptor.createWithPayload(string_literal208)
                            root_0 = self._adaptor.becomeRoot(string_literal208_tree, root_0)



                        self._state.following.append(self.FOLLOW_bitwise_or_expr_in_logic_and_expr1768)
                        bitwise_or_expr209 = self.bitwise_or_expr()

                        self._state.following.pop()
                        if self._state.backtracking == 0:
                            self._adaptor.addChild(root_0, bitwise_or_expr209.tree)



                    else:
                        break #loop45




                retval.stop = self.input.LT(-1)


                if self._state.backtracking == 0:
                    retval.tree = self._adaptor.rulePostProcessing(root_0)
                    self._adaptor.setTokenBoundaries(retval.tree, retval.start, retval.stop)



            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)
                retval.tree = self._adaptor.errorNode(self.input, retval.start, self.input.LT(-1), re)

        finally:
            pass
        return retval

    # $ANTLR end "logic_and_expr"


    class bitwise_or_expr_return(ParserRuleReturnScope):
        def __init__(self):
            super(ExprParser.bitwise_or_expr_return, self).__init__()

            self.tree = None





    # $ANTLR start "bitwise_or_expr"
    # Expr.g:285:1: bitwise_or_expr : bitwise_xor_expr ( '|' ^ bitwise_xor_expr )* ;
    def bitwise_or_expr(self, ):
        retval = self.bitwise_or_expr_return()
        retval.start = self.input.LT(1)


        root_0 = None

        char_literal211 = None
        bitwise_xor_expr210 = None
        bitwise_xor_expr212 = None

        char_literal211_tree = None

        try:
            try:
                # Expr.g:286:2: ( bitwise_xor_expr ( '|' ^ bitwise_xor_expr )* )
                # Expr.g:286:4: bitwise_xor_expr ( '|' ^ bitwise_xor_expr )*
                pass 
                root_0 = self._adaptor.nil()


                self._state.following.append(self.FOLLOW_bitwise_xor_expr_in_bitwise_or_expr1780)
                bitwise_xor_expr210 = self.bitwise_xor_expr()

                self._state.following.pop()
                if self._state.backtracking == 0:
                    self._adaptor.addChild(root_0, bitwise_xor_expr210.tree)


                # Expr.g:286:21: ( '|' ^ bitwise_xor_expr )*
                while True: #loop46
                    alt46 = 2
                    LA46_0 = self.input.LA(1)

                    if (LA46_0 == 133) :
                        alt46 = 1


                    if alt46 == 1:
                        # Expr.g:286:22: '|' ^ bitwise_xor_expr
                        pass 
                        char_literal211 = self.match(self.input, 133, self.FOLLOW_133_in_bitwise_or_expr1783)
                        if self._state.backtracking == 0:
                            char_literal211_tree = self._adaptor.createWithPayload(char_literal211)
                            root_0 = self._adaptor.becomeRoot(char_literal211_tree, root_0)



                        self._state.following.append(self.FOLLOW_bitwise_xor_expr_in_bitwise_or_expr1786)
                        bitwise_xor_expr212 = self.bitwise_xor_expr()

                        self._state.following.pop()
                        if self._state.backtracking == 0:
                            self._adaptor.addChild(root_0, bitwise_xor_expr212.tree)



                    else:
                        break #loop46




                retval.stop = self.input.LT(-1)


                if self._state.backtracking == 0:
                    retval.tree = self._adaptor.rulePostProcessing(root_0)
                    self._adaptor.setTokenBoundaries(retval.tree, retval.start, retval.stop)



            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)
                retval.tree = self._adaptor.errorNode(self.input, retval.start, self.input.LT(-1), re)

        finally:
            pass
        return retval

    # $ANTLR end "bitwise_or_expr"


    class bitwise_xor_expr_return(ParserRuleReturnScope):
        def __init__(self):
            super(ExprParser.bitwise_xor_expr_return, self).__init__()

            self.tree = None





    # $ANTLR start "bitwise_xor_expr"
    # Expr.g:288:1: bitwise_xor_expr : bitwise_and_expr ( '^' ^ bitwise_and_expr )* ;
    def bitwise_xor_expr(self, ):
        retval = self.bitwise_xor_expr_return()
        retval.start = self.input.LT(1)


        root_0 = None

        char_literal214 = None
        bitwise_and_expr213 = None
        bitwise_and_expr215 = None

        char_literal214_tree = None

        try:
            try:
                # Expr.g:289:2: ( bitwise_and_expr ( '^' ^ bitwise_and_expr )* )
                # Expr.g:289:4: bitwise_and_expr ( '^' ^ bitwise_and_expr )*
                pass 
                root_0 = self._adaptor.nil()


                self._state.following.append(self.FOLLOW_bitwise_and_expr_in_bitwise_xor_expr1798)
                bitwise_and_expr213 = self.bitwise_and_expr()

                self._state.following.pop()
                if self._state.backtracking == 0:
                    self._adaptor.addChild(root_0, bitwise_and_expr213.tree)


                # Expr.g:289:21: ( '^' ^ bitwise_and_expr )*
                while True: #loop47
                    alt47 = 2
                    LA47_0 = self.input.LA(1)

                    if (LA47_0 == 102) :
                        alt47 = 1


                    if alt47 == 1:
                        # Expr.g:289:22: '^' ^ bitwise_and_expr
                        pass 
                        char_literal214 = self.match(self.input, 102, self.FOLLOW_102_in_bitwise_xor_expr1801)
                        if self._state.backtracking == 0:
                            char_literal214_tree = self._adaptor.createWithPayload(char_literal214)
                            root_0 = self._adaptor.becomeRoot(char_literal214_tree, root_0)



                        self._state.following.append(self.FOLLOW_bitwise_and_expr_in_bitwise_xor_expr1804)
                        bitwise_and_expr215 = self.bitwise_and_expr()

                        self._state.following.pop()
                        if self._state.backtracking == 0:
                            self._adaptor.addChild(root_0, bitwise_and_expr215.tree)



                    else:
                        break #loop47




                retval.stop = self.input.LT(-1)


                if self._state.backtracking == 0:
                    retval.tree = self._adaptor.rulePostProcessing(root_0)
                    self._adaptor.setTokenBoundaries(retval.tree, retval.start, retval.stop)



            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)
                retval.tree = self._adaptor.errorNode(self.input, retval.start, self.input.LT(-1), re)

        finally:
            pass
        return retval

    # $ANTLR end "bitwise_xor_expr"


    class bitwise_and_expr_return(ParserRuleReturnScope):
        def __init__(self):
            super(ExprParser.bitwise_and_expr_return, self).__init__()

            self.tree = None





    # $ANTLR start "bitwise_and_expr"
    # Expr.g:291:1: bitwise_and_expr : relation_expr ( '&' ^ relation_expr )* ;
    def bitwise_and_expr(self, ):
        retval = self.bitwise_and_expr_return()
        retval.start = self.input.LT(1)


        root_0 = None

        char_literal217 = None
        relation_expr216 = None
        relation_expr218 = None

        char_literal217_tree = None

        try:
            try:
                # Expr.g:292:2: ( relation_expr ( '&' ^ relation_expr )* )
                # Expr.g:292:4: relation_expr ( '&' ^ relation_expr )*
                pass 
                root_0 = self._adaptor.nil()


                self._state.following.append(self.FOLLOW_relation_expr_in_bitwise_and_expr1816)
                relation_expr216 = self.relation_expr()

                self._state.following.pop()
                if self._state.backtracking == 0:
                    self._adaptor.addChild(root_0, relation_expr216.tree)


                # Expr.g:292:18: ( '&' ^ relation_expr )*
                while True: #loop48
                    alt48 = 2
                    LA48_0 = self.input.LA(1)

                    if (LA48_0 == 73) :
                        alt48 = 1


                    if alt48 == 1:
                        # Expr.g:292:19: '&' ^ relation_expr
                        pass 
                        char_literal217 = self.match(self.input, 73, self.FOLLOW_73_in_bitwise_and_expr1819)
                        if self._state.backtracking == 0:
                            char_literal217_tree = self._adaptor.createWithPayload(char_literal217)
                            root_0 = self._adaptor.becomeRoot(char_literal217_tree, root_0)



                        self._state.following.append(self.FOLLOW_relation_expr_in_bitwise_and_expr1822)
                        relation_expr218 = self.relation_expr()

                        self._state.following.pop()
                        if self._state.backtracking == 0:
                            self._adaptor.addChild(root_0, relation_expr218.tree)



                    else:
                        break #loop48




                retval.stop = self.input.LT(-1)


                if self._state.backtracking == 0:
                    retval.tree = self._adaptor.rulePostProcessing(root_0)
                    self._adaptor.setTokenBoundaries(retval.tree, retval.start, retval.stop)



            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)
                retval.tree = self._adaptor.errorNode(self.input, retval.start, self.input.LT(-1), re)

        finally:
            pass
        return retval

    # $ANTLR end "bitwise_and_expr"


    class relation_expr_return(ParserRuleReturnScope):
        def __init__(self):
            super(ExprParser.relation_expr_return, self).__init__()

            self.tree = None





    # $ANTLR start "relation_expr"
    # Expr.g:294:1: relation_expr : add_expr ( ( '<' | '>' | '<=' | '>=' | '==' | '!=' ) ^ add_expr )? ;
    def relation_expr(self, ):
        retval = self.relation_expr_return()
        retval.start = self.input.LT(1)


        root_0 = None

        set220 = None
        add_expr219 = None
        add_expr221 = None

        set220_tree = None

        try:
            try:
                # Expr.g:295:2: ( add_expr ( ( '<' | '>' | '<=' | '>=' | '==' | '!=' ) ^ add_expr )? )
                # Expr.g:295:4: add_expr ( ( '<' | '>' | '<=' | '>=' | '==' | '!=' ) ^ add_expr )?
                pass 
                root_0 = self._adaptor.nil()


                self._state.following.append(self.FOLLOW_add_expr_in_relation_expr1834)
                add_expr219 = self.add_expr()

                self._state.following.pop()
                if self._state.backtracking == 0:
                    self._adaptor.addChild(root_0, add_expr219.tree)


                # Expr.g:295:13: ( ( '<' | '>' | '<=' | '>=' | '==' | '!=' ) ^ add_expr )?
                alt49 = 2
                LA49_0 = self.input.LA(1)

                if (LA49_0 == 69 or (93 <= LA49_0 <= 94) or LA49_0 == 96 or (98 <= LA49_0 <= 99)) :
                    alt49 = 1
                if alt49 == 1:
                    # Expr.g:295:14: ( '<' | '>' | '<=' | '>=' | '==' | '!=' ) ^ add_expr
                    pass 
                    set220 = self.input.LT(1)

                    set220 = self.input.LT(1)

                    if self.input.LA(1) == 69 or (93 <= self.input.LA(1) <= 94) or self.input.LA(1) == 96 or (98 <= self.input.LA(1) <= 99):
                        self.input.consume()
                        if self._state.backtracking == 0:
                            root_0 = self._adaptor.becomeRoot(self._adaptor.createWithPayload(set220), root_0)

                        self._state.errorRecovery = False


                    else:
                        if self._state.backtracking > 0:
                            raise BacktrackingFailed


                        mse = MismatchedSetException(None, self.input)
                        raise mse



                    self._state.following.append(self.FOLLOW_add_expr_in_relation_expr1852)
                    add_expr221 = self.add_expr()

                    self._state.following.pop()
                    if self._state.backtracking == 0:
                        self._adaptor.addChild(root_0, add_expr221.tree)







                retval.stop = self.input.LT(-1)


                if self._state.backtracking == 0:
                    retval.tree = self._adaptor.rulePostProcessing(root_0)
                    self._adaptor.setTokenBoundaries(retval.tree, retval.start, retval.stop)



            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)
                retval.tree = self._adaptor.errorNode(self.input, retval.start, self.input.LT(-1), re)

        finally:
            pass
        return retval

    # $ANTLR end "relation_expr"


    class add_expr_return(ParserRuleReturnScope):
        def __init__(self):
            super(ExprParser.add_expr_return, self).__init__()

            self.tree = None





    # $ANTLR start "add_expr"
    # Expr.g:297:1: add_expr : mul_expr ( ( '+' | '-' ) ^ mul_expr )* ;
    def add_expr(self, ):
        retval = self.add_expr_return()
        retval.start = self.input.LT(1)


        root_0 = None

        set223 = None
        mul_expr222 = None
        mul_expr224 = None

        set223_tree = None

        try:
            try:
                # Expr.g:298:2: ( mul_expr ( ( '+' | '-' ) ^ mul_expr )* )
                # Expr.g:298:4: mul_expr ( ( '+' | '-' ) ^ mul_expr )*
                pass 
                root_0 = self._adaptor.nil()


                self._state.following.append(self.FOLLOW_mul_expr_in_add_expr1864)
                mul_expr222 = self.mul_expr()

                self._state.following.pop()
                if self._state.backtracking == 0:
                    self._adaptor.addChild(root_0, mul_expr222.tree)


                # Expr.g:298:13: ( ( '+' | '-' ) ^ mul_expr )*
                while True: #loop50
                    alt50 = 2
                    LA50_0 = self.input.LA(1)

                    if (LA50_0 == 79 or LA50_0 == 83) :
                        alt50 = 1


                    if alt50 == 1:
                        # Expr.g:298:14: ( '+' | '-' ) ^ mul_expr
                        pass 
                        set223 = self.input.LT(1)

                        set223 = self.input.LT(1)

                        if self.input.LA(1) == 79 or self.input.LA(1) == 83:
                            self.input.consume()
                            if self._state.backtracking == 0:
                                root_0 = self._adaptor.becomeRoot(self._adaptor.createWithPayload(set223), root_0)

                            self._state.errorRecovery = False


                        else:
                            if self._state.backtracking > 0:
                                raise BacktrackingFailed


                            mse = MismatchedSetException(None, self.input)
                            raise mse



                        self._state.following.append(self.FOLLOW_mul_expr_in_add_expr1874)
                        mul_expr224 = self.mul_expr()

                        self._state.following.pop()
                        if self._state.backtracking == 0:
                            self._adaptor.addChild(root_0, mul_expr224.tree)



                    else:
                        break #loop50




                retval.stop = self.input.LT(-1)


                if self._state.backtracking == 0:
                    retval.tree = self._adaptor.rulePostProcessing(root_0)
                    self._adaptor.setTokenBoundaries(retval.tree, retval.start, retval.stop)



            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)
                retval.tree = self._adaptor.errorNode(self.input, retval.start, self.input.LT(-1), re)

        finally:
            pass
        return retval

    # $ANTLR end "add_expr"


    class mul_expr_return(ParserRuleReturnScope):
        def __init__(self):
            super(ExprParser.mul_expr_return, self).__init__()

            self.tree = None





    # $ANTLR start "mul_expr"
    # Expr.g:300:1: mul_expr : not_expr ( ( '*' | '/' | '%' ) ^ not_expr )* ;
    def mul_expr(self, ):
        retval = self.mul_expr_return()
        retval.start = self.input.LT(1)


        root_0 = None

        set226 = None
        not_expr225 = None
        not_expr227 = None

        set226_tree = None

        try:
            try:
                # Expr.g:301:2: ( not_expr ( ( '*' | '/' | '%' ) ^ not_expr )* )
                # Expr.g:301:4: not_expr ( ( '*' | '/' | '%' ) ^ not_expr )*
                pass 
                root_0 = self._adaptor.nil()


                self._state.following.append(self.FOLLOW_not_expr_in_mul_expr1886)
                not_expr225 = self.not_expr()

                self._state.following.pop()
                if self._state.backtracking == 0:
                    self._adaptor.addChild(root_0, not_expr225.tree)


                # Expr.g:301:13: ( ( '*' | '/' | '%' ) ^ not_expr )*
                while True: #loop51
                    alt51 = 2
                    LA51_0 = self.input.LA(1)

                    if (LA51_0 == 70 or LA51_0 == 77 or LA51_0 == 89) :
                        alt51 = 1


                    if alt51 == 1:
                        # Expr.g:301:14: ( '*' | '/' | '%' ) ^ not_expr
                        pass 
                        set226 = self.input.LT(1)

                        set226 = self.input.LT(1)

                        if self.input.LA(1) == 70 or self.input.LA(1) == 77 or self.input.LA(1) == 89:
                            self.input.consume()
                            if self._state.backtracking == 0:
                                root_0 = self._adaptor.becomeRoot(self._adaptor.createWithPayload(set226), root_0)

                            self._state.errorRecovery = False


                        else:
                            if self._state.backtracking > 0:
                                raise BacktrackingFailed


                            mse = MismatchedSetException(None, self.input)
                            raise mse



                        self._state.following.append(self.FOLLOW_not_expr_in_mul_expr1898)
                        not_expr227 = self.not_expr()

                        self._state.following.pop()
                        if self._state.backtracking == 0:
                            self._adaptor.addChild(root_0, not_expr227.tree)



                    else:
                        break #loop51




                retval.stop = self.input.LT(-1)


                if self._state.backtracking == 0:
                    retval.tree = self._adaptor.rulePostProcessing(root_0)
                    self._adaptor.setTokenBoundaries(retval.tree, retval.start, retval.stop)



            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)
                retval.tree = self._adaptor.errorNode(self.input, retval.start, self.input.LT(-1), re)

        finally:
            pass
        return retval

    # $ANTLR end "mul_expr"


    class not_expr_return(ParserRuleReturnScope):
        def __init__(self):
            super(ExprParser.not_expr_return, self).__init__()

            self.tree = None





    # $ANTLR start "not_expr"
    # Expr.g:303:1: not_expr : (op= '!' )? negative_expr -> {$op != None}? ^( '!' negative_expr ) -> negative_expr ;
    def not_expr(self, ):
        retval = self.not_expr_return()
        retval.start = self.input.LT(1)


        root_0 = None

        op = None
        negative_expr228 = None

        op_tree = None
        stream_68 = RewriteRuleTokenStream(self._adaptor, "token 68")
        stream_negative_expr = RewriteRuleSubtreeStream(self._adaptor, "rule negative_expr")
        try:
            try:
                # Expr.g:304:2: ( (op= '!' )? negative_expr -> {$op != None}? ^( '!' negative_expr ) -> negative_expr )
                # Expr.g:304:4: (op= '!' )? negative_expr
                pass 
                # Expr.g:304:6: (op= '!' )?
                alt52 = 2
                LA52_0 = self.input.LA(1)

                if (LA52_0 == 68) :
                    alt52 = 1
                if alt52 == 1:
                    # Expr.g:304:6: op= '!'
                    pass 
                    op = self.match(self.input, 68, self.FOLLOW_68_in_not_expr1912) 
                    if self._state.backtracking == 0:
                        stream_68.add(op)





                self._state.following.append(self.FOLLOW_negative_expr_in_not_expr1915)
                negative_expr228 = self.negative_expr()

                self._state.following.pop()
                if self._state.backtracking == 0:
                    stream_negative_expr.add(negative_expr228.tree)


                # AST Rewrite
                # elements: negative_expr, negative_expr, 68
                # token labels: 
                # rule labels: retval
                # token list labels: 
                # rule list labels: 
                # wildcard labels: 
                if self._state.backtracking == 0:
                    retval.tree = root_0
                    if retval is not None:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "rule retval", retval.tree)
                    else:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "token retval", None)


                    root_0 = self._adaptor.nil()
                    if op != None:
                        # 305:3: -> {$op != None}? ^( '!' negative_expr )
                        # Expr.g:306:4: ^( '!' negative_expr )
                        root_1 = self._adaptor.nil()
                        root_1 = self._adaptor.becomeRoot(
                        stream_68.nextNode()
                        , root_1)

                        self._adaptor.addChild(root_1, stream_negative_expr.nextTree())

                        self._adaptor.addChild(root_0, root_1)



                    else: 
                        # 307:4: -> negative_expr
                        self._adaptor.addChild(root_0, stream_negative_expr.nextTree())



                    retval.tree = root_0





                retval.stop = self.input.LT(-1)


                if self._state.backtracking == 0:
                    retval.tree = self._adaptor.rulePostProcessing(root_0)
                    self._adaptor.setTokenBoundaries(retval.tree, retval.start, retval.stop)



            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)
                retval.tree = self._adaptor.errorNode(self.input, retval.start, self.input.LT(-1), re)

        finally:
            pass
        return retval

    # $ANTLR end "not_expr"


    class negative_expr_return(ParserRuleReturnScope):
        def __init__(self):
            super(ExprParser.negative_expr_return, self).__init__()

            self.tree = None





    # $ANTLR start "negative_expr"
    # Expr.g:309:1: negative_expr : (op= '-' )? atom -> {$op != None}? ^( NEGATIVE atom ) -> atom ;
    def negative_expr(self, ):
        retval = self.negative_expr_return()
        retval.start = self.input.LT(1)


        root_0 = None

        op = None
        atom229 = None

        op_tree = None
        stream_83 = RewriteRuleTokenStream(self._adaptor, "token 83")
        stream_atom = RewriteRuleSubtreeStream(self._adaptor, "rule atom")
        try:
            try:
                # Expr.g:310:2: ( (op= '-' )? atom -> {$op != None}? ^( NEGATIVE atom ) -> atom )
                # Expr.g:310:4: (op= '-' )? atom
                pass 
                # Expr.g:310:4: (op= '-' )?
                alt53 = 2
                LA53_0 = self.input.LA(1)

                if (LA53_0 == 83) :
                    alt53 = 1
                if alt53 == 1:
                    # Expr.g:310:5: op= '-'
                    pass 
                    op = self.match(self.input, 83, self.FOLLOW_83_in_negative_expr1950) 
                    if self._state.backtracking == 0:
                        stream_83.add(op)





                self._state.following.append(self.FOLLOW_atom_in_negative_expr1954)
                atom229 = self.atom()

                self._state.following.pop()
                if self._state.backtracking == 0:
                    stream_atom.add(atom229.tree)


                # AST Rewrite
                # elements: atom, atom
                # token labels: 
                # rule labels: retval
                # token list labels: 
                # rule list labels: 
                # wildcard labels: 
                if self._state.backtracking == 0:
                    retval.tree = root_0
                    if retval is not None:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "rule retval", retval.tree)
                    else:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "token retval", None)


                    root_0 = self._adaptor.nil()
                    if op != None:
                        # 311:3: -> {$op != None}? ^( NEGATIVE atom )
                        # Expr.g:312:4: ^( NEGATIVE atom )
                        root_1 = self._adaptor.nil()
                        root_1 = self._adaptor.becomeRoot(
                        self._adaptor.createFromType(NEGATIVE, "NEGATIVE")
                        , root_1)

                        self._adaptor.addChild(root_1, stream_atom.nextTree())

                        self._adaptor.addChild(root_0, root_1)



                    else: 
                        # 313:4: -> atom
                        self._adaptor.addChild(root_0, stream_atom.nextTree())



                    retval.tree = root_0





                retval.stop = self.input.LT(-1)


                if self._state.backtracking == 0:
                    retval.tree = self._adaptor.rulePostProcessing(root_0)
                    self._adaptor.setTokenBoundaries(retval.tree, retval.start, retval.stop)



            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)
                retval.tree = self._adaptor.errorNode(self.input, retval.start, self.input.LT(-1), re)

        finally:
            pass
        return retval

    # $ANTLR end "negative_expr"


    class atom_return(ParserRuleReturnScope):
        def __init__(self):
            super(ExprParser.atom_return, self).__init__()

            self.tree = None





    # $ANTLR start "atom"
    # Expr.g:316:1: atom : ( literal | member_expr | array_decl | object_decl | new_clause | sprintf | '(' expr ')' -> expr );
    def atom(self, ):
        retval = self.atom_return()
        retval.start = self.input.LT(1)


        root_0 = None

        char_literal236 = None
        char_literal238 = None
        literal230 = None
        member_expr231 = None
        array_decl232 = None
        object_decl233 = None
        new_clause234 = None
        sprintf235 = None
        expr237 = None

        char_literal236_tree = None
        char_literal238_tree = None
        stream_75 = RewriteRuleTokenStream(self._adaptor, "token 75")
        stream_76 = RewriteRuleTokenStream(self._adaptor, "token 76")
        stream_expr = RewriteRuleSubtreeStream(self._adaptor, "rule expr")
        try:
            try:
                # Expr.g:317:2: ( literal | member_expr | array_decl | object_decl | new_clause | sprintf | '(' expr ')' -> expr )
                alt54 = 7
                LA54 = self.input.LA(1)
                if LA54 == BOOL or LA54 == FLOAT or LA54 == INT or LA54 == NULL or LA54 == STRING:
                    alt54 = 1
                elif LA54 == ID:
                    alt54 = 2
                elif LA54 == 100:
                    alt54 = 3
                elif LA54 == 132:
                    alt54 = 4
                elif LA54 == 121:
                    alt54 = 5
                elif LA54 == 126:
                    alt54 = 6
                elif LA54 == 75:
                    alt54 = 7
                else:
                    if self._state.backtracking > 0:
                        raise BacktrackingFailed


                    nvae = NoViableAltException("", 54, 0, self.input)

                    raise nvae


                if alt54 == 1:
                    # Expr.g:317:4: literal
                    pass 
                    root_0 = self._adaptor.nil()


                    self._state.following.append(self.FOLLOW_literal_in_atom1987)
                    literal230 = self.literal()

                    self._state.following.pop()
                    if self._state.backtracking == 0:
                        self._adaptor.addChild(root_0, literal230.tree)



                elif alt54 == 2:
                    # Expr.g:318:4: member_expr
                    pass 
                    root_0 = self._adaptor.nil()


                    self._state.following.append(self.FOLLOW_member_expr_in_atom1992)
                    member_expr231 = self.member_expr()

                    self._state.following.pop()
                    if self._state.backtracking == 0:
                        self._adaptor.addChild(root_0, member_expr231.tree)



                elif alt54 == 3:
                    # Expr.g:319:4: array_decl
                    pass 
                    root_0 = self._adaptor.nil()


                    self._state.following.append(self.FOLLOW_array_decl_in_atom1997)
                    array_decl232 = self.array_decl()

                    self._state.following.pop()
                    if self._state.backtracking == 0:
                        self._adaptor.addChild(root_0, array_decl232.tree)



                elif alt54 == 4:
                    # Expr.g:320:4: object_decl
                    pass 
                    root_0 = self._adaptor.nil()


                    self._state.following.append(self.FOLLOW_object_decl_in_atom2002)
                    object_decl233 = self.object_decl()

                    self._state.following.pop()
                    if self._state.backtracking == 0:
                        self._adaptor.addChild(root_0, object_decl233.tree)



                elif alt54 == 5:
                    # Expr.g:321:4: new_clause
                    pass 
                    root_0 = self._adaptor.nil()


                    self._state.following.append(self.FOLLOW_new_clause_in_atom2007)
                    new_clause234 = self.new_clause()

                    self._state.following.pop()
                    if self._state.backtracking == 0:
                        self._adaptor.addChild(root_0, new_clause234.tree)



                elif alt54 == 6:
                    # Expr.g:322:4: sprintf
                    pass 
                    root_0 = self._adaptor.nil()


                    self._state.following.append(self.FOLLOW_sprintf_in_atom2012)
                    sprintf235 = self.sprintf()

                    self._state.following.pop()
                    if self._state.backtracking == 0:
                        self._adaptor.addChild(root_0, sprintf235.tree)



                elif alt54 == 7:
                    # Expr.g:323:4: '(' expr ')'
                    pass 
                    char_literal236 = self.match(self.input, 75, self.FOLLOW_75_in_atom2017) 
                    if self._state.backtracking == 0:
                        stream_75.add(char_literal236)


                    self._state.following.append(self.FOLLOW_expr_in_atom2019)
                    expr237 = self.expr()

                    self._state.following.pop()
                    if self._state.backtracking == 0:
                        stream_expr.add(expr237.tree)


                    char_literal238 = self.match(self.input, 76, self.FOLLOW_76_in_atom2021) 
                    if self._state.backtracking == 0:
                        stream_76.add(char_literal238)


                    # AST Rewrite
                    # elements: expr
                    # token labels: 
                    # rule labels: retval
                    # token list labels: 
                    # rule list labels: 
                    # wildcard labels: 
                    if self._state.backtracking == 0:
                        retval.tree = root_0
                        if retval is not None:
                            stream_retval = RewriteRuleSubtreeStream(self._adaptor, "rule retval", retval.tree)
                        else:
                            stream_retval = RewriteRuleSubtreeStream(self._adaptor, "token retval", None)


                        root_0 = self._adaptor.nil()
                        # 323:17: -> expr
                        self._adaptor.addChild(root_0, stream_expr.nextTree())




                        retval.tree = root_0




                retval.stop = self.input.LT(-1)


                if self._state.backtracking == 0:
                    retval.tree = self._adaptor.rulePostProcessing(root_0)
                    self._adaptor.setTokenBoundaries(retval.tree, retval.start, retval.stop)



            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)
                retval.tree = self._adaptor.errorNode(self.input, retval.start, self.input.LT(-1), re)

        finally:
            pass
        return retval

    # $ANTLR end "atom"


    class literal_return(ParserRuleReturnScope):
        def __init__(self):
            super(ExprParser.literal_return, self).__init__()

            self.tree = None





    # $ANTLR start "literal"
    # Expr.g:325:1: literal : ( BOOL | NULL | INT | FLOAT | STRING );
    def literal(self, ):
        retval = self.literal_return()
        retval.start = self.input.LT(1)


        root_0 = None

        set239 = None

        set239_tree = None

        try:
            try:
                # Expr.g:326:2: ( BOOL | NULL | INT | FLOAT | STRING )
                # Expr.g:
                pass 
                root_0 = self._adaptor.nil()


                set239 = self.input.LT(1)

                if self.input.LA(1) == BOOL or self.input.LA(1) == FLOAT or self.input.LA(1) == INT or self.input.LA(1) == NULL or self.input.LA(1) == STRING:
                    self.input.consume()
                    if self._state.backtracking == 0:
                        self._adaptor.addChild(root_0, self._adaptor.createWithPayload(set239))

                    self._state.errorRecovery = False


                else:
                    if self._state.backtracking > 0:
                        raise BacktrackingFailed


                    mse = MismatchedSetException(None, self.input)
                    raise mse





                retval.stop = self.input.LT(-1)


                if self._state.backtracking == 0:
                    retval.tree = self._adaptor.rulePostProcessing(root_0)
                    self._adaptor.setTokenBoundaries(retval.tree, retval.start, retval.stop)



            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)
                retval.tree = self._adaptor.errorNode(self.input, retval.start, self.input.LT(-1), re)

        finally:
            pass
        return retval

    # $ANTLR end "literal"


    class new_clause_return(ParserRuleReturnScope):
        def __init__(self):
            super(ExprParser.new_clause_return, self).__init__()

            self.tree = None





    # $ANTLR start "new_clause"
    # Expr.g:329:1: new_clause : 'new' module call_expr -> ^( NEW module call_expr ) ;
    def new_clause(self, ):
        retval = self.new_clause_return()
        retval.start = self.input.LT(1)


        root_0 = None

        string_literal240 = None
        module241 = None
        call_expr242 = None

        string_literal240_tree = None
        stream_121 = RewriteRuleTokenStream(self._adaptor, "token 121")
        stream_module = RewriteRuleSubtreeStream(self._adaptor, "rule module")
        stream_call_expr = RewriteRuleSubtreeStream(self._adaptor, "rule call_expr")
        try:
            try:
                # Expr.g:330:2: ( 'new' module call_expr -> ^( NEW module call_expr ) )
                # Expr.g:330:4: 'new' module call_expr
                pass 
                string_literal240 = self.match(self.input, 121, self.FOLLOW_121_in_new_clause2062) 
                if self._state.backtracking == 0:
                    stream_121.add(string_literal240)


                self._state.following.append(self.FOLLOW_module_in_new_clause2064)
                module241 = self.module()

                self._state.following.pop()
                if self._state.backtracking == 0:
                    stream_module.add(module241.tree)


                self._state.following.append(self.FOLLOW_call_expr_in_new_clause2066)
                call_expr242 = self.call_expr()

                self._state.following.pop()
                if self._state.backtracking == 0:
                    stream_call_expr.add(call_expr242.tree)


                # AST Rewrite
                # elements: module, call_expr
                # token labels: 
                # rule labels: retval
                # token list labels: 
                # rule list labels: 
                # wildcard labels: 
                if self._state.backtracking == 0:
                    retval.tree = root_0
                    if retval is not None:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "rule retval", retval.tree)
                    else:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "token retval", None)


                    root_0 = self._adaptor.nil()
                    # 331:3: -> ^( NEW module call_expr )
                    # Expr.g:331:6: ^( NEW module call_expr )
                    root_1 = self._adaptor.nil()
                    root_1 = self._adaptor.becomeRoot(
                    self._adaptor.createFromType(NEW, "NEW")
                    , root_1)

                    self._adaptor.addChild(root_1, stream_module.nextTree())

                    self._adaptor.addChild(root_1, stream_call_expr.nextTree())

                    self._adaptor.addChild(root_0, root_1)




                    retval.tree = root_0





                retval.stop = self.input.LT(-1)


                if self._state.backtracking == 0:
                    retval.tree = self._adaptor.rulePostProcessing(root_0)
                    self._adaptor.setTokenBoundaries(retval.tree, retval.start, retval.stop)



            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)
                retval.tree = self._adaptor.errorNode(self.input, retval.start, self.input.LT(-1), re)

        finally:
            pass
        return retval

    # $ANTLR end "new_clause"


    class module_return(ParserRuleReturnScope):
        def __init__(self):
            super(ExprParser.module_return, self).__init__()

            self.tree = None





    # $ANTLR start "module"
    # Expr.g:333:1: module : ID ( '.' ID )* -> ^( MODULE ( ID )+ ) ;
    def module(self, ):
        retval = self.module_return()
        retval.start = self.input.LT(1)


        root_0 = None

        ID243 = None
        char_literal244 = None
        ID245 = None

        ID243_tree = None
        char_literal244_tree = None
        ID245_tree = None
        stream_ID = RewriteRuleTokenStream(self._adaptor, "token ID")
        stream_86 = RewriteRuleTokenStream(self._adaptor, "token 86")

        try:
            try:
                # Expr.g:334:2: ( ID ( '.' ID )* -> ^( MODULE ( ID )+ ) )
                # Expr.g:334:4: ID ( '.' ID )*
                pass 
                ID243 = self.match(self.input, ID, self.FOLLOW_ID_in_module2088) 
                if self._state.backtracking == 0:
                    stream_ID.add(ID243)


                # Expr.g:334:7: ( '.' ID )*
                while True: #loop55
                    alt55 = 2
                    LA55_0 = self.input.LA(1)

                    if (LA55_0 == 86) :
                        alt55 = 1


                    if alt55 == 1:
                        # Expr.g:334:8: '.' ID
                        pass 
                        char_literal244 = self.match(self.input, 86, self.FOLLOW_86_in_module2091) 
                        if self._state.backtracking == 0:
                            stream_86.add(char_literal244)


                        ID245 = self.match(self.input, ID, self.FOLLOW_ID_in_module2093) 
                        if self._state.backtracking == 0:
                            stream_ID.add(ID245)



                    else:
                        break #loop55


                # AST Rewrite
                # elements: ID
                # token labels: 
                # rule labels: retval
                # token list labels: 
                # rule list labels: 
                # wildcard labels: 
                if self._state.backtracking == 0:
                    retval.tree = root_0
                    if retval is not None:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "rule retval", retval.tree)
                    else:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "token retval", None)


                    root_0 = self._adaptor.nil()
                    # 335:3: -> ^( MODULE ( ID )+ )
                    # Expr.g:335:6: ^( MODULE ( ID )+ )
                    root_1 = self._adaptor.nil()
                    root_1 = self._adaptor.becomeRoot(
                    self._adaptor.createFromType(MODULE, "MODULE")
                    , root_1)

                    # Expr.g:335:15: ( ID )+
                    if not (stream_ID.hasNext()):
                        raise RewriteEarlyExitException()

                    while stream_ID.hasNext():
                        self._adaptor.addChild(root_1, 
                        stream_ID.nextNode()
                        )


                    stream_ID.reset()

                    self._adaptor.addChild(root_0, root_1)




                    retval.tree = root_0





                retval.stop = self.input.LT(-1)


                if self._state.backtracking == 0:
                    retval.tree = self._adaptor.rulePostProcessing(root_0)
                    self._adaptor.setTokenBoundaries(retval.tree, retval.start, retval.stop)



            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)
                retval.tree = self._adaptor.errorNode(self.input, retval.start, self.input.LT(-1), re)

        finally:
            pass
        return retval

    # $ANTLR end "module"


    class array_decl_return(ParserRuleReturnScope):
        def __init__(self):
            super(ExprParser.array_decl_return, self).__init__()

            self.tree = None





    # $ANTLR start "array_decl"
    # Expr.g:339:1: array_decl : '[' ( expr_list )? ']' -> ^( ARRAY ( expr_list )? ) ;
    def array_decl(self, ):
        retval = self.array_decl_return()
        retval.start = self.input.LT(1)


        root_0 = None

        char_literal246 = None
        char_literal248 = None
        expr_list247 = None

        char_literal246_tree = None
        char_literal248_tree = None
        stream_101 = RewriteRuleTokenStream(self._adaptor, "token 101")
        stream_100 = RewriteRuleTokenStream(self._adaptor, "token 100")
        stream_expr_list = RewriteRuleSubtreeStream(self._adaptor, "rule expr_list")
        try:
            try:
                # Expr.g:340:2: ( '[' ( expr_list )? ']' -> ^( ARRAY ( expr_list )? ) )
                # Expr.g:340:4: '[' ( expr_list )? ']'
                pass 
                char_literal246 = self.match(self.input, 100, self.FOLLOW_100_in_array_decl2118) 
                if self._state.backtracking == 0:
                    stream_100.add(char_literal246)


                # Expr.g:340:8: ( expr_list )?
                alt56 = 2
                LA56_0 = self.input.LA(1)

                if (LA56_0 == BOOL or LA56_0 == FLOAT or LA56_0 == ID or LA56_0 == INT or LA56_0 == NULL or LA56_0 == STRING or LA56_0 == 68 or LA56_0 == 75 or LA56_0 == 83 or LA56_0 == 100 or LA56_0 == 121 or LA56_0 == 126 or LA56_0 == 132) :
                    alt56 = 1
                if alt56 == 1:
                    # Expr.g:340:8: expr_list
                    pass 
                    self._state.following.append(self.FOLLOW_expr_list_in_array_decl2120)
                    expr_list247 = self.expr_list()

                    self._state.following.pop()
                    if self._state.backtracking == 0:
                        stream_expr_list.add(expr_list247.tree)





                char_literal248 = self.match(self.input, 101, self.FOLLOW_101_in_array_decl2123) 
                if self._state.backtracking == 0:
                    stream_101.add(char_literal248)


                # AST Rewrite
                # elements: expr_list
                # token labels: 
                # rule labels: retval
                # token list labels: 
                # rule list labels: 
                # wildcard labels: 
                if self._state.backtracking == 0:
                    retval.tree = root_0
                    if retval is not None:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "rule retval", retval.tree)
                    else:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "token retval", None)


                    root_0 = self._adaptor.nil()
                    # 341:3: -> ^( ARRAY ( expr_list )? )
                    # Expr.g:341:6: ^( ARRAY ( expr_list )? )
                    root_1 = self._adaptor.nil()
                    root_1 = self._adaptor.becomeRoot(
                    self._adaptor.createFromType(ARRAY, "ARRAY")
                    , root_1)

                    # Expr.g:341:14: ( expr_list )?
                    if stream_expr_list.hasNext():
                        self._adaptor.addChild(root_1, stream_expr_list.nextTree())


                    stream_expr_list.reset();

                    self._adaptor.addChild(root_0, root_1)




                    retval.tree = root_0





                retval.stop = self.input.LT(-1)


                if self._state.backtracking == 0:
                    retval.tree = self._adaptor.rulePostProcessing(root_0)
                    self._adaptor.setTokenBoundaries(retval.tree, retval.start, retval.stop)



            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)
                retval.tree = self._adaptor.errorNode(self.input, retval.start, self.input.LT(-1), re)

        finally:
            pass
        return retval

    # $ANTLR end "array_decl"


    class object_decl_return(ParserRuleReturnScope):
        def __init__(self):
            super(ExprParser.object_decl_return, self).__init__()

            self.tree = None





    # $ANTLR start "object_decl"
    # Expr.g:344:1: object_decl : '{' ( property )? ( ',' property )* ( ',' )? '}' -> ^( OBJECT ( property )* ) ;
    def object_decl(self, ):
        retval = self.object_decl_return()
        retval.start = self.input.LT(1)


        root_0 = None

        char_literal249 = None
        char_literal251 = None
        char_literal253 = None
        char_literal254 = None
        property250 = None
        property252 = None

        char_literal249_tree = None
        char_literal251_tree = None
        char_literal253_tree = None
        char_literal254_tree = None
        stream_132 = RewriteRuleTokenStream(self._adaptor, "token 132")
        stream_136 = RewriteRuleTokenStream(self._adaptor, "token 136")
        stream_82 = RewriteRuleTokenStream(self._adaptor, "token 82")
        stream_property = RewriteRuleSubtreeStream(self._adaptor, "rule property")
        try:
            try:
                # Expr.g:345:2: ( '{' ( property )? ( ',' property )* ( ',' )? '}' -> ^( OBJECT ( property )* ) )
                # Expr.g:345:4: '{' ( property )? ( ',' property )* ( ',' )? '}'
                pass 
                char_literal249 = self.match(self.input, 132, self.FOLLOW_132_in_object_decl2145) 
                if self._state.backtracking == 0:
                    stream_132.add(char_literal249)


                # Expr.g:345:8: ( property )?
                alt57 = 2
                LA57_0 = self.input.LA(1)

                if (LA57_0 == ID or LA57_0 == INT or LA57_0 == STRING) :
                    alt57 = 1
                if alt57 == 1:
                    # Expr.g:345:8: property
                    pass 
                    self._state.following.append(self.FOLLOW_property_in_object_decl2147)
                    property250 = self.property()

                    self._state.following.pop()
                    if self._state.backtracking == 0:
                        stream_property.add(property250.tree)





                # Expr.g:345:18: ( ',' property )*
                while True: #loop58
                    alt58 = 2
                    LA58_0 = self.input.LA(1)

                    if (LA58_0 == 82) :
                        LA58_1 = self.input.LA(2)

                        if (LA58_1 == ID or LA58_1 == INT or LA58_1 == STRING) :
                            alt58 = 1




                    if alt58 == 1:
                        # Expr.g:345:19: ',' property
                        pass 
                        char_literal251 = self.match(self.input, 82, self.FOLLOW_82_in_object_decl2151) 
                        if self._state.backtracking == 0:
                            stream_82.add(char_literal251)


                        self._state.following.append(self.FOLLOW_property_in_object_decl2153)
                        property252 = self.property()

                        self._state.following.pop()
                        if self._state.backtracking == 0:
                            stream_property.add(property252.tree)



                    else:
                        break #loop58


                # Expr.g:345:34: ( ',' )?
                alt59 = 2
                LA59_0 = self.input.LA(1)

                if (LA59_0 == 82) :
                    alt59 = 1
                if alt59 == 1:
                    # Expr.g:345:34: ','
                    pass 
                    char_literal253 = self.match(self.input, 82, self.FOLLOW_82_in_object_decl2157) 
                    if self._state.backtracking == 0:
                        stream_82.add(char_literal253)





                char_literal254 = self.match(self.input, 136, self.FOLLOW_136_in_object_decl2160) 
                if self._state.backtracking == 0:
                    stream_136.add(char_literal254)


                # AST Rewrite
                # elements: property
                # token labels: 
                # rule labels: retval
                # token list labels: 
                # rule list labels: 
                # wildcard labels: 
                if self._state.backtracking == 0:
                    retval.tree = root_0
                    if retval is not None:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "rule retval", retval.tree)
                    else:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "token retval", None)


                    root_0 = self._adaptor.nil()
                    # 346:3: -> ^( OBJECT ( property )* )
                    # Expr.g:346:6: ^( OBJECT ( property )* )
                    root_1 = self._adaptor.nil()
                    root_1 = self._adaptor.becomeRoot(
                    self._adaptor.createFromType(OBJECT, "OBJECT")
                    , root_1)

                    # Expr.g:346:15: ( property )*
                    while stream_property.hasNext():
                        self._adaptor.addChild(root_1, stream_property.nextTree())


                    stream_property.reset();

                    self._adaptor.addChild(root_0, root_1)




                    retval.tree = root_0





                retval.stop = self.input.LT(-1)


                if self._state.backtracking == 0:
                    retval.tree = self._adaptor.rulePostProcessing(root_0)
                    self._adaptor.setTokenBoundaries(retval.tree, retval.start, retval.stop)



            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)
                retval.tree = self._adaptor.errorNode(self.input, retval.start, self.input.LT(-1), re)

        finally:
            pass
        return retval

    # $ANTLR end "object_decl"


    class property_return(ParserRuleReturnScope):
        def __init__(self):
            super(ExprParser.property_return, self).__init__()

            self.tree = None





    # $ANTLR start "property"
    # Expr.g:348:1: property : ( ID | STRING | INT ) ':' expr ;
    def property(self, ):
        retval = self.property_return()
        retval.start = self.input.LT(1)


        root_0 = None

        set255 = None
        char_literal256 = None
        expr257 = None

        set255_tree = None
        char_literal256_tree = None

        try:
            try:
                # Expr.g:349:2: ( ( ID | STRING | INT ) ':' expr )
                # Expr.g:349:4: ( ID | STRING | INT ) ':' expr
                pass 
                root_0 = self._adaptor.nil()


                set255 = self.input.LT(1)

                if self.input.LA(1) == ID or self.input.LA(1) == INT or self.input.LA(1) == STRING:
                    self.input.consume()
                    if self._state.backtracking == 0:
                        self._adaptor.addChild(root_0, self._adaptor.createWithPayload(set255))

                    self._state.errorRecovery = False


                else:
                    if self._state.backtracking > 0:
                        raise BacktrackingFailed


                    mse = MismatchedSetException(None, self.input)
                    raise mse



                char_literal256 = self.match(self.input, 91, self.FOLLOW_91_in_property2193)
                if self._state.backtracking == 0:
                    char_literal256_tree = self._adaptor.createWithPayload(char_literal256)
                    self._adaptor.addChild(root_0, char_literal256_tree)



                self._state.following.append(self.FOLLOW_expr_in_property2195)
                expr257 = self.expr()

                self._state.following.pop()
                if self._state.backtracking == 0:
                    self._adaptor.addChild(root_0, expr257.tree)




                retval.stop = self.input.LT(-1)


                if self._state.backtracking == 0:
                    retval.tree = self._adaptor.rulePostProcessing(root_0)
                    self._adaptor.setTokenBoundaries(retval.tree, retval.start, retval.stop)



            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)
                retval.tree = self._adaptor.errorNode(self.input, retval.start, self.input.LT(-1), re)

        finally:
            pass
        return retval

    # $ANTLR end "property"


    class sprintf_return(ParserRuleReturnScope):
        def __init__(self):
            super(ExprParser.sprintf_return, self).__init__()

            self.tree = None





    # $ANTLR start "sprintf"
    # Expr.g:353:1: sprintf : 'sprintf' '(' expr ( ',' expr_list )? ')' -> ^( SPRINTF expr ( expr_list )? ) ;
    def sprintf(self, ):
        retval = self.sprintf_return()
        retval.start = self.input.LT(1)


        root_0 = None

        string_literal258 = None
        char_literal259 = None
        char_literal261 = None
        char_literal263 = None
        expr260 = None
        expr_list262 = None

        string_literal258_tree = None
        char_literal259_tree = None
        char_literal261_tree = None
        char_literal263_tree = None
        stream_126 = RewriteRuleTokenStream(self._adaptor, "token 126")
        stream_82 = RewriteRuleTokenStream(self._adaptor, "token 82")
        stream_75 = RewriteRuleTokenStream(self._adaptor, "token 75")
        stream_76 = RewriteRuleTokenStream(self._adaptor, "token 76")
        stream_expr = RewriteRuleSubtreeStream(self._adaptor, "rule expr")
        stream_expr_list = RewriteRuleSubtreeStream(self._adaptor, "rule expr_list")
        try:
            try:
                # Expr.g:354:2: ( 'sprintf' '(' expr ( ',' expr_list )? ')' -> ^( SPRINTF expr ( expr_list )? ) )
                # Expr.g:354:4: 'sprintf' '(' expr ( ',' expr_list )? ')'
                pass 
                string_literal258 = self.match(self.input, 126, self.FOLLOW_126_in_sprintf2207) 
                if self._state.backtracking == 0:
                    stream_126.add(string_literal258)


                char_literal259 = self.match(self.input, 75, self.FOLLOW_75_in_sprintf2209) 
                if self._state.backtracking == 0:
                    stream_75.add(char_literal259)


                self._state.following.append(self.FOLLOW_expr_in_sprintf2211)
                expr260 = self.expr()

                self._state.following.pop()
                if self._state.backtracking == 0:
                    stream_expr.add(expr260.tree)


                # Expr.g:354:23: ( ',' expr_list )?
                alt60 = 2
                LA60_0 = self.input.LA(1)

                if (LA60_0 == 82) :
                    alt60 = 1
                if alt60 == 1:
                    # Expr.g:354:24: ',' expr_list
                    pass 
                    char_literal261 = self.match(self.input, 82, self.FOLLOW_82_in_sprintf2214) 
                    if self._state.backtracking == 0:
                        stream_82.add(char_literal261)


                    self._state.following.append(self.FOLLOW_expr_list_in_sprintf2216)
                    expr_list262 = self.expr_list()

                    self._state.following.pop()
                    if self._state.backtracking == 0:
                        stream_expr_list.add(expr_list262.tree)





                char_literal263 = self.match(self.input, 76, self.FOLLOW_76_in_sprintf2220) 
                if self._state.backtracking == 0:
                    stream_76.add(char_literal263)


                # AST Rewrite
                # elements: expr_list, expr
                # token labels: 
                # rule labels: retval
                # token list labels: 
                # rule list labels: 
                # wildcard labels: 
                if self._state.backtracking == 0:
                    retval.tree = root_0
                    if retval is not None:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "rule retval", retval.tree)
                    else:
                        stream_retval = RewriteRuleSubtreeStream(self._adaptor, "token retval", None)


                    root_0 = self._adaptor.nil()
                    # 355:3: -> ^( SPRINTF expr ( expr_list )? )
                    # Expr.g:355:6: ^( SPRINTF expr ( expr_list )? )
                    root_1 = self._adaptor.nil()
                    root_1 = self._adaptor.becomeRoot(
                    self._adaptor.createFromType(SPRINTF, "SPRINTF")
                    , root_1)

                    self._adaptor.addChild(root_1, stream_expr.nextTree())

                    # Expr.g:355:21: ( expr_list )?
                    if stream_expr_list.hasNext():
                        self._adaptor.addChild(root_1, stream_expr_list.nextTree())


                    stream_expr_list.reset();

                    self._adaptor.addChild(root_0, root_1)




                    retval.tree = root_0





                retval.stop = self.input.LT(-1)


                if self._state.backtracking == 0:
                    retval.tree = self._adaptor.rulePostProcessing(root_0)
                    self._adaptor.setTokenBoundaries(retval.tree, retval.start, retval.stop)



            except RecognitionException, re:
                self.reportError(re)
                self.recover(self.input, re)
                retval.tree = self._adaptor.errorNode(self.input, retval.start, self.input.LT(-1), re)

        finally:
            pass
        return retval

    # $ANTLR end "sprintf"

    # $ANTLR start "synpred1_Expr"
    def synpred1_Expr_fragment(self, ):
        # Expr.g:232:4: ( '[' expr ']' )
        # Expr.g:232:4: '[' expr ']'
        pass 
        root_0 = self._adaptor.nil()


        self.match(self.input, 100, self.FOLLOW_100_in_synpred1_Expr1439)


        self._state.following.append(self.FOLLOW_expr_in_synpred1_Expr1441)
        self.expr()

        self._state.following.pop()


        self.match(self.input, 101, self.FOLLOW_101_in_synpred1_Expr1443)




    # $ANTLR end "synpred1_Expr"




    def synpred1_Expr(self):
        self._state.backtracking += 1
        start = self.input.mark()
        try:
            self.synpred1_Expr_fragment()
        except BacktrackingFailed:
            success = False
        else:
            success = True
        self.input.rewind(start)
        self._state.backtracking -= 1
        return success



    # lookup tables for DFA #6

    DFA6_eot = DFA.unpack(
        u"\6\uffff"
        )

    DFA6_eof = DFA.unpack(
        u"\6\uffff"
        )

    DFA6_min = DFA.unpack(
        u"\1\42\1\122\1\42\2\uffff\1\122"
        )

    DFA6_max = DFA.unpack(
        u"\1\42\1\134\1\42\2\uffff\1\134"
        )

    DFA6_accept = DFA.unpack(
        u"\3\uffff\1\1\1\2\1\uffff"
        )

    DFA6_special = DFA.unpack(
        u"\6\uffff"
        )


    DFA6_transition = [
        DFA.unpack(u"\1\1"),
        DFA.unpack(u"\1\3\3\uffff\1\2\1\4\4\uffff\1\3"),
        DFA.unpack(u"\1\5"),
        DFA.unpack(u""),
        DFA.unpack(u""),
        DFA.unpack(u"\1\3\3\uffff\1\2\1\4\4\uffff\1\3")
    ]

    # class definition for DFA #6

    class DFA6(DFA):
        pass


 

    FOLLOW_EOF_in_prog211 = frozenset([1])
    FOLLOW_stmt_in_prog220 = frozenset([1, 34, 80, 84, 92, 105, 108, 109, 111, 115, 116, 117, 118, 119, 122, 123, 125, 128, 129, 130, 131])
    FOLLOW_92_in_stmt232 = frozenset([1])
    FOLLOW_exec_stmt_in_stmt239 = frozenset([1])
    FOLLOW_import_stmt_in_stmt244 = frozenset([1])
    FOLLOW_print_stmt_in_stmt249 = frozenset([1])
    FOLLOW_printf_stmt_in_stmt253 = frozenset([1])
    FOLLOW_break_stmt_in_stmt258 = frozenset([1])
    FOLLOW_continue_stmt_in_stmt263 = frozenset([1])
    FOLLOW_return_stmt_in_stmt268 = frozenset([1])
    FOLLOW_if_stmt_in_stmt273 = frozenset([1])
    FOLLOW_while_stmt_in_stmt278 = frozenset([1])
    FOLLOW_do_while_stmt_in_stmt283 = frozenset([1])
    FOLLOW_switch_stmt_in_stmt288 = frozenset([1])
    FOLLOW_for_stmt_in_stmt293 = frozenset([1])
    FOLLOW_foreach_stmt_in_stmt298 = frozenset([1])
    FOLLOW_throw_stmt_in_stmt303 = frozenset([1])
    FOLLOW_try_stmt_in_stmt308 = frozenset([1])
    FOLLOW_func_decl_in_stmt313 = frozenset([1])
    FOLLOW_class_decl_in_stmt318 = frozenset([1])
    FOLLOW_132_in_block331 = frozenset([34, 80, 84, 92, 105, 108, 109, 111, 115, 116, 117, 118, 119, 122, 123, 125, 128, 129, 130, 131, 136])
    FOLLOW_stmt_in_block333 = frozenset([34, 80, 84, 92, 105, 108, 109, 111, 115, 116, 117, 118, 119, 122, 123, 125, 128, 129, 130, 131, 136])
    FOLLOW_136_in_block336 = frozenset([1])
    FOLLOW_119_in_import_stmt358 = frozenset([34])
    FOLLOW_module_path_in_import_stmt360 = frozenset([82, 92])
    FOLLOW_82_in_import_stmt363 = frozenset([34])
    FOLLOW_module_path_in_import_stmt365 = frozenset([82, 92])
    FOLLOW_92_in_import_stmt369 = frozenset([1])
    FOLLOW_module_in_module_path390 = frozenset([1])
    FOLLOW_module_in_module_path395 = frozenset([87])
    FOLLOW_87_in_module_path397 = frozenset([1])
    FOLLOW_123_in_printf_stmt408 = frozenset([75])
    FOLLOW_75_in_printf_stmt410 = frozenset([8, 30, 34, 39, 47, 61, 68, 75, 83, 100, 121, 126, 132])
    FOLLOW_expr_in_printf_stmt412 = frozenset([76, 82])
    FOLLOW_82_in_printf_stmt415 = frozenset([8, 30, 34, 39, 47, 61, 68, 75, 83, 100, 121, 126, 132])
    FOLLOW_expr_list_in_printf_stmt417 = frozenset([76])
    FOLLOW_76_in_printf_stmt421 = frozenset([92])
    FOLLOW_92_in_printf_stmt423 = frozenset([1])
    FOLLOW_122_in_print_stmt452 = frozenset([8, 30, 34, 39, 47, 61, 68, 75, 83, 100, 121, 126, 132])
    FOLLOW_expr_list_in_print_stmt455 = frozenset([92])
    FOLLOW_92_in_print_stmt457 = frozenset([1])
    FOLLOW_105_in_break_stmt478 = frozenset([92])
    FOLLOW_92_in_break_stmt480 = frozenset([1])
    FOLLOW_109_in_continue_stmt496 = frozenset([92])
    FOLLOW_92_in_continue_stmt498 = frozenset([1])
    FOLLOW_125_in_return_stmt514 = frozenset([8, 30, 34, 39, 47, 61, 68, 75, 83, 92, 100, 121, 126, 132])
    FOLLOW_expr_in_return_stmt516 = frozenset([92])
    FOLLOW_92_in_return_stmt519 = frozenset([1])
    FOLLOW_if_clause_in_if_stmt541 = frozenset([1, 112])
    FOLLOW_else_if_clause_in_if_stmt543 = frozenset([1, 112])
    FOLLOW_else_clause_in_if_stmt546 = frozenset([1])
    FOLLOW_118_in_if_clause557 = frozenset([75])
    FOLLOW_75_in_if_clause559 = frozenset([8, 30, 34, 39, 47, 61, 68, 75, 83, 100, 121, 126, 132])
    FOLLOW_expr_in_if_clause561 = frozenset([76])
    FOLLOW_76_in_if_clause563 = frozenset([132])
    FOLLOW_block_in_if_clause565 = frozenset([1])
    FOLLOW_112_in_else_if_clause587 = frozenset([118])
    FOLLOW_if_clause_in_else_if_clause589 = frozenset([1])
    FOLLOW_112_in_else_clause609 = frozenset([132])
    FOLLOW_block_in_else_clause611 = frozenset([1])
    FOLLOW_131_in_while_stmt632 = frozenset([75])
    FOLLOW_75_in_while_stmt634 = frozenset([8, 30, 34, 39, 47, 61, 68, 75, 83, 100, 121, 126, 132])
    FOLLOW_expr_in_while_stmt636 = frozenset([76])
    FOLLOW_76_in_while_stmt638 = frozenset([132])
    FOLLOW_block_in_while_stmt640 = frozenset([1])
    FOLLOW_111_in_do_while_stmt663 = frozenset([132])
    FOLLOW_block_in_do_while_stmt665 = frozenset([131])
    FOLLOW_131_in_do_while_stmt667 = frozenset([75])
    FOLLOW_75_in_do_while_stmt669 = frozenset([8, 30, 34, 39, 47, 61, 68, 75, 83, 100, 121, 126, 132])
    FOLLOW_expr_in_do_while_stmt671 = frozenset([76])
    FOLLOW_76_in_do_while_stmt673 = frozenset([92])
    FOLLOW_92_in_do_while_stmt675 = frozenset([1])
    FOLLOW_128_in_switch_stmt698 = frozenset([75])
    FOLLOW_75_in_switch_stmt700 = frozenset([8, 30, 34, 39, 47, 61, 68, 75, 83, 100, 121, 126, 132])
    FOLLOW_expr_in_switch_stmt702 = frozenset([76])
    FOLLOW_76_in_switch_stmt704 = frozenset([132])
    FOLLOW_case_block_in_switch_stmt706 = frozenset([1])
    FOLLOW_132_in_case_block728 = frozenset([106])
    FOLLOW_case_clause_in_case_block731 = frozenset([106, 110, 136])
    FOLLOW_default_clause_in_case_block736 = frozenset([136])
    FOLLOW_136_in_case_block740 = frozenset([1])
    FOLLOW_case_test_in_case_clause750 = frozenset([34, 80, 84, 92, 105, 106, 108, 109, 111, 115, 116, 117, 118, 119, 122, 123, 125, 128, 129, 130, 131])
    FOLLOW_stmt_in_case_clause753 = frozenset([34, 80, 84, 92, 105, 108, 109, 111, 115, 116, 117, 118, 119, 122, 123, 125, 128, 129, 130, 131])
    FOLLOW_break_stmt_in_case_clause756 = frozenset([1])
    FOLLOW_106_in_case_test782 = frozenset([8, 30, 34, 39, 47, 61, 68, 75, 83, 100, 121, 126, 132])
    FOLLOW_expr_in_case_test784 = frozenset([91])
    FOLLOW_91_in_case_test786 = frozenset([1])
    FOLLOW_110_in_default_clause806 = frozenset([91])
    FOLLOW_91_in_default_clause808 = frozenset([1, 34, 80, 84, 92, 105, 108, 109, 111, 115, 116, 117, 118, 119, 122, 123, 125, 128, 129, 130, 131])
    FOLLOW_stmt_in_default_clause810 = frozenset([1, 34, 80, 84, 92, 105, 108, 109, 111, 115, 116, 117, 118, 119, 122, 123, 125, 128, 129, 130, 131])
    FOLLOW_115_in_for_stmt833 = frozenset([75])
    FOLLOW_75_in_for_stmt835 = frozenset([34, 80, 84, 92])
    FOLLOW_exec_list_in_for_stmt839 = frozenset([92])
    FOLLOW_92_in_for_stmt842 = frozenset([8, 30, 34, 39, 47, 61, 68, 75, 83, 100, 121, 126, 132])
    FOLLOW_expr_in_for_stmt844 = frozenset([92])
    FOLLOW_92_in_for_stmt846 = frozenset([34, 76, 80, 84])
    FOLLOW_exec_list_in_for_stmt850 = frozenset([76])
    FOLLOW_76_in_for_stmt853 = frozenset([132])
    FOLLOW_block_in_for_stmt855 = frozenset([1])
    FOLLOW_116_in_foreach_stmt886 = frozenset([75])
    FOLLOW_75_in_foreach_stmt888 = frozenset([8, 30, 34, 39, 47, 61, 68, 75, 83, 100, 121, 126, 132])
    FOLLOW_expr_in_foreach_stmt890 = frozenset([104])
    FOLLOW_104_in_foreach_stmt892 = frozenset([34])
    FOLLOW_each_in_foreach_stmt894 = frozenset([76])
    FOLLOW_76_in_foreach_stmt896 = frozenset([132])
    FOLLOW_block_in_foreach_stmt898 = frozenset([1])
    FOLLOW_each_val_in_each922 = frozenset([1])
    FOLLOW_ID_in_each937 = frozenset([97])
    FOLLOW_97_in_each939 = frozenset([34])
    FOLLOW_each_val_in_each941 = frozenset([1])
    FOLLOW_ID_in_each_val963 = frozenset([1, 82])
    FOLLOW_82_in_each_val966 = frozenset([34])
    FOLLOW_ID_in_each_val968 = frozenset([1, 82])
    FOLLOW_129_in_throw_stmt993 = frozenset([8, 30, 34, 39, 47, 61, 68, 75, 83, 100, 121, 126, 132])
    FOLLOW_expr_in_throw_stmt995 = frozenset([92])
    FOLLOW_92_in_throw_stmt997 = frozenset([1])
    FOLLOW_130_in_try_stmt1017 = frozenset([132])
    FOLLOW_block_in_try_stmt1019 = frozenset([107])
    FOLLOW_catch_clause_in_try_stmt1021 = frozenset([1, 107, 114])
    FOLLOW_finally_clause_in_try_stmt1024 = frozenset([1])
    FOLLOW_107_in_catch_clause1051 = frozenset([75])
    FOLLOW_75_in_catch_clause1053 = frozenset([34])
    FOLLOW_module_in_catch_clause1055 = frozenset([34, 76])
    FOLLOW_ID_in_catch_clause1057 = frozenset([76])
    FOLLOW_76_in_catch_clause1060 = frozenset([132])
    FOLLOW_block_in_catch_clause1062 = frozenset([1])
    FOLLOW_114_in_finally_clause1087 = frozenset([132])
    FOLLOW_block_in_finally_clause1089 = frozenset([1])
    FOLLOW_117_in_func_decl1111 = frozenset([34])
    FOLLOW_ID_in_func_decl1113 = frozenset([75])
    FOLLOW_params_in_func_decl1115 = frozenset([132])
    FOLLOW_block_in_func_decl1117 = frozenset([1])
    FOLLOW_75_in_params1141 = frozenset([34, 76, 82])
    FOLLOW_param_decl_in_params1143 = frozenset([76, 82])
    FOLLOW_82_in_params1147 = frozenset([34])
    FOLLOW_param_decl_in_params1149 = frozenset([76, 82])
    FOLLOW_76_in_params1153 = frozenset([1])
    FOLLOW_ID_in_param_decl1174 = frozenset([1, 95])
    FOLLOW_95_in_param_decl1177 = frozenset([8, 30, 34, 39, 47, 61, 75, 100, 121, 126, 132])
    FOLLOW_atom_in_param_decl1179 = frozenset([1])
    FOLLOW_108_in_class_decl1192 = frozenset([34])
    FOLLOW_ID_in_class_decl1194 = frozenset([113, 132])
    FOLLOW_113_in_class_decl1197 = frozenset([34])
    FOLLOW_ID_in_class_decl1199 = frozenset([132])
    FOLLOW_132_in_class_decl1205 = frozenset([117, 124, 136])
    FOLLOW_class_element_in_class_decl1207 = frozenset([117, 124, 136])
    FOLLOW_136_in_class_decl1210 = frozenset([1])
    FOLLOW_var_def_in_class_element1236 = frozenset([1])
    FOLLOW_constructor_in_class_element1240 = frozenset([1])
    FOLLOW_func_decl_in_class_element1244 = frozenset([1])
    FOLLOW_124_in_var_def1254 = frozenset([34])
    FOLLOW_ID_in_var_def1256 = frozenset([92, 95])
    FOLLOW_95_in_var_def1259 = frozenset([8, 30, 34, 39, 47, 61, 68, 75, 83, 100, 121, 126, 132])
    FOLLOW_expr_in_var_def1261 = frozenset([92])
    FOLLOW_92_in_var_def1265 = frozenset([1])
    FOLLOW_124_in_var_def1283 = frozenset([127])
    FOLLOW_127_in_var_def1285 = frozenset([34])
    FOLLOW_ID_in_var_def1287 = frozenset([92, 95])
    FOLLOW_95_in_var_def1290 = frozenset([8, 30, 34, 39, 47, 61, 68, 75, 83, 100, 121, 126, 132])
    FOLLOW_expr_in_var_def1292 = frozenset([92])
    FOLLOW_92_in_var_def1296 = frozenset([1])
    FOLLOW_117_in_constructor1321 = frozenset([120])
    FOLLOW_120_in_constructor1323 = frozenset([75])
    FOLLOW_params_in_constructor1325 = frozenset([132])
    FOLLOW_block_in_constructor1327 = frozenset([1])
    FOLLOW_primary_in_member_expr1354 = frozenset([1, 86])
    FOLLOW_86_in_member_expr1357 = frozenset([34])
    FOLLOW_primary_in_member_expr1359 = frozenset([1, 86])
    FOLLOW_ID_in_primary1382 = frozenset([1, 75, 100])
    FOLLOW_index_expr_in_primary1384 = frozenset([1, 75, 100])
    FOLLOW_call_expr_in_primary1387 = frozenset([1])
    FOLLOW_75_in_call_expr1398 = frozenset([8, 30, 34, 39, 47, 61, 68, 75, 76, 83, 100, 121, 126, 132])
    FOLLOW_expr_list_in_call_expr1400 = frozenset([76])
    FOLLOW_76_in_call_expr1403 = frozenset([1])
    FOLLOW_100_in_index_expr1439 = frozenset([8, 30, 34, 39, 47, 61, 68, 75, 83, 100, 121, 126, 132])
    FOLLOW_expr_in_index_expr1441 = frozenset([101])
    FOLLOW_101_in_index_expr1443 = frozenset([1])
    FOLLOW_100_in_index_expr1458 = frozenset([8, 30, 34, 39, 47, 61, 68, 75, 83, 100, 121, 126, 132])
    FOLLOW_expr_in_index_expr1460 = frozenset([88])
    FOLLOW_88_in_index_expr1462 = frozenset([8, 30, 34, 39, 47, 61, 68, 75, 83, 100, 101, 121, 126, 132])
    FOLLOW_expr_in_index_expr1464 = frozenset([101])
    FOLLOW_101_in_index_expr1467 = frozenset([1])
    FOLLOW_exec_expr_in_exec_list1492 = frozenset([1, 82])
    FOLLOW_82_in_exec_list1495 = frozenset([34, 80, 84])
    FOLLOW_exec_expr_in_exec_list1497 = frozenset([1, 82])
    FOLLOW_member_expr_in_member_list1520 = frozenset([1, 82])
    FOLLOW_82_in_member_list1523 = frozenset([34])
    FOLLOW_member_expr_in_member_list1525 = frozenset([1, 82])
    FOLLOW_member_expr_in_exec_expr1537 = frozenset([1, 71, 74, 78, 80, 81, 84, 85, 90, 95, 103, 134])
    FOLLOW_assign_op_in_exec_expr1542 = frozenset([8, 30, 34, 39, 47, 61, 68, 75, 83, 100, 121, 126, 132])
    FOLLOW_expr_in_exec_expr1544 = frozenset([1])
    FOLLOW_80_in_exec_expr1565 = frozenset([1])
    FOLLOW_84_in_exec_expr1582 = frozenset([1])
    FOLLOW_80_in_exec_expr1613 = frozenset([34])
    FOLLOW_member_expr_in_exec_expr1615 = frozenset([1])
    FOLLOW_84_in_exec_expr1630 = frozenset([34])
    FOLLOW_member_expr_in_exec_expr1632 = frozenset([1])
    FOLLOW_exec_list_in_exec_stmt1678 = frozenset([92])
    FOLLOW_92_in_exec_stmt1680 = frozenset([1])
    FOLLOW_expr_in_expr_list1703 = frozenset([1, 82])
    FOLLOW_82_in_expr_list1706 = frozenset([8, 30, 34, 39, 47, 61, 68, 75, 83, 100, 121, 126, 132])
    FOLLOW_expr_in_expr_list1708 = frozenset([1, 82])
    FOLLOW_82_in_expr_list1712 = frozenset([1])
    FOLLOW_logic_or_expr_in_expr1734 = frozenset([1])
    FOLLOW_logic_and_expr_in_logic_or_expr1744 = frozenset([1, 135])
    FOLLOW_135_in_logic_or_expr1747 = frozenset([8, 30, 34, 39, 47, 61, 68, 75, 83, 100, 121, 126, 132])
    FOLLOW_logic_and_expr_in_logic_or_expr1750 = frozenset([1, 135])
    FOLLOW_bitwise_or_expr_in_logic_and_expr1762 = frozenset([1, 72])
    FOLLOW_72_in_logic_and_expr1765 = frozenset([8, 30, 34, 39, 47, 61, 68, 75, 83, 100, 121, 126, 132])
    FOLLOW_bitwise_or_expr_in_logic_and_expr1768 = frozenset([1, 72])
    FOLLOW_bitwise_xor_expr_in_bitwise_or_expr1780 = frozenset([1, 133])
    FOLLOW_133_in_bitwise_or_expr1783 = frozenset([8, 30, 34, 39, 47, 61, 68, 75, 83, 100, 121, 126, 132])
    FOLLOW_bitwise_xor_expr_in_bitwise_or_expr1786 = frozenset([1, 133])
    FOLLOW_bitwise_and_expr_in_bitwise_xor_expr1798 = frozenset([1, 102])
    FOLLOW_102_in_bitwise_xor_expr1801 = frozenset([8, 30, 34, 39, 47, 61, 68, 75, 83, 100, 121, 126, 132])
    FOLLOW_bitwise_and_expr_in_bitwise_xor_expr1804 = frozenset([1, 102])
    FOLLOW_relation_expr_in_bitwise_and_expr1816 = frozenset([1, 73])
    FOLLOW_73_in_bitwise_and_expr1819 = frozenset([8, 30, 34, 39, 47, 61, 68, 75, 83, 100, 121, 126, 132])
    FOLLOW_relation_expr_in_bitwise_and_expr1822 = frozenset([1, 73])
    FOLLOW_add_expr_in_relation_expr1834 = frozenset([1, 69, 93, 94, 96, 98, 99])
    FOLLOW_set_in_relation_expr1837 = frozenset([8, 30, 34, 39, 47, 61, 68, 75, 83, 100, 121, 126, 132])
    FOLLOW_add_expr_in_relation_expr1852 = frozenset([1])
    FOLLOW_mul_expr_in_add_expr1864 = frozenset([1, 79, 83])
    FOLLOW_set_in_add_expr1867 = frozenset([8, 30, 34, 39, 47, 61, 68, 75, 83, 100, 121, 126, 132])
    FOLLOW_mul_expr_in_add_expr1874 = frozenset([1, 79, 83])
    FOLLOW_not_expr_in_mul_expr1886 = frozenset([1, 70, 77, 89])
    FOLLOW_set_in_mul_expr1889 = frozenset([8, 30, 34, 39, 47, 61, 68, 75, 83, 100, 121, 126, 132])
    FOLLOW_not_expr_in_mul_expr1898 = frozenset([1, 70, 77, 89])
    FOLLOW_68_in_not_expr1912 = frozenset([8, 30, 34, 39, 47, 61, 75, 83, 100, 121, 126, 132])
    FOLLOW_negative_expr_in_not_expr1915 = frozenset([1])
    FOLLOW_83_in_negative_expr1950 = frozenset([8, 30, 34, 39, 47, 61, 75, 100, 121, 126, 132])
    FOLLOW_atom_in_negative_expr1954 = frozenset([1])
    FOLLOW_literal_in_atom1987 = frozenset([1])
    FOLLOW_member_expr_in_atom1992 = frozenset([1])
    FOLLOW_array_decl_in_atom1997 = frozenset([1])
    FOLLOW_object_decl_in_atom2002 = frozenset([1])
    FOLLOW_new_clause_in_atom2007 = frozenset([1])
    FOLLOW_sprintf_in_atom2012 = frozenset([1])
    FOLLOW_75_in_atom2017 = frozenset([8, 30, 34, 39, 47, 61, 68, 75, 83, 100, 121, 126, 132])
    FOLLOW_expr_in_atom2019 = frozenset([76])
    FOLLOW_76_in_atom2021 = frozenset([1])
    FOLLOW_121_in_new_clause2062 = frozenset([34])
    FOLLOW_module_in_new_clause2064 = frozenset([75])
    FOLLOW_call_expr_in_new_clause2066 = frozenset([1])
    FOLLOW_ID_in_module2088 = frozenset([1, 86])
    FOLLOW_86_in_module2091 = frozenset([34])
    FOLLOW_ID_in_module2093 = frozenset([1, 86])
    FOLLOW_100_in_array_decl2118 = frozenset([8, 30, 34, 39, 47, 61, 68, 75, 83, 100, 101, 121, 126, 132])
    FOLLOW_expr_list_in_array_decl2120 = frozenset([101])
    FOLLOW_101_in_array_decl2123 = frozenset([1])
    FOLLOW_132_in_object_decl2145 = frozenset([34, 39, 61, 82, 136])
    FOLLOW_property_in_object_decl2147 = frozenset([82, 136])
    FOLLOW_82_in_object_decl2151 = frozenset([34, 39, 61])
    FOLLOW_property_in_object_decl2153 = frozenset([82, 136])
    FOLLOW_82_in_object_decl2157 = frozenset([136])
    FOLLOW_136_in_object_decl2160 = frozenset([1])
    FOLLOW_set_in_property2181 = frozenset([91])
    FOLLOW_91_in_property2193 = frozenset([8, 30, 34, 39, 47, 61, 68, 75, 83, 100, 121, 126, 132])
    FOLLOW_expr_in_property2195 = frozenset([1])
    FOLLOW_126_in_sprintf2207 = frozenset([75])
    FOLLOW_75_in_sprintf2209 = frozenset([8, 30, 34, 39, 47, 61, 68, 75, 83, 100, 121, 126, 132])
    FOLLOW_expr_in_sprintf2211 = frozenset([76, 82])
    FOLLOW_82_in_sprintf2214 = frozenset([8, 30, 34, 39, 47, 61, 68, 75, 83, 100, 121, 126, 132])
    FOLLOW_expr_list_in_sprintf2216 = frozenset([76])
    FOLLOW_76_in_sprintf2220 = frozenset([1])
    FOLLOW_100_in_synpred1_Expr1439 = frozenset([8, 30, 34, 39, 47, 61, 68, 75, 83, 100, 121, 126, 132])
    FOLLOW_expr_in_synpred1_Expr1441 = frozenset([101])
    FOLLOW_101_in_synpred1_Expr1443 = frozenset([1])



def main(argv, stdin=sys.stdin, stdout=sys.stdout, stderr=sys.stderr):
    from antlr3.main import ParserMain
    main = ParserMain("ExprLexer", ExprParser)

    main.stdin = stdin
    main.stdout = stdout
    main.stderr = stderr
    main.execute(argv)



if __name__ == '__main__':
    main(sys.argv)
