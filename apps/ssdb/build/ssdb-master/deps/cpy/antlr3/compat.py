"""Compatibility stuff"""

# begin[licence]
#
# [The "BSD licence"]
# Copyright (c) 2005-2008 Terence Parr
# All rights reserved.
#
# Redistribution and use in source and binary forms, with or without
# modification, are permitted provided that the following conditions
# are met:
# 1. Redistributions of source code must retain the above copyright
#    notice, this list of conditions and the following disclaimer.
# 2. Redistributions in binary form must reproduce the above copyright
#    notice, this list of conditions and the following disclaimer in the
#    documentation and/or other materials provided with the distribution.
# 3. The name of the author may not be used to endorse or promote products
#    derived from this software without specific prior written permission.
#
# THIS SOFTWARE IS PROVIDED BY THE AUTHOR ``AS IS'' AND ANY EXPRESS OR
# IMPLIED WARRANTIES, INCLUDING, BUT NOT LIMITED TO, THE IMPLIED WARRANTIES
# OF MERCHANTABILITY AND FITNESS FOR A PARTICULAR PURPOSE ARE DISCLAIMED.
# IN NO EVENT SHALL THE AUTHOR BE LIABLE FOR ANY DIRECT, INDIRECT,
# INCIDENTAL, SPECIAL, EXEMPLARY, OR CONSEQUENTIAL DAMAGES (INCLUDING, BUT
# NOT LIMITED TO, PROCUREMENT OF SUBSTITUTE GOODS OR SERVICES; LOSS OF USE,
# DATA, OR PROFITS; OR BUSINESS INTERRUPTION) HOWEVER CAUSED AND ON ANY
# THEORY OF LIABILITY, WHETHER IN CONTRACT, STRICT LIABILITY, OR TORT
# (INCLUDING NEGLIGENCE OR OTHERWISE) ARISING IN ANY WAY OUT OF THE USE OF
# THIS SOFTWARE, EVEN IF ADVISED OF THE POSSIBILITY OF SUCH DAMAGE.
#
# end[licence]

try:
    set = set
    frozenset = frozenset
except NameError:
    from sets import Set as set, ImmutableSet as frozenset


try:
    reversed = reversed
except NameError:
    def reversed(l):
        l = l[:]
        l.reverse()
        return l


