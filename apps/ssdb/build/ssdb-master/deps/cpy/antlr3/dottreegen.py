""" @package antlr3.dottreegenerator
@brief ANTLR3 runtime package, tree module

This module contains all support classes for AST construction and tree parsers.

"""

# begin[licence]
#
# [The "BSD licence"]
# Copyright (c) 2005-2008 Terence Parr
# All rights reserved.
#
# Redistribution and use in source and binary forms, with or without
# modification, are permitted provided that the following conditions
# are met:
# 1. Redistributions of source code must retain the above copyright
#    notice, this list of conditions and the following disclaimer.
# 2. Redistributions in binary form must reproduce the above copyright
#    notice, this list of conditions and the following disclaimer in the
#    documentation and/or other materials provided with the distribution.
# 3. The name of the author may not be used to endorse or promote products
#    derived from this software without specific prior written permission.
#
# THIS SOFTWARE IS PROVIDED BY THE AUTHOR ``AS IS'' AND ANY EXPRESS OR
# IMPLIED WARRANTIES, INCLUDING, BUT NOT LIMITED TO, THE IMPLIED WARRANTIES
# OF MERCHANTABILITY AND FITNESS FOR A PARTICULAR PURPOSE ARE DISCLAIMED.
# IN NO EVENT SHALL THE AUTHOR BE LIABLE FOR ANY DIRECT, INDIRECT,
# INCIDENTAL, SPECIAL, EXEMPLARY, OR CONSEQUENTIAL DAMAGES (INCLUDING, BUT
# NOT LIMITED TO, PROCUREMENT OF SUBSTITUTE GOODS OR SERVICES; LOSS OF USE,
# DATA, OR PROFITS; OR BUSINESS INTERRUPTION) HOWEVER CAUSED AND ON ANY
# THEORY OF LIABILITY, WHETHER IN CONTRACT, STRICT LIABILITY, OR TORT
# (INCLUDING NEGLIGENCE OR OTHERWISE) ARISING IN ANY WAY OUT OF THE USE OF
# THIS SOFTWARE, EVEN IF ADVISED OF THE POSSIBILITY OF SUCH DAMAGE.
#
# end[licence]

# lot's of docstrings are missing, don't complain for now...
# pylint: disable-msg=C0111

from antlr3.tree import CommonTreeAdaptor
import stringtemplate3

class DOTTreeGenerator(object):
    """
    A utility class to generate DOT diagrams (graphviz) from
    arbitrary trees.  You can pass in your own templates and
    can pass in any kind of tree or use Tree interface method.
    """

    _treeST = stringtemplate3.StringTemplate(
        template=(
        "digraph {\n" +
        "  ordering=out;\n" +
        "  ranksep=.4;\n" +
        "  node [shape=plaintext, fixedsize=true, fontsize=11, fontname=\"Courier\",\n" +
        "        width=.25, height=.25];\n" +
        "  edge [arrowsize=.5]\n" +
        "  $nodes$\n" +
        "  $edges$\n" +
        "}\n")
        )

    _nodeST = stringtemplate3.StringTemplate(
        template="$name$ [label=\"$text$\"];\n"
        )

    _edgeST = stringtemplate3.StringTemplate(
        template="$parent$ -> $child$ // \"$parentText$\" -> \"$childText$\"\n"
        )

    def __init__(self):
        ## Track node to number mapping so we can get proper node name back
        self.nodeToNumberMap = {}

        ## Track node number so we can get unique node names
        self.nodeNumber = 0


    def toDOT(self, tree, adaptor=None, treeST=_treeST, edgeST=_edgeST):
        if adaptor is None:
            adaptor = CommonTreeAdaptor()

        treeST = treeST.getInstanceOf()

        self.nodeNumber = 0
        self.toDOTDefineNodes(tree, adaptor, treeST)

        self.nodeNumber = 0
        self.toDOTDefineEdges(tree, adaptor, treeST, edgeST)
        return treeST


    def toDOTDefineNodes(self, tree, adaptor, treeST, knownNodes=None):
        if knownNodes is None:
            knownNodes = set()

        if tree is None:
            return

        n = adaptor.getChildCount(tree)
        if n == 0:
            # must have already dumped as child from previous
            # invocation; do nothing
            return

        # define parent node
        number = self.getNodeNumber(tree)
        if number not in knownNodes:
            parentNodeST = self.getNodeST(adaptor, tree)
            treeST.setAttribute("nodes", parentNodeST)
            knownNodes.add(number)

        # for each child, do a "<unique-name> [label=text]" node def
        for i in range(n):
            child = adaptor.getChild(tree, i)
            
            number = self.getNodeNumber(child)
            if number not in knownNodes:
                nodeST = self.getNodeST(adaptor, child)
                treeST.setAttribute("nodes", nodeST)
                knownNodes.add(number)

            self.toDOTDefineNodes(child, adaptor, treeST, knownNodes)


    def toDOTDefineEdges(self, tree, adaptor, treeST, edgeST):
        if tree is None:
            return

        n = adaptor.getChildCount(tree)
        if n == 0:
            # must have already dumped as child from previous
            # invocation; do nothing
            return

        parentName = "n%d" % self.getNodeNumber(tree)

        # for each child, do a parent -> child edge using unique node names
        parentText = adaptor.getText(tree)
        for i in range(n):
            child = adaptor.getChild(tree, i)
            childText = adaptor.getText(child)
            childName = "n%d" % self.getNodeNumber(child)
            edgeST = edgeST.getInstanceOf()
            edgeST.setAttribute("parent", parentName)
            edgeST.setAttribute("child", childName)
            edgeST.setAttribute("parentText", parentText)
            edgeST.setAttribute("childText", childText)
            treeST.setAttribute("edges", edgeST)
            self.toDOTDefineEdges(child, adaptor, treeST, edgeST)


    def getNodeST(self, adaptor, t):
        text = adaptor.getText(t)
        nodeST = self._nodeST.getInstanceOf()
        uniqueName = "n%d" % self.getNodeNumber(t)
        nodeST.setAttribute("name", uniqueName)
        if text is not None:
            text = text.replace('"', r'\\"')
        nodeST.setAttribute("text", text)
        return nodeST


    def getNodeNumber(self, t):
        try:
            return self.nodeToNumberMap[t]
        except KeyError:
            self.nodeToNumberMap[t] = self.nodeNumber
            self.nodeNumber += 1
            return self.nodeNumber - 1


def toDOT(tree, adaptor=None, treeST=DOTTreeGenerator._treeST, edgeST=DOTTreeGenerator._edgeST):
    """
    Generate DOT (graphviz) for a whole tree not just a node.
    For example, 3+4*5 should generate:

    digraph {
        node [shape=plaintext, fixedsize=true, fontsize=11, fontname="Courier",
            width=.4, height=.2];
        edge [arrowsize=.7]
        "+"->3
        "+"->"*"
        "*"->4
        "*"->5
    }

    Return the ST not a string in case people want to alter.

    Takes a Tree interface object.

    Example of invokation:

        import antlr3
        import antlr3.extras

        input = antlr3.ANTLRInputStream(sys.stdin)
        lex = TLexer(input)
        tokens = antlr3.CommonTokenStream(lex)
        parser = TParser(tokens)
        tree = parser.e().tree
        print tree.toStringTree()
        st = antlr3.extras.toDOT(t)
        print st
        
    """

    gen = DOTTreeGenerator()
    return gen.toDOT(tree, adaptor, treeST, edgeST)
