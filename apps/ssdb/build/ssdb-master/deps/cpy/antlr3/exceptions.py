"""ANTLR3 exception hierarchy"""

# begin[licence]
#
# [The "BSD licence"]
# Copyright (c) 2005-2008 Terence Parr
# All rights reserved.
#
# Redistribution and use in source and binary forms, with or without
# modification, are permitted provided that the following conditions
# are met:
# 1. Redistributions of source code must retain the above copyright
#    notice, this list of conditions and the following disclaimer.
# 2. Redistributions in binary form must reproduce the above copyright
#    notice, this list of conditions and the following disclaimer in the
#    documentation and/or other materials provided with the distribution.
# 3. The name of the author may not be used to endorse or promote products
#    derived from this software without specific prior written permission.
#
# THIS SOFTWARE IS PROVIDED BY THE AUTHOR ``AS IS'' AND ANY EXPRESS OR
# IMPLIED WARRANTIES, INCLUDING, BUT NOT LIMITED TO, THE IMPLIED WARRANTIES
# OF MERCHANTABILITY AND FITNESS FOR A PARTICULAR PURPOSE ARE DISCLAIMED.
# IN NO EVENT SHALL THE AUTHOR BE LIABLE FOR ANY DIRECT, INDIRECT,
# INCIDENTAL, SPECIAL, EXEMPLARY, OR CONSEQUENTIAL DAMAGES (INCLUDING, BUT
# NOT LIMITED TO, PROCUREMENT OF SUBSTITUTE GOODS OR SERVICES; LOSS OF USE,
# DATA, OR PROFITS; OR BUSINESS INTERRUPTION) HOWEVER CAUSED AND ON ANY
# THEORY OF LIABILITY, WHETHER IN CONTRACT, STRICT LIABILITY, OR TORT
# (INCLUDING NEGLIGENCE OR OTHERWISE) ARISING IN ANY WAY OUT OF THE USE OF
# THIS SOFTWARE, EVEN IF ADVISED OF THE POSSIBILITY OF SUCH DAMAGE.
#
# end[licence]

from antlr3.constants import INVALID_TOKEN_TYPE


class BacktrackingFailed(Exception):
    """@brief Raised to signal failed backtrack attempt"""

    pass


class RecognitionException(Exception):
    """@brief The root of the ANTLR exception hierarchy.

    To avoid English-only error messages and to generally make things
    as flexible as possible, these exceptions are not created with strings,
    but rather the information necessary to generate an error.  Then
    the various reporting methods in Parser and Lexer can be overridden
    to generate a localized error message.  For example, MismatchedToken
    exceptions are built with the expected token type.
    So, don't expect getMessage() to return anything.

    Note that as of Java 1.4, you can access the stack trace, which means
    that you can compute the complete trace of rules from the start symbol.
    This gives you considerable context information with which to generate
    useful error messages.

    ANTLR generates code that throws exceptions upon recognition error and
    also generates code to catch these exceptions in each rule.  If you
    want to quit upon first error, you can turn off the automatic error
    handling mechanism using rulecatch action, but you still need to
    override methods mismatch and recoverFromMismatchSet.
    
    In general, the recognition exceptions can track where in a grammar a
    problem occurred and/or what was the expected input.  While the parser
    knows its state (such as current input symbol and line info) that
    state can change before the exception is reported so current token index
    is computed and stored at exception time.  From this info, you can
    perhaps print an entire line of input not just a single token, for example.
    Better to just say the recognizer had a problem and then let the parser
    figure out a fancy report.
    
    """

    def __init__(self, input=None):
        Exception.__init__(self)

	# What input stream did the error occur in?
        self.input = None

        # What is index of token/char were we looking at when the error
        # occurred?
        self.index = None

	# The current Token when an error occurred.  Since not all streams
	# can retrieve the ith Token, we have to track the Token object.
	# For parsers.  Even when it's a tree parser, token might be set.
        self.token = None

	# If this is a tree parser exception, node is set to the node with
	# the problem.
        self.node = None

	# The current char when an error occurred. For lexers.
        self.c = None

	# Track the line at which the error occurred in case this is
	# generated from a lexer.  We need to track this since the
        # unexpected char doesn't carry the line info.
        self.line = None

        self.charPositionInLine = None

        # If you are parsing a tree node stream, you will encounter som
        # imaginary nodes w/o line/col info.  We now search backwards looking
        # for most recent token with line/col info, but notify getErrorHeader()
        # that info is approximate.
        self.approximateLineInfo = False

        
        if input is not None:
            self.input = input
            self.index = input.index()

            # late import to avoid cyclic dependencies
            from antlr3.streams import TokenStream, CharStream
            from antlr3.tree import TreeNodeStream

            if isinstance(self.input, TokenStream):
                self.token = self.input.LT(1)
                self.line = self.token.line
                self.charPositionInLine = self.token.charPositionInLine

            if isinstance(self.input, TreeNodeStream):
                self.extractInformationFromTreeNodeStream(self.input)

            else:
                if isinstance(self.input, CharStream):
                    self.c = self.input.LT(1)
                    self.line = self.input.line
                    self.charPositionInLine = self.input.charPositionInLine

                else:
                    self.c = self.input.LA(1)

    def extractInformationFromTreeNodeStream(self, nodes):
        from antlr3.tree import Tree, CommonTree
        from antlr3.tokens import CommonToken
        
        self.node = nodes.LT(1)
        adaptor = nodes.adaptor
        payload = adaptor.getToken(self.node)
        if payload is not None:
            self.token = payload
            if payload.line <= 0:
                # imaginary node; no line/pos info; scan backwards
                i = -1
                priorNode = nodes.LT(i)
                while priorNode is not None:
                    priorPayload = adaptor.getToken(priorNode)
                    if priorPayload is not None and priorPayload.line > 0:
                        # we found the most recent real line / pos info
                        self.line = priorPayload.line
                        self.charPositionInLine = priorPayload.charPositionInLine
                        self.approximateLineInfo = True
                        break
                    
                    i -= 1
                    priorNode = nodes.LT(i)
                    
            else: # node created from real token
                self.line = payload.line
                self.charPositionInLine = payload.charPositionInLine
                
        elif isinstance(self.node, Tree):
            self.line = self.node.line
            self.charPositionInLine = self.node.charPositionInLine
            if isinstance(self.node, CommonTree):
                self.token = self.node.token

        else:
            type = adaptor.getType(self.node)
            text = adaptor.getText(self.node)
            self.token = CommonToken(type=type, text=text)

     
    def getUnexpectedType(self):
        """Return the token type or char of the unexpected input element"""

        from antlr3.streams import TokenStream
        from antlr3.tree import TreeNodeStream

        if isinstance(self.input, TokenStream):
            return self.token.type

        elif isinstance(self.input, TreeNodeStream):
            adaptor = self.input.treeAdaptor
            return adaptor.getType(self.node)

        else:
            return self.c

    unexpectedType = property(getUnexpectedType)
    

class MismatchedTokenException(RecognitionException):
    """@brief A mismatched char or Token or tree node."""
    
    def __init__(self, expecting, input):
        RecognitionException.__init__(self, input)
        self.expecting = expecting
        

    def __str__(self):
        #return "MismatchedTokenException("+self.expecting+")"
        return "MismatchedTokenException(%r!=%r)" % (
            self.getUnexpectedType(), self.expecting
            )
    __repr__ = __str__


class UnwantedTokenException(MismatchedTokenException):
    """An extra token while parsing a TokenStream"""

    def getUnexpectedToken(self):
        return self.token


    def __str__(self):
        exp = ", expected %s" % self.expecting
        if self.expecting == INVALID_TOKEN_TYPE:
            exp = ""

        if self.token is None:
            return "UnwantedTokenException(found=%s%s)" % (None, exp)

        return "UnwantedTokenException(found=%s%s)" % (self.token.text, exp)
    __repr__ = __str__


class MissingTokenException(MismatchedTokenException):
    """
    We were expecting a token but it's not found.  The current token
    is actually what we wanted next.
    """

    def __init__(self, expecting, input, inserted):
        MismatchedTokenException.__init__(self, expecting, input)

        self.inserted = inserted


    def getMissingType(self):
        return self.expecting


    def __str__(self):
        if self.inserted is not None and self.token is not None:
            return "MissingTokenException(inserted %r at %r)" % (
                self.inserted, self.token.text)

        if self.token is not None:
            return "MissingTokenException(at %r)" % self.token.text

        return "MissingTokenException"
    __repr__ = __str__


class MismatchedRangeException(RecognitionException):
    """@brief The next token does not match a range of expected types."""

    def __init__(self, a, b, input):
        RecognitionException.__init__(self, input)

        self.a = a
        self.b = b
        

    def __str__(self):
        return "MismatchedRangeException(%r not in [%r..%r])" % (
            self.getUnexpectedType(), self.a, self.b
            )
    __repr__ = __str__
    

class MismatchedSetException(RecognitionException):
    """@brief The next token does not match a set of expected types."""

    def __init__(self, expecting, input):
        RecognitionException.__init__(self, input)

        self.expecting = expecting
        

    def __str__(self):
        return "MismatchedSetException(%r not in %r)" % (
            self.getUnexpectedType(), self.expecting
            )
    __repr__ = __str__


class MismatchedNotSetException(MismatchedSetException):
    """@brief Used for remote debugger deserialization"""
    
    def __str__(self):
        return "MismatchedNotSetException(%r!=%r)" % (
            self.getUnexpectedType(), self.expecting
            )
    __repr__ = __str__


class NoViableAltException(RecognitionException):
    """@brief Unable to decide which alternative to choose."""

    def __init__(
        self, grammarDecisionDescription, decisionNumber, stateNumber, input
        ):
        RecognitionException.__init__(self, input)

        self.grammarDecisionDescription = grammarDecisionDescription
        self.decisionNumber = decisionNumber
        self.stateNumber = stateNumber


    def __str__(self):
        return "NoViableAltException(%r!=[%r])" % (
            self.unexpectedType, self.grammarDecisionDescription
            )
    __repr__ = __str__
    

class EarlyExitException(RecognitionException):
    """@brief The recognizer did not match anything for a (..)+ loop."""

    def __init__(self, decisionNumber, input):
        RecognitionException.__init__(self, input)

        self.decisionNumber = decisionNumber


class FailedPredicateException(RecognitionException):
    """@brief A semantic predicate failed during validation.

    Validation of predicates
    occurs when normally parsing the alternative just like matching a token.
    Disambiguating predicate evaluation occurs when we hoist a predicate into
    a prediction decision.
    """

    def __init__(self, input, ruleName, predicateText):
        RecognitionException.__init__(self, input)
        
        self.ruleName = ruleName
        self.predicateText = predicateText


    def __str__(self):
        return "FailedPredicateException("+self.ruleName+",{"+self.predicateText+"}?)"
    __repr__ = __str__
    

class MismatchedTreeNodeException(RecognitionException):
    """@brief The next tree mode does not match the expected type."""

    def __init__(self, expecting, input):
        RecognitionException.__init__(self, input)
        
        self.expecting = expecting

    def __str__(self):
        return "MismatchedTreeNodeException(%r!=%r)" % (
            self.getUnexpectedType(), self.expecting
            )
    __repr__ = __str__
