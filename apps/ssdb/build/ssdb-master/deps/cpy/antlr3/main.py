"""ANTLR3 runtime package"""

# begin[licence]
#
# [The "BSD licence"]
# Copyright (c) 2005-2008 Terence Parr
# All rights reserved.
#
# Redistribution and use in source and binary forms, with or without
# modification, are permitted provided that the following conditions
# are met:
# 1. Redistributions of source code must retain the above copyright
#    notice, this list of conditions and the following disclaimer.
# 2. Redistributions in binary form must reproduce the above copyright
#    notice, this list of conditions and the following disclaimer in the
#    documentation and/or other materials provided with the distribution.
# 3. The name of the author may not be used to endorse or promote products
#    derived from this software without specific prior written permission.
#
# THIS SOFTWARE IS PROVIDED BY THE AUTHOR ``AS IS'' AND ANY EXPRESS OR
# IMPLIED WARRANTIES, INCLUDING, BUT NOT LIMITED TO, THE IMPLIED WARRANTIES
# OF MERCHANTABILITY AND FITNESS FOR A PARTICULAR PURPOSE ARE DISCLAIMED.
# IN NO EVENT SHALL THE AUTHOR BE LIABLE FOR ANY DIRECT, INDIRECT,
# INCIDENTAL, SPECIAL, EXEMPLARY, OR CONSEQUENTIAL DAMAGES (INCLUDING, BUT
# NOT LIMITED TO, PROCUREMENT OF SUBSTITUTE GOODS OR SERVICES; LOSS OF USE,
# DATA, OR PROFITS; OR BUSINESS INTERRUPTION) HOWEVER CAUSED AND ON ANY
# THEORY OF LIABILITY, WHETHER IN CONTRACT, STRICT LIABILITY, OR TORT
# (INCLUDING NEGLIGENCE OR OTHERWISE) ARISING IN ANY WAY OUT OF THE USE OF
# THIS SOFTWARE, EVEN IF ADVISED OF THE POSSIBILITY OF SUCH DAMAGE.
#
# end[licence]


import sys
import optparse

import antlr3


class _Main(object):
    def __init__(self):
        self.stdin = sys.stdin
        self.stdout = sys.stdout
        self.stderr = sys.stderr

        
    def parseOptions(self, argv):
        optParser = optparse.OptionParser()
        optParser.add_option(
            "--encoding",
            action="store",
            type="string",
            dest="encoding"
            )
        optParser.add_option(
            "--input",
            action="store",
            type="string",
            dest="input"
            )
        optParser.add_option(
            "--interactive", "-i",
            action="store_true",
            dest="interactive"
            )
        optParser.add_option(
            "--no-output",
            action="store_true",
            dest="no_output"
            )
        optParser.add_option(
            "--profile",
            action="store_true",
            dest="profile"
            )
        optParser.add_option(
            "--hotshot",
            action="store_true",
            dest="hotshot"
            )

        self.setupOptions(optParser)
        
        return optParser.parse_args(argv[1:])


    def setupOptions(self, optParser):
        pass


    def execute(self, argv):
        options, args = self.parseOptions(argv)

        self.setUp(options)
        
        if options.interactive:
            while True:
                try:
                    input = raw_input(">>> ")
                except (EOFError, KeyboardInterrupt):
                    self.stdout.write("\nBye.\n")
                    break
            
                inStream = antlr3.ANTLRStringStream(input)
                self.parseStream(options, inStream)
            
        else:
            if options.input is not None:
                inStream = antlr3.ANTLRStringStream(options.input)

            elif len(args) == 1 and args[0] != '-':
                inStream = antlr3.ANTLRFileStream(
                    args[0], encoding=options.encoding
                    )

            else:
                inStream = antlr3.ANTLRInputStream(
                    self.stdin, encoding=options.encoding
                    )

            if options.profile:
                try:
                    import cProfile as profile
                except ImportError:
                    import profile

                profile.runctx(
                    'self.parseStream(options, inStream)',
                    globals(),
                    locals(),
                    'profile.dat'
                    )

                import pstats
                stats = pstats.Stats('profile.dat')
                stats.strip_dirs()
                stats.sort_stats('time')
                stats.print_stats(100)

            elif options.hotshot:
                import hotshot

                profiler = hotshot.Profile('hotshot.dat')
                profiler.runctx(
                    'self.parseStream(options, inStream)',
                    globals(),
                    locals()
                    )

            else:
                self.parseStream(options, inStream)


    def setUp(self, options):
        pass

    
    def parseStream(self, options, inStream):
        raise NotImplementedError


    def write(self, options, text):
        if not options.no_output:
            self.stdout.write(text)


    def writeln(self, options, text):
        self.write(options, text + '\n')


class LexerMain(_Main):
    def __init__(self, lexerClass):
        _Main.__init__(self)

        self.lexerClass = lexerClass
        
    
    def parseStream(self, options, inStream):
        lexer = self.lexerClass(inStream)
        for token in lexer:
            self.writeln(options, str(token))


class ParserMain(_Main):
    def __init__(self, lexerClassName, parserClass):
        _Main.__init__(self)

        self.lexerClassName = lexerClassName
        self.lexerClass = None
        self.parserClass = parserClass
        
    
    def setupOptions(self, optParser):
        optParser.add_option(
            "--lexer",
            action="store",
            type="string",
            dest="lexerClass",
            default=self.lexerClassName
            )
        optParser.add_option(
            "--rule",
            action="store",
            type="string",
            dest="parserRule"
            )


    def setUp(self, options):
        lexerMod = __import__(options.lexerClass)
        self.lexerClass = getattr(lexerMod, options.lexerClass)

        
    def parseStream(self, options, inStream):
        lexer = self.lexerClass(inStream)
        tokenStream = antlr3.CommonTokenStream(lexer)
        parser = self.parserClass(tokenStream)
        result = getattr(parser, options.parserRule)()
        if result is not None:
            if hasattr(result, 'tree'):
                if result.tree is not None:
                    self.writeln(options, result.tree.toStringTree())
            else:
                self.writeln(options, repr(result))


class WalkerMain(_Main):
    def __init__(self, walkerClass):
        _Main.__init__(self)

        self.lexerClass = None
        self.parserClass = None
        self.walkerClass = walkerClass
        
    
    def setupOptions(self, optParser):
        optParser.add_option(
            "--lexer",
            action="store",
            type="string",
            dest="lexerClass",
            default=None
            )
        optParser.add_option(
            "--parser",
            action="store",
            type="string",
            dest="parserClass",
            default=None
            )
        optParser.add_option(
            "--parser-rule",
            action="store",
            type="string",
            dest="parserRule",
            default=None
            )
        optParser.add_option(
            "--rule",
            action="store",
            type="string",
            dest="walkerRule"
            )


    def setUp(self, options):
        lexerMod = __import__(options.lexerClass)
        self.lexerClass = getattr(lexerMod, options.lexerClass)
        parserMod = __import__(options.parserClass)
        self.parserClass = getattr(parserMod, options.parserClass)

        
    def parseStream(self, options, inStream):
        lexer = self.lexerClass(inStream)
        tokenStream = antlr3.CommonTokenStream(lexer)
        parser = self.parserClass(tokenStream)
        result = getattr(parser, options.parserRule)()
        if result is not None:
            assert hasattr(result, 'tree'), "Parser did not return an AST"
            nodeStream = antlr3.tree.CommonTreeNodeStream(result.tree)
            nodeStream.setTokenStream(tokenStream)
            walker = self.walkerClass(nodeStream)
            result = getattr(walker, options.walkerRule)()
            if result is not None:
                if hasattr(result, 'tree'):
                    self.writeln(options, result.tree.toStringTree())
                else:
                    self.writeln(options, repr(result))

