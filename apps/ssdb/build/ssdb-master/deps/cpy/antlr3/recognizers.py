"""ANTLR3 runtime package"""

# begin[licence]
#
# [The "BSD licence"]
# Copyright (c) 2005-2008 Terence Parr
# All rights reserved.
#
# Redistribution and use in source and binary forms, with or without
# modification, are permitted provided that the following conditions
# are met:
# 1. Redistributions of source code must retain the above copyright
#    notice, this list of conditions and the following disclaimer.
# 2. Redistributions in binary form must reproduce the above copyright
#    notice, this list of conditions and the following disclaimer in the
#    documentation and/or other materials provided with the distribution.
# 3. The name of the author may not be used to endorse or promote products
#    derived from this software without specific prior written permission.
#
# THIS SOFTWARE IS PROVIDED BY THE AUTHOR ``AS IS'' AND ANY EXPRESS OR
# IMPLIED WARRANTIES, INCLUDING, BUT NOT LIMITED TO, THE IMPLIED WARRANTIES
# OF MERCHANTABILITY AND FITNESS FOR A PARTICULAR PURPOSE ARE DISCLAIMED.
# IN NO EVENT SHALL THE AUTHOR BE LIABLE FOR ANY DIRECT, INDIRECT,
# INCIDENTAL, SPECIAL, EXEMPLARY, OR CONSEQUENTIAL DAMAGES (INCLUDING, BUT
# NOT LIMITED TO, PROCUREMENT OF SUBSTITUTE GOODS OR SERVICES; LOSS OF USE,
# DATA, OR PROFITS; OR BUSINESS INTERRUPTION) HOWEVER CAUSED AND ON ANY
# THEORY OF LIABILITY, WHETHER IN CONTRACT, STRICT LIABILITY, OR TORT
# (INCLUDING NEGLIGENCE OR OTHERWISE) ARISING IN ANY WAY OUT OF THE USE OF
# THIS SOFTWARE, EVEN IF ADVISED OF THE POSSIBILITY OF SUCH DAMAGE.
#
# end[licence]

import sys
import inspect

from antlr3 import runtime_version, runtime_version_str
from antlr3.constants import DEFAULT_CHANNEL, HIDDEN_CHANNEL, EOF, \
     EOR_TOKEN_TYPE, INVALID_TOKEN_TYPE
from antlr3.exceptions import RecognitionException, MismatchedTokenException, \
     MismatchedRangeException, MismatchedTreeNodeException, \
     NoViableAltException, EarlyExitException, MismatchedSetException, \
     MismatchedNotSetException, FailedPredicateException, \
     BacktrackingFailed, UnwantedTokenException, MissingTokenException
from antlr3.tokens import CommonToken, EOF_TOKEN, SKIP_TOKEN
from antlr3.compat import set, frozenset, reversed


class RecognizerSharedState(object):
    """
    The set of fields needed by an abstract recognizer to recognize input
    and recover from errors etc...  As a separate state object, it can be
    shared among multiple grammars; e.g., when one grammar imports another.

    These fields are publically visible but the actual state pointer per
    parser is protected.
    """

    def __init__(self):
        # Track the set of token types that can follow any rule invocation.
        # Stack grows upwards.
        self.following = []

        # This is true when we see an error and before having successfully
        # matched a token.  Prevents generation of more than one error message
        # per error.
        self.errorRecovery = False

        # The index into the input stream where the last error occurred.
        # This is used to prevent infinite loops where an error is found
        # but no token is consumed during recovery...another error is found,
        # ad naseum.  This is a failsafe mechanism to guarantee that at least
        # one token/tree node is consumed for two errors.
        self.lastErrorIndex = -1

        # If 0, no backtracking is going on.  Safe to exec actions etc...
        # If >0 then it's the level of backtracking.
        self.backtracking = 0

        # An array[size num rules] of Map<Integer,Integer> that tracks
        # the stop token index for each rule.  ruleMemo[ruleIndex] is
        # the memoization table for ruleIndex.  For key ruleStartIndex, you
        # get back the stop token for associated rule or MEMO_RULE_FAILED.
        #
        # This is only used if rule memoization is on (which it is by default).
        self.ruleMemo = None

        ## Did the recognizer encounter a syntax error?  Track how many.
        self.syntaxErrors = 0


        # LEXER FIELDS (must be in same state object to avoid casting
        # constantly in generated code and Lexer object) :(


	## The goal of all lexer rules/methods is to create a token object.
        # This is an instance variable as multiple rules may collaborate to
        # create a single token.  nextToken will return this object after
        # matching lexer rule(s).  If you subclass to allow multiple token
        # emissions, then set this to the last token to be matched or
        # something nonnull so that the auto token emit mechanism will not
        # emit another token.
        self.token = None

        ## What character index in the stream did the current token start at?
        # Needed, for example, to get the text for current token.  Set at
        # the start of nextToken.
        self.tokenStartCharIndex = -1

        ## The line on which the first character of the token resides
        self.tokenStartLine = None

        ## The character position of first character within the line
        self.tokenStartCharPositionInLine = None

        ## The channel number for the current token
        self.channel = None

        ## The token type for the current token
        self.type = None

        ## You can set the text for the current token to override what is in
        # the input char buffer.  Use setText() or can set this instance var.
        self.text = None
        

class BaseRecognizer(object):
    """
    @brief Common recognizer functionality.
    
    A generic recognizer that can handle recognizers generated from
    lexer, parser, and tree grammars.  This is all the parsing
    support code essentially; most of it is error recovery stuff and
    backtracking.
    """

    MEMO_RULE_FAILED = -2
    MEMO_RULE_UNKNOWN = -1

    # copies from Token object for convenience in actions
    DEFAULT_TOKEN_CHANNEL = DEFAULT_CHANNEL

    # for convenience in actions
    HIDDEN = HIDDEN_CHANNEL

    # overridden by generated subclasses
    tokenNames = None

    # The antlr_version attribute has been introduced in 3.1. If it is not
    # overwritten in the generated recognizer, we assume a default of 3.0.1.
    antlr_version = (3, 0, 1, 0)
    antlr_version_str = "3.0.1"

    def __init__(self, state=None):
        # Input stream of the recognizer. Must be initialized by a subclass.
        self.input = None

        ## State of a lexer, parser, or tree parser are collected into a state
        # object so the state can be shared.  This sharing is needed to
        # have one grammar import others and share same error variables
        # and other state variables.  It's a kind of explicit multiple
        # inheritance via delegation of methods and shared state.
        if state is None:
            state = RecognizerSharedState()
        self._state = state

        if self.antlr_version > runtime_version:
            raise RuntimeError(
                "ANTLR version mismatch: "
                "The recognizer has been generated by V%s, but this runtime "
                "is V%s. Please use the V%s runtime or higher."
                % (self.antlr_version_str,
                   runtime_version_str,
                   self.antlr_version_str))
        elif (self.antlr_version < (3, 1, 0, 0) and
              self.antlr_version != runtime_version):
            print self.antlr_version
            print runtime_version
            # FIXME: make the runtime compatible with 3.0.1 codegen
            # and remove this block.
            raise RuntimeError(
                "ANTLR version mismatch: "
                "The recognizer has been generated by V%s, but this runtime "
                "is V%s. Please use the V%s runtime."
                % (self.antlr_version_str,
                   runtime_version_str,
                   self.antlr_version_str))

    # this one only exists to shut up pylint :(
    def setInput(self, input):
        self.input = input

        
    def reset(self):
        """
        reset the parser's state; subclasses must rewinds the input stream
        """
        
        # wack everything related to error recovery
        if self._state is None:
            # no shared state work to do
            return
        
        self._state.following = []
        self._state.errorRecovery = False
        self._state.lastErrorIndex = -1
        self._state.syntaxErrors = 0
        # wack everything related to backtracking and memoization
        self._state.backtracking = 0
        if self._state.ruleMemo is not None:
            self._state.ruleMemo = {}


    def match(self, input, ttype, follow):
        """
        Match current input symbol against ttype.  Attempt
        single token insertion or deletion error recovery.  If
        that fails, throw MismatchedTokenException.

        To turn off single token insertion or deletion error
        recovery, override mismatchRecover() and have it call
        plain mismatch(), which does not recover.  Then any error
        in a rule will cause an exception and immediate exit from
        rule.  Rule would recover by resynchronizing to the set of
        symbols that can follow rule ref.
        """
        
        matchedSymbol = self.getCurrentInputSymbol(input)
        if self.input.LA(1) == ttype:
            self.input.consume()
            self._state.errorRecovery = False
            return matchedSymbol

        if self._state.backtracking > 0:
            # FIXME: need to return matchedSymbol here as well. damn!!
            raise BacktrackingFailed

        matchedSymbol = self.recoverFromMismatchedToken(input, ttype, follow)
        return matchedSymbol


    def matchAny(self, input):
        """Match the wildcard: in a symbol"""

        self._state.errorRecovery = False
        self.input.consume()


    def mismatchIsUnwantedToken(self, input, ttype):
        return input.LA(2) == ttype


    def mismatchIsMissingToken(self, input, follow):
        if follow is None:
            # we have no information about the follow; we can only consume
            # a single token and hope for the best
            return False
        
        # compute what can follow this grammar element reference
        if EOR_TOKEN_TYPE in follow:
            if len(self._state.following) > 0:
                # remove EOR if we're not the start symbol
                follow = follow - set([EOR_TOKEN_TYPE])

            viableTokensFollowingThisRule = self.computeContextSensitiveRuleFOLLOW()
            follow = follow | viableTokensFollowingThisRule

        # if current token is consistent with what could come after set
        # then we know we're missing a token; error recovery is free to
        # "insert" the missing token
        if input.LA(1) in follow or EOR_TOKEN_TYPE in follow:
            return True

        return False


    def mismatch(self, input, ttype, follow):
        """
        Factor out what to do upon token mismatch so tree parsers can behave
        differently.  Override and call mismatchRecover(input, ttype, follow)
        to get single token insertion and deletion. Use this to turn of
        single token insertion and deletion. Override mismatchRecover
        to call this instead.
        """

        if self.mismatchIsUnwantedToken(input, ttype):
            raise UnwantedTokenException(ttype, input)

        elif self.mismatchIsMissingToken(input, follow):
            raise MissingTokenException(ttype, input, None)

        raise MismatchedTokenException(ttype, input)


##     def mismatchRecover(self, input, ttype, follow):
##         if self.mismatchIsUnwantedToken(input, ttype):
##             mte = UnwantedTokenException(ttype, input)

##         elif self.mismatchIsMissingToken(input, follow):
##             mte = MissingTokenException(ttype, input)

##         else:
##             mte = MismatchedTokenException(ttype, input)

##         self.recoverFromMismatchedToken(input, mte, ttype, follow)


    def reportError(self, e):
        """Report a recognition problem.
            
        This method sets errorRecovery to indicate the parser is recovering
        not parsing.  Once in recovery mode, no errors are generated.
        To get out of recovery mode, the parser must successfully match
        a token (after a resync).  So it will go:

        1. error occurs
        2. enter recovery mode, report error
        3. consume until token found in resynch set
        4. try to resume parsing
        5. next match() will reset errorRecovery mode

        If you override, make sure to update syntaxErrors if you care about
        that.
        
        """
        
        # if we've already reported an error and have not matched a token
        # yet successfully, don't report any errors.
        if self._state.errorRecovery:
            return

        self._state.syntaxErrors += 1 # don't count spurious
        self._state.errorRecovery = True

        self.displayRecognitionError(self.tokenNames, e)


    def displayRecognitionError(self, tokenNames, e):
        hdr = self.getErrorHeader(e)
        msg = self.getErrorMessage(e, tokenNames)
        self.emitErrorMessage(hdr+" "+msg)


    def getErrorMessage(self, e, tokenNames):
        """
        What error message should be generated for the various
        exception types?
        
        Not very object-oriented code, but I like having all error message
        generation within one method rather than spread among all of the
        exception classes. This also makes it much easier for the exception
        handling because the exception classes do not have to have pointers back
        to this object to access utility routines and so on. Also, changing
        the message for an exception type would be difficult because you
        would have to subclassing exception, but then somehow get ANTLR
        to make those kinds of exception objects instead of the default.
        This looks weird, but trust me--it makes the most sense in terms
        of flexibility.

        For grammar debugging, you will want to override this to add
        more information such as the stack frame with
        getRuleInvocationStack(e, this.getClass().getName()) and,
        for no viable alts, the decision description and state etc...

        Override this to change the message generated for one or more
        exception types.
        """

        if isinstance(e, UnwantedTokenException):
            tokenName = "<unknown>"
            if e.expecting == EOF:
                tokenName = "EOF"

            else:
                tokenName = self.tokenNames[e.expecting]

            msg = "extraneous input %s expecting %s" % (
                self.getTokenErrorDisplay(e.getUnexpectedToken()),
                tokenName
                )

        elif isinstance(e, MissingTokenException):
            tokenName = "<unknown>"
            if e.expecting == EOF:
                tokenName = "EOF"

            else:
                tokenName = self.tokenNames[e.expecting]

            msg = "missing %s at %s" % (
                tokenName, self.getTokenErrorDisplay(e.token)
                )

        elif isinstance(e, MismatchedTokenException):
            tokenName = "<unknown>"
            if e.expecting == EOF:
                tokenName = "EOF"
            else:
                tokenName = self.tokenNames[e.expecting]

            msg = "mismatched input " \
                  + self.getTokenErrorDisplay(e.token) \
                  + " expecting " \
                  + tokenName

        elif isinstance(e, MismatchedTreeNodeException):
            tokenName = "<unknown>"
            if e.expecting == EOF:
                tokenName = "EOF"
            else:
                tokenName = self.tokenNames[e.expecting]

            msg = "mismatched tree node: %s expecting %s" \
                  % (e.node, tokenName)

        elif isinstance(e, NoViableAltException):
            msg = "no viable alternative at input " \
                  + self.getTokenErrorDisplay(e.token)

        elif isinstance(e, EarlyExitException):
            msg = "required (...)+ loop did not match anything at input " \
                  + self.getTokenErrorDisplay(e.token)

        elif isinstance(e, MismatchedSetException):
            msg = "mismatched input " \
                  + self.getTokenErrorDisplay(e.token) \
                  + " expecting set " \
                  + repr(e.expecting)

        elif isinstance(e, MismatchedNotSetException):
            msg = "mismatched input " \
                  + self.getTokenErrorDisplay(e.token) \
                  + " expecting set " \
                  + repr(e.expecting)

        elif isinstance(e, FailedPredicateException):
            msg = "rule " \
                  + e.ruleName \
                  + " failed predicate: {" \
                  + e.predicateText \
                  + "}?"

        else:
            msg = str(e)

        return msg
    

    def getNumberOfSyntaxErrors(self):
        """
        Get number of recognition errors (lexer, parser, tree parser).  Each
        recognizer tracks its own number.  So parser and lexer each have
        separate count.  Does not count the spurious errors found between
        an error and next valid token match

        See also reportError()
	"""
        return self._state.syntaxErrors


    def getErrorHeader(self, e):
        """
        What is the error header, normally line/character position information?
        """
        
        return "line %d:%d" % (e.line, e.charPositionInLine)


    def getTokenErrorDisplay(self, t):
        """
        How should a token be displayed in an error message? The default
        is to display just the text, but during development you might
        want to have a lot of information spit out.  Override in that case
        to use t.toString() (which, for CommonToken, dumps everything about
        the token). This is better than forcing you to override a method in
        your token objects because you don't have to go modify your lexer
        so that it creates a new Java type.
        """
        
        s = t.text
        if s is None:
            if t.type == EOF:
                s = "<EOF>"
            else:
                s = "<"+t.type+">"

        return repr(s)
    

    def emitErrorMessage(self, msg):
        """Override this method to change where error messages go"""
        sys.stderr.write(msg + '\n')


    def recover(self, input, re):
        """
        Recover from an error found on the input stream.  This is
        for NoViableAlt and mismatched symbol exceptions.  If you enable
        single token insertion and deletion, this will usually not
        handle mismatched symbol exceptions but there could be a mismatched
        token that the match() routine could not recover from.
        """
        
        # PROBLEM? what if input stream is not the same as last time
        # perhaps make lastErrorIndex a member of input
        if self._state.lastErrorIndex == input.index():
            # uh oh, another error at same token index; must be a case
            # where LT(1) is in the recovery token set so nothing is
            # consumed; consume a single token so at least to prevent
            # an infinite loop; this is a failsafe.
            input.consume()

        self._state.lastErrorIndex = input.index()
        followSet = self.computeErrorRecoverySet()
        
        self.beginResync()
        self.consumeUntil(input, followSet)
        self.endResync()


    def beginResync(self):
        """
        A hook to listen in on the token consumption during error recovery.
        The DebugParser subclasses this to fire events to the listenter.
        """

        pass


    def endResync(self):
        """
        A hook to listen in on the token consumption during error recovery.
        The DebugParser subclasses this to fire events to the listenter.
        """

        pass


    def computeErrorRecoverySet(self):
        """
        Compute the error recovery set for the current rule.  During
        rule invocation, the parser pushes the set of tokens that can
        follow that rule reference on the stack; this amounts to
        computing FIRST of what follows the rule reference in the
        enclosing rule. This local follow set only includes tokens
        from within the rule; i.e., the FIRST computation done by
        ANTLR stops at the end of a rule.

        EXAMPLE

        When you find a "no viable alt exception", the input is not
        consistent with any of the alternatives for rule r.  The best
        thing to do is to consume tokens until you see something that
        can legally follow a call to r *or* any rule that called r.
        You don't want the exact set of viable next tokens because the
        input might just be missing a token--you might consume the
        rest of the input looking for one of the missing tokens.

        Consider grammar:

        a : '[' b ']'
          | '(' b ')'
          ;
        b : c '^' INT ;
        c : ID
          | INT
          ;

        At each rule invocation, the set of tokens that could follow
        that rule is pushed on a stack.  Here are the various "local"
        follow sets:

        FOLLOW(b1_in_a) = FIRST(']') = ']'
        FOLLOW(b2_in_a) = FIRST(')') = ')'
        FOLLOW(c_in_b) = FIRST('^') = '^'

        Upon erroneous input "[]", the call chain is

        a -> b -> c

        and, hence, the follow context stack is:

        depth  local follow set     after call to rule
          0         \<EOF>                    a (from main())
          1          ']'                     b
          3          '^'                     c

        Notice that ')' is not included, because b would have to have
        been called from a different context in rule a for ')' to be
        included.

        For error recovery, we cannot consider FOLLOW(c)
        (context-sensitive or otherwise).  We need the combined set of
        all context-sensitive FOLLOW sets--the set of all tokens that
        could follow any reference in the call chain.  We need to
        resync to one of those tokens.  Note that FOLLOW(c)='^' and if
        we resync'd to that token, we'd consume until EOF.  We need to
        sync to context-sensitive FOLLOWs for a, b, and c: {']','^'}.
        In this case, for input "[]", LA(1) is in this set so we would
        not consume anything and after printing an error rule c would
        return normally.  It would not find the required '^' though.
        At this point, it gets a mismatched token error and throws an
        exception (since LA(1) is not in the viable following token
        set).  The rule exception handler tries to recover, but finds
        the same recovery set and doesn't consume anything.  Rule b
        exits normally returning to rule a.  Now it finds the ']' (and
        with the successful match exits errorRecovery mode).

        So, you cna see that the parser walks up call chain looking
        for the token that was a member of the recovery set.

        Errors are not generated in errorRecovery mode.

        ANTLR's error recovery mechanism is based upon original ideas:

        "Algorithms + Data Structures = Programs" by Niklaus Wirth

        and

        "A note on error recovery in recursive descent parsers":
        http://portal.acm.org/citation.cfm?id=947902.947905

        Later, Josef Grosch had some good ideas:

        "Efficient and Comfortable Error Recovery in Recursive Descent
        Parsers":
        ftp://www.cocolab.com/products/cocktail/doca4.ps/ell.ps.zip

        Like Grosch I implemented local FOLLOW sets that are combined
        at run-time upon error to avoid overhead during parsing.
        """
        
        return self.combineFollows(False)

        
    def computeContextSensitiveRuleFOLLOW(self):
        """
        Compute the context-sensitive FOLLOW set for current rule.
        This is set of token types that can follow a specific rule
        reference given a specific call chain.  You get the set of
        viable tokens that can possibly come next (lookahead depth 1)
        given the current call chain.  Contrast this with the
        definition of plain FOLLOW for rule r:

         FOLLOW(r)={x | S=>*alpha r beta in G and x in FIRST(beta)}

        where x in T* and alpha, beta in V*; T is set of terminals and
        V is the set of terminals and nonterminals.  In other words,
        FOLLOW(r) is the set of all tokens that can possibly follow
        references to r in *any* sentential form (context).  At
        runtime, however, we know precisely which context applies as
        we have the call chain.  We may compute the exact (rather
        than covering superset) set of following tokens.

        For example, consider grammar:

        stat : ID '=' expr ';'      // FOLLOW(stat)=={EOF}
             | "return" expr '.'
             ;
        expr : atom ('+' atom)* ;   // FOLLOW(expr)=={';','.',')'}
        atom : INT                  // FOLLOW(atom)=={'+',')',';','.'}
             | '(' expr ')'
             ;

        The FOLLOW sets are all inclusive whereas context-sensitive
        FOLLOW sets are precisely what could follow a rule reference.
        For input input "i=(3);", here is the derivation:

        stat => ID '=' expr ';'
             => ID '=' atom ('+' atom)* ';'
             => ID '=' '(' expr ')' ('+' atom)* ';'
             => ID '=' '(' atom ')' ('+' atom)* ';'
             => ID '=' '(' INT ')' ('+' atom)* ';'
             => ID '=' '(' INT ')' ';'

        At the "3" token, you'd have a call chain of

          stat -> expr -> atom -> expr -> atom

        What can follow that specific nested ref to atom?  Exactly ')'
        as you can see by looking at the derivation of this specific
        input.  Contrast this with the FOLLOW(atom)={'+',')',';','.'}.

        You want the exact viable token set when recovering from a
        token mismatch.  Upon token mismatch, if LA(1) is member of
        the viable next token set, then you know there is most likely
        a missing token in the input stream.  "Insert" one by just not
        throwing an exception.
        """

        return self.combineFollows(True)


    def combineFollows(self, exact):
        followSet = set()
        for idx, localFollowSet in reversed(list(enumerate(self._state.following))):
            followSet |= localFollowSet
            if exact:
                # can we see end of rule?
                if EOR_TOKEN_TYPE in localFollowSet:
                    # Only leave EOR in set if at top (start rule); this lets
                    # us know if have to include follow(start rule); i.e., EOF
                    if idx > 0:
                        followSet.remove(EOR_TOKEN_TYPE)
                        
                else:
                    # can't see end of rule, quit
                    break

        return followSet


    def recoverFromMismatchedToken(self, input, ttype, follow):
        """Attempt to recover from a single missing or extra token.

        EXTRA TOKEN

        LA(1) is not what we are looking for.  If LA(2) has the right token,
        however, then assume LA(1) is some extra spurious token.  Delete it
        and LA(2) as if we were doing a normal match(), which advances the
        input.

        MISSING TOKEN

        If current token is consistent with what could come after
        ttype then it is ok to 'insert' the missing token, else throw
        exception For example, Input 'i=(3;' is clearly missing the
        ')'.  When the parser returns from the nested call to expr, it
        will have call chain:

          stat -> expr -> atom

        and it will be trying to match the ')' at this point in the
        derivation:

             => ID '=' '(' INT ')' ('+' atom)* ';'
                                ^
        match() will see that ';' doesn't match ')' and report a
        mismatched token error.  To recover, it sees that LA(1)==';'
        is in the set of tokens that can follow the ')' token
        reference in rule atom.  It can assume that you forgot the ')'.
        """

        e = None

        # if next token is what we are looking for then "delete" this token
        if self. mismatchIsUnwantedToken(input, ttype):
            e = UnwantedTokenException(ttype, input)

            self.beginResync()
            input.consume() # simply delete extra token
            self.endResync()

            # report after consuming so AW sees the token in the exception
            self.reportError(e)

            # we want to return the token we're actually matching
            matchedSymbol = self.getCurrentInputSymbol(input)

            # move past ttype token as if all were ok
            input.consume()
            return matchedSymbol

        # can't recover with single token deletion, try insertion
        if self.mismatchIsMissingToken(input, follow):
            inserted = self.getMissingSymbol(input, e, ttype, follow)
            e = MissingTokenException(ttype, input, inserted)

            # report after inserting so AW sees the token in the exception
            self.reportError(e)
            return inserted

        # even that didn't work; must throw the exception
        e = MismatchedTokenException(ttype, input)
        raise e


    def recoverFromMismatchedSet(self, input, e, follow):
        """Not currently used"""

        if self.mismatchIsMissingToken(input, follow):
            self.reportError(e)
            # we don't know how to conjure up a token for sets yet
            return self.getMissingSymbol(input, e, INVALID_TOKEN_TYPE, follow)

        # TODO do single token deletion like above for Token mismatch
        raise e


    def getCurrentInputSymbol(self, input):
        """
        Match needs to return the current input symbol, which gets put
        into the label for the associated token ref; e.g., x=ID.  Token
        and tree parsers need to return different objects. Rather than test
        for input stream type or change the IntStream interface, I use
        a simple method to ask the recognizer to tell me what the current
        input symbol is.

        This is ignored for lexers.
        """
        
        return None


    def getMissingSymbol(self, input, e, expectedTokenType, follow):
        """Conjure up a missing token during error recovery.

        The recognizer attempts to recover from single missing
        symbols. But, actions might refer to that missing symbol.
        For example, x=ID {f($x);}. The action clearly assumes
        that there has been an identifier matched previously and that
        $x points at that token. If that token is missing, but
        the next token in the stream is what we want we assume that
        this token is missing and we keep going. Because we
        have to return some token to replace the missing token,
        we have to conjure one up. This method gives the user control
        over the tokens returned for missing tokens. Mostly,
        you will want to create something special for identifier
        tokens. For literals such as '{' and ',', the default
        action in the parser or tree parser works. It simply creates
        a CommonToken of the appropriate type. The text will be the token.
        If you change what tokens must be created by the lexer,
        override this method to create the appropriate tokens.
        """

        return None


##     def recoverFromMissingElement(self, input, e, follow):
##         """
##         This code is factored out from mismatched token and mismatched set
##         recovery.  It handles "single token insertion" error recovery for
##         both.  No tokens are consumed to recover from insertions.  Return
##         true if recovery was possible else return false.
##         """
        
##         if self.mismatchIsMissingToken(input, follow):
##             self.reportError(e)
##             return True

##         # nothing to do; throw exception
##         return False


    def consumeUntil(self, input, tokenTypes):
        """
        Consume tokens until one matches the given token or token set

        tokenTypes can be a single token type or a set of token types
        
        """
        
        if not isinstance(tokenTypes, (set, frozenset)):
            tokenTypes = frozenset([tokenTypes])

        ttype = input.LA(1)
        while ttype != EOF and ttype not in tokenTypes:
            input.consume()
            ttype = input.LA(1)


    def getRuleInvocationStack(self):
        """
        Return List<String> of the rules in your parser instance
        leading up to a call to this method.  You could override if
        you want more details such as the file/line info of where
        in the parser java code a rule is invoked.

        This is very useful for error messages and for context-sensitive
        error recovery.

        You must be careful, if you subclass a generated recognizers.
        The default implementation will only search the module of self
        for rules, but the subclass will not contain any rules.
        You probably want to override this method to look like

        def getRuleInvocationStack(self):
            return self._getRuleInvocationStack(<class>.__module__)

        where <class> is the class of the generated recognizer, e.g.
        the superclass of self.
        """

        return self._getRuleInvocationStack(self.__module__)


    def _getRuleInvocationStack(cls, module):
        """
        A more general version of getRuleInvocationStack where you can
        pass in, for example, a RecognitionException to get it's rule
        stack trace.  This routine is shared with all recognizers, hence,
        static.

        TODO: move to a utility class or something; weird having lexer call
        this
        """

        # mmmhhh,... perhaps look at the first argument
        # (f_locals[co_varnames[0]]?) and test if it's a (sub)class of
        # requested recognizer...
        
        rules = []
        for frame in reversed(inspect.stack()):
            code = frame[0].f_code
            codeMod = inspect.getmodule(code)
            if codeMod is None:
                continue

            # skip frames not in requested module
            if codeMod.__name__ != module:
                continue

            # skip some unwanted names
            if code.co_name in ('nextToken', '<module>'):
                continue

            rules.append(code.co_name)

        return rules
        
    _getRuleInvocationStack = classmethod(_getRuleInvocationStack)
    

    def getBacktrackingLevel(self):
        return self._state.backtracking


    def getGrammarFileName(self):
        """For debugging and other purposes, might want the grammar name.
        
        Have ANTLR generate an implementation for this method.
        """

        return self.grammarFileName


    def getSourceName(self):
        raise NotImplementedError

    
    def toStrings(self, tokens):
        """A convenience method for use most often with template rewrites.

        Convert a List<Token> to List<String>
        """

        if tokens is None:
            return None

        return [token.text for token in tokens]


    def getRuleMemoization(self, ruleIndex, ruleStartIndex):
        """
        Given a rule number and a start token index number, return
        MEMO_RULE_UNKNOWN if the rule has not parsed input starting from
        start index.  If this rule has parsed input starting from the
        start index before, then return where the rule stopped parsing.
        It returns the index of the last token matched by the rule.
        """
        
        if ruleIndex not in self._state.ruleMemo:
            self._state.ruleMemo[ruleIndex] = {}

        return self._state.ruleMemo[ruleIndex].get(
            ruleStartIndex, self.MEMO_RULE_UNKNOWN
            )


    def alreadyParsedRule(self, input, ruleIndex):
        """
        Has this rule already parsed input at the current index in the
        input stream?  Return the stop token index or MEMO_RULE_UNKNOWN.
        If we attempted but failed to parse properly before, return
        MEMO_RULE_FAILED.

        This method has a side-effect: if we have seen this input for
        this rule and successfully parsed before, then seek ahead to
        1 past the stop token matched for this rule last time.
        """

        stopIndex = self.getRuleMemoization(ruleIndex, input.index())
        if stopIndex == self.MEMO_RULE_UNKNOWN:
            return False

        if stopIndex == self.MEMO_RULE_FAILED:
            raise BacktrackingFailed

        else:
            input.seek(stopIndex + 1)

        return True


    def memoize(self, input, ruleIndex, ruleStartIndex, success):
        """
        Record whether or not this rule parsed the input at this position
        successfully.
        """

        if success:
            stopTokenIndex = input.index() - 1
        else:
            stopTokenIndex = self.MEMO_RULE_FAILED
        
        if ruleIndex in self._state.ruleMemo:
            self._state.ruleMemo[ruleIndex][ruleStartIndex] = stopTokenIndex


    def traceIn(self, ruleName, ruleIndex, inputSymbol):
        sys.stdout.write("enter %s %s" % (ruleName, inputSymbol))
        
##         if self._state.failed:
##             sys.stdout.write(" failed=%s" % self._state.failed)

        if self._state.backtracking > 0:
            sys.stdout.write(" backtracking=%s" % self._state.backtracking)

        sys.stdout.write('\n')


    def traceOut(self, ruleName, ruleIndex, inputSymbol):
        sys.stdout.write("exit %s %s" % (ruleName, inputSymbol))
        
##         if self._state.failed:
##             sys.stdout.write(" failed=%s" % self._state.failed)

        if self._state.backtracking > 0:
            sys.stdout.write(" backtracking=%s" % self._state.backtracking)

        sys.stdout.write('\n')



class TokenSource(object):
    """
    @brief Abstract baseclass for token producers.
    
    A source of tokens must provide a sequence of tokens via nextToken()
    and also must reveal it's source of characters; CommonToken's text is
    computed from a CharStream; it only store indices into the char stream.

    Errors from the lexer are never passed to the parser.  Either you want
    to keep going or you do not upon token recognition error.  If you do not
    want to continue lexing then you do not want to continue parsing.  Just
    throw an exception not under RecognitionException and Java will naturally
    toss you all the way out of the recognizers.  If you want to continue
    lexing then you should not throw an exception to the parser--it has already
    requested a token.  Keep lexing until you get a valid one.  Just report
    errors and keep going, looking for a valid token.
    """
    
    def nextToken(self):
        """Return a Token object from your input stream (usually a CharStream).
        
        Do not fail/return upon lexing error; keep chewing on the characters
        until you get a good one; errors are not passed through to the parser.
        """

        raise NotImplementedError
    

    def __iter__(self):
        """The TokenSource is an interator.

        The iteration will not include the final EOF token, see also the note
        for the next() method.

        """
        
        return self

    
    def next(self):
        """Return next token or raise StopIteration.

        Note that this will raise StopIteration when hitting the EOF token,
        so EOF will not be part of the iteration.
        
        """

        token = self.nextToken()
        if token is None or token.type == EOF:
            raise StopIteration
        return token

    
class Lexer(BaseRecognizer, TokenSource):
    """
    @brief Baseclass for generated lexer classes.
    
    A lexer is recognizer that draws input symbols from a character stream.
    lexer grammars result in a subclass of this object. A Lexer object
    uses simplified match() and error recovery mechanisms in the interest
    of speed.
    """

    def __init__(self, input, state=None):
        BaseRecognizer.__init__(self, state)
        TokenSource.__init__(self)
        
        # Where is the lexer drawing characters from?
        self.input = input


    def reset(self):
        BaseRecognizer.reset(self) # reset all recognizer state variables

        if self.input is not None:
            # rewind the input
            self.input.seek(0)

        if self._state is None:
            # no shared state work to do
            return
        
        # wack Lexer state variables
        self._state.token = None
        self._state.type = INVALID_TOKEN_TYPE
        self._state.channel = DEFAULT_CHANNEL
        self._state.tokenStartCharIndex = -1
        self._state.tokenStartLine = -1
        self._state.tokenStartCharPositionInLine = -1
        self._state.text = None


    def nextToken(self):
        """
        Return a token from this source; i.e., match a token on the char
        stream.
        """
        
        while 1:
            self._state.token = None
            self._state.channel = DEFAULT_CHANNEL
            self._state.tokenStartCharIndex = self.input.index()
            self._state.tokenStartCharPositionInLine = self.input.charPositionInLine
            self._state.tokenStartLine = self.input.line
            self._state.text = None
            if self.input.LA(1) == EOF:
                return EOF_TOKEN

            try:
                self.mTokens()
                
                if self._state.token is None:
                    self.emit()
                    
                elif self._state.token == SKIP_TOKEN:
                    continue

                return self._state.token

            except NoViableAltException, re:
                self.reportError(re)
                self.recover(re) # throw out current char and try again

            except RecognitionException, re:
                self.reportError(re)
                # match() routine has already called recover()


    def skip(self):
        """
        Instruct the lexer to skip creating a token for current lexer rule
        and look for another token.  nextToken() knows to keep looking when
        a lexer rule finishes with token set to SKIP_TOKEN.  Recall that
        if token==null at end of any token rule, it creates one for you
        and emits it.
        """
        
        self._state.token = SKIP_TOKEN


    def mTokens(self):
        """This is the lexer entry point that sets instance var 'token'"""

        # abstract method
        raise NotImplementedError
    

    def setCharStream(self, input):
        """Set the char stream and reset the lexer"""
        self.input = None
        self.reset()
        self.input = input


    def getSourceName(self):
        return self.input.getSourceName()


    def emit(self, token=None):
        """
        The standard method called to automatically emit a token at the
        outermost lexical rule.  The token object should point into the
        char buffer start..stop.  If there is a text override in 'text',
        use that to set the token's text.  Override this method to emit
        custom Token objects.

        If you are building trees, then you should also override
        Parser or TreeParser.getMissingSymbol().
        """

        if token is None:
            token = CommonToken(
                input=self.input,
                type=self._state.type,
                channel=self._state.channel,
                start=self._state.tokenStartCharIndex,
                stop=self.getCharIndex()-1
                )
            token.line = self._state.tokenStartLine
            token.text = self._state.text
            token.charPositionInLine = self._state.tokenStartCharPositionInLine

        self._state.token = token
        
        return token


    def match(self, s):
        if isinstance(s, basestring):
            for c in s:
                if self.input.LA(1) != ord(c):
                    if self._state.backtracking > 0:
                        raise BacktrackingFailed

                    mte = MismatchedTokenException(c, self.input)
                    self.recover(mte)
                    raise mte

                self.input.consume()

        else:
            if self.input.LA(1) != s:
                if self._state.backtracking > 0:
                    raise BacktrackingFailed

                mte = MismatchedTokenException(unichr(s), self.input)
                self.recover(mte) # don't really recover; just consume in lexer
                raise mte
        
            self.input.consume()
            

    def matchAny(self):
        self.input.consume()


    def matchRange(self, a, b):
        if self.input.LA(1) < a or self.input.LA(1) > b:
            if self._state.backtracking > 0:
                raise BacktrackingFailed

            mre = MismatchedRangeException(unichr(a), unichr(b), self.input)
            self.recover(mre)
            raise mre

        self.input.consume()


    def getLine(self):
        return self.input.line


    def getCharPositionInLine(self):
        return self.input.charPositionInLine


    def getCharIndex(self):
        """What is the index of the current character of lookahead?"""
        
        return self.input.index()


    def getText(self):
        """
        Return the text matched so far for the current token or any
        text override.
        """
        if self._state.text is not None:
            return self._state.text
        
        return self.input.substring(
            self._state.tokenStartCharIndex,
            self.getCharIndex()-1
            )


    def setText(self, text):
        """
        Set the complete text of this token; it wipes any previous
        changes to the text.
        """
        self._state.text = text


    text = property(getText, setText)


    def reportError(self, e):
        ## TODO: not thought about recovery in lexer yet.

        ## # if we've already reported an error and have not matched a token
        ## # yet successfully, don't report any errors.
        ## if self.errorRecovery:
        ##     #System.err.print("[SPURIOUS] ");
        ##     return;
        ## 
        ## self.errorRecovery = True

        self.displayRecognitionError(self.tokenNames, e)


    def getErrorMessage(self, e, tokenNames):
        msg = None
        
        if isinstance(e, MismatchedTokenException):
            msg = "mismatched character " \
                  + self.getCharErrorDisplay(e.c) \
                  + " expecting " \
                  + self.getCharErrorDisplay(e.expecting)

        elif isinstance(e, NoViableAltException):
            msg = "no viable alternative at character " \
                  + self.getCharErrorDisplay(e.c)

        elif isinstance(e, EarlyExitException):
            msg = "required (...)+ loop did not match anything at character " \
                  + self.getCharErrorDisplay(e.c)
            
        elif isinstance(e, MismatchedNotSetException):
            msg = "mismatched character " \
                  + self.getCharErrorDisplay(e.c) \
                  + " expecting set " \
                  + repr(e.expecting)

        elif isinstance(e, MismatchedSetException):
            msg = "mismatched character " \
                  + self.getCharErrorDisplay(e.c) \
                  + " expecting set " \
                  + repr(e.expecting)

        elif isinstance(e, MismatchedRangeException):
            msg = "mismatched character " \
                  + self.getCharErrorDisplay(e.c) \
                  + " expecting set " \
                  + self.getCharErrorDisplay(e.a) \
                  + ".." \
                  + self.getCharErrorDisplay(e.b)

        else:
            msg = BaseRecognizer.getErrorMessage(self, e, tokenNames)

        return msg


    def getCharErrorDisplay(self, c):
        if c == EOF:
            c = '<EOF>'
        return repr(c)


    def recover(self, re):
        """
        Lexers can normally match any char in it's vocabulary after matching
        a token, so do the easy thing and just kill a character and hope
        it all works out.  You can instead use the rule invocation stack
        to do sophisticated error recovery if you are in a fragment rule.
        """

        self.input.consume()


    def traceIn(self, ruleName, ruleIndex):
        inputSymbol = "%s line=%d:%s" % (self.input.LT(1),
                                         self.getLine(),
                                         self.getCharPositionInLine()
                                         )
        
        BaseRecognizer.traceIn(self, ruleName, ruleIndex, inputSymbol)


    def traceOut(self, ruleName, ruleIndex):
        inputSymbol = "%s line=%d:%s" % (self.input.LT(1),
                                         self.getLine(),
                                         self.getCharPositionInLine()
                                         )

        BaseRecognizer.traceOut(self, ruleName, ruleIndex, inputSymbol)



class Parser(BaseRecognizer):
    """
    @brief Baseclass for generated parser classes.
    """
    
    def __init__(self, lexer, state=None):
        BaseRecognizer.__init__(self, state)

        self.setTokenStream(lexer)


    def reset(self):
        BaseRecognizer.reset(self) # reset all recognizer state variables
        if self.input is not None:
            self.input.seek(0) # rewind the input


    def getCurrentInputSymbol(self, input):
        return input.LT(1)


    def getMissingSymbol(self, input, e, expectedTokenType, follow):
        if expectedTokenType == EOF:
            tokenText = "<missing EOF>"
        else:
            tokenText = "<missing " + self.tokenNames[expectedTokenType] + ">"
        t = CommonToken(type=expectedTokenType, text=tokenText)
        current = input.LT(1)
        if current.type == EOF:
            current = input.LT(-1)

        if current is not None:
            t.line = current.line
            t.charPositionInLine = current.charPositionInLine
        t.channel = DEFAULT_CHANNEL
        return t


    def setTokenStream(self, input):
        """Set the token stream and reset the parser"""
        
        self.input = None
        self.reset()
        self.input = input


    def getTokenStream(self):
        return self.input


    def getSourceName(self):
        return self.input.getSourceName()


    def traceIn(self, ruleName, ruleIndex):
        BaseRecognizer.traceIn(self, ruleName, ruleIndex, self.input.LT(1))


    def traceOut(self, ruleName, ruleIndex):
        BaseRecognizer.traceOut(self, ruleName, ruleIndex, self.input.LT(1))


class RuleReturnScope(object):
    """
    Rules can return start/stop info as well as possible trees and templates.
    """

    def getStart(self):
        """Return the start token or tree."""
        return None
    

    def getStop(self):
        """Return the stop token or tree."""
        return None

    
    def getTree(self):
        """Has a value potentially if output=AST."""
        return None


    def getTemplate(self):
        """Has a value potentially if output=template."""
        return None


class ParserRuleReturnScope(RuleReturnScope):
    """
    Rules that return more than a single value must return an object
    containing all the values.  Besides the properties defined in
    RuleLabelScope.predefinedRulePropertiesScope there may be user-defined
    return values.  This class simply defines the minimum properties that
    are always defined and methods to access the others that might be
    available depending on output option such as template and tree.

    Note text is not an actual property of the return value, it is computed
    from start and stop using the input stream's toString() method.  I
    could add a ctor to this so that we can pass in and store the input
    stream, but I'm not sure we want to do that.  It would seem to be undefined
    to get the .text property anyway if the rule matches tokens from multiple
    input streams.

    I do not use getters for fields of objects that are used simply to
    group values such as this aggregate.  The getters/setters are there to
    satisfy the superclass interface.
    """

    def __init__(self):
        self.start = None
        self.stop = None

    
    def getStart(self):
        return self.start


    def getStop(self):
        return self.stop

