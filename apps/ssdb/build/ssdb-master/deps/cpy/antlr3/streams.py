"""ANTLR3 runtime package"""

# begin[licence]
#
# [The "BSD licence"]
# Copyright (c) 2005-2008 Terence Parr
# All rights reserved.
#
# Redistribution and use in source and binary forms, with or without
# modification, are permitted provided that the following conditions
# are met:
# 1. Redistributions of source code must retain the above copyright
#    notice, this list of conditions and the following disclaimer.
# 2. Redistributions in binary form must reproduce the above copyright
#    notice, this list of conditions and the following disclaimer in the
#    documentation and/or other materials provided with the distribution.
# 3. The name of the author may not be used to endorse or promote products
#    derived from this software without specific prior written permission.
#
# THIS SOFTWARE IS PROVIDED BY THE AUTHOR ``AS IS'' AND ANY EXPRESS OR
# IMPLIED WARRANTIES, INCLUDING, BUT NOT LIMITED TO, THE IMPLIED WARRANTIES
# OF MERCHANTABILITY AND FITNESS FOR A PARTICULAR PURPOSE ARE DISCLAIMED.
# IN NO EVENT SHALL THE AUTHOR BE LIABLE FOR ANY DIRECT, INDIRECT,
# INCIDENTAL, SPECIAL, EXEMPLARY, OR CONSEQUENTIAL DAMAGES (INCLUDING, BUT
# NOT LIMITED TO, PROCUREMENT OF SUBSTITUTE GOODS OR SERVICES; LOSS OF USE,
# DATA, OR PROFITS; OR BUSINESS INTERRUPTION) HOWEVER CAUSED AND ON ANY
# THEORY OF LIABILITY, WHETHER IN CONTRACT, STRICT LIABILITY, OR TORT
# (INCLUDING NEGLIGENCE OR OTHERWISE) ARISING IN ANY WAY OUT OF THE USE OF
# THIS SOFTWARE, EVEN IF ADVISED OF THE POSSIBILITY OF SUCH DAMAGE.
#
# end[licence]

import codecs
from StringIO import StringIO

from antlr3.constants import DEFAULT_CHANNEL, EOF
from antlr3.tokens import Token, EOF_TOKEN


############################################################################
#
# basic interfaces
#   IntStream
#    +- CharStream
#    \- TokenStream
#
# subclasses must implemented all methods
#
############################################################################

class IntStream(object):
    """
    @brief Base interface for streams of integer values.

    A simple stream of integers used when all I care about is the char
    or token type sequence (such as interpretation).
    """

    def consume(self):
        raise NotImplementedError
    

    def LA(self, i):
        """Get int at current input pointer + i ahead where i=1 is next int.

        Negative indexes are allowed.  LA(-1) is previous token (token
	just matched).  LA(-i) where i is before first token should
	yield -1, invalid char / EOF.
	"""
        
        raise NotImplementedError
        

    def mark(self):
        """
        Tell the stream to start buffering if it hasn't already.  Return
        current input position, index(), or some other marker so that
        when passed to rewind() you get back to the same spot.
        rewind(mark()) should not affect the input cursor.  The Lexer
        track line/col info as well as input index so its markers are
        not pure input indexes.  Same for tree node streams.
        """

        raise NotImplementedError


    def index(self):
        """
        Return the current input symbol index 0..n where n indicates the
        last symbol has been read.  The index is the symbol about to be
        read not the most recently read symbol.
        """

        raise NotImplementedError


    def rewind(self, marker=None):
        """
        Reset the stream so that next call to index would return marker.
        The marker will usually be index() but it doesn't have to be.  It's
        just a marker to indicate what state the stream was in.  This is
        essentially calling release() and seek().  If there are markers
        created after this marker argument, this routine must unroll them
        like a stack.  Assume the state the stream was in when this marker
        was created.

        If marker is None:
        Rewind to the input position of the last marker.
        Used currently only after a cyclic DFA and just
        before starting a sem/syn predicate to get the
        input position back to the start of the decision.
        Do not "pop" the marker off the state.  mark(i)
        and rewind(i) should balance still. It is
        like invoking rewind(last marker) but it should not "pop"
        the marker off.  It's like seek(last marker's input position).       
	"""

        raise NotImplementedError


    def release(self, marker=None):
        """
        You may want to commit to a backtrack but don't want to force the
        stream to keep bookkeeping objects around for a marker that is
        no longer necessary.  This will have the same behavior as
        rewind() except it releases resources without the backward seek.
        This must throw away resources for all markers back to the marker
        argument.  So if you're nested 5 levels of mark(), and then release(2)
        you have to release resources for depths 2..5.
	"""

        raise NotImplementedError


    def seek(self, index):
        """
        Set the input cursor to the position indicated by index.  This is
        normally used to seek ahead in the input stream.  No buffering is
        required to do this unless you know your stream will use seek to
        move backwards such as when backtracking.

        This is different from rewind in its multi-directional
        requirement and in that its argument is strictly an input cursor
        (index).

        For char streams, seeking forward must update the stream state such
        as line number.  For seeking backwards, you will be presumably
        backtracking using the mark/rewind mechanism that restores state and
        so this method does not need to update state when seeking backwards.

        Currently, this method is only used for efficient backtracking using
        memoization, but in the future it may be used for incremental parsing.

        The index is 0..n-1.  A seek to position i means that LA(1) will
        return the ith symbol.  So, seeking to 0 means LA(1) will return the
        first element in the stream. 
        """

        raise NotImplementedError


    def size(self):
        """
        Only makes sense for streams that buffer everything up probably, but
        might be useful to display the entire stream or for testing.  This
        value includes a single EOF.
	"""

        raise NotImplementedError


    def getSourceName(self):
        """
        Where are you getting symbols from?  Normally, implementations will
        pass the buck all the way to the lexer who can ask its input stream
        for the file name or whatever.
        """

        raise NotImplementedError


class CharStream(IntStream):
    """
    @brief A source of characters for an ANTLR lexer.

    This is an abstract class that must be implemented by a subclass.
    
    """

    # pylint does not realize that this is an interface, too
    #pylint: disable-msg=W0223
    
    EOF = -1


    def substring(self, start, stop):
        """
        For infinite streams, you don't need this; primarily I'm providing
        a useful interface for action code.  Just make sure actions don't
        use this on streams that don't support it.
        """

        raise NotImplementedError
        
    
    def LT(self, i):
        """
        Get the ith character of lookahead.  This is the same usually as
        LA(i).  This will be used for labels in the generated
        lexer code.  I'd prefer to return a char here type-wise, but it's
        probably better to be 32-bit clean and be consistent with LA.
        """

        raise NotImplementedError


    def getLine(self):
        """ANTLR tracks the line information automatically"""

        raise NotImplementedError


    def setLine(self, line):
        """
        Because this stream can rewind, we need to be able to reset the line
        """

        raise NotImplementedError


    def getCharPositionInLine(self):
        """
        The index of the character relative to the beginning of the line 0..n-1
        """

        raise NotImplementedError


    def setCharPositionInLine(self, pos):
        raise NotImplementedError


class TokenStream(IntStream):
    """

    @brief A stream of tokens accessing tokens from a TokenSource

    This is an abstract class that must be implemented by a subclass.
    
    """
    
    # pylint does not realize that this is an interface, too
    #pylint: disable-msg=W0223
    
    def LT(self, k):
        """
        Get Token at current input pointer + i ahead where i=1 is next Token.
        i<0 indicates tokens in the past.  So -1 is previous token and -2 is
        two tokens ago. LT(0) is undefined.  For i>=n, return Token.EOFToken.
        Return null for LT(0) and any index that results in an absolute address
        that is negative.
	"""

        raise NotImplementedError


    def get(self, i):
        """
        Get a token at an absolute index i; 0..n-1.  This is really only
        needed for profiling and debugging and token stream rewriting.
        If you don't want to buffer up tokens, then this method makes no
        sense for you.  Naturally you can't use the rewrite stream feature.
        I believe DebugTokenStream can easily be altered to not use
        this method, removing the dependency.
        """

        raise NotImplementedError


    def getTokenSource(self):
        """
        Where is this stream pulling tokens from?  This is not the name, but
        the object that provides Token objects.
	"""

        raise NotImplementedError


    def toString(self, start=None, stop=None):
        """
        Return the text of all tokens from start to stop, inclusive.
        If the stream does not buffer all the tokens then it can just
        return "" or null;  Users should not access $ruleLabel.text in
        an action of course in that case.

        Because the user is not required to use a token with an index stored
        in it, we must provide a means for two token objects themselves to
        indicate the start/end location.  Most often this will just delegate
        to the other toString(int,int).  This is also parallel with
        the TreeNodeStream.toString(Object,Object).
	"""

        raise NotImplementedError

        
############################################################################
#
# character streams for use in lexers
#   CharStream
#   \- ANTLRStringStream
#
############################################################################


class ANTLRStringStream(CharStream):
    """
    @brief CharStream that pull data from a unicode string.
    
    A pretty quick CharStream that pulls all data from an array
    directly.  Every method call counts in the lexer.

    """

    
    def __init__(self, data):
        """
        @param data This should be a unicode string holding the data you want
           to parse. If you pass in a byte string, the Lexer will choke on
           non-ascii data.
           
        """
        
        CharStream.__init__(self)
        
  	# The data being scanned
        self.strdata = unicode(data)
        self.data = [ord(c) for c in self.strdata]
        
	# How many characters are actually in the buffer
        self.n = len(data)

 	# 0..n-1 index into string of next char
        self.p = 0

	# line number 1..n within the input
        self.line = 1

 	# The index of the character relative to the beginning of the
        # line 0..n-1
        self.charPositionInLine = 0

	# A list of CharStreamState objects that tracks the stream state
        # values line, charPositionInLine, and p that can change as you
        # move through the input stream.  Indexed from 0..markDepth-1.
        self._markers = [ ]
        self.lastMarker = None
        self.markDepth = 0

        # What is name or source of this char stream?
        self.name = None


    def reset(self):
        """
        Reset the stream so that it's in the same state it was
        when the object was created *except* the data array is not
        touched.
        """
        
        self.p = 0
        self.line = 1
        self.charPositionInLine = 0
        self._markers = [ ]


    def consume(self):
        try:
            if self.data[self.p] == 10: # \n
                self.line += 1
                self.charPositionInLine = 0
            else:
                self.charPositionInLine += 1

            self.p += 1
            
        except IndexError:
            # happend when we reached EOF and self.data[self.p] fails
            # just do nothing
            pass



    def LA(self, i):
        if i == 0:
            return 0 # undefined

        if i < 0:
            i += 1 # e.g., translate LA(-1) to use offset i=0; then data[p+0-1]

        try:
            return self.data[self.p+i-1]
        except IndexError:
            return EOF



    def LT(self, i):
        if i == 0:
            return 0 # undefined

        if i < 0:
            i += 1 # e.g., translate LA(-1) to use offset i=0; then data[p+0-1]

        try:
            return self.strdata[self.p+i-1]
        except IndexError:
            return EOF


    def index(self):
        """
        Return the current input symbol index 0..n where n indicates the
        last symbol has been read.  The index is the index of char to
        be returned from LA(1).
        """
        
        return self.p


    def size(self):
        return self.n


    def mark(self):
        state = (self.p, self.line, self.charPositionInLine)
        try:
            self._markers[self.markDepth] = state
        except IndexError:
            self._markers.append(state)
        self.markDepth += 1
        
        self.lastMarker = self.markDepth
        
        return self.lastMarker


    def rewind(self, marker=None):
        if marker is None:
            marker = self.lastMarker

        p, line, charPositionInLine = self._markers[marker-1]

        self.seek(p)
        self.line = line
        self.charPositionInLine = charPositionInLine
        self.release(marker)


    def release(self, marker=None):
        if marker is None:
            marker = self.lastMarker

        self.markDepth = marker-1


    def seek(self, index):
        """
        consume() ahead until p==index; can't just set p=index as we must
        update line and charPositionInLine.
        """
        
        if index <= self.p:
            self.p = index # just jump; don't update stream state (line, ...)
            return

        # seek forward, consume until p hits index
        while self.p < index:
            self.consume()


    def substring(self, start, stop):
        return self.strdata[start:stop+1]


    def getLine(self):
        """Using setter/getter methods is deprecated. Use o.line instead."""
        return self.line


    def getCharPositionInLine(self):
        """
        Using setter/getter methods is deprecated. Use o.charPositionInLine
        instead.
        """
        return self.charPositionInLine


    def setLine(self, line):
        """Using setter/getter methods is deprecated. Use o.line instead."""
        self.line = line


    def setCharPositionInLine(self, pos):
        """
        Using setter/getter methods is deprecated. Use o.charPositionInLine
        instead.
        """
        self.charPositionInLine = pos


    def getSourceName(self):
        return self.name


class ANTLRFileStream(ANTLRStringStream):
    """
    @brief CharStream that opens a file to read the data.
    
    This is a char buffer stream that is loaded from a file
    all at once when you construct the object.
    """

    def __init__(self, fileName, encoding=None):
        """
        @param fileName The path to the file to be opened. The file will be
           opened with mode 'rb'.

        @param encoding If you set the optional encoding argument, then the
           data will be decoded on the fly.
           
        """
        
        self.fileName = fileName

        fp = codecs.open(fileName, 'rb', encoding)
        try:
            data = fp.read()
        finally:
            fp.close()
            
        ANTLRStringStream.__init__(self, data)


    def getSourceName(self):
        """Deprecated, access o.fileName directly."""
        
        return self.fileName


class ANTLRInputStream(ANTLRStringStream):
    """
    @brief CharStream that reads data from a file-like object.

    This is a char buffer stream that is loaded from a file like object
    all at once when you construct the object.
    
    All input is consumed from the file, but it is not closed.
    """

    def __init__(self, file, encoding=None):
        """
        @param file A file-like object holding your input. Only the read()
           method must be implemented.

        @param encoding If you set the optional encoding argument, then the
           data will be decoded on the fly.
           
        """
        
        if encoding is not None:
            # wrap input in a decoding reader
            reader = codecs.lookup(encoding)[2]
            file = reader(file)

        data = file.read()
            
        ANTLRStringStream.__init__(self, data)


# I guess the ANTLR prefix exists only to avoid a name clash with some Java
# mumbojumbo. A plain "StringStream" looks better to me, which should be
# the preferred name in Python.
StringStream = ANTLRStringStream
FileStream = ANTLRFileStream
InputStream = ANTLRInputStream


############################################################################
#
# Token streams
#   TokenStream
#   +- CommonTokenStream
#   \- TokenRewriteStream
#
############################################################################


class CommonTokenStream(TokenStream):
    """
    @brief The most common stream of tokens
    
    The most common stream of tokens is one where every token is buffered up
    and tokens are prefiltered for a certain channel (the parser will only
    see these tokens and cannot change the filter channel number during the
    parse).
    """

    def __init__(self, tokenSource=None, channel=DEFAULT_CHANNEL):
        """
        @param tokenSource A TokenSource instance (usually a Lexer) to pull
            the tokens from.

        @param channel Skip tokens on any channel but this one; this is how we
            skip whitespace...
            
        """
        
        TokenStream.__init__(self)
        
        self.tokenSource = tokenSource

	# Record every single token pulled from the source so we can reproduce
        # chunks of it later.
        self.tokens = []

	# Map<tokentype, channel> to override some Tokens' channel numbers
        self.channelOverrideMap = {}

	# Set<tokentype>; discard any tokens with this type
        self.discardSet = set()

	# Skip tokens on any channel but this one; this is how we skip whitespace...
        self.channel = channel

	# By default, track all incoming tokens
        self.discardOffChannelTokens = False

	# The index into the tokens list of the current token (next token
        # to consume).  p==-1 indicates that the tokens list is empty
        self.p = -1

        # Remember last marked position
        self.lastMarker = None
        

    def setTokenSource(self, tokenSource):
        """Reset this token stream by setting its token source."""
        
        self.tokenSource = tokenSource
        self.tokens = []
        self.p = -1
        self.channel = DEFAULT_CHANNEL


    def reset(self):
        self.p = 0
        self.lastMarker = None


    def fillBuffer(self):
        """
        Load all tokens from the token source and put in tokens.
	This is done upon first LT request because you might want to
        set some token type / channel overrides before filling buffer.
        """
        

        index = 0
        t = self.tokenSource.nextToken()
        while t is not None and t.type != EOF:
            discard = False
            
            if self.discardSet is not None and t.type in self.discardSet:
                discard = True

            elif self.discardOffChannelTokens and t.channel != self.channel:
                discard = True

            # is there a channel override for token type?
            try:
                overrideChannel = self.channelOverrideMap[t.type]
                
            except KeyError:
                # no override for this type
                pass
            
            else:
                if overrideChannel == self.channel:
                    t.channel = overrideChannel
                else:
                    discard = True
            
            if not discard:
                t.index = index
                self.tokens.append(t)
                index += 1

            t = self.tokenSource.nextToken()
       
        # leave p pointing at first token on channel
        self.p = 0
        self.p = self.skipOffTokenChannels(self.p)


    def consume(self):
        """
        Move the input pointer to the next incoming token.  The stream
        must become active with LT(1) available.  consume() simply
        moves the input pointer so that LT(1) points at the next
        input symbol. Consume at least one token.

        Walk past any token not on the channel the parser is listening to.
        """
        
        if self.p < len(self.tokens):
            self.p += 1

            self.p = self.skipOffTokenChannels(self.p) # leave p on valid token


    def skipOffTokenChannels(self, i):
        """
        Given a starting index, return the index of the first on-channel
        token.
        """

        try:
            while self.tokens[i].channel != self.channel:
                i += 1
        except IndexError:
            # hit the end of token stream
            pass
        
        return i


    def skipOffTokenChannelsReverse(self, i):
        while i >= 0 and self.tokens[i].channel != self.channel:
            i -= 1

        return i


    def setTokenTypeChannel(self, ttype, channel):
        """
        A simple filter mechanism whereby you can tell this token stream
        to force all tokens of type ttype to be on channel.  For example,
        when interpreting, we cannot exec actions so we need to tell
        the stream to force all WS and NEWLINE to be a different, ignored
        channel.
	"""
        
        self.channelOverrideMap[ttype] = channel


    def discardTokenType(self, ttype):
        self.discardSet.add(ttype)


    def getTokens(self, start=None, stop=None, types=None):
        """
        Given a start and stop index, return a list of all tokens in
        the token type set.  Return None if no tokens were found.  This
        method looks at both on and off channel tokens.
        """

        if self.p == -1:
            self.fillBuffer()

        if stop is None or stop >= len(self.tokens):
            stop = len(self.tokens) - 1
            
        if start is None or stop < 0:
            start = 0

        if start > stop:
            return None

        if isinstance(types, (int, long)):
            # called with a single type, wrap into set
            types = set([types])
            
        filteredTokens = [
            token for token in self.tokens[start:stop]
            if types is None or token.type in types
            ]

        if len(filteredTokens) == 0:
            return None

        return filteredTokens


    def LT(self, k):
        """
        Get the ith token from the current position 1..n where k=1 is the
        first symbol of lookahead.
        """

        if self.p == -1:
            self.fillBuffer()

        if k == 0:
            return None

        if k < 0:
            return self.LB(-k)
                
        i = self.p
        n = 1
        # find k good tokens
        while n < k:
            # skip off-channel tokens
            i = self.skipOffTokenChannels(i+1) # leave p on valid token
            n += 1

        try:
            return self.tokens[i]
        except IndexError:
            return EOF_TOKEN


    def LB(self, k):
        """Look backwards k tokens on-channel tokens"""

        if self.p == -1:
            self.fillBuffer()

        if k == 0:
            return None

        if self.p - k < 0:
            return None

        i = self.p
        n = 1
        # find k good tokens looking backwards
        while n <= k:
            # skip off-channel tokens
            i = self.skipOffTokenChannelsReverse(i-1) # leave p on valid token
            n += 1

        if i < 0:
            return None
            
        return self.tokens[i]


    def get(self, i):
        """
        Return absolute token i; ignore which channel the tokens are on;
        that is, count all tokens not just on-channel tokens.
        """

        return self.tokens[i]


    def LA(self, i):
        return self.LT(i).type


    def mark(self):
        self.lastMarker = self.index()
        return self.lastMarker
    

    def release(self, marker=None):
        # no resources to release
        pass
    

    def size(self):
        return len(self.tokens)


    def index(self):
        return self.p


    def rewind(self, marker=None):
        if marker is None:
            marker = self.lastMarker
            
        self.seek(marker)


    def seek(self, index):
        self.p = index


    def getTokenSource(self):
        return self.tokenSource


    def getSourceName(self):
        return self.tokenSource.getSourceName()


    def toString(self, start=None, stop=None):
        if self.p == -1:
            self.fillBuffer()

        if start is None:
            start = 0
        elif not isinstance(start, int):
            start = start.index

        if stop is None:
            stop = len(self.tokens) - 1
        elif not isinstance(stop, int):
            stop = stop.index
        
        if stop >= len(self.tokens):
            stop = len(self.tokens) - 1

        return ''.join([t.text for t in self.tokens[start:stop+1]])


class RewriteOperation(object):
    """@brief Internal helper class."""
    
    def __init__(self, stream, index, text):
        self.stream = stream
        self.index = index
        self.text = text

    def execute(self, buf):
        """Execute the rewrite operation by possibly adding to the buffer.
        Return the index of the next token to operate on.
        """

        return self.index

    def toString(self):
        opName = self.__class__.__name__
        return '<%s@%d:"%s">' % (opName, self.index, self.text)

    __str__ = toString
    __repr__ = toString


class InsertBeforeOp(RewriteOperation):
    """@brief Internal helper class."""

    def execute(self, buf):
        buf.write(self.text)
        buf.write(self.stream.tokens[self.index].text)
        return self.index + 1


class ReplaceOp(RewriteOperation):
    """
    @brief Internal helper class.
    
    I'm going to try replacing range from x..y with (y-x)+1 ReplaceOp
    instructions.
    """

    def __init__(self, stream, first, last, text):
        RewriteOperation.__init__(self, stream, first, text)
        self.lastIndex = last


    def execute(self, buf):
        if self.text is not None:
            buf.write(self.text)

        return self.lastIndex + 1


    def toString(self):
        return '<ReplaceOp@%d..%d:"%s">' % (
            self.index, self.lastIndex, self.text)

    __str__ = toString
    __repr__ = toString


class DeleteOp(ReplaceOp):
    """
    @brief Internal helper class.
    """

    def __init__(self, stream, first, last):
        ReplaceOp.__init__(self, stream, first, last, None)


    def toString(self):
        return '<DeleteOp@%d..%d>' % (self.index, self.lastIndex)

    __str__ = toString
    __repr__ = toString


class TokenRewriteStream(CommonTokenStream):
    """@brief CommonTokenStream that can be modified.

    Useful for dumping out the input stream after doing some
    augmentation or other manipulations.

    You can insert stuff, replace, and delete chunks.  Note that the
    operations are done lazily--only if you convert the buffer to a
    String.  This is very efficient because you are not moving data around
    all the time.  As the buffer of tokens is converted to strings, the
    toString() method(s) check to see if there is an operation at the
    current index.  If so, the operation is done and then normal String
    rendering continues on the buffer.  This is like having multiple Turing
    machine instruction streams (programs) operating on a single input tape. :)

    Since the operations are done lazily at toString-time, operations do not
    screw up the token index values.  That is, an insert operation at token
    index i does not change the index values for tokens i+1..n-1.

    Because operations never actually alter the buffer, you may always get
    the original token stream back without undoing anything.  Since
    the instructions are queued up, you can easily simulate transactions and
    roll back any changes if there is an error just by removing instructions.
    For example,

     CharStream input = new ANTLRFileStream("input");
     TLexer lex = new TLexer(input);
     TokenRewriteStream tokens = new TokenRewriteStream(lex);
     T parser = new T(tokens);
     parser.startRule();

     Then in the rules, you can execute
        Token t,u;
        ...
        input.insertAfter(t, "text to put after t");}
        input.insertAfter(u, "text after u");}
        System.out.println(tokens.toString());

    Actually, you have to cast the 'input' to a TokenRewriteStream. :(

    You can also have multiple "instruction streams" and get multiple
    rewrites from a single pass over the input.  Just name the instruction
    streams and use that name again when printing the buffer.  This could be
    useful for generating a C file and also its header file--all from the
    same buffer:

        tokens.insertAfter("pass1", t, "text to put after t");}
        tokens.insertAfter("pass2", u, "text after u");}
        System.out.println(tokens.toString("pass1"));
        System.out.println(tokens.toString("pass2"));

    If you don't use named rewrite streams, a "default" stream is used as
    the first example shows.
    """
    
    DEFAULT_PROGRAM_NAME = "default"
    MIN_TOKEN_INDEX = 0

    def __init__(self, tokenSource=None, channel=DEFAULT_CHANNEL):
        CommonTokenStream.__init__(self, tokenSource, channel)

        # You may have multiple, named streams of rewrite operations.
        # I'm calling these things "programs."
        #  Maps String (name) -> rewrite (List)
        self.programs = {}
        self.programs[self.DEFAULT_PROGRAM_NAME] = []
        
 	# Map String (program name) -> Integer index
        self.lastRewriteTokenIndexes = {}
        

    def rollback(self, *args):
        """
        Rollback the instruction stream for a program so that
        the indicated instruction (via instructionIndex) is no
        longer in the stream.  UNTESTED!
        """

        if len(args) == 2:
            programName = args[0]
            instructionIndex = args[1]
        elif len(args) == 1:
            programName = self.DEFAULT_PROGRAM_NAME
            instructionIndex = args[0]
        else:
            raise TypeError("Invalid arguments")
        
        p = self.programs.get(programName, None)
        if p is not None:
            self.programs[programName] = (
                p[self.MIN_TOKEN_INDEX:instructionIndex])


    def deleteProgram(self, programName=DEFAULT_PROGRAM_NAME):
        """Reset the program so that no instructions exist"""
            
        self.rollback(programName, self.MIN_TOKEN_INDEX)


    def insertAfter(self, *args):
        if len(args) == 2:
            programName = self.DEFAULT_PROGRAM_NAME
            index = args[0]
            text = args[1]
            
        elif len(args) == 3:
            programName = args[0]
            index = args[1]
            text = args[2]

        else:
            raise TypeError("Invalid arguments")

        if isinstance(index, Token):
            # index is a Token, grap the stream index from it
            index = index.index

        # to insert after, just insert before next index (even if past end)
        self.insertBefore(programName, index+1, text)


    def insertBefore(self, *args):
        if len(args) == 2:
            programName = self.DEFAULT_PROGRAM_NAME
            index = args[0]
            text = args[1]
            
        elif len(args) == 3:
            programName = args[0]
            index = args[1]
            text = args[2]

        else:
            raise TypeError("Invalid arguments")

        if isinstance(index, Token):
            # index is a Token, grap the stream index from it
            index = index.index

        op = InsertBeforeOp(self, index, text)
        rewrites = self.getProgram(programName)
        rewrites.append(op)


    def replace(self, *args):
        if len(args) == 2:
            programName = self.DEFAULT_PROGRAM_NAME
            first = args[0]
            last = args[0]
            text = args[1]
            
        elif len(args) == 3:
            programName = self.DEFAULT_PROGRAM_NAME
            first = args[0]
            last = args[1]
            text = args[2]
            
        elif len(args) == 4:
            programName = args[0]
            first = args[1]
            last = args[2]
            text = args[3]

        else:
            raise TypeError("Invalid arguments")

        if isinstance(first, Token):
            # first is a Token, grap the stream index from it
            first = first.index

        if isinstance(last, Token):
            # last is a Token, grap the stream index from it
            last = last.index

        if first > last or first < 0 or last < 0 or last >= len(self.tokens):
            raise ValueError(
                "replace: range invalid: "+first+".."+last+
                "(size="+len(self.tokens)+")")

        op = ReplaceOp(self, first, last, text)
        rewrites = self.getProgram(programName)
        rewrites.append(op)
        

    def delete(self, *args):
        self.replace(*(list(args) + [None]))


    def getLastRewriteTokenIndex(self, programName=DEFAULT_PROGRAM_NAME):
        return self.lastRewriteTokenIndexes.get(programName, -1)


    def setLastRewriteTokenIndex(self, programName, i):
        self.lastRewriteTokenIndexes[programName] = i


    def getProgram(self, name):
        p = self.programs.get(name, None)
        if p is  None:
            p = self.initializeProgram(name)

        return p


    def initializeProgram(self, name):
        p = []
        self.programs[name] = p
        return p


    def toOriginalString(self, start=None, end=None):
        if start is None:
            start = self.MIN_TOKEN_INDEX
        if end is None:
            end = self.size() - 1
        
        buf = StringIO()
        i = start
        while i >= self.MIN_TOKEN_INDEX and i <= end and i < len(self.tokens):
            buf.write(self.get(i).text)
            i += 1

        return buf.getvalue()


    def toString(self, *args):
        if len(args) == 0:
            programName = self.DEFAULT_PROGRAM_NAME
            start = self.MIN_TOKEN_INDEX
            end = self.size() - 1
            
        elif len(args) == 1:
            programName = args[0]
            start = self.MIN_TOKEN_INDEX
            end = self.size() - 1

        elif len(args) == 2:
            programName = self.DEFAULT_PROGRAM_NAME
            start = args[0]
            end = args[1]
            
        if start is None:
            start = self.MIN_TOKEN_INDEX
        elif not isinstance(start, int):
            start = start.index

        if end is None:
            end = len(self.tokens) - 1
        elif not isinstance(end, int):
            end = end.index

        # ensure start/end are in range
        if end >= len(self.tokens):
            end = len(self.tokens) - 1

        if start < 0:
            start = 0

        rewrites = self.programs.get(programName)
        if rewrites is None or len(rewrites) == 0:
            # no instructions to execute
            return self.toOriginalString(start, end)
        
        buf = StringIO()

        # First, optimize instruction stream
        indexToOp = self.reduceToSingleOperationPerIndex(rewrites)

        # Walk buffer, executing instructions and emitting tokens
        i = start
        while i <= end and i < len(self.tokens):
            op = indexToOp.get(i)
            # remove so any left have index size-1
            try:
                del indexToOp[i]
            except KeyError:
                pass

            t = self.tokens[i]
            if op is None:
                # no operation at that index, just dump token
                buf.write(t.text)
                i += 1 # move to next token

            else:
                i = op.execute(buf) # execute operation and skip

        # include stuff after end if it's last index in buffer
        # So, if they did an insertAfter(lastValidIndex, "foo"), include
        # foo if end==lastValidIndex.
        if end == len(self.tokens) - 1:
            # Scan any remaining operations after last token
            # should be included (they will be inserts).
            for i in sorted(indexToOp.keys()):
                op = indexToOp[i]
                if op.index >= len(self.tokens)-1:
                    buf.write(op.text)

        return buf.getvalue()

    __str__ = toString


    def reduceToSingleOperationPerIndex(self, rewrites):
        """
        We need to combine operations and report invalid operations (like
        overlapping replaces that are not completed nested).  Inserts to
        same index need to be combined etc...   Here are the cases:

        I.i.u I.j.v                           leave alone, nonoverlapping
        I.i.u I.i.v                           combine: Iivu

        R.i-j.u R.x-y.v | i-j in x-y          delete first R
        R.i-j.u R.i-j.v                       delete first R
        R.i-j.u R.x-y.v | x-y in i-j          ERROR
        R.i-j.u R.x-y.v | boundaries overlap  ERROR

        I.i.u R.x-y.v   | i in x-y            delete I
        I.i.u R.x-y.v   | i not in x-y        leave alone, nonoverlapping
        R.x-y.v I.i.u   | i in x-y            ERROR
        R.x-y.v I.x.u                         R.x-y.uv (combine, delete I)
        R.x-y.v I.i.u   | i not in x-y        leave alone, nonoverlapping

        I.i.u = insert u before op @ index i
        R.x-y.u = replace x-y indexed tokens with u

        First we need to examine replaces.  For any replace op:

          1. wipe out any insertions before op within that range.
          2. Drop any replace op before that is contained completely within
             that range.
          3. Throw exception upon boundary overlap with any previous replace.

        Then we can deal with inserts:

          1. for any inserts to same index, combine even if not adjacent.
          2. for any prior replace with same left boundary, combine this
             insert with replace and delete this replace.
          3. throw exception if index in same range as previous replace

        Don't actually delete; make op null in list. Easier to walk list.
        Later we can throw as we add to index -> op map.

        Note that I.2 R.2-2 will wipe out I.2 even though, technically, the
        inserted stuff would be before the replace range.  But, if you
        add tokens in front of a method body '{' and then delete the method
        body, I think the stuff before the '{' you added should disappear too.

        Return a map from token index to operation.
        """
        
        # WALK REPLACES
        for i, rop in enumerate(rewrites):
            if rop is None:
                continue

            if not isinstance(rop, ReplaceOp):
                continue

            # Wipe prior inserts within range
            for j, iop in self.getKindOfOps(rewrites, InsertBeforeOp, i):
                if iop.index >= rop.index and iop.index <= rop.lastIndex:
                    rewrites[j] = None  # delete insert as it's a no-op.

            # Drop any prior replaces contained within
            for j, prevRop in self.getKindOfOps(rewrites, ReplaceOp, i):
                if (prevRop.index >= rop.index
                    and prevRop.lastIndex <= rop.lastIndex):
                    rewrites[j] = None  # delete replace as it's a no-op.
                    continue

                # throw exception unless disjoint or identical
                disjoint = (prevRop.lastIndex < rop.index
                            or prevRop.index > rop.lastIndex)
                same = (prevRop.index == rop.index
                        and prevRop.lastIndex == rop.lastIndex)
                if not disjoint and not same:
                    raise ValueError(
                        "replace op boundaries of %s overlap with previous %s"
                        % (rop, prevRop))

        # WALK INSERTS
        for i, iop in enumerate(rewrites):
            if iop is None:
                continue

            if not isinstance(iop, InsertBeforeOp):
                continue

            # combine current insert with prior if any at same index
            for j, prevIop in self.getKindOfOps(rewrites, InsertBeforeOp, i):
                if prevIop.index == iop.index: # combine objects
                    # convert to strings...we're in process of toString'ing
                    # whole token buffer so no lazy eval issue with any
                    # templates
                    iop.text = self.catOpText(iop.text, prevIop.text)
                    rewrites[j] = None  # delete redundant prior insert

            # look for replaces where iop.index is in range; error
            for j, rop in self.getKindOfOps(rewrites, ReplaceOp, i):
                if iop.index == rop.index:
                    rop.text = self.catOpText(iop.text, rop.text)
                    rewrites[i] = None  # delete current insert
                    continue

                if iop.index >= rop.index and iop.index <= rop.lastIndex:
                    raise ValueError(
                        "insert op %s within boundaries of previous %s"
                        % (iop, rop))
        
        m = {}
        for i, op in enumerate(rewrites):
            if op is None:
                continue # ignore deleted ops

            assert op.index not in m, "should only be one op per index"
            m[op.index] = op

        return m


    def catOpText(self, a, b):
        x = ""
        y = ""
        if a is not None:
            x = a
        if b is not None:
            y = b
        return x + y


    def getKindOfOps(self, rewrites, kind, before=None):
        if before is None:
            before = len(rewrites)
        elif before > len(rewrites):
            before = len(rewrites)

        for i, op in enumerate(rewrites[:before]):
            if op is None:
                # ignore deleted
                continue
            if op.__class__ == kind:
                yield i, op


    def toDebugString(self, start=None, end=None):
        if start is None:
            start = self.MIN_TOKEN_INDEX
        if end is None:
            end = self.size() - 1

        buf = StringIO()
        i = start
        while i >= self.MIN_TOKEN_INDEX and i <= end and i < len(self.tokens):
            buf.write(self.get(i))
            i += 1

        return buf.getvalue()
