"""ANTLR3 runtime package"""

# begin[licence]
#
# [The "BSD licence"]
# Copyright (c) 2005-2008 Terence Parr
# All rights reserved.
#
# Redistribution and use in source and binary forms, with or without
# modification, are permitted provided that the following conditions
# are met:
# 1. Redistributions of source code must retain the above copyright
#    notice, this list of conditions and the following disclaimer.
# 2. Redistributions in binary form must reproduce the above copyright
#    notice, this list of conditions and the following disclaimer in the
#    documentation and/or other materials provided with the distribution.
# 3. The name of the author may not be used to endorse or promote products
#    derived from this software without specific prior written permission.
#
# THIS SOFTWARE IS PROVIDED BY THE AUTHOR ``AS IS'' AND ANY EXPRESS OR
# IMPLIED WARRANTIES, INCLUDING, BUT NOT LIMITED TO, THE IMPLIED WARRANTIES
# OF MERCHANTABILITY AND FITNESS FOR A PARTICULAR PURPOSE ARE DISCLAIMED.
# IN NO EVENT SHALL THE AUTHOR BE LIABLE FOR ANY DIRECT, INDIRECT,
# INCIDENTAL, SPECIAL, EXEMPLARY, OR CONSEQUENTIAL DAMAGES (INCLUDING, BUT
# NOT LIMITED TO, PROCUREMENT OF SUBSTITUTE GOODS OR SERVICES; LOSS OF USE,
# DATA, OR PROFITS; OR BUSINESS INTERRUPTION) HOWEVER CAUSED AND ON ANY
# THEORY OF LIABILITY, WHETHER IN CONTRACT, STRICT LIABILITY, OR TORT
# (INCLUDING NEGLIGENCE OR OTHERWISE) ARISING IN ANY WAY OUT OF THE USE OF
# THIS SOFTWARE, EVEN IF ADVISED OF THE POSSIBILITY OF SUCH DAMAGE.
#
# end[licence]

from antlr3.constants import EOF, DEFAULT_CHANNEL, INVALID_TOKEN_TYPE

############################################################################
#
# basic token interface
#
############################################################################

class Token(object):
    """@brief Abstract token baseclass."""

    def getText(self):
        """@brief Get the text of the token.

        Using setter/getter methods is deprecated. Use o.text instead.
        """
        raise NotImplementedError
    
    def setText(self, text):
        """@brief Set the text of the token.

        Using setter/getter methods is deprecated. Use o.text instead.
        """
        raise NotImplementedError


    def getType(self):
        """@brief Get the type of the token.

        Using setter/getter methods is deprecated. Use o.type instead."""

        raise NotImplementedError
    
    def setType(self, ttype):
        """@brief Get the type of the token.

        Using setter/getter methods is deprecated. Use o.type instead."""

        raise NotImplementedError
    
    
    def getLine(self):
        """@brief Get the line number on which this token was matched

        Lines are numbered 1..n
        
        Using setter/getter methods is deprecated. Use o.line instead."""

        raise NotImplementedError
    
    def setLine(self, line):
        """@brief Set the line number on which this token was matched

        Using setter/getter methods is deprecated. Use o.line instead."""

        raise NotImplementedError
    
    
    def getCharPositionInLine(self):
        """@brief Get the column of the tokens first character,
        
        Columns are numbered 0..n-1
        
        Using setter/getter methods is deprecated. Use o.charPositionInLine instead."""

        raise NotImplementedError
    
    def setCharPositionInLine(self, pos):
        """@brief Set the column of the tokens first character,

        Using setter/getter methods is deprecated. Use o.charPositionInLine instead."""

        raise NotImplementedError
    

    def getChannel(self):
        """@brief Get the channel of the token

        Using setter/getter methods is deprecated. Use o.channel instead."""

        raise NotImplementedError
    
    def setChannel(self, channel):
        """@brief Set the channel of the token

        Using setter/getter methods is deprecated. Use o.channel instead."""

        raise NotImplementedError
    

    def getTokenIndex(self):
        """@brief Get the index in the input stream.

        An index from 0..n-1 of the token object in the input stream.
        This must be valid in order to use the ANTLRWorks debugger.
        
        Using setter/getter methods is deprecated. Use o.index instead."""

        raise NotImplementedError
    
    def setTokenIndex(self, index):
        """@brief Set the index in the input stream.

        Using setter/getter methods is deprecated. Use o.index instead."""

        raise NotImplementedError


    def getInputStream(self):
        """@brief From what character stream was this token created.

        You don't have to implement but it's nice to know where a Token
        comes from if you have include files etc... on the input."""

        raise NotImplementedError

    def setInputStream(self, input):
        """@brief From what character stream was this token created.

        You don't have to implement but it's nice to know where a Token
        comes from if you have include files etc... on the input."""

        raise NotImplementedError


############################################################################
#
# token implementations
#
# Token
# +- CommonToken
# \- ClassicToken
#
############################################################################

class CommonToken(Token):
    """@brief Basic token implementation.

    This implementation does not copy the text from the input stream upon
    creation, but keeps start/stop pointers into the stream to avoid
    unnecessary copy operations.

    """
    
    def __init__(self, type=None, channel=DEFAULT_CHANNEL, text=None,
                 input=None, start=None, stop=None, oldToken=None):
        Token.__init__(self)
        
        if oldToken is not None:
            self.type = oldToken.type
            self.line = oldToken.line
            self.charPositionInLine = oldToken.charPositionInLine
            self.channel = oldToken.channel
            self.index = oldToken.index
            self._text = oldToken._text
            if isinstance(oldToken, CommonToken):
                self.input = oldToken.input
                self.start = oldToken.start
                self.stop = oldToken.stop
            
        else:
            self.type = type
            self.input = input
            self.charPositionInLine = -1 # set to invalid position
            self.line = 0
            self.channel = channel
            
	    #What token number is this from 0..n-1 tokens; < 0 implies invalid index
            self.index = -1
            
            # We need to be able to change the text once in a while.  If
            # this is non-null, then getText should return this.  Note that
            # start/stop are not affected by changing this.
            self._text = text

            # The char position into the input buffer where this token starts
            self.start = start

            # The char position into the input buffer where this token stops
            # This is the index of the last char, *not* the index after it!
            self.stop = stop


    def getText(self):
        if self._text is not None:
            return self._text

        if self.input is None:
            return None
        
        return self.input.substring(self.start, self.stop)


    def setText(self, text):
        """
        Override the text for this token.  getText() will return this text
        rather than pulling from the buffer.  Note that this does not mean
        that start/stop indexes are not valid.  It means that that input
        was converted to a new string in the token object.
	"""
        self._text = text

    text = property(getText, setText)


    def getType(self):
        return self.type 

    def setType(self, ttype):
        self.type = ttype

    
    def getLine(self):
        return self.line
    
    def setLine(self, line):
        self.line = line


    def getCharPositionInLine(self):
        return self.charPositionInLine
    
    def setCharPositionInLine(self, pos):
        self.charPositionInLine = pos


    def getChannel(self):
        return self.channel
    
    def setChannel(self, channel):
        self.channel = channel
    

    def getTokenIndex(self):
        return self.index
    
    def setTokenIndex(self, index):
        self.index = index


    def getInputStream(self):
        return self.input

    def setInputStream(self, input):
        self.input = input


    def __str__(self):
        if self.type == EOF:
            return "<EOF>"

        channelStr = ""
        if self.channel > 0:
            channelStr = ",channel=" + str(self.channel)

        txt = self.text
        if txt is not None:
            txt = txt.replace("\n","\\\\n")
            txt = txt.replace("\r","\\\\r")
            txt = txt.replace("\t","\\\\t")
        else:
            txt = "<no text>"

        return "[@%d,%d:%d=%r,<%d>%s,%d:%d]" % (
            self.index,
            self.start, self.stop,
            txt,
            self.type, channelStr,
            self.line, self.charPositionInLine
            )
    

class ClassicToken(Token):
    """@brief Alternative token implementation.
    
    A Token object like we'd use in ANTLR 2.x; has an actual string created
    and associated with this object.  These objects are needed for imaginary
    tree nodes that have payload objects.  We need to create a Token object
    that has a string; the tree node will point at this token.  CommonToken
    has indexes into a char stream and hence cannot be used to introduce
    new strings.
    """

    def __init__(self, type=None, text=None, channel=DEFAULT_CHANNEL,
                 oldToken=None
                 ):
        Token.__init__(self)
        
        if oldToken is not None:
            self.text = oldToken.text
            self.type = oldToken.type
            self.line = oldToken.line
            self.charPositionInLine = oldToken.charPositionInLine
            self.channel = oldToken.channel
            
        self.text = text
        self.type = type
        self.line = None
        self.charPositionInLine = None
        self.channel = channel
        self.index = None


    def getText(self):
        return self.text

    def setText(self, text):
        self.text = text


    def getType(self):
        return self.type 

    def setType(self, ttype):
        self.type = ttype

    
    def getLine(self):
        return self.line
    
    def setLine(self, line):
        self.line = line


    def getCharPositionInLine(self):
        return self.charPositionInLine
    
    def setCharPositionInLine(self, pos):
        self.charPositionInLine = pos


    def getChannel(self):
        return self.channel
    
    def setChannel(self, channel):
        self.channel = channel
    

    def getTokenIndex(self):
        return self.index
    
    def setTokenIndex(self, index):
        self.index = index


    def getInputStream(self):
        return None

    def setInputStream(self, input):
        pass


    def toString(self):
        channelStr = ""
        if self.channel > 0:
            channelStr = ",channel=" + str(self.channel)
            
        txt = self.text
        if txt is None:
            txt = "<no text>"

        return "[@%r,%r,<%r>%s,%r:%r]" % (self.index,
                                          txt,
                                          self.type,
                                          channelStr,
                                          self.line,
                                          self.charPositionInLine
                                          )
    

    __str__ = toString
    __repr__ = toString



EOF_TOKEN = CommonToken(type=EOF)
	
INVALID_TOKEN = CommonToken(type=INVALID_TOKEN_TYPE)

# In an action, a lexer rule can set token to this SKIP_TOKEN and ANTLR
# will avoid creating a token for this symbol and try to fetch another.
SKIP_TOKEN = CommonToken(type=INVALID_TOKEN_TYPE)


