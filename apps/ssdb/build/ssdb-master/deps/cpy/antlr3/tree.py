""" @package antlr3.tree
@brief ANTLR3 runtime package, tree module

This module contains all support classes for AST construction and tree parsers.

"""

# begin[licence]
#
# [The "BSD licence"]
# Copyright (c) 2005-2008 Terence Parr
# All rights reserved.
#
# Redistribution and use in source and binary forms, with or without
# modification, are permitted provided that the following conditions
# are met:
# 1. Redistributions of source code must retain the above copyright
#    notice, this list of conditions and the following disclaimer.
# 2. Redistributions in binary form must reproduce the above copyright
#    notice, this list of conditions and the following disclaimer in the
#    documentation and/or other materials provided with the distribution.
# 3. The name of the author may not be used to endorse or promote products
#    derived from this software without specific prior written permission.
#
# THIS SOFTWARE IS PROVIDED BY THE AUTHOR ``AS IS'' AND ANY EXPRESS OR
# IMPLIED WARRANTIES, INCLUDING, BUT NOT LIMITED TO, THE IMPLIED WARRANTIES
# OF MERCHANTABILITY AND FITNESS FOR A PARTICULAR PURPOSE ARE DISCLAIMED.
# IN NO EVENT SHALL THE AUTHOR BE LIABLE FOR ANY DIRECT, INDIRECT,
# INCIDENTAL, SPECIAL, EXEMPLARY, OR CONSEQUENTIAL DAMAGES (INCLUDING, BUT
# NOT LIMITED TO, PROCUREMENT OF SUBSTITUTE GOODS OR SERVICES; LOSS OF USE,
# DATA, OR PROFITS; OR BUSINESS INTERRUPTION) HOWEVER CAUSED AND ON ANY
# THEORY OF LIABILITY, WHETHER IN CONTRACT, STRICT LIABILITY, OR TORT
# (INCLUDING NEGLIGENCE OR OTHERWISE) ARISING IN ANY WAY OUT OF THE USE OF
# THIS SOFTWARE, EVEN IF ADVISED OF THE POSSIBILITY OF SUCH DAMAGE.
#
# end[licence]

# lot's of docstrings are missing, don't complain for now...
# pylint: disable-msg=C0111

from antlr3.constants import UP, DOWN, EOF, INVALID_TOKEN_TYPE
from antlr3.recognizers import BaseRecognizer, RuleReturnScope
from antlr3.streams import IntStream
from antlr3.tokens import CommonToken, Token, INVALID_TOKEN
from antlr3.exceptions import MismatchedTreeNodeException, \
     MissingTokenException, UnwantedTokenException, MismatchedTokenException, \
     NoViableAltException


############################################################################
#
# tree related exceptions
#
############################################################################


class RewriteCardinalityException(RuntimeError):
    """
    @brief Base class for all exceptions thrown during AST rewrite construction.

    This signifies a case where the cardinality of two or more elements
    in a subrule are different: (ID INT)+ where |ID|!=|INT|
    """

    def __init__(self, elementDescription):
        RuntimeError.__init__(self, elementDescription)

        self.elementDescription = elementDescription


    def getMessage(self):
        return self.elementDescription


class RewriteEarlyExitException(RewriteCardinalityException):
    """@brief No elements within a (...)+ in a rewrite rule"""

    def __init__(self, elementDescription=None):
        RewriteCardinalityException.__init__(self, elementDescription)


class RewriteEmptyStreamException(RewriteCardinalityException):
    """
    @brief Ref to ID or expr but no tokens in ID stream or subtrees in expr stream
    """

    pass


############################################################################
#
# basic Tree and TreeAdaptor interfaces
#
############################################################################

class Tree(object):
    """
    @brief Abstract baseclass for tree nodes.
    
    What does a tree look like?  ANTLR has a number of support classes
    such as CommonTreeNodeStream that work on these kinds of trees.  You
    don't have to make your trees implement this interface, but if you do,
    you'll be able to use more support code.

    NOTE: When constructing trees, ANTLR can build any kind of tree; it can
    even use Token objects as trees if you add a child list to your tokens.
    
    This is a tree node without any payload; just navigation and factory stuff.
    """


    def getChild(self, i):
        raise NotImplementedError
    

    def getChildCount(self):
        raise NotImplementedError
    

    def getParent(self):
        """Tree tracks parent and child index now > 3.0"""

        raise NotImplementedError
    
    def setParent(self, t):
        """Tree tracks parent and child index now > 3.0"""

        raise NotImplementedError
    

    def getChildIndex(self):
        """This node is what child index? 0..n-1"""

        raise NotImplementedError
        
    def setChildIndex(self, index):
        """This node is what child index? 0..n-1"""

        raise NotImplementedError
        

    def freshenParentAndChildIndexes(self):
        """Set the parent and child index values for all children"""
        
        raise NotImplementedError

        
    def addChild(self, t):
        """
        Add t as a child to this node.  If t is null, do nothing.  If t
        is nil, add all children of t to this' children.
        """

        raise NotImplementedError
    

    def setChild(self, i, t):
        """Set ith child (0..n-1) to t; t must be non-null and non-nil node"""

        raise NotImplementedError

            
    def deleteChild(self, i):
        raise NotImplementedError
        
 
    def replaceChildren(self, startChildIndex, stopChildIndex, t):
        """
        Delete children from start to stop and replace with t even if t is
        a list (nil-root tree).  num of children can increase or decrease.
        For huge child lists, inserting children can force walking rest of
        children to set their childindex; could be slow.
        """

        raise NotImplementedError


    def isNil(self):
        """
        Indicates the node is a nil node but may still have children, meaning
        the tree is a flat list.
        """

        raise NotImplementedError
    

    def getTokenStartIndex(self):
        """
        What is the smallest token index (indexing from 0) for this node
           and its children?
        """

        raise NotImplementedError


    def setTokenStartIndex(self, index):
        raise NotImplementedError


    def getTokenStopIndex(self):
        """
        What is the largest token index (indexing from 0) for this node
        and its children?
        """

        raise NotImplementedError


    def setTokenStopIndex(self, index):
        raise NotImplementedError


    def dupNode(self):
        raise NotImplementedError
    
    
    def getType(self):
        """Return a token type; needed for tree parsing."""

        raise NotImplementedError
    

    def getText(self):
        raise NotImplementedError
    

    def getLine(self):
        """
        In case we don't have a token payload, what is the line for errors?
        """

        raise NotImplementedError
    

    def getCharPositionInLine(self):
        raise NotImplementedError


    def toStringTree(self):
        raise NotImplementedError


    def toString(self):
        raise NotImplementedError



class TreeAdaptor(object):
    """
    @brief Abstract baseclass for tree adaptors.
    
    How to create and navigate trees.  Rather than have a separate factory
    and adaptor, I've merged them.  Makes sense to encapsulate.

    This takes the place of the tree construction code generated in the
    generated code in 2.x and the ASTFactory.

    I do not need to know the type of a tree at all so they are all
    generic Objects.  This may increase the amount of typecasting needed. :(
    """
    
    # C o n s t r u c t i o n

    def createWithPayload(self, payload):
        """
        Create a tree node from Token object; for CommonTree type trees,
        then the token just becomes the payload.  This is the most
        common create call.

        Override if you want another kind of node to be built.
        """

        raise NotImplementedError
    

    def dupNode(self, treeNode):
        """Duplicate a single tree node.

        Override if you want another kind of node to be built."""

        raise NotImplementedError


    def dupTree(self, tree):
        """Duplicate tree recursively, using dupNode() for each node"""

        raise NotImplementedError


    def nil(self):
        """
        Return a nil node (an empty but non-null node) that can hold
        a list of element as the children.  If you want a flat tree (a list)
        use "t=adaptor.nil(); t.addChild(x); t.addChild(y);"
        """

        raise NotImplementedError


    def errorNode(self, input, start, stop, exc):
        """
        Return a tree node representing an error.  This node records the
        tokens consumed during error recovery.  The start token indicates the
        input symbol at which the error was detected.  The stop token indicates
        the last symbol consumed during recovery.

        You must specify the input stream so that the erroneous text can
        be packaged up in the error node.  The exception could be useful
        to some applications; default implementation stores ptr to it in
        the CommonErrorNode.

        This only makes sense during token parsing, not tree parsing.
        Tree parsing should happen only when parsing and tree construction
        succeed.
        """

        raise NotImplementedError


    def isNil(self, tree):
        """Is tree considered a nil node used to make lists of child nodes?"""

        raise NotImplementedError


    def addChild(self, t, child):
        """
        Add a child to the tree t.  If child is a flat tree (a list), make all
        in list children of t.  Warning: if t has no children, but child does
        and child isNil then you can decide it is ok to move children to t via
        t.children = child.children; i.e., without copying the array.  Just
        make sure that this is consistent with have the user will build
        ASTs. Do nothing if t or child is null.
        """

        raise NotImplementedError


    def becomeRoot(self, newRoot, oldRoot):
        """
        If oldRoot is a nil root, just copy or move the children to newRoot.
        If not a nil root, make oldRoot a child of newRoot.
        
           old=^(nil a b c), new=r yields ^(r a b c)
           old=^(a b c), new=r yields ^(r ^(a b c))

        If newRoot is a nil-rooted single child tree, use the single
        child as the new root node.

           old=^(nil a b c), new=^(nil r) yields ^(r a b c)
           old=^(a b c), new=^(nil r) yields ^(r ^(a b c))

        If oldRoot was null, it's ok, just return newRoot (even if isNil).

           old=null, new=r yields r
           old=null, new=^(nil r) yields ^(nil r)

        Return newRoot.  Throw an exception if newRoot is not a
        simple node or nil root with a single child node--it must be a root
        node.  If newRoot is ^(nil x) return x as newRoot.

        Be advised that it's ok for newRoot to point at oldRoot's
        children; i.e., you don't have to copy the list.  We are
        constructing these nodes so we should have this control for
        efficiency.
        """

        raise NotImplementedError


    def rulePostProcessing(self, root):
        """
        Given the root of the subtree created for this rule, post process
        it to do any simplifications or whatever you want.  A required
        behavior is to convert ^(nil singleSubtree) to singleSubtree
        as the setting of start/stop indexes relies on a single non-nil root
        for non-flat trees.

        Flat trees such as for lists like "idlist : ID+ ;" are left alone
        unless there is only one ID.  For a list, the start/stop indexes
        are set in the nil node.

        This method is executed after all rule tree construction and right
        before setTokenBoundaries().
        """

        raise NotImplementedError


    def getUniqueID(self, node):
        """For identifying trees.

        How to identify nodes so we can say "add node to a prior node"?
        Even becomeRoot is an issue.  Use System.identityHashCode(node)
        usually.
        """

        raise NotImplementedError


    # R e w r i t e  R u l e s

    def createFromToken(self, tokenType, fromToken, text=None):
        """
        Create a new node derived from a token, with a new token type and
        (optionally) new text.

        This is invoked from an imaginary node ref on right side of a
        rewrite rule as IMAG[$tokenLabel] or IMAG[$tokenLabel "IMAG"].

        This should invoke createToken(Token).
        """

        raise NotImplementedError


    def createFromType(self, tokenType, text):
        """Create a new node derived from a token, with a new token type.

        This is invoked from an imaginary node ref on right side of a
        rewrite rule as IMAG["IMAG"].

        This should invoke createToken(int,String).
        """

        raise NotImplementedError


    # C o n t e n t

    def getType(self, t):
        """For tree parsing, I need to know the token type of a node"""

        raise NotImplementedError


    def setType(self, t, type):
        """Node constructors can set the type of a node"""

        raise NotImplementedError


    def getText(self, t):
        raise NotImplementedError

    def setText(self, t, text):
        """Node constructors can set the text of a node"""

        raise NotImplementedError


    def getToken(self, t):
        """Return the token object from which this node was created.

        Currently used only for printing an error message.
        The error display routine in BaseRecognizer needs to
        display where the input the error occurred. If your
        tree of limitation does not store information that can
        lead you to the token, you can create a token filled with
        the appropriate information and pass that back.  See
        BaseRecognizer.getErrorMessage().
        """

        raise NotImplementedError


    def setTokenBoundaries(self, t, startToken, stopToken):
        """
        Where are the bounds in the input token stream for this node and
        all children?  Each rule that creates AST nodes will call this
        method right before returning.  Flat trees (i.e., lists) will
        still usually have a nil root node just to hold the children list.
        That node would contain the start/stop indexes then.
        """

        raise NotImplementedError


    def getTokenStartIndex(self, t):
        """
        Get the token start index for this subtree; return -1 if no such index
        """

        raise NotImplementedError

        
    def getTokenStopIndex(self, t):
        """
        Get the token stop index for this subtree; return -1 if no such index
        """

        raise NotImplementedError
        

    # N a v i g a t i o n  /  T r e e  P a r s i n g

    def getChild(self, t, i):
        """Get a child 0..n-1 node"""

        raise NotImplementedError


    def setChild(self, t, i, child):
        """Set ith child (0..n-1) to t; t must be non-null and non-nil node"""

        raise NotImplementedError


    def deleteChild(self, t, i):
        """Remove ith child and shift children down from right."""
        
        raise NotImplementedError


    def getChildCount(self, t):
        """How many children?  If 0, then this is a leaf node"""

        raise NotImplementedError


    def getParent(self, t):
        """
        Who is the parent node of this node; if null, implies node is root.
        If your node type doesn't handle this, it's ok but the tree rewrites
        in tree parsers need this functionality.
        """
        
        raise NotImplementedError


    def setParent(self, t, parent):
        """
        Who is the parent node of this node; if null, implies node is root.
        If your node type doesn't handle this, it's ok but the tree rewrites
        in tree parsers need this functionality.
        """

        raise NotImplementedError


    def getChildIndex(self, t):
        """
        What index is this node in the child list? Range: 0..n-1
        If your node type doesn't handle this, it's ok but the tree rewrites
        in tree parsers need this functionality.
        """

        raise NotImplementedError

        
    def setChildIndex(self, t, index):
        """
        What index is this node in the child list? Range: 0..n-1
        If your node type doesn't handle this, it's ok but the tree rewrites
        in tree parsers need this functionality.
        """

        raise NotImplementedError


    def replaceChildren(self, parent, startChildIndex, stopChildIndex, t):
        """
        Replace from start to stop child index of parent with t, which might
        be a list.  Number of children may be different
        after this call.

        If parent is null, don't do anything; must be at root of overall tree.
        Can't replace whatever points to the parent externally.  Do nothing.
        """

        raise NotImplementedError


    # Misc

    def create(self, *args):
        """
        Deprecated, use createWithPayload, createFromToken or createFromType.

        This method only exists to mimic the Java interface of TreeAdaptor.
        
        """

        if len(args) == 1 and isinstance(args[0], Token):
            # Object create(Token payload);
##             warnings.warn(
##                 "Using create() is deprecated, use createWithPayload()",
##                 DeprecationWarning,
##                 stacklevel=2
##                 )
            return self.createWithPayload(args[0])

        if (len(args) == 2
            and isinstance(args[0], (int, long))
            and isinstance(args[1], Token)
            ):
            # Object create(int tokenType, Token fromToken);
##             warnings.warn(
##                 "Using create() is deprecated, use createFromToken()",
##                 DeprecationWarning,
##                 stacklevel=2
##                 )
            return self.createFromToken(args[0], args[1])

        if (len(args) == 3
            and isinstance(args[0], (int, long))
            and isinstance(args[1], Token)
            and isinstance(args[2], basestring)
            ):
            # Object create(int tokenType, Token fromToken, String text);
##             warnings.warn(
##                 "Using create() is deprecated, use createFromToken()",
##                 DeprecationWarning,
##                 stacklevel=2
##                 )
            return self.createFromToken(args[0], args[1], args[2])

        if (len(args) == 2
            and isinstance(args[0], (int, long))
            and isinstance(args[1], basestring)
            ):
            # Object create(int tokenType, String text);
##             warnings.warn(
##                 "Using create() is deprecated, use createFromType()",
##                 DeprecationWarning,
##                 stacklevel=2
##                 )
            return self.createFromType(args[0], args[1])

        raise TypeError(
            "No create method with this signature found: %s"
            % (', '.join(type(v).__name__ for v in args))
            )
    

############################################################################
#
# base implementation of Tree and TreeAdaptor
#
# Tree
# \- BaseTree
#
# TreeAdaptor
# \- BaseTreeAdaptor
#
############################################################################


class BaseTree(Tree):
    """
    @brief A generic tree implementation with no payload.

    You must subclass to
    actually have any user data.  ANTLR v3 uses a list of children approach
    instead of the child-sibling approach in v2.  A flat tree (a list) is
    an empty node whose children represent the list.  An empty, but
    non-null node is called "nil".
    """

    # BaseTree is abstract, no need to complain about not implemented abstract
    # methods
    # pylint: disable-msg=W0223
    
    def __init__(self, node=None):
        """
        Create a new node from an existing node does nothing for BaseTree
        as there are no fields other than the children list, which cannot
        be copied as the children are not considered part of this node. 
        """
        
        Tree.__init__(self)
        self.children = []
        self.parent = None
        self.childIndex = 0
        

    def getChild(self, i):
        try:
            return self.children[i]
        except IndexError:
            return None


    def getChildren(self):
        """@brief Get the children internal List

        Note that if you directly mess with
        the list, do so at your own risk.
        """
        
        # FIXME: mark as deprecated
        return self.children


    def getFirstChildWithType(self, treeType):
        for child in self.children:
            if child.getType() == treeType:
                return child

        return None


    def getChildCount(self):
        return len(self.children)


    def addChild(self, childTree):
        """Add t as child of this node.

        Warning: if t has no children, but child does
        and child isNil then this routine moves children to t via
        t.children = child.children; i.e., without copying the array.
        """

        # this implementation is much simpler and probably less efficient
        # than the mumbo-jumbo that Ter did for the Java runtime.
        
        if childTree is None:
            return

        if childTree.isNil():
            # t is an empty node possibly with children

            if self.children is childTree.children:
                raise ValueError("attempt to add child list to itself")

            # fix parent pointer and childIndex for new children
            for idx, child in enumerate(childTree.children):
                child.parent = self
                child.childIndex = len(self.children) + idx
                
            self.children += childTree.children

        else:
            # child is not nil (don't care about children)
            self.children.append(childTree)
            childTree.parent = self
            childTree.childIndex = len(self.children) - 1


    def addChildren(self, children):
        """Add all elements of kids list as children of this node"""

        self.children += children


    def setChild(self, i, t):
        if t is None:
            return

        if t.isNil():
            raise ValueError("Can't set single child to a list")
        
        self.children[i] = t
        t.parent = self
        t.childIndex = i
        

    def deleteChild(self, i):
        killed = self.children[i]
        
        del self.children[i]
        
        # walk rest and decrement their child indexes
        for idx, child in enumerate(self.children[i:]):
            child.childIndex = i + idx
            
        return killed

    
    def replaceChildren(self, startChildIndex, stopChildIndex, newTree):
        """
        Delete children from start to stop and replace with t even if t is
        a list (nil-root tree).  num of children can increase or decrease.
        For huge child lists, inserting children can force walking rest of
        children to set their childindex; could be slow.
        """

        if (startChildIndex >= len(self.children)
            or stopChildIndex >= len(self.children)
            ):
            raise IndexError("indexes invalid")

        replacingHowMany = stopChildIndex - startChildIndex + 1

        # normalize to a list of children to add: newChildren
        if newTree.isNil():
            newChildren = newTree.children

        else:
            newChildren = [newTree]

        replacingWithHowMany = len(newChildren)
        delta = replacingHowMany - replacingWithHowMany
        
        
        if delta == 0:
            # if same number of nodes, do direct replace
            for idx, child in enumerate(newChildren):
                self.children[idx + startChildIndex] = child
                child.parent = self
                child.childIndex = idx + startChildIndex

        else:
            # length of children changes...

            # ...delete replaced segment...
            del self.children[startChildIndex:stopChildIndex+1]

            # ...insert new segment...
            self.children[startChildIndex:startChildIndex] = newChildren

            # ...and fix indeces
            self.freshenParentAndChildIndexes(startChildIndex)
            

    def isNil(self):
        return False


    def freshenParentAndChildIndexes(self, offset=0):
        for idx, child in enumerate(self.children[offset:]):
            child.childIndex = idx + offset
            child.parent = self


    def sanityCheckParentAndChildIndexes(self, parent=None, i=-1):
        if parent != self.parent:
            raise ValueError(
                "parents don't match; expected %r found %r"
                % (parent, self.parent)
                )
        
        if i != self.childIndex:
            raise ValueError(
                "child indexes don't match; expected %d found %d"
                % (i, self.childIndex)
                )

        for idx, child in enumerate(self.children):
            child.sanityCheckParentAndChildIndexes(self, idx)


    def getChildIndex(self):
        """BaseTree doesn't track child indexes."""
        
        return 0


    def setChildIndex(self, index):
        """BaseTree doesn't track child indexes."""

        pass
    

    def getParent(self):
        """BaseTree doesn't track parent pointers."""

        return None

    def setParent(self, t):
        """BaseTree doesn't track parent pointers."""

        pass


    def toStringTree(self):
        """Print out a whole tree not just a node"""

        if len(self.children) == 0:
            return self.toString()

        buf = []
        if not self.isNil():
            buf.append('(')
            buf.append(self.toString())
            buf.append(' ')

        for i, child in enumerate(self.children):
            if i > 0:
                buf.append(' ')
            buf.append(child.toStringTree())

        if not self.isNil():
            buf.append(')')

        return ''.join(buf)


    def getLine(self):
        return 0


    def getCharPositionInLine(self):
        return 0


    def toString(self):
        """Override to say how a node (not a tree) should look as text"""

        raise NotImplementedError



class BaseTreeAdaptor(TreeAdaptor):
    """
    @brief A TreeAdaptor that works with any Tree implementation.
    """
    
    # BaseTreeAdaptor is abstract, no need to complain about not implemented
    # abstract methods
    # pylint: disable-msg=W0223
    
    def nil(self):
        return self.createWithPayload(None)


    def errorNode(self, input, start, stop, exc):
        """
        create tree node that holds the start and stop tokens associated
        with an error.

        If you specify your own kind of tree nodes, you will likely have to
        override this method. CommonTree returns Token.INVALID_TOKEN_TYPE
        if no token payload but you might have to set token type for diff
        node type.
        """
        
        return CommonErrorNode(input, start, stop, exc)
    

    def isNil(self, tree):
        return tree.isNil()


    def dupTree(self, t, parent=None):
        """
        This is generic in the sense that it will work with any kind of
        tree (not just Tree interface).  It invokes the adaptor routines
        not the tree node routines to do the construction.
        """

        if t is None:
            return None

        newTree = self.dupNode(t)
        
        # ensure new subtree root has parent/child index set

        # same index in new tree
        self.setChildIndex(newTree, self.getChildIndex(t))
        
        self.setParent(newTree, parent)

        for i in range(self.getChildCount(t)):
            child = self.getChild(t, i)
            newSubTree = self.dupTree(child, t)
            self.addChild(newTree, newSubTree)

        return newTree


    def addChild(self, tree, child):
        """
        Add a child to the tree t.  If child is a flat tree (a list), make all
        in list children of t.  Warning: if t has no children, but child does
        and child isNil then you can decide it is ok to move children to t via
        t.children = child.children; i.e., without copying the array.  Just
        make sure that this is consistent with have the user will build
        ASTs.
        """

        #if isinstance(child, Token):
        #    child = self.createWithPayload(child)
        
        if tree is not None and child is not None:
            tree.addChild(child)


    def becomeRoot(self, newRoot, oldRoot):
        """
        If oldRoot is a nil root, just copy or move the children to newRoot.
        If not a nil root, make oldRoot a child of newRoot.

          old=^(nil a b c), new=r yields ^(r a b c)
          old=^(a b c), new=r yields ^(r ^(a b c))

        If newRoot is a nil-rooted single child tree, use the single
        child as the new root node.

          old=^(nil a b c), new=^(nil r) yields ^(r a b c)
          old=^(a b c), new=^(nil r) yields ^(r ^(a b c))

        If oldRoot was null, it's ok, just return newRoot (even if isNil).

          old=null, new=r yields r
          old=null, new=^(nil r) yields ^(nil r)

        Return newRoot.  Throw an exception if newRoot is not a
        simple node or nil root with a single child node--it must be a root
        node.  If newRoot is ^(nil x) return x as newRoot.

        Be advised that it's ok for newRoot to point at oldRoot's
        children; i.e., you don't have to copy the list.  We are
        constructing these nodes so we should have this control for
        efficiency.
        """

        if isinstance(newRoot, Token):
            newRoot = self.create(newRoot)

        if oldRoot is None:
            return newRoot
        
        if not isinstance(newRoot, CommonTree):
            newRoot = self.createWithPayload(newRoot)

        # handle ^(nil real-node)
        if newRoot.isNil():
            nc = newRoot.getChildCount()
            if nc == 1:
                newRoot = newRoot.getChild(0)
                
            elif nc > 1:
                # TODO: make tree run time exceptions hierarchy
                raise RuntimeError("more than one node as root")

        # add oldRoot to newRoot; addChild takes care of case where oldRoot
        # is a flat list (i.e., nil-rooted tree).  All children of oldRoot
        # are added to newRoot.
        newRoot.addChild(oldRoot)
        return newRoot


    def rulePostProcessing(self, root):
        """Transform ^(nil x) to x and nil to null"""
        
        if root is not None and root.isNil():
            if root.getChildCount() == 0:
                root = None

            elif root.getChildCount() == 1:
                root = root.getChild(0)
                # whoever invokes rule will set parent and child index
                root.setParent(None)
                root.setChildIndex(-1)

        return root


    def createFromToken(self, tokenType, fromToken, text=None):
        assert isinstance(tokenType, (int, long)), type(tokenType).__name__
        assert isinstance(fromToken, Token), type(fromToken).__name__
        assert text is None or isinstance(text, basestring), type(text).__name__

        fromToken = self.createToken(fromToken)
        fromToken.type = tokenType
        if text is not None:
            fromToken.text = text
        t = self.createWithPayload(fromToken)
        return t


    def createFromType(self, tokenType, text):
        assert isinstance(tokenType, (int, long)), type(tokenType).__name__
        assert isinstance(text, basestring), type(text).__name__
                          
        fromToken = self.createToken(tokenType=tokenType, text=text)
        t = self.createWithPayload(fromToken)
        return t


    def getType(self, t):
        return t.getType()


    def setType(self, t, type):
        raise RuntimeError("don't know enough about Tree node")


    def getText(self, t):
        return t.getText()


    def setText(self, t, text):
        raise RuntimeError("don't know enough about Tree node")


    def getChild(self, t, i):
        return t.getChild(i)


    def setChild(self, t, i, child):
        t.setChild(i, child)


    def deleteChild(self, t, i):
        return t.deleteChild(i)


    def getChildCount(self, t):
        return t.getChildCount()


    def getUniqueID(self, node):
        return hash(node)


    def createToken(self, fromToken=None, tokenType=None, text=None):
        """
        Tell me how to create a token for use with imaginary token nodes.
        For example, there is probably no input symbol associated with imaginary
        token DECL, but you need to create it as a payload or whatever for
        the DECL node as in ^(DECL type ID).

        If you care what the token payload objects' type is, you should
        override this method and any other createToken variant.
        """

        raise NotImplementedError


############################################################################
#
# common tree implementation
#
# Tree
# \- BaseTree
#    \- CommonTree
#       \- CommonErrorNode
#
# TreeAdaptor
# \- BaseTreeAdaptor
#    \- CommonTreeAdaptor
#
############################################################################


class CommonTree(BaseTree):
    """@brief A tree node that is wrapper for a Token object.

    After 3.0 release
    while building tree rewrite stuff, it became clear that computing
    parent and child index is very difficult and cumbersome.  Better to
    spend the space in every tree node.  If you don't want these extra
    fields, it's easy to cut them out in your own BaseTree subclass.
    
    """

    def __init__(self, payload):
        BaseTree.__init__(self)
        
        # What token indexes bracket all tokens associated with this node
        # and below?
        self.startIndex = -1
        self.stopIndex = -1

        # Who is the parent node of this node; if null, implies node is root
        self.parent = None
        
        # What index is this node in the child list? Range: 0..n-1
        self.childIndex = -1

        # A single token is the payload
        if payload is None:
            self.token = None
            
        elif isinstance(payload, CommonTree):
            self.token = payload.token
            self.startIndex = payload.startIndex
            self.stopIndex = payload.stopIndex
            
        elif payload is None or isinstance(payload, Token):
            self.token = payload
            
        else:
            raise TypeError(type(payload).__name__)



    def getToken(self):
        return self.token


    def dupNode(self):
        return CommonTree(self)


    def isNil(self):
        return self.token is None


    def getType(self):
        if self.token is None:
            return INVALID_TOKEN_TYPE

        return self.token.getType()

    type = property(getType)
    

    def getText(self):
        if self.token is None:
            return None
        
        return self.token.text

    text = property(getText)
    

    def getLine(self):
        if self.token is None or self.token.getLine() == 0:
            if self.getChildCount():
                return self.getChild(0).getLine()
            else:
                return 0

        return self.token.getLine()

    line = property(getLine)
    

    def getCharPositionInLine(self):
        if self.token is None or self.token.getCharPositionInLine() == -1:
            if self.getChildCount():
                return self.getChild(0).getCharPositionInLine()
            else:
                return 0

        else:
            return self.token.getCharPositionInLine()

    charPositionInLine = property(getCharPositionInLine)
    

    def getTokenStartIndex(self):
        if self.startIndex == -1 and self.token is not None:
            return self.token.getTokenIndex()
        
        return self.startIndex
    
    def setTokenStartIndex(self, index):
        self.startIndex = index

    tokenStartIndex = property(getTokenStartIndex, setTokenStartIndex)


    def getTokenStopIndex(self):
        if self.stopIndex == -1 and self.token is not None:
            return self.token.getTokenIndex()
        
        return self.stopIndex

    def setTokenStopIndex(self, index):
        self.stopIndex = index

    tokenStopIndex = property(getTokenStopIndex, setTokenStopIndex)


    def getChildIndex(self):
        #FIXME: mark as deprecated
        return self.childIndex


    def setChildIndex(self, idx):
        #FIXME: mark as deprecated
        self.childIndex = idx


    def getParent(self):
        #FIXME: mark as deprecated
        return self.parent


    def setParent(self, t):
        #FIXME: mark as deprecated
        self.parent = t

        
    def toString(self):
        if self.isNil():
            return "nil"

        if self.getType() == INVALID_TOKEN_TYPE:
            return "<errornode>"

        return self.token.text

    __str__ = toString   



    def toStringTree(self):
        if not self.children:
            return self.toString()

        ret = ''
        if not self.isNil():
            ret += '(%s ' % (self.toString())
        
        ret += ' '.join([child.toStringTree() for child in self.children])

        if not self.isNil():
            ret += ')'

        return ret


INVALID_NODE = CommonTree(INVALID_TOKEN)


class CommonErrorNode(CommonTree):
    """A node representing erroneous token range in token stream"""

    def __init__(self, input, start, stop, exc):
        CommonTree.__init__(self, None)

        if (stop is None or
            (stop.getTokenIndex() < start.getTokenIndex() and
             stop.getType() != EOF
             )
            ):
            # sometimes resync does not consume a token (when LT(1) is
            # in follow set.  So, stop will be 1 to left to start. adjust.
            # Also handle case where start is the first token and no token
            # is consumed during recovery; LT(-1) will return null.
            stop = start

        self.input = input
        self.start = start
        self.stop = stop
        self.trappedException = exc


    def isNil(self):
        return False


    def getType(self):
        return INVALID_TOKEN_TYPE


    def getText(self):
        if isinstance(self.start, Token):
            i = self.start.getTokenIndex()
            j = self.stop.getTokenIndex()
            if self.stop.getType() == EOF:
                j = self.input.size()

            badText = self.input.toString(i, j)

        elif isinstance(self.start, Tree):
            badText = self.input.toString(self.start, self.stop)

        else:
            # people should subclass if they alter the tree type so this
            # next one is for sure correct.
            badText = "<unknown>"

        return badText


    def toString(self):
        if isinstance(self.trappedException, MissingTokenException):
            return ("<missing type: "
                    + str(self.trappedException.getMissingType())
                    + ">")

        elif isinstance(self.trappedException, UnwantedTokenException):
            return ("<extraneous: "
                    + str(self.trappedException.getUnexpectedToken())
                    + ", resync=" + self.getText() + ">")

        elif isinstance(self.trappedException, MismatchedTokenException):
            return ("<mismatched token: "
                    + str(self.trappedException.token)
                    + ", resync=" + self.getText() + ">")

        elif isinstance(self.trappedException, NoViableAltException):
            return ("<unexpected: "
                    + str(self.trappedException.token)
                    + ", resync=" + self.getText() + ">")

        return "<error: "+self.getText()+">"


class CommonTreeAdaptor(BaseTreeAdaptor):
    """
    @brief A TreeAdaptor that works with any Tree implementation.
    
    It provides
    really just factory methods; all the work is done by BaseTreeAdaptor.
    If you would like to have different tokens created than ClassicToken
    objects, you need to override this and then set the parser tree adaptor to
    use your subclass.

    To get your parser to build nodes of a different type, override
    create(Token).
    """
    
    def dupNode(self, treeNode):
        """
        Duplicate a node.  This is part of the factory;
        override if you want another kind of node to be built.

        I could use reflection to prevent having to override this
        but reflection is slow.
        """

        if treeNode is None:
            return None
        
        return treeNode.dupNode()


    def createWithPayload(self, payload):
        return CommonTree(payload)


    def createToken(self, fromToken=None, tokenType=None, text=None):
        """
        Tell me how to create a token for use with imaginary token nodes.
        For example, there is probably no input symbol associated with imaginary
        token DECL, but you need to create it as a payload or whatever for
        the DECL node as in ^(DECL type ID).

        If you care what the token payload objects' type is, you should
        override this method and any other createToken variant.
        """
        
        if fromToken is not None:
            return CommonToken(oldToken=fromToken)

        return CommonToken(type=tokenType, text=text)


    def setTokenBoundaries(self, t, startToken, stopToken):
        """
        Track start/stop token for subtree root created for a rule.
        Only works with Tree nodes.  For rules that match nothing,
        seems like this will yield start=i and stop=i-1 in a nil node.
        Might be useful info so I'll not force to be i..i.
        """
        
        if t is None:
            return

        start = 0
        stop = 0
        
        if startToken is not None:
            start = startToken.index
                
        if stopToken is not None:
            stop = stopToken.index

        t.setTokenStartIndex(start)
        t.setTokenStopIndex(stop)


    def getTokenStartIndex(self, t):
        if t is None:
            return -1
        return t.getTokenStartIndex()


    def getTokenStopIndex(self, t):
        if t is None:
            return -1
        return t.getTokenStopIndex()


    def getText(self, t):
        if t is None:
            return None
        return t.getText()


    def getType(self, t):
        if t is None:
            return INVALID_TOKEN_TYPE
        
        return t.getType()


    def getToken(self, t):
        """
        What is the Token associated with this node?  If
        you are not using CommonTree, then you must
        override this in your own adaptor.
        """

        if isinstance(t, CommonTree):
            return t.getToken()

        return None # no idea what to do


    def getChild(self, t, i):
        if t is None:
            return None
        return t.getChild(i)


    def getChildCount(self, t):
        if t is None:
            return 0
        return t.getChildCount()


    def getParent(self, t):
        return t.getParent()


    def setParent(self, t, parent):
        t.setParent(parent)


    def getChildIndex(self, t):
        return t.getChildIndex()


    def setChildIndex(self, t, index):
        t.setChildIndex(index)


    def replaceChildren(self, parent, startChildIndex, stopChildIndex, t):
        if parent is not None:
            parent.replaceChildren(startChildIndex, stopChildIndex, t)


############################################################################
#
# streams
#
# TreeNodeStream
# \- BaseTree
#    \- CommonTree
#
# TreeAdaptor
# \- BaseTreeAdaptor
#    \- CommonTreeAdaptor
#
############################################################################



class TreeNodeStream(IntStream):
    """@brief A stream of tree nodes

    It accessing nodes from a tree of some kind.
    """
    
    # TreeNodeStream is abstract, no need to complain about not implemented
    # abstract methods
    # pylint: disable-msg=W0223
    
    def get(self, i):
        """Get a tree node at an absolute index i; 0..n-1.
        If you don't want to buffer up nodes, then this method makes no
        sense for you.
        """

        raise NotImplementedError


    def LT(self, k):
        """
        Get tree node at current input pointer + i ahead where i=1 is next node.
        i<0 indicates nodes in the past.  So LT(-1) is previous node, but
        implementations are not required to provide results for k < -1.
        LT(0) is undefined.  For i>=n, return null.
        Return null for LT(0) and any index that results in an absolute address
        that is negative.

        This is analogus to the LT() method of the TokenStream, but this
        returns a tree node instead of a token.  Makes code gen identical
        for both parser and tree grammars. :)
        """

        raise NotImplementedError


    def getTreeSource(self):
        """
        Where is this stream pulling nodes from?  This is not the name, but
        the object that provides node objects.
        """

        raise NotImplementedError
    

    def getTokenStream(self):
        """
        If the tree associated with this stream was created from a TokenStream,
        you can specify it here.  Used to do rule $text attribute in tree
        parser.  Optional unless you use tree parser rule text attribute
        or output=template and rewrite=true options.
        """

        raise NotImplementedError


    def getTreeAdaptor(self):
        """
        What adaptor can tell me how to interpret/navigate nodes and
        trees.  E.g., get text of a node.
        """

        raise NotImplementedError
        

    def setUniqueNavigationNodes(self, uniqueNavigationNodes):
        """
        As we flatten the tree, we use UP, DOWN nodes to represent
        the tree structure.  When debugging we need unique nodes
        so we have to instantiate new ones.  When doing normal tree
        parsing, it's slow and a waste of memory to create unique
        navigation nodes.  Default should be false;
        """

        raise NotImplementedError
        

    def toString(self, start, stop):
        """
        Return the text of all nodes from start to stop, inclusive.
        If the stream does not buffer all the nodes then it can still
        walk recursively from start until stop.  You can always return
        null or "" too, but users should not access $ruleLabel.text in
        an action of course in that case.
        """

        raise NotImplementedError


    # REWRITING TREES (used by tree parser)
    def replaceChildren(self, parent, startChildIndex, stopChildIndex, t):
        """
 	Replace from start to stop child index of parent with t, which might
        be a list.  Number of children may be different
        after this call.  The stream is notified because it is walking the
        tree and might need to know you are monkeying with the underlying
        tree.  Also, it might be able to modify the node stream to avoid
        restreaming for future phases.

        If parent is null, don't do anything; must be at root of overall tree.
        Can't replace whatever points to the parent externally.  Do nothing.
        """

        raise NotImplementedError


class CommonTreeNodeStream(TreeNodeStream):
    """@brief A buffered stream of tree nodes.

    Nodes can be from a tree of ANY kind.

    This node stream sucks all nodes out of the tree specified in
    the constructor during construction and makes pointers into
    the tree using an array of Object pointers. The stream necessarily
    includes pointers to DOWN and UP and EOF nodes.

    This stream knows how to mark/release for backtracking.

    This stream is most suitable for tree interpreters that need to
    jump around a lot or for tree parsers requiring speed (at cost of memory).
    There is some duplicated functionality here with UnBufferedTreeNodeStream
    but just in bookkeeping, not tree walking etc...

    @see UnBufferedTreeNodeStream
    """
    
    def __init__(self, *args):
        TreeNodeStream.__init__(self)

        if len(args) == 1:
            adaptor = CommonTreeAdaptor()
            tree = args[0]

        elif len(args) == 2:
            adaptor = args[0]
            tree = args[1]

        else:
            raise TypeError("Invalid arguments")
        
        # all these navigation nodes are shared and hence they
        # cannot contain any line/column info
        self.down = adaptor.createFromType(DOWN, "DOWN")
        self.up = adaptor.createFromType(UP, "UP")
        self.eof = adaptor.createFromType(EOF, "EOF")

        # The complete mapping from stream index to tree node.
        # This buffer includes pointers to DOWN, UP, and EOF nodes.
        # It is built upon ctor invocation.  The elements are type
        #  Object as we don't what the trees look like.

        # Load upon first need of the buffer so we can set token types
        # of interest for reverseIndexing.  Slows us down a wee bit to
        # do all of the if p==-1 testing everywhere though.
        self.nodes = []

        # Pull nodes from which tree?
        self.root = tree

        # IF this tree (root) was created from a token stream, track it.
        self.tokens = None

        # What tree adaptor was used to build these trees
        self.adaptor = adaptor

        # Reuse same DOWN, UP navigation nodes unless this is true
        self.uniqueNavigationNodes = False

        # The index into the nodes list of the current node (next node
        # to consume).  If -1, nodes array not filled yet.
        self.p = -1

        # Track the last mark() call result value for use in rewind().
        self.lastMarker = None

        # Stack of indexes used for push/pop calls
        self.calls = []


    def fillBuffer(self):
        """Walk tree with depth-first-search and fill nodes buffer.
        Don't do DOWN, UP nodes if its a list (t is isNil).
        """

        self._fillBuffer(self.root)
        self.p = 0 # buffer of nodes intialized now


    def _fillBuffer(self, t):
        nil = self.adaptor.isNil(t)
        
        if not nil:
            self.nodes.append(t) # add this node

        # add DOWN node if t has children
        n = self.adaptor.getChildCount(t)
        if not nil and n > 0:
            self.addNavigationNode(DOWN)

        # and now add all its children
        for c in range(n):
            self._fillBuffer(self.adaptor.getChild(t, c))

        # add UP node if t has children
        if not nil and n > 0:
            self.addNavigationNode(UP)


    def getNodeIndex(self, node):
        """What is the stream index for node? 0..n-1
        Return -1 if node not found.
        """
        
        if self.p == -1:
            self.fillBuffer()

        for i, t in enumerate(self.nodes):
            if t == node:
                return i

        return -1


    def addNavigationNode(self, ttype):
        """
        As we flatten the tree, we use UP, DOWN nodes to represent
        the tree structure.  When debugging we need unique nodes
        so instantiate new ones when uniqueNavigationNodes is true.
        """
        
        navNode = None
        
        if ttype == DOWN:
            if self.hasUniqueNavigationNodes():
                navNode = self.adaptor.createFromType(DOWN, "DOWN")

            else:
                navNode = self.down

        else:
            if self.hasUniqueNavigationNodes():
                navNode = self.adaptor.createFromType(UP, "UP")
                
            else:
                navNode = self.up

        self.nodes.append(navNode)


    def get(self, i):
        if self.p == -1:
            self.fillBuffer()

        return self.nodes[i]


    def LT(self, k):
        if self.p == -1:
            self.fillBuffer()

        if k == 0:
            return None

        if k < 0:
            return self.LB(-k)

        #System.out.print("LT(p="+p+","+k+")=");
        if self.p + k - 1 >= len(self.nodes):
            return self.eof

        return self.nodes[self.p + k - 1]
    

    def getCurrentSymbol(self):
        return self.LT(1)


    def LB(self, k):
        """Look backwards k nodes"""
        
        if k == 0:
            return None

        if self.p - k < 0:
            return None

        return self.nodes[self.p - k]


    def getTreeSource(self):
        return self.root


    def getSourceName(self):
        return self.getTokenStream().getSourceName()


    def getTokenStream(self):
        return self.tokens


    def setTokenStream(self, tokens):
        self.tokens = tokens


    def getTreeAdaptor(self):
        return self.adaptor


    def hasUniqueNavigationNodes(self):
        return self.uniqueNavigationNodes


    def setUniqueNavigationNodes(self, uniqueNavigationNodes):
        self.uniqueNavigationNodes = uniqueNavigationNodes


    def consume(self):
        if self.p == -1:
            self.fillBuffer()
            
        self.p += 1

        
    def LA(self, i):
        return self.adaptor.getType(self.LT(i))


    def mark(self):
        if self.p == -1:
            self.fillBuffer()

        
        self.lastMarker = self.index()
        return self.lastMarker


    def release(self, marker=None):
        # no resources to release

        pass


    def index(self):
        return self.p


    def rewind(self, marker=None):
        if marker is None:
            marker = self.lastMarker
            
        self.seek(marker)


    def seek(self, index):
        if self.p == -1:
            self.fillBuffer()

        self.p = index


    def push(self, index):
        """
        Make stream jump to a new location, saving old location.
        Switch back with pop().
        """

        self.calls.append(self.p) # save current index
        self.seek(index)


    def pop(self):
        """
        Seek back to previous index saved during last push() call.
        Return top of stack (return index).
        """

        ret = self.calls.pop(-1)
        self.seek(ret)
        return ret


    def reset(self):
        self.p = 0
        self.lastMarker = 0
        self.calls = []

        
    def size(self):
        if self.p == -1:
            self.fillBuffer()

        return len(self.nodes)


    # TREE REWRITE INTERFACE

    def replaceChildren(self, parent, startChildIndex, stopChildIndex, t):
        if parent is not None:
            self.adaptor.replaceChildren(
                parent, startChildIndex, stopChildIndex, t
                )


    def __str__(self):
        """Used for testing, just return the token type stream"""

        if self.p == -1:
            self.fillBuffer()

        return ' '.join([str(self.adaptor.getType(node))
                         for node in self.nodes
                         ])


    def toString(self, start, stop):
        if start is None or stop is None:
            return None

        if self.p == -1:
            self.fillBuffer()

        #System.out.println("stop: "+stop);
        #if ( start instanceof CommonTree )
        #    System.out.print("toString: "+((CommonTree)start).getToken()+", ");
        #else
        #    System.out.println(start);
        #if ( stop instanceof CommonTree )
        #    System.out.println(((CommonTree)stop).getToken());
        #else
        #    System.out.println(stop);
            
        # if we have the token stream, use that to dump text in order
        if self.tokens is not None:
            beginTokenIndex = self.adaptor.getTokenStartIndex(start)
            endTokenIndex = self.adaptor.getTokenStopIndex(stop)
            
            # if it's a tree, use start/stop index from start node
            # else use token range from start/stop nodes
            if self.adaptor.getType(stop) == UP:
                endTokenIndex = self.adaptor.getTokenStopIndex(start)

            elif self.adaptor.getType(stop) == EOF:
                endTokenIndex = self.size() -2 # don't use EOF

            return self.tokens.toString(beginTokenIndex, endTokenIndex)

        # walk nodes looking for start
        i, t = 0, None
        for i, t in enumerate(self.nodes):
            if t == start:
                break

        # now walk until we see stop, filling string buffer with text
        buf = []
        t = self.nodes[i]
        while t != stop:
            text = self.adaptor.getText(t)
            if text is None:
                text = " " + self.adaptor.getType(t)

            buf.append(text)
            i += 1
            t = self.nodes[i]

        # include stop node too
        text = self.adaptor.getText(stop)
        if text is None:
            text = " " +self.adaptor.getType(stop)

        buf.append(text)
        
        return ''.join(buf)
    

    ## iterator interface
    def __iter__(self):
        if self.p == -1:
            self.fillBuffer()

        for node in self.nodes:
            yield node


#############################################################################
#
# tree parser
#
#############################################################################

class TreeParser(BaseRecognizer):
    """@brief Baseclass for generated tree parsers.
    
    A parser for a stream of tree nodes.  "tree grammars" result in a subclass
    of this.  All the error reporting and recovery is shared with Parser via
    the BaseRecognizer superclass.
    """

    def __init__(self, input, state=None):
        BaseRecognizer.__init__(self, state)

        self.input = None
        self.setTreeNodeStream(input)


    def reset(self):
        BaseRecognizer.reset(self) # reset all recognizer state variables
        if self.input is not None:
            self.input.seek(0) # rewind the input


    def setTreeNodeStream(self, input):
        """Set the input stream"""

        self.input = input


    def getTreeNodeStream(self):
        return self.input


    def getSourceName(self):
        return self.input.getSourceName()


    def getCurrentInputSymbol(self, input):
        return input.LT(1)


    def getMissingSymbol(self, input, e, expectedTokenType, follow):
        tokenText = "<missing " + self.tokenNames[expectedTokenType] + ">"
        return CommonTree(CommonToken(type=expectedTokenType, text=tokenText))


    def matchAny(self, ignore): # ignore stream, copy of this.input
        """
        Match '.' in tree parser has special meaning.  Skip node or
        entire tree if node has children.  If children, scan until
        corresponding UP node.
        """
        
        self._state.errorRecovery = False

        look = self.input.LT(1)
        if self.input.getTreeAdaptor().getChildCount(look) == 0:
            self.input.consume() # not subtree, consume 1 node and return
            return

        # current node is a subtree, skip to corresponding UP.
        # must count nesting level to get right UP
        level = 0
        tokenType = self.input.getTreeAdaptor().getType(look)
        while tokenType != EOF and not (tokenType == UP and level==0):
            self.input.consume()
            look = self.input.LT(1)
            tokenType = self.input.getTreeAdaptor().getType(look)
            if tokenType == DOWN:
                level += 1

            elif tokenType == UP:
                level -= 1

        self.input.consume() # consume UP


    def mismatch(self, input, ttype, follow):
        """
        We have DOWN/UP nodes in the stream that have no line info; override.
        plus we want to alter the exception type. Don't try to recover
        from tree parser errors inline...
        """

        raise MismatchedTreeNodeException(ttype, input)


    def getErrorHeader(self, e):
        """
        Prefix error message with the grammar name because message is
        always intended for the programmer because the parser built
        the input tree not the user.
        """

        return (self.getGrammarFileName() +
                ": node from %sline %s:%s"
                % (['', "after "][e.approximateLineInfo],
                   e.line,
                   e.charPositionInLine
                   )
                )

    def getErrorMessage(self, e, tokenNames):
        """
        Tree parsers parse nodes they usually have a token object as
        payload. Set the exception token and do the default behavior.
        """

        if isinstance(self, TreeParser):
            adaptor = e.input.getTreeAdaptor()
            e.token = adaptor.getToken(e.node)
            if e.token is not None: # could be an UP/DOWN node
                e.token = CommonToken(
                    type=adaptor.getType(e.node),
                    text=adaptor.getText(e.node)
                    )

        return BaseRecognizer.getErrorMessage(self, e, tokenNames)


    def traceIn(self, ruleName, ruleIndex):
        BaseRecognizer.traceIn(self, ruleName, ruleIndex, self.input.LT(1))


    def traceOut(self, ruleName, ruleIndex):
        BaseRecognizer.traceOut(self, ruleName, ruleIndex, self.input.LT(1))


#############################################################################
#
# streams for rule rewriting
#
#############################################################################

class RewriteRuleElementStream(object):
    """@brief Internal helper class.
    
    A generic list of elements tracked in an alternative to be used in
    a -> rewrite rule.  We need to subclass to fill in the next() method,
    which returns either an AST node wrapped around a token payload or
    an existing subtree.

    Once you start next()ing, do not try to add more elements.  It will
    break the cursor tracking I believe.

    @see org.antlr.runtime.tree.RewriteRuleSubtreeStream
    @see org.antlr.runtime.tree.RewriteRuleTokenStream
    
    TODO: add mechanism to detect/puke on modification after reading from
    stream
    """

    def __init__(self, adaptor, elementDescription, elements=None):
        # Cursor 0..n-1.  If singleElement!=null, cursor is 0 until you next(),
        # which bumps it to 1 meaning no more elements.
        self.cursor = 0

        # Track single elements w/o creating a list.  Upon 2nd add, alloc list
        self.singleElement = None

        # The list of tokens or subtrees we are tracking
        self.elements = None

        # Once a node / subtree has been used in a stream, it must be dup'd
        # from then on.  Streams are reset after subrules so that the streams
        # can be reused in future subrules.  So, reset must set a dirty bit.
        # If dirty, then next() always returns a dup.
        self.dirty = False
        
        # The element or stream description; usually has name of the token or
        # rule reference that this list tracks.  Can include rulename too, but
        # the exception would track that info.
        self.elementDescription = elementDescription

        self.adaptor = adaptor

        if isinstance(elements, (list, tuple)):
            # Create a stream, but feed off an existing list
            self.singleElement = None
            self.elements = elements

        else:
            # Create a stream with one element
            self.add(elements)


    def reset(self):
        """
        Reset the condition of this stream so that it appears we have
        not consumed any of its elements.  Elements themselves are untouched.
        Once we reset the stream, any future use will need duplicates.  Set
        the dirty bit.
        """
        
        self.cursor = 0
        self.dirty = True

        
    def add(self, el):
        if el is None:
            return

        if self.elements is not None: # if in list, just add
            self.elements.append(el)
            return

        if self.singleElement is None: # no elements yet, track w/o list
            self.singleElement = el
            return

        # adding 2nd element, move to list
        self.elements = []
        self.elements.append(self.singleElement)
        self.singleElement = None
        self.elements.append(el)


    def nextTree(self):
        """
        Return the next element in the stream.  If out of elements, throw
        an exception unless size()==1.  If size is 1, then return elements[0].
        
        Return a duplicate node/subtree if stream is out of elements and
        size==1. If we've already used the element, dup (dirty bit set).
        """
        
        if (self.dirty
            or (self.cursor >= len(self) and len(self) == 1)
            ):
            # if out of elements and size is 1, dup
            el = self._next()
            return self.dup(el)

        # test size above then fetch
        el = self._next()
        return el


    def _next(self):
        """
        do the work of getting the next element, making sure that it's
        a tree node or subtree.  Deal with the optimization of single-
        element list versus list of size > 1.  Throw an exception
        if the stream is empty or we're out of elements and size>1.
        protected so you can override in a subclass if necessary.
        """

        if len(self) == 0:
            raise RewriteEmptyStreamException(self.elementDescription)
            
        if self.cursor >= len(self): # out of elements?
            if len(self) == 1: # if size is 1, it's ok; return and we'll dup 
                return self.toTree(self.singleElement)

            # out of elements and size was not 1, so we can't dup
            raise RewriteCardinalityException(self.elementDescription)

        # we have elements
        if self.singleElement is not None:
            self.cursor += 1 # move cursor even for single element list
            return self.toTree(self.singleElement)

        # must have more than one in list, pull from elements
        o = self.toTree(self.elements[self.cursor])
        self.cursor += 1
        return o


    def dup(self, el):
        """
        When constructing trees, sometimes we need to dup a token or AST
        subtree.  Dup'ing a token means just creating another AST node
        around it.  For trees, you must call the adaptor.dupTree() unless
        the element is for a tree root; then it must be a node dup.
        """

        raise NotImplementedError
    

    def toTree(self, el):
        """
        Ensure stream emits trees; tokens must be converted to AST nodes.
        AST nodes can be passed through unmolested.
        """

        return el


    def hasNext(self):
        return ( (self.singleElement is not None and self.cursor < 1)
                 or (self.elements is not None
                     and self.cursor < len(self.elements)
                     )
                 )

                 
    def size(self):
        if self.singleElement is not None:
            return 1

        if self.elements is not None:
            return len(self.elements)

        return 0

    __len__ = size
    

    def getDescription(self):
        """Deprecated. Directly access elementDescription attribute"""
        
        return self.elementDescription


class RewriteRuleTokenStream(RewriteRuleElementStream):
    """@brief Internal helper class."""

    def toTree(self, el):
        # Don't convert to a tree unless they explicitly call nextTree.
        # This way we can do hetero tree nodes in rewrite.
        return el


    def nextNode(self):
        t = self._next()
        return self.adaptor.createWithPayload(t)

    
    def nextToken(self):
        return self._next()

    
    def dup(self, el):
        raise TypeError("dup can't be called for a token stream.")


class RewriteRuleSubtreeStream(RewriteRuleElementStream):
    """@brief Internal helper class."""

    def nextNode(self):
        """
        Treat next element as a single node even if it's a subtree.
        This is used instead of next() when the result has to be a
        tree root node.  Also prevents us from duplicating recently-added
        children; e.g., ^(type ID)+ adds ID to type and then 2nd iteration
        must dup the type node, but ID has been added.

        Referencing a rule result twice is ok; dup entire tree as
        we can't be adding trees as root; e.g., expr expr.

        Hideous code duplication here with super.next().  Can't think of
        a proper way to refactor.  This needs to always call dup node
        and super.next() doesn't know which to call: dup node or dup tree.
        """
        
        if (self.dirty
            or (self.cursor >= len(self) and len(self) == 1)
            ):
            # if out of elements and size is 1, dup (at most a single node
            # since this is for making root nodes).
            el = self._next()
            return self.adaptor.dupNode(el)

        # test size above then fetch
        el = self._next()
        return el


    def dup(self, el):
        return self.adaptor.dupTree(el)



class RewriteRuleNodeStream(RewriteRuleElementStream):
    """
    Queues up nodes matched on left side of -> in a tree parser. This is
    the analog of RewriteRuleTokenStream for normal parsers. 
    """
    
    def nextNode(self):
        return self._next()


    def toTree(self, el):
        return self.adaptor.dupNode(el)


    def dup(self, el):
        # we dup every node, so don't have to worry about calling dup; short-
        #circuited next() so it doesn't call.
        raise TypeError("dup can't be called for a node stream.")


class TreeRuleReturnScope(RuleReturnScope):
    """
    This is identical to the ParserRuleReturnScope except that
    the start property is a tree nodes not Token object
    when you are parsing trees.  To be generic the tree node types
    have to be Object.
    """

    def __init__(self):
        self.start = None
        self.tree = None
        
    
    def getStart(self):
        return self.start

    
    def getTree(self):
        return self.tree

