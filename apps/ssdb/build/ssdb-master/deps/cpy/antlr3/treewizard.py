""" @package antlr3.tree
@brief ANTLR3 runtime package, treewizard module

A utility module to create ASTs at runtime.
See <http://www.antlr.org/wiki/display/~admin/2007/07/02/Exploring+Concept+of+TreeWizard> for an overview. Note that the API of the Python implementation is slightly different.

"""

# begin[licence]
#
# [The "BSD licence"]
# Copyright (c) 2005-2008 Terence Parr
# All rights reserved.
#
# Redistribution and use in source and binary forms, with or without
# modification, are permitted provided that the following conditions
# are met:
# 1. Redistributions of source code must retain the above copyright
#    notice, this list of conditions and the following disclaimer.
# 2. Redistributions in binary form must reproduce the above copyright
#    notice, this list of conditions and the following disclaimer in the
#    documentation and/or other materials provided with the distribution.
# 3. The name of the author may not be used to endorse or promote products
#    derived from this software without specific prior written permission.
#
# THIS SOFTWARE IS PROVIDED BY THE AUTHOR ``AS IS'' AND ANY EXPRESS OR
# IMPLIED WARRANTIES, INCLUDING, BUT NOT LIMITED TO, THE IMPLIED WARRANTIES
# OF MERCHANTABILITY AND FITNESS FOR A PARTICULAR PURPOSE ARE DISCLAIMED.
# IN NO EVENT SHALL THE AUTHOR BE LIABLE FOR ANY DIRECT, INDIRECT,
# INCIDENTAL, SPECIAL, EXEMPLARY, OR CONSEQUENTIAL DAMAGES (INCLUDING, BUT
# NOT LIMITED TO, PROCUREMENT OF SUBSTITUTE GOODS OR SERVICES; LOSS OF USE,
# DATA, OR PROFITS; OR BUSINESS INTERRUPTION) HOWEVER CAUSED AND ON ANY
# THEORY OF LIABILITY, WHETHER IN CONTRACT, STRICT LIABILITY, OR TORT
# (INCLUDING NEGLIGENCE OR OTHERWISE) ARISING IN ANY WAY OUT OF THE USE OF
# THIS SOFTWARE, EVEN IF ADVISED OF THE POSSIBILITY OF SUCH DAMAGE.
#
# end[licence]

from antlr3.constants import INVALID_TOKEN_TYPE
from antlr3.tokens import CommonToken
from antlr3.tree import CommonTree, CommonTreeAdaptor


def computeTokenTypes(tokenNames):
    """
    Compute a dict that is an inverted index of
    tokenNames (which maps int token types to names).
    """

    if tokenNames is None:
        return {}

    return dict((name, type) for type, name in enumerate(tokenNames))


## token types for pattern parser
EOF = -1
BEGIN = 1
END = 2
ID = 3
ARG = 4
PERCENT = 5
COLON = 6
DOT = 7

class TreePatternLexer(object):
    def __init__(self, pattern):
        ## The tree pattern to lex like "(A B C)"
        self.pattern = pattern

	## Index into input string
        self.p = -1

	## Current char
        self.c = None

	## How long is the pattern in char?
        self.n = len(pattern)

	## Set when token type is ID or ARG
        self.sval = None

        self.error = False

        self.consume()


    __idStartChar = frozenset(
        'abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ_'
        )
    __idChar = __idStartChar | frozenset('0123456789')
    
    def nextToken(self):
        self.sval = ""
        while self.c != EOF:
            if self.c in (' ', '\n', '\r', '\t'):
                self.consume()
                continue

            if self.c in self.__idStartChar:
                self.sval += self.c
                self.consume()
                while self.c in self.__idChar:
                    self.sval += self.c
                    self.consume()

                return ID

            if self.c == '(':
                self.consume()
                return BEGIN

            if self.c == ')':
                self.consume()
                return END

            if self.c == '%':
                self.consume()
                return PERCENT

            if self.c == ':':
                self.consume()
                return COLON

            if self.c == '.':
                self.consume()
                return DOT

            if self.c == '[': # grab [x] as a string, returning x
                self.consume()
                while self.c != ']':
                    if self.c == '\\':
                        self.consume()
                        if self.c != ']':
                            self.sval += '\\'

                        self.sval += self.c

                    else:
                        self.sval += self.c

                    self.consume()

                self.consume()
                return ARG

            self.consume()
            self.error = True
            return EOF

        return EOF


    def consume(self):
        self.p += 1
        if self.p >= self.n:
            self.c = EOF

        else:
            self.c = self.pattern[self.p]


class TreePatternParser(object):
    def __init__(self, tokenizer, wizard, adaptor):
        self.tokenizer = tokenizer
        self.wizard = wizard
        self.adaptor = adaptor
        self.ttype = tokenizer.nextToken() # kickstart


    def pattern(self):
        if self.ttype == BEGIN:
            return self.parseTree()

        elif self.ttype == ID:
            node = self.parseNode()
            if self.ttype == EOF:
                return node

            return None # extra junk on end

        return None


    def parseTree(self):
        if self.ttype != BEGIN:
            return None

        self.ttype = self.tokenizer.nextToken()
        root = self.parseNode()
        if root is None:
            return None

        while self.ttype in (BEGIN, ID, PERCENT, DOT):
            if self.ttype == BEGIN:
                subtree = self.parseTree()
                self.adaptor.addChild(root, subtree)

            else:
                child = self.parseNode()
                if child is None:
                    return None

                self.adaptor.addChild(root, child)

        if self.ttype != END:
            return None

        self.ttype = self.tokenizer.nextToken()
        return root


    def parseNode(self):
        # "%label:" prefix
        label = None
        
        if self.ttype == PERCENT:
            self.ttype = self.tokenizer.nextToken()
            if self.ttype != ID:
                return None

            label = self.tokenizer.sval
            self.ttype = self.tokenizer.nextToken()
            if self.ttype != COLON:
                return None
            
            self.ttype = self.tokenizer.nextToken() # move to ID following colon

        # Wildcard?
        if self.ttype == DOT:
            self.ttype = self.tokenizer.nextToken()
            wildcardPayload = CommonToken(0, ".")
            node = WildcardTreePattern(wildcardPayload)
            if label is not None:
                node.label = label
            return node

        # "ID" or "ID[arg]"
        if self.ttype != ID:
            return None

        tokenName = self.tokenizer.sval
        self.ttype = self.tokenizer.nextToken()
        
        if tokenName == "nil":
            return self.adaptor.nil()

        text = tokenName
        # check for arg
        arg = None
        if self.ttype == ARG:
            arg = self.tokenizer.sval
            text = arg
            self.ttype = self.tokenizer.nextToken()

        # create node
        treeNodeType = self.wizard.getTokenType(tokenName)
        if treeNodeType == INVALID_TOKEN_TYPE:
            return None

        node = self.adaptor.createFromType(treeNodeType, text)
        if label is not None and isinstance(node, TreePattern):
            node.label = label

        if arg is not None and isinstance(node, TreePattern):
            node.hasTextArg = True

        return node


class TreePattern(CommonTree):
    """
    When using %label:TOKENNAME in a tree for parse(), we must
    track the label.
    """

    def __init__(self, payload):
        CommonTree.__init__(self, payload)

        self.label = None
        self.hasTextArg = None
        

    def toString(self):
        if self.label is not None:
            return '%' + self.label + ':' + CommonTree.toString(self)
        
        else:
            return CommonTree.toString(self)


class WildcardTreePattern(TreePattern):
    pass


class TreePatternTreeAdaptor(CommonTreeAdaptor):
    """This adaptor creates TreePattern objects for use during scan()"""

    def createWithPayload(self, payload):
        return TreePattern(payload)


class TreeWizard(object):
    """
    Build and navigate trees with this object.  Must know about the names
    of tokens so you have to pass in a map or array of token names (from which
    this class can build the map).  I.e., Token DECL means nothing unless the
    class can translate it to a token type.

    In order to create nodes and navigate, this class needs a TreeAdaptor.

    This class can build a token type -> node index for repeated use or for
    iterating over the various nodes with a particular type.

    This class works in conjunction with the TreeAdaptor rather than moving
    all this functionality into the adaptor.  An adaptor helps build and
    navigate trees using methods.  This class helps you do it with string
    patterns like "(A B C)".  You can create a tree from that pattern or
    match subtrees against it.
    """

    def __init__(self, adaptor=None, tokenNames=None, typeMap=None):
        self.adaptor = adaptor
        if typeMap is None:
            self.tokenNameToTypeMap = computeTokenTypes(tokenNames)

        else:
            if tokenNames is not None:
                raise ValueError("Can't have both tokenNames and typeMap")

            self.tokenNameToTypeMap = typeMap


    def getTokenType(self, tokenName):
        """Using the map of token names to token types, return the type."""

        try:
            return self.tokenNameToTypeMap[tokenName]
        except KeyError:
            return INVALID_TOKEN_TYPE


    def create(self, pattern):
        """
        Create a tree or node from the indicated tree pattern that closely
        follows ANTLR tree grammar tree element syntax:
        
        (root child1 ... child2).
        
        You can also just pass in a node: ID
         
        Any node can have a text argument: ID[foo]
        (notice there are no quotes around foo--it's clear it's a string).
        
        nil is a special name meaning "give me a nil node".  Useful for
        making lists: (nil A B C) is a list of A B C.
        """
        
        tokenizer = TreePatternLexer(pattern)
        parser = TreePatternParser(tokenizer, self, self.adaptor)
        return parser.pattern()


    def index(self, tree):
        """Walk the entire tree and make a node name to nodes mapping.
        
        For now, use recursion but later nonrecursive version may be
        more efficient.  Returns a dict int -> list where the list is
        of your AST node type.  The int is the token type of the node.
        """

        m = {}
        self._index(tree, m)
        return m


    def _index(self, t, m):
        """Do the work for index"""

        if t is None:
            return

        ttype = self.adaptor.getType(t)
        elements = m.get(ttype)
        if elements is None:
            m[ttype] = elements = []

        elements.append(t)
        for i in range(self.adaptor.getChildCount(t)):
            child = self.adaptor.getChild(t, i)
            self._index(child, m)


    def find(self, tree, what):
        """Return a list of matching token.

        what may either be an integer specifzing the token type to find or
        a string with a pattern that must be matched.
        
        """
        
        if isinstance(what, (int, long)):
            return self._findTokenType(tree, what)

        elif isinstance(what, basestring):
            return self._findPattern(tree, what)

        else:
            raise TypeError("'what' must be string or integer")


    def _findTokenType(self, t, ttype):
        """Return a List of tree nodes with token type ttype"""

        nodes = []

        def visitor(tree, parent, childIndex, labels):
            nodes.append(tree)

        self.visit(t, ttype, visitor)

        return nodes


    def _findPattern(self, t, pattern):
        """Return a List of subtrees matching pattern."""
        
        subtrees = []
        
        # Create a TreePattern from the pattern
        tokenizer = TreePatternLexer(pattern)
        parser = TreePatternParser(tokenizer, self, TreePatternTreeAdaptor())
        tpattern = parser.pattern()
        
        # don't allow invalid patterns
        if (tpattern is None or tpattern.isNil()
            or isinstance(tpattern, WildcardTreePattern)):
            return None

        rootTokenType = tpattern.getType()

        def visitor(tree, parent, childIndex, label):
            if self._parse(tree, tpattern, None):
                subtrees.append(tree)
                
        self.visit(t, rootTokenType, visitor)

        return subtrees


    def visit(self, tree, what, visitor):
        """Visit every node in tree matching what, invoking the visitor.

        If what is a string, it is parsed as a pattern and only matching
        subtrees will be visited.
        The implementation uses the root node of the pattern in combination
        with visit(t, ttype, visitor) so nil-rooted patterns are not allowed.
        Patterns with wildcard roots are also not allowed.

        If what is an integer, it is used as a token type and visit will match
        all nodes of that type (this is faster than the pattern match).
        The labels arg of the visitor action method is never set (it's None)
        since using a token type rather than a pattern doesn't let us set a
        label.
        """

        if isinstance(what, (int, long)):
            self._visitType(tree, None, 0, what, visitor)

        elif isinstance(what, basestring):
            self._visitPattern(tree, what, visitor)

        else:
            raise TypeError("'what' must be string or integer")
        
              
    def _visitType(self, t, parent, childIndex, ttype, visitor):
        """Do the recursive work for visit"""
        
        if t is None:
            return

        if self.adaptor.getType(t) == ttype:
            visitor(t, parent, childIndex, None)

        for i in range(self.adaptor.getChildCount(t)):
            child = self.adaptor.getChild(t, i)
            self._visitType(child, t, i, ttype, visitor)


    def _visitPattern(self, tree, pattern, visitor):
        """
        For all subtrees that match the pattern, execute the visit action.
        """

        # Create a TreePattern from the pattern
        tokenizer = TreePatternLexer(pattern)
        parser = TreePatternParser(tokenizer, self, TreePatternTreeAdaptor())
        tpattern = parser.pattern()
        
        # don't allow invalid patterns
        if (tpattern is None or tpattern.isNil()
            or isinstance(tpattern, WildcardTreePattern)):
            return

        rootTokenType = tpattern.getType()

        def rootvisitor(tree, parent, childIndex, labels):
            labels = {}
            if self._parse(tree, tpattern, labels):
                visitor(tree, parent, childIndex, labels)
                
        self.visit(tree, rootTokenType, rootvisitor)
        

    def parse(self, t, pattern, labels=None):
        """
        Given a pattern like (ASSIGN %lhs:ID %rhs:.) with optional labels
        on the various nodes and '.' (dot) as the node/subtree wildcard,
        return true if the pattern matches and fill the labels Map with
        the labels pointing at the appropriate nodes.  Return false if
        the pattern is malformed or the tree does not match.

        If a node specifies a text arg in pattern, then that must match
        for that node in t.
        """

        tokenizer = TreePatternLexer(pattern)
        parser = TreePatternParser(tokenizer, self, TreePatternTreeAdaptor())
        tpattern = parser.pattern()

        return self._parse(t, tpattern, labels)


    def _parse(self, t1, t2, labels):
        """
        Do the work for parse. Check to see if the t2 pattern fits the
        structure and token types in t1.  Check text if the pattern has
        text arguments on nodes.  Fill labels map with pointers to nodes
        in tree matched against nodes in pattern with labels.
	"""
        
        # make sure both are non-null
        if t1 is None or t2 is None:
            return False

        # check roots (wildcard matches anything)
        if not isinstance(t2, WildcardTreePattern):
            if self.adaptor.getType(t1) != t2.getType():
                return False

            if t2.hasTextArg and self.adaptor.getText(t1) != t2.getText():
                return False

        if t2.label is not None and labels is not None:
            # map label in pattern to node in t1
            labels[t2.label] = t1

        # check children
        n1 = self.adaptor.getChildCount(t1)
        n2 = t2.getChildCount()
        if n1 != n2:
            return False

        for i in range(n1):
            child1 = self.adaptor.getChild(t1, i)
            child2 = t2.getChild(i)
            if not self._parse(child1, child2, labels):
                return False

        return True


    def equals(self, t1, t2, adaptor=None):
        """
        Compare t1 and t2; return true if token types/text, structure match
        exactly.
        The trees are examined in their entirety so that (A B) does not match
        (A B C) nor (A (B C)). 
        """

        if adaptor is None:
            adaptor = self.adaptor

        return self._equals(t1, t2, adaptor)


    def _equals(self, t1, t2, adaptor):
        # make sure both are non-null
        if t1 is None or t2 is None:
            return False

        # check roots
        if adaptor.getType(t1) != adaptor.getType(t2):
            return False

        if adaptor.getText(t1) != adaptor.getText(t2):
            return False
        
        # check children
        n1 = adaptor.getChildCount(t1)
        n2 = adaptor.getChildCount(t2)
        if n1 != n2:
            return False

        for i in range(n1):
            child1 = adaptor.getChild(t1, i)
            child2 = adaptor.getChild(t2, i)
            if not self._equals(child1, child2, adaptor):
                return False

        return True
