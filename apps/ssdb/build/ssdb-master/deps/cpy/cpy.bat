
@echo off
python %~dp0cpy.py %1 %2 %3 %4 %5 %6 %7 %8 %9
