# encoding=utf-8
#################################
# Author: ideawu
# Link: http://www.ideawu.net/
#################################

import sys, os
import signal
def __sigint__(n, f):
	sys.exit(0)
signal.signal(signal.SIGINT, __sigint__);

def usage():
	print ('Cpy - A C-like scripting language.')
	print ('Copyright (c) 2012 ideawu.com')
	print ('')
	print ('Usage:')
	print ('    cpy source_file')

# 不然管道时报错
reload(sys)
sys.setdefaultencoding('utf-8')

from engine import CpyEngine
cpy = CpyEngine()

if len(sys.argv) < 2:
	usage()
	sys.exit(0)

is_compile = False;
if sys.argv[1] == '-c':
	is_compile = True
	if len(sys.argv) >= 3:
		srcfile = sys.argv[2]
	else:
		usage()
		sys.exit(0)
else:
	srcfile = sys.argv[1]

if not srcfile.endswith('.cpy'):
	srcfile += '.cpy'
if not os.path.exists(srcfile):
	print ("File not found!: " + srcfile)
	sys.exit(0)

base_dir, tail = os.path.split(srcfile)
if len(base_dir) == 0:
	base_dir = '.'

dstfile = cpy.compile(srcfile, base_dir, base_dir + '/_cpy_')

#print ('-----')
#print (''.join(open(dstfile, 'r').readlines()))
#print ('-----')

dstfile = os.path.abspath(dstfile)
sys.path.append(os.path.dirname(os.path.abspath(srcfile)));
sys.path.append(os.path.dirname(os.path.abspath(dstfile)));

os.chdir(os.path.dirname(os.path.abspath(srcfile)));
#print os.getcwd();

if not is_compile:
	sys.argv = sys.argv[1 :]
	sys.path.insert(0, os.path.dirname(dstfile))
	try:
		execfile(dstfile)
	except Exception:
		import traceback
		sys.stderr.write(traceback.format_exc())
		pass
