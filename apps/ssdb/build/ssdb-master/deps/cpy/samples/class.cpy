class A{
	public a = 0;
	public static s = 1;

	function init(a){
		this.a = a;
		print 'A init', a;
	}

	function f(a, b=1){
		return a + b;
	}
}

print A.s; // 1
a = new A(1); // A init 1
print a.f(1, 2);
