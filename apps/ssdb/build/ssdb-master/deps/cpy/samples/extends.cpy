class A{
	function f(){
		print 'A.f';
	}
}
class B extends A{
	function g(){
		print "B.g";
	}
}

b = new B();
b.f();
b.g();
