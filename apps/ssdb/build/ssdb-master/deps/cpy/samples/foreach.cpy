
arr = [10, 11, 12];
foreach(arr as k=>v){
	print k, v;
}

# output: #

d = {
	'a': 1,
	'b': 2,
	'c': 3,
	};

foreach(d as k=>v){
	print k, v;
}
