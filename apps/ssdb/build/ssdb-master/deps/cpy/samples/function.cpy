
function f(a, b=1){
	return a + b;
}

print f(1);
print f(1, 2);

