
printf("Hello World!\n");
