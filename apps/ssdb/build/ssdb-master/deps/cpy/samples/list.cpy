a = []; // empty array
a.append(1);
a.append(2);
print a[0]; // output: 1
print a; // output: [1, 2]

a = [1, 2];
print a;
