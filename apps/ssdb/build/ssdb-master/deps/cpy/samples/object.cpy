
a = {}; // empty dictionary
a['x'] = [1, 2];
a['y'] = [3, 4];
foreach(a as k=>v1, v2){
	printf('%s: %d, %d\n', k, v1, v2);
}

