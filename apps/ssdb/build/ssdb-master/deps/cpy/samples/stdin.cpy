

print "input 'q' to quit:";

while(true){
	printf("> ");
	line = stdin.readline();
	line = line.strip().lower();
	if(line == 'q'){
		print "bye.";
		break;
	}else{
		print 'your input:', repr(line);
	}
}
