a = {}; // empty dictionary
a['x'] = 1;
a['y'] = 2;
foreach(a as k,v){
	print k, v;
}
