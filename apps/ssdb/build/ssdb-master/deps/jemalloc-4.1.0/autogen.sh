#!/bin/sh

for i in autoconf; do
    echo "$i"
    $i
    if [ $? -ne 0 ]; then
	echo "Error $? in $i"
	exit 1
    fi
done

echo "./configure --enable-autogen $@"
./configure --enable-autogen $@
if [ $? -ne 0 ]; then
    echo "Error $? in ./configure"
    exit 1
fi
