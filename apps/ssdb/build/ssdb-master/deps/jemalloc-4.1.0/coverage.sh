#!/bin/sh

set -e

objdir=$1
suffix=$2
shift 2
objs=$@

gcov -b -p -f -o "${objdir}" ${objs}

# Move gcov outputs so that subsequent gcov invocations won't clobber results
# for the same sources with different compilation flags.
for f in `find . -maxdepth 1 -type f -name '*.gcov'` ; do
  mv "${f}" "${f}.${suffix}"
done
