<xsl:stylesheet xmlns:xsl="http://www.w3.org/1999/XSL/Transform" version="1.0">
  <xsl:param name="funcsynopsis.style">ansi</xsl:param>
  <xsl:param name="function.parens" select="1"/>
  <xsl:template match="mallctl">
    "<xsl:call-template name="inline.monoseq"/>"
  </xsl:template>
</xsl:stylesheet>
